"""Stochastic CA & Monte-Carlo tier (docs/STOCHASTIC.md).

The TPU-cluster Ising paper (PAPERS.md, arXiv:1903.11714) runs the exact
stencil + halo skeleton this repo already has — what it adds is *noise*:
Metropolis sweeps whose accept/reject draws come from an on-device
counter-based PRNG.  This package is that tier:

- :mod:`tpu_life.mc.prng` — portable Threefry-2x32 keyed by
  ``(seed, step, cell, substream)``: any trajectory is bit-reproducible
  from its seed regardless of chunking, backend (numpy vs XLA), or
  checkpoint/resume point, because the stream is a pure function of the
  counter, never of execution order.
- :mod:`tpu_life.mc.ising` — Metropolis–Hastings via the checkerboard
  decomposition (two half-lattice updates per sweep), temperature as a
  per-session scalar folded into a 5-entry uint32 acceptance table.
- :mod:`tpu_life.mc.noisy` — noisy-Life: any registered 2-state rule
  composed with a per-cell flip probability.
- :mod:`tpu_life.mc.packed` — the bitplane-packed Metropolis fast path:
  32 spins per uint32 lane, checkerboard folded into the packing,
  acceptance evaluated per-lane — bit-identical to the roll path, and
  the carrier of the wide (two-word) PRNG cell index for mega-boards.
- :mod:`tpu_life.mc.engine` — the serve executors (vmapped device batch
  + numpy ground truth, mixed temperatures in ONE CompileKey) and the
  single-run Runners behind ``run --rule ising``.

The dispatchers below (``step_np`` / ``run_np`` / ``make_step_fn``) are
the single seam the backends, engines and tests share, so the jax and
numpy paths cannot drift.
"""

from __future__ import annotations

import numpy as np

from tpu_life.models.rules import IsingRule, NoisyRule, Rule
from tpu_life.mc import prng
from tpu_life.mc.prng import key_halves, seeded_board
from tpu_life.mc import ising, noisy


def validate_params(rule: Rule, temperature: float | None) -> None:
    """Typed errors for the (rule, temperature) pairing — shared by the
    driver, the serve submit path and the gateway protocol so every
    front speaks the same contract."""
    if isinstance(rule, IsingRule):
        if temperature is None:
            raise ValueError(
                f"rule {rule.name!r} is a Metropolis sampler and needs a "
                f"temperature (e.g. --temperature 2.27)"
            )
        t = float(temperature)
        if not np.isfinite(t) or t < 0.0:
            raise ValueError(
                f"temperature must be a finite number >= 0, got {temperature!r}"
            )
    elif temperature is not None:
        raise ValueError(
            f"temperature only applies to the 'ising' rule; rule "
            f"{rule.name!r} does not take one"
        )


#: Executors implementing the counter-based key schedule.  THE single
#: allow-list — the driver pre-check, the runner factory and the serve
#: engine factory all consult it, so adding a stochastic-capable backend
#: (e.g. a future sharded path) is a one-line change.
SUPPORTED_BACKENDS = ("jax", "numpy")


def require_key_schedule(rule: Rule, backend_name: str) -> None:
    """The hard gate: ``backend_name`` must implement the key schedule.
    A silent fallback would produce a different (and irreproducible)
    trajectory, which is worse than an error."""
    if backend_name not in SUPPORTED_BACKENDS:
        raise ValueError(
            f"stochastic rule {rule.name!r} needs the jax or numpy backend "
            f"(the counter-based per-cell key schedule is not implemented "
            f"for {backend_name!r}); a silent deterministic fallback would "
            f"not be the rule you asked for"
        )


def ensure_backend_supported(rule: Rule, backend_name: str) -> None:
    """Driver-facing form of :func:`require_key_schedule`: ``auto`` is
    allowed through (get_backend resolves it to a supported executor)."""
    if getattr(rule, "stochastic", False) and backend_name != "auto":
        require_key_schedule(rule, backend_name)


def packed_supports(rule: Rule) -> bool:
    """True when the bitplane-packed Metropolis path (``tpu_life.mc.packed``)
    covers ``rule`` — structural check only, import-light on purpose so
    admission fronts can consult it without touching the packed module."""
    return isinstance(rule, IsingRule)


def wide_counter_capable(
    rule: Rule, backend_name: str, *, bitpack: bool = True
) -> bool:
    """Whether this (rule, backend, bitpack) admission will run on an
    executor implementing the two-word (wide) PRNG cell index.

    Only the packed path carries the wide schedule; the int8 roll path
    is pinned to the narrow one-word index, so over-2^32-cell boards on
    it are a typed rejection (``validate_board_shape``), never a silent
    counter wraparound.  ``auto`` resolves stochastic rules to jax, which
    defaults to the packed path; explicit numpy stays the roll ground
    truth (packed numpy runners are constructed explicitly).
    """
    return (
        bitpack
        and packed_supports(rule)
        and backend_name in ("auto", "jax")
    )


def validate_board_shape(
    rule: Rule, shape: tuple[int, int], *, wide_counter: bool = False
) -> None:
    """Typed rejection for lattices the rule cannot run correctly.

    The ising checkerboard 2-coloring is only a valid independent-set
    decomposition on the torus when BOTH dimensions are even: with an
    odd dimension, wrap-seam neighbors share a parity, so the two
    half-updates would step coupled spins simultaneously — no longer
    Metropolis.  Rejected loudly at every front rather than sampling
    the wrong distribution.

    Board AREA is validated against the PRNG counter width for every
    stochastic rule: past ``prng.MAX_NARROW_CELLS`` the one-word cell
    index would wrap mod 2^32 and silently reuse draws — a typed
    rejection on the narrow (roll) path, legal on executors carrying the
    two-word wide index (``wide_counter=True``: the packed path).
    """
    if not getattr(rule, "stochastic", False):
        return
    h, w = int(shape[0]), int(shape[1])
    if isinstance(rule, IsingRule) and (h % 2 or w % 2):
        raise ValueError(
            f"rule {rule.name!r} needs even lattice dimensions (the "
            f"torus checkerboard 2-coloring breaks across the wrap "
            f"seam on odd sizes), got {h}x{w}"
        )
    if h * w > prng.MAX_NARROW_CELLS and not wide_counter:
        raise ValueError(
            f"board has {h * w} cells, past the one-word PRNG cell index "
            f"({prng.MAX_NARROW_CELLS} cells): the narrow counter would "
            f"wrap and reuse draws.  Only the packed executors (wide "
            f"two-word cell index) carry the schedule for boards this "
            f"size, and staging one additionally needs shard-wise I/O "
            f"(cell_uniforms(origin=...) blocks; see docs/STOCHASTIC.md "
            f"limits) — or shrink the lattice"
        )


def make_step_fn(xp, rule: Rule):
    """One stochastic step as ``fn(board, k0, k1, step, thresholds)``.

    ``xp`` is ``numpy`` or ``jax.numpy``; the returned callable is pure
    and traceable (usable under jit/vmap/scan when ``xp`` is jnp).
    ``thresholds`` is the ising uint32[5] acceptance table (per-slot in
    the batched engine); noisy rules ignore it (their flip probability is
    frozen in the rule itself).
    """
    if isinstance(rule, IsingRule):
        def step(board, k0, k1, step_idx, thresholds):
            return ising.sweep(xp, board, k0, k1, step_idx, thresholds)

        return step
    if isinstance(rule, NoisyRule):
        base = noisy.make_noisy_step(xp, rule)

        def step(board, k0, k1, step_idx, thresholds=None):  # noqa: ARG001
            return base(board, k0, k1, step_idx)

        return step
    raise ValueError(f"rule {rule.name!r} is not stochastic")


def step_np(
    rule: Rule,
    board: np.ndarray,
    seed: int,
    step: int,
    *,
    temperature: float | None = None,
) -> np.ndarray:
    """One ground-truth NumPy step at absolute step index ``step``."""
    k0, k1 = key_halves(seed)
    thr = (
        ising.acceptance_thresholds(temperature)
        if isinstance(rule, IsingRule)
        else None
    )
    return make_step_fn(np, rule)(board, k0, k1, np.uint32(step), thr)


def run_np(
    rule: Rule,
    board: np.ndarray,
    seed: int,
    steps: int,
    *,
    temperature: float | None = None,
    start_step: int = 0,
) -> np.ndarray:
    """``steps`` ground-truth NumPy steps from absolute ``start_step`` —
    the oracle every other executor is pinned bit-identical against."""
    validate_params(rule, temperature)
    k0, k1 = key_halves(seed)
    thr = (
        ising.acceptance_thresholds(temperature)
        if isinstance(rule, IsingRule)
        else None
    )
    fn = make_step_fn(np, rule)
    board = np.asarray(board, np.int8)
    for i in range(steps):
        board = fn(board, k0, k1, np.uint32(start_step + i), thr)
    return board


__all__ = [
    "SUPPORTED_BACKENDS",
    "IsingRule",
    "NoisyRule",
    "ensure_backend_supported",
    "packed_supports",
    "require_key_schedule",
    "validate_board_shape",
    "wide_counter_capable",
    "ising",
    "key_halves",
    "make_step_fn",
    "noisy",
    "prng",
    "run_np",
    "seeded_board",
    "step_np",
    "validate_params",
]
