"""Counter-based PRNG: Threefry-2x32 keyed by (seed, step, cell, substream).

The reproducibility contract of the stochastic tier rests on one idea:
every random draw is a **pure function of its coordinates**, never of
execution order.  The draw for cell ``(r, c)`` at absolute step ``s`` in
substream ``m`` of a run seeded ``S`` is::

    u32 = threefry2x32(key=(S_lo, S_hi), counter=(r*w + c, s*NSUB + m))[0]

so the same (seed, rule, temperature, board) produces the byte-identical
trajectory

- across host-sync chunk sizes (the counter is the *absolute* step, not
  the chunk-local one),
- across checkpoint/resume (the resume step re-enters the stream at the
  right counter),
- across executors (the hash is ~20 uint32 add/rotl/xor rounds, which
  NumPy and XLA implement with identical wrapping semantics — asserted
  against the Random123 known-answer vectors in tests/test_mc_prng.py),
- across batch slots (vmap maps the same pure function over per-slot
  keys).

This is deliberately NOT ``jax.random``: serving needs the numpy ground
truth to produce bit-identical streams, so the hash is implemented once
here against an array-module parameter ``xp`` (numpy or jax.numpy) and
shared by both.  It is the same Threefry-2x32/20 JAX itself uses, and
matches ``jax._src.prng.threefry_2x32`` bit-for-bit.

Substreams keep logically distinct draw families from colliding at the
same (cell, step): the two checkerboard half-sweeps, the noisy-Life flip
mask, and board seeding each own one.
"""

from __future__ import annotations

from contextlib import nullcontext as _nullcontext

import numpy as np

#: Substream ids — one per independent draw family at the same (cell, step).
SUB_EVEN = 0  # checkerboard half-sweep, parity 0
SUB_ODD = 1  # checkerboard half-sweep, parity 1
SUB_NOISE = 2  # noisy-Life flip mask
SUB_BOARD = 3  # seeded initial-board staging
NSUB = 4

_ROT_A = (13, 15, 26, 6)
_ROT_B = (17, 29, 16, 24)


def _rotl(xp, x, r: int):
    r = xp.uint32(r)
    return (x << r) | (x >> (xp.uint32(32) - r))


def threefry2x32(xp, k0, k1, c0, c1):
    """Threefry-2x32, 20 rounds: counter ``(c0, c1)`` under key ``(k0, k1)``.

    All inputs are uint32 (scalars or arrays; ``c0``/``c1`` broadcast);
    returns the two uint32 output words.  ``xp`` is numpy or jax.numpy —
    uint32 arithmetic wraps identically in both, which is the whole
    portability story.
    """
    # wraparound is the algorithm; numpy warns on *scalar* uint32 overflow
    # (0-d counters), so the intent is declared explicitly for that path
    guard = np.errstate(over="ignore") if xp is np else _nullcontext()
    with guard:
        k0 = xp.uint32(k0)
        k1 = xp.uint32(k1)
        ks2 = k0 ^ k1 ^ xp.uint32(0x1BD11BDA)
        x0 = xp.asarray(c0, dtype=xp.uint32) + k0
        x1 = xp.asarray(c1, dtype=xp.uint32) + k1
        keys = (k0, k1, ks2)
        for group in range(5):
            for r in _ROT_A if group % 2 == 0 else _ROT_B:
                x0 = x0 + x1
                x1 = _rotl(xp, x1, r)
                x1 = x1 ^ x0
            x0 = x0 + keys[(group + 1) % 3]
            x1 = x1 + keys[(group + 2) % 3] + xp.uint32(group + 1)
        return x0, x1


def key_halves(seed: int) -> tuple[int, int]:
    """Split a Python-int seed into the (lo, hi) uint32 key words.

    Negative seeds are well-defined (two's complement of the low 64
    bits), so ``seed=-1`` is a valid, distinct stream.
    """
    seed = int(seed)
    return seed & 0xFFFFFFFF, (seed >> 32) & 0xFFFFFFFF


def cell_uniforms(xp, shape: tuple[int, int], k0, k1, step, substream: int):
    """uint32[h, w] of i.i.d. draws for every cell at ``step``/``substream``.

    ``k0``/``k1``/``step`` may be traced scalars (per-slot under vmap);
    ``shape`` and ``substream`` are static.  Cell index wraps mod 2^32 —
    boards at or beyond 65536^2 cells would reuse counters and must move
    to a 2-word cell index first.
    """
    h, w = shape
    c0 = xp.arange(h * w, dtype=xp.uint32).reshape(h, w)
    c1 = xp.uint32(step) * xp.uint32(NSUB) + xp.uint32(substream)
    u, _ = threefry2x32(xp, k0, k1, c0, c1)
    return u


def threshold_u32(p: float) -> int:
    """``p`` in [0, 1] -> the uint32 threshold t with P(u < t) ~= p.

    Exact at the ends in the strict-less-than convention: p<=0 -> 0
    (never), p>=1 -> callers must branch (no uint32 t makes ``u < t``
    always true); interior p is within 2^-32 of exact.
    """
    if p <= 0.0:
        return 0
    return min(0xFFFFFFFF, int(float(p) * 4294967296.0))


def seeded_board(
    height: int,
    width: int,
    density: float = 0.5,
    *,
    states: int = 2,
    seed: int = 0,
) -> np.ndarray:
    """A seeded random board from the counter-based stream (int8).

    Replaces the numpy-Generator staging for seeded runs so the board a
    seed names is identical everywhere a seed can be replayed — CLI,
    serve spool, gateway, any host — and is stamped into telemetry as
    the full replay record.  Uses ``SUB_BOARD`` at step 0, so it never
    collides with any simulation draw of the same seed.
    """
    if not 0.0 <= density <= 1.0:
        raise ValueError(f"density must be in [0, 1], got {density}")
    if states < 2:
        raise ValueError(f"states must be >= 2, got {states}")
    k0, k1 = key_halves(seed)
    u = cell_uniforms(np, (height, width), k0, k1, np.uint32(0), SUB_BOARD)
    if density >= 1.0:
        alive = np.ones((height, width), dtype=bool)
    else:
        alive = u < np.uint32(threshold_u32(density))
    if states == 2:
        return alive.astype(np.int8)
    # multi-state: reuse the high-quality word 1 for the state choice so
    # the alive mask and the state draw stay independent
    _, u2 = threefry2x32(
        np,
        k0,
        k1,
        np.arange(height * width, dtype=np.uint32).reshape(height, width),
        np.uint32(1) * np.uint32(NSUB) + np.uint32(SUB_BOARD),
    )
    state = (u2 % np.uint32(states - 1)).astype(np.int8) + np.int8(1)
    return np.where(alive, state, np.int8(0)).astype(np.int8)
