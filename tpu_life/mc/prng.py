"""Counter-based PRNG: Threefry-2x32 keyed by (seed, step, cell, substream).

The reproducibility contract of the stochastic tier rests on one idea:
every random draw is a **pure function of its coordinates**, never of
execution order.  The draw for cell ``(r, c)`` at absolute step ``s`` in
substream ``m`` of a run seeded ``S`` is::

    u32 = threefry2x32(key=(S_lo, S_hi), counter=(r*w + c, s*NSUB + m))[0]

so the same (seed, rule, temperature, board) produces the byte-identical
trajectory

- across host-sync chunk sizes (the counter is the *absolute* step, not
  the chunk-local one),
- across checkpoint/resume (the resume step re-enters the stream at the
  right counter),
- across executors (the hash is ~20 uint32 add/rotl/xor rounds, which
  NumPy and XLA implement with identical wrapping semantics — asserted
  against the Random123 known-answer vectors in tests/test_mc_prng.py),
- across batch slots (vmap maps the same pure function over per-slot
  keys).

This is deliberately NOT ``jax.random``: serving needs the numpy ground
truth to produce bit-identical streams, so the hash is implemented once
here against an array-module parameter ``xp`` (numpy or jax.numpy) and
shared by both.  It is the same Threefry-2x32/20 JAX itself uses, and
matches ``jax._src.prng.threefry_2x32`` bit-for-bit.

Substreams keep logically distinct draw families from colliding at the
same (cell, step): the two checkerboard half-sweeps, the noisy-Life flip
mask, and board seeding each own one.
"""

from __future__ import annotations

from contextlib import nullcontext as _nullcontext

import numpy as np

#: Substream ids — one per independent draw family at the same (cell, step).
SUB_EVEN = 0  # checkerboard half-sweep, parity 0
SUB_ODD = 1  # checkerboard half-sweep, parity 1
SUB_NOISE = 2  # noisy-Life flip mask
SUB_BOARD = 3  # seeded initial-board staging
NSUB = 4

#: Cells addressable by the narrow (one-word) schedule: flat indices
#: 0 .. 2^32 - 1 fit a single uint32 counter word.  Bigger boards MUST go
#: through the wide (two-word) cell index below — on the narrow schedule
#: their indices would wrap mod 2^32 and silently reuse draws.
MAX_NARROW_CELLS = 1 << 32

#: The c1 word of the wide-index key-derivation hash.  Simulation draws
#: use c1 = step * NSUB + substream, which only reaches this value at
#: step ~(2^32 - 1) / NSUB ≈ 1.07e9 — far past any realistic trajectory,
#: so the derivation counter space never collides with a draw's.
WIDE_KEY_TAG = 0xFFFFFFFF

_ROT_A = (13, 15, 26, 6)
_ROT_B = (17, 29, 16, 24)


def _rotl(xp, x, r: int):
    r = xp.uint32(r)
    return (x << r) | (x >> (xp.uint32(32) - r))


def threefry2x32(xp, k0, k1, c0, c1):
    """Threefry-2x32, 20 rounds: counter ``(c0, c1)`` under key ``(k0, k1)``.

    All inputs are uint32 (scalars or arrays; ``c0``/``c1`` broadcast);
    returns the two uint32 output words.  ``xp`` is numpy or jax.numpy —
    uint32 arithmetic wraps identically in both, which is the whole
    portability story.
    """
    # wraparound is the algorithm; numpy warns on *scalar* uint32 overflow
    # (0-d counters), so the intent is declared explicitly for that path
    guard = np.errstate(over="ignore") if xp is np else _nullcontext()
    with guard:
        k0 = xp.uint32(k0)
        k1 = xp.uint32(k1)
        ks2 = k0 ^ k1 ^ xp.uint32(0x1BD11BDA)
        x0 = xp.asarray(c0, dtype=xp.uint32) + k0
        x1 = xp.asarray(c1, dtype=xp.uint32) + k1
        keys = (k0, k1, ks2)
        for group in range(5):
            for r in _ROT_A if group % 2 == 0 else _ROT_B:
                x0 = x0 + x1
                x1 = _rotl(xp, x1, r)
                x1 = x1 ^ x0
            x0 = x0 + keys[(group + 1) % 3]
            x1 = x1 + keys[(group + 2) % 3] + xp.uint32(group + 1)
        return x0, x1


def key_halves(seed: int) -> tuple[int, int]:
    """Split a Python-int seed into the (lo, hi) uint32 key words.

    Negative seeds are well-defined (two's complement of the low 64
    bits), so ``seed=-1`` is a valid, distinct stream.
    """
    seed = int(seed)
    return seed & 0xFFFFFFFF, (seed >> 32) & 0xFFFFFFFF


def split_cell_index(idx) -> tuple[np.ndarray, np.ndarray]:
    """64-bit flat cell indices -> ``(lo, hi)`` uint32 word arrays.

    Host-side (numpy) split of the two-word cell coordinate; ``hi`` is
    zero everywhere for indices below 2^32, which is exactly the
    condition under which the wide schedule reproduces the narrow one.
    """
    idx = np.asarray(idx, np.int64)
    if idx.size and int(idx.min()) < 0:
        raise ValueError("cell indices must be >= 0")
    return (idx & 0xFFFFFFFF).astype(np.uint32), (idx >> 32).astype(np.uint32)


def derive_wide_keys(xp, k0, k1, hi):
    """Per-cell ``(k0', k1')`` for the two-word cell index.

    Block 0 (``hi == 0``) keeps the run key VERBATIM — so every board
    whose indices fit one word draws the byte-identical narrow stream,
    which is the wide-index KAT contract (tests/test_mc_packed.py).
    Blocks ``hi > 0`` re-key through one extra Threefry evaluation on
    counter ``(hi, WIDE_KEY_TAG)``: each 2^32-cell block owns a derived
    subkey, so the (lo, step) counter space never collides across blocks.
    Same integer ops under numpy and XLA, like every draw here.
    """
    d0, d1 = threefry2x32(xp, k0, k1, hi, xp.uint32(WIDE_KEY_TAG))
    narrow = xp.asarray(hi, dtype=xp.uint32) == xp.uint32(0)
    return (
        xp.where(narrow, xp.uint32(k0), d0),
        xp.where(narrow, xp.uint32(k1), d1),
    )


def cell_uniforms_at(xp, lo, hi, k0, k1, step, substream: int):
    """uint32 draws at explicit two-word cell coordinates ``(hi, lo)``.

    ``hi = None`` selects the narrow schedule outright (a *static*,
    host-side decision — callers know their board's index range at build
    time), skipping the key-derivation hash entirely; an all-zero ``hi``
    array produces the identical stream through the wide machinery.
    """
    c1 = xp.uint32(step) * xp.uint32(NSUB) + xp.uint32(substream)
    if hi is None:
        u, _ = threefry2x32(xp, k0, k1, lo, c1)
        return u
    wk0, wk1 = derive_wide_keys(xp, k0, k1, hi)
    u, _ = threefry2x32(xp, wk0, wk1, lo, c1)
    return u


def cell_uniforms(
    xp, shape: tuple[int, int], k0, k1, step, substream: int, *, origin: int = 0
):
    """uint32[h, w] of i.i.d. draws for every cell at ``step``/``substream``.

    ``k0``/``k1``/``step`` may be traced scalars (per-slot under vmap);
    ``shape``, ``substream`` and ``origin`` are static.  ``origin`` is the
    absolute flat index of element (0, 0) — a shard of a mega-board (or a
    test) addresses the wide two-word index space with it.  Indices that
    fit one word (``origin + h*w <= 2^32``) take the narrow schedule
    verbatim, so every pre-wide trajectory reproduces byte-for-byte; past
    that the two-word split kicks in (``derive_wide_keys``).  The 64-bit
    coordinate arithmetic is done in uint32 pairs — identical numpy/jax
    (JAX runs with x64 disabled).
    """
    h, w = shape
    n = h * w
    origin = int(origin)
    if origin < 0:
        raise ValueError(f"origin must be >= 0, got {origin}")
    if n > MAX_NARROW_CELLS:
        raise ValueError(
            f"cannot materialize draws for {n} cells in one array; "
            f"address a mega-board shard-wise via origin"
        )
    c1 = xp.uint32(step) * xp.uint32(NSUB) + xp.uint32(substream)
    if origin == 0:  # n <= MAX_NARROW_CELLS is guaranteed above
        c0 = xp.arange(n, dtype=xp.uint32).reshape(h, w)
        u, _ = threefry2x32(xp, k0, k1, c0, c1)
        return u
    base_lo = xp.uint32(origin & 0xFFFFFFFF)
    base_hi = xp.uint32((origin >> 32) & 0xFFFFFFFF)
    off = xp.arange(n, dtype=xp.uint32).reshape(h, w)
    lo = base_lo + off  # wraps mod 2^32
    # off < 2^32, so at most one carry: it happened iff the sum wrapped
    hi = base_hi + (lo < base_lo).astype(xp.uint32)
    if origin + n <= MAX_NARROW_CELLS:
        hi = None  # still inside block 0: narrow schedule, statically
    return cell_uniforms_at(xp, lo, hi, k0, k1, step, substream)


def threshold_u32(p: float) -> int:
    """``p`` in [0, 1] -> the uint32 threshold t with P(u < t) ~= p.

    Exact at the ends in the strict-less-than convention: p<=0 -> 0
    (never), p>=1 -> callers must branch (no uint32 t makes ``u < t``
    always true); interior p is within 2^-32 of exact.
    """
    if p <= 0.0:
        return 0
    return min(0xFFFFFFFF, int(float(p) * 4294967296.0))


def seeded_board(
    height: int,
    width: int,
    density: float = 0.5,
    *,
    states: int = 2,
    seed: int = 0,
) -> np.ndarray:
    """A seeded random board from the counter-based stream (int8).

    Replaces the numpy-Generator staging for seeded runs so the board a
    seed names is identical everywhere a seed can be replayed — CLI,
    serve spool, gateway, any host — and is stamped into telemetry as
    the full replay record.  Uses ``SUB_BOARD`` at step 0, so it never
    collides with any simulation draw of the same seed.
    """
    if not 0.0 <= density <= 1.0:
        raise ValueError(f"density must be in [0, 1], got {density}")
    if states < 2:
        raise ValueError(f"states must be >= 2, got {states}")
    k0, k1 = key_halves(seed)
    u = cell_uniforms(np, (height, width), k0, k1, np.uint32(0), SUB_BOARD)
    if density >= 1.0:
        alive = np.ones((height, width), dtype=bool)
    else:
        alive = u < np.uint32(threshold_u32(density))
    if states == 2:
        return alive.astype(np.int8)
    # multi-state: reuse the high-quality word 1 for the state choice so
    # the alive mask and the state draw stay independent
    _, u2 = threefry2x32(
        np,
        k0,
        k1,
        np.arange(height * width, dtype=np.uint32).reshape(height, width),
        np.uint32(1) * np.uint32(NSUB) + np.uint32(SUB_BOARD),
    )
    state = (u2 % np.uint32(states - 1)).astype(np.int8) + np.int8(1)
    return np.where(alive, state, np.int8(0)).astype(np.int8)
