"""Noisy-Life: a deterministic 2-state rule composed with per-cell flips.

The step is ``flip(base_step(board))`` where ``flip`` inverts each cell
independently with probability ``rule.flip_p``, drawn from the counter
stream's ``SUB_NOISE`` substream at the cell's absolute step — so the
noise is as reproducible as the rule, and the deterministic half reuses
the existing stencil executors untouched (a :class:`NoisyRule` carries
its base rule's structural fields, so ``ops.stencil.make_step`` /
``ops.reference.step_np`` apply verbatim).

``flip_p`` is frozen in the rule spec (``noisy:<p>/<base>``), so the
endpoint probabilities specialize at build time: p = 0 compiles to the
bare base step, p = 1 to an exact unconditional inversion — no 2^-32
edge-of-threshold residue at either end.
"""

from __future__ import annotations

import numpy as np

from tpu_life.mc import prng
from tpu_life.models.rules import NoisyRule


def make_noisy_step(xp, rule: NoisyRule):
    """``fn(board, k0, k1, step) -> board`` for numpy or jax.numpy.

    The base step comes from the module-appropriate deterministic
    executor — the two are bit-identical by the repo's core invariant,
    so the composed stochastic step is too.
    """
    if xp is np:
        from tpu_life.ops.reference import step_np

        base = lambda b: step_np(b, rule)
    else:
        from tpu_life.ops.stencil import make_step

        base = make_step(rule)
    p = float(rule.flip_p)
    if p <= 0.0:
        return lambda board, k0, k1, step: base(board)
    h_thr = prng.threshold_u32(p)

    def step(board, k0, k1, step_idx):
        nxt = base(board)
        if p >= 1.0:
            return (1 - nxt).astype(nxt.dtype)
        shape = (nxt.shape[-2], nxt.shape[-1])
        u = prng.cell_uniforms(xp, shape, k0, k1, step_idx, prng.SUB_NOISE)
        flip = u < xp.uint32(h_thr)
        return xp.where(flip, (1 - nxt).astype(nxt.dtype), nxt)

    return step
