"""Bit-sliced Life: 32 cells per uint32 lane, neighbor counts as bitplanes.

The fast path for 2-state radius-1 (life-like) rules — the family the
reference implements (Parallel_Life_MPI.cpp:37-54).  Where the reference
spends ~9 branchy reads per cell (`countNeighbours`, :16-35) and the plain
XLA stencil spends int32 vector adds per cell, this path packs 32 cells into
each uint32 and computes all eight neighbor contributions with bitwise
full-adders — ~1.3 VPU bit-ops per cell per step, and 8x less HBM traffic
(1 bit/cell instead of 1 byte).

Layout: board row of W cells -> ceil(W/32) uint32 words; cell at column
``c = 32*j + b`` is bit ``b`` (LSB-first) of word ``j``.  Horizontal
neighbor access is a 1-bit word shift plus a carry bit from the adjacent
word — the adjacent-word fetch is a lane shift of an array 32x smaller than
the board, which is what makes this fast on TPU where unaligned lane
accesses on the full board are the bottleneck.

Counting (classic bit-slicing, cf. the public "Life in bitplanes" trick):
vertical 3-row sums as (ones, twos) bitplanes via carry-save adders, then a
horizontal 3-column add of those planes giving total-sum bitplanes
b0,b1,b2,b3 (total = center + 8 neighbors, range 0..9).  The rule is then
applied as the Quine-McCluskey-minimized sum-of-products of
``alive'(b0..b3, x)`` (``tpu_life.ops.boolmin``) — a handful of wide AND/OR
products instead of one 4-bit equality mask per birth/survive count.
"""

from __future__ import annotations

import sys
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from tpu_life.models.rules import Rule

WORD = 32
_U1 = np.uint32(1)
_LITTLE = sys.byteorder == "little"


def packed_width(width: int) -> int:
    return -(-width // WORD)


def supports(rule: Rule) -> bool:
    """The bit path covers exactly the reference's rule family."""
    return (
        rule.states == 2
        and rule.radius == 1
        and not rule.include_center
        and rule.neighborhood == "moore"
        and rule.boundary == "clamped"
    )


# --- pack / unpack ------------------------------------------------------------

def pack_np(board: np.ndarray) -> np.ndarray:
    """Host-side pack: int8[H, W] -> uint32[H, ceil(W/32)] (LSB-first).

    Packs *alive* (== 1) bits; any other state would corrupt word sums, so
    it is masked here and rejected earlier by the driver's state validation.

    Uses ``np.packbits`` (C loop) — the byte layout of LSB-first bytes read
    as native little-endian uint32 is exactly the LSB-first word layout.  On
    a big-endian host falls back to the explicit weighted-sum pack.
    """
    h, w = board.shape
    alive = board == 1
    wp = packed_width(w) * WORD
    if wp != w:
        alive = np.pad(alive, ((0, 0), (0, wp - w)))
    if _LITTLE:
        by = np.packbits(alive, axis=1, bitorder="little")
        return np.ascontiguousarray(by).view(np.uint32)
    bits = alive.astype(np.uint32).reshape(h, wp // WORD, WORD)
    weights = (_U1 << np.arange(WORD, dtype=np.uint32)).astype(np.uint32)
    return (bits * weights).sum(axis=-1, dtype=np.uint32)


def unpack_np(packed: np.ndarray, width: int) -> np.ndarray:
    """Host-side unpack: uint32[H, Wp] -> int8[H, width]."""
    h, wp = packed.shape
    if _LITTLE:
        by = np.ascontiguousarray(packed).view(np.uint8)
        bits = np.unpackbits(by, axis=1, bitorder="little")
        return bits[:, :width].astype(np.int8)
    shifts = np.arange(WORD, dtype=np.uint32)
    bits = (packed[:, :, None] >> shifts[None, None, :]) & _U1
    return bits.reshape(h, wp * WORD)[:, :width].astype(np.int8)


def pack(board: jax.Array) -> jax.Array:
    """int8[H, W] -> uint32[H, ceil(W/32)] bitboard of the alive (==1) bits."""
    h, w = board.shape
    board = (board == 1).astype(jnp.uint32)
    wp = packed_width(w) * WORD
    if wp != w:
        board = jnp.pad(board, ((0, 0), (0, wp - w)))
    bits = board.reshape(h, wp // WORD, WORD)
    weights = (_U1 << np.arange(WORD, dtype=np.uint32)).astype(np.uint32)
    return (bits * weights).sum(axis=-1, dtype=jnp.uint32)


def unpack(packed: jax.Array, width: int) -> jax.Array:
    """uint32[H, Wp] bitboard -> int8[H, width]."""
    h, wp = packed.shape
    shifts = jnp.arange(WORD, dtype=jnp.uint32)
    bits = (packed[:, :, None] >> shifts[None, None, :]) & _U1
    return bits.reshape(h, wp * WORD)[:, :width].astype(jnp.int8)


# --- the step -----------------------------------------------------------------

def _hshift_left(x: jax.Array) -> jax.Array:
    """Plane of left neighbors: L[c] = x[c-1]; clamped zero at column 0."""
    carry = jnp.pad(x[:, :-1], ((0, 0), (1, 0)))  # word j-1, zeros at j=0
    return (x << _U1) | (carry >> np.uint32(WORD - 1))


def _hshift_right(x: jax.Array) -> jax.Array:
    """Plane of right neighbors: R[c] = x[c+1]; clamped zero at last column."""
    carry = jnp.pad(x[:, 1:], ((0, 0), (0, 1)))
    return (x >> _U1) | (carry << np.uint32(WORD - 1))


def _vshift(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """(up, down) row-neighbor planes, clamped zero at board edges."""
    zero = jnp.zeros_like(x[:1])
    up = jnp.concatenate([x[1:], zero], axis=0)  # U[r] = x[r+1]
    down = jnp.concatenate([zero, x[:-1]], axis=0)  # D[r] = x[r-1]
    return up, down


def _csa(a, b, c):
    """Carry-save adder: a+b+c -> (sum bit, carry bit)."""
    ab = a ^ b
    return ab ^ c, (a & b) | (ab & c)


def make_total_planes(
    hshift_left: Callable, hshift_right: Callable, vshift: Callable
) -> Callable:
    """Build the bitplane counter over pluggable neighbor-plane shifts.

    The XLA step shifts via pad/concat (below); the Pallas kernel substitutes
    ``pltpu.roll``-based lane shifts with the board-edge carries masked —
    same adder tree, two executors.
    """

    def total_planes(x: jax.Array) -> tuple[jax.Array, ...]:
        """Bitplanes (b0, b1, b2, b3) of total = center + 8 neighbors (0..9)."""
        up, down = vshift(x)
        ones, twos = _csa(up, x, down)  # vertical 3-sum per column, 2-bit
        o_l, o_r = hshift_left(ones), hshift_right(ones)
        t_l, t_r = hshift_left(twos), hshift_right(twos)
        b0, c1 = _csa(o_l, ones, o_r)  # ones-plane horizontal sum
        s1, c2 = _csa(t_l, twos, t_r)  # twos-plane horizontal sum (weight 2)
        b1 = c1 ^ s1  # weight-2 bits
        u2 = c1 & s1  # carry into weight 4
        b2 = c2 ^ u2
        b3 = c2 & u2  # weight 8 (totals 8, 9)
        return b0, b1, b2, b3

    return total_planes


_total_planes = make_total_planes(_hshift_left, _hshift_right, _vshift)


def make_packed_step(
    rule: Rule, total_planes: Callable | None = None
) -> Callable[[jax.Array], jax.Array]:
    """One life-like CA step on a packed bitboard (clamped boundary).

    ``total_planes`` swaps in an alternative bitplane counter (the Pallas
    kernel's roll-based one); default is the XLA pad/concat version.

    The rule itself is applied as the Quine-McCluskey-minimized
    sum-of-products of ``alive'(b0..b3, x)`` (``tpu_life.ops.boolmin``):
    for count-rich rules this replaces one 4-bit equality mask per
    birth/survive value with a handful of wide implicants — e.g. Day &
    Night's 9 masks collapse to a few products — and the exhaustive
    truth-table check in ``rule_sop`` pins the synthesis to the original
    OR-of-equalities semantics.
    """
    if not supports(rule):
        raise ValueError(f"bit-sliced path supports life-like rules only, got {rule}")
    if total_planes is None:
        total_planes = _total_planes
    from tpu_life.ops.boolmin import rule_sop

    sop = rule_sop(rule.birth, rule.survive)

    def step(x: jax.Array) -> jax.Array:
        planes = total_planes(x)
        literals = (*planes, x)  # input bits 0..3 = total planes, bit 4 = x
        inverted = [None] * 5  # lazily-shared complements
        out = None
        for mask, value in sop:
            term = None
            for bit in range(5):
                if not mask & (1 << bit):
                    continue
                if value & (1 << bit):
                    lit = literals[bit]
                else:
                    if inverted[bit] is None:
                        inverted[bit] = ~literals[bit]
                    lit = inverted[bit]
                term = lit if term is None else term & lit
            if term is None:  # (0, 0): constant-true cover
                term = ~jnp.zeros_like(x)
            out = term if out is None else out | term
        return jnp.zeros_like(x) if out is None else out

    return step


def make_masked_packed_step(
    rule: Rule, logical_shape: tuple[int, int]
) -> Callable[..., jax.Array]:
    """Packed step that pins cells outside the logical board dead.

    ``row_offset`` is the global row of packed row 0, ``word_offset`` the
    global packed-word index of word column 0 (both traced inside
    shard_map; ``word_offset`` matters on 2-D meshes where the word axis is
    sharded too).  Column padding bits are masked per the global layout.
    """
    step = make_packed_step(rule)
    lh, lw = logical_shape
    full, rem = divmod(lw, WORD)

    def masked(
        x: jax.Array,
        row_offset: jax.Array | int = 0,
        word_offset: jax.Array | int = 0,
    ) -> jax.Array:
        h, wp = x.shape
        rows = row_offset + jnp.arange(h)
        row_ok = ((rows >= 0) & (rows < lh)).astype(jnp.uint32)[:, None]
        gw = word_offset + jnp.arange(wp)
        cmask = jnp.where(
            gw < full,
            jnp.uint32(0xFFFFFFFF),
            jnp.where(
                (gw == full) & (rem > 0),
                jnp.uint32((1 << rem) - 1),  # == 0 when rem == 0 (branch dead then)
                jnp.uint32(0),
            ),
        )[None, :]
        # negative word indices (left halo beyond the global edge) fall in
        # neither branch above only via gw < full — guard them explicitly
        cmask = jnp.where((gw >= 0)[None, :], cmask, jnp.uint32(0))
        return step(x) & (row_ok * cmask)

    return masked


from functools import partial as _partial


@jax.jit
def live_count_packed(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Live-cell count of a packed bitboard as ``(hi, lo)`` uint32 scalars
    (count = ``(hi << 8) + lo``, combined on host by
    :func:`combine_live_count`).

    On a sharded board this is the SURVEY §5 "live-cell count via sharded
    reduction": each device popcounts and reduces its own shard, XLA inserts
    the cross-device ``psum``, and only two scalars ever reach the host — no
    board gather (contrast a host-side ``np.count_nonzero`` after a full
    gather).  The hi/lo split keeps the count exact where a single uint32 sum
    would wrap (65536² = 2**32 cells) and float32 would round: per-row
    popcounts are ≤ width, and the 8-bit split bounds each half-sum by
    ``H * W / 256`` resp. ``H * 255`` — exact up to 2**40 cells.
    """
    rows = jnp.sum(
        jax.lax.population_count(x).astype(jnp.uint32), axis=1, dtype=jnp.uint32
    )
    hi = jnp.sum(rows >> 8, dtype=jnp.uint32)
    lo = jnp.sum(rows & jnp.uint32(0xFF), dtype=jnp.uint32)
    return hi, lo


@jax.jit
def live_count_cells(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Live-cell (state == 1) count of an int8 board as ``(hi, lo)`` —
    the unpacked-domain twin of :func:`live_count_packed`, same sharded
    reduction shape."""
    rows = jnp.sum((x == 1).astype(jnp.uint32), axis=1, dtype=jnp.uint32)
    hi = jnp.sum(rows >> 8, dtype=jnp.uint32)
    lo = jnp.sum(rows & jnp.uint32(0xFF), dtype=jnp.uint32)
    return hi, lo


def combine_live_count(hi_lo: tuple[jax.Array, jax.Array]) -> int:
    """Host-side combine of the two reduction scalars into an exact int."""
    hi, lo = hi_lo
    return (int(hi) << 8) + int(lo)


@_partial(
    jax.jit,
    static_argnames=("rule", "steps", "logical_shape"),
    donate_argnums=0,
)
def multi_step_packed(
    x: jax.Array,
    *,
    rule: Rule,
    steps: int,
    logical_shape: tuple[int, int],
) -> jax.Array:
    """``steps`` fused bit-sliced CA steps under one jit (packed domain)."""
    masked = make_masked_packed_step(rule, tuple(logical_shape))
    out, _ = jax.lax.scan(lambda b, _: (masked(b), None), x, None, length=steps)
    return out
