"""Bit-sliced Life: 32 cells per uint32 lane, neighbor counts as bitplanes.

The fast path for 2-state radius-1 (life-like) rules — the family the
reference implements (Parallel_Life_MPI.cpp:37-54).  Where the reference
spends ~9 branchy reads per cell (`countNeighbours`, :16-35) and the plain
XLA stencil spends int32 vector adds per cell, this path packs 32 cells into
each uint32 and computes all eight neighbor contributions with bitwise
full-adders — ~1.3 VPU bit-ops per cell per step, and 8x less HBM traffic
(1 bit/cell instead of 1 byte).

Layout: board row of W cells -> ceil(W/32) uint32 words; cell at column
``c = 32*j + b`` is bit ``b`` (LSB-first) of word ``j``.  Horizontal
neighbor access is a 1-bit word shift plus a carry bit from the adjacent
word — the adjacent-word fetch is a lane shift of an array 32x smaller than
the board, which is what makes this fast on TPU where unaligned lane
accesses on the full board are the bottleneck.

Counting (classic bit-slicing, cf. the public "Life in bitplanes" trick):
vertical 3-row sums as (ones, twos) bitplanes via carry-save adders, then a
horizontal 3-column add of those planes giving total-sum bitplanes
b0,b1,b2,b3 (total = center + 8 neighbors, range 0..9).  The rule is then
applied as the Quine-McCluskey-minimized sum-of-products of
``alive'(b0..b3, x)`` (``tpu_life.ops.boolmin``) — a handful of wide AND/OR
products instead of one 4-bit equality mask per birth/survive count.
"""

from __future__ import annotations

import sys
from functools import partial as _partial
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from tpu_life.models.rules import Rule

WORD = 32
_U1 = np.uint32(1)
_LITTLE = sys.byteorder == "little"


def packed_width(width: int) -> int:
    return -(-width // WORD)


def supports_family(rule: Rule) -> bool:
    """Life-like structure (2-state, Moore r=1, no center) — the rule
    family the bitplane adder tree computes, independent of boundary.
    Boundary semantics live in the neighbor-plane shifts plugged into
    :func:`make_total_planes` (clamped default, torus variants below)."""
    return (
        rule.states == 2
        and rule.radius == 1
        and not rule.include_center
        and rule.neighborhood == "moore"
    )


def supports(rule: Rule) -> bool:
    """The bit path covers exactly the reference's rule family."""
    return supports_family(rule) and rule.boundary == "clamped"


def supports_torus(rule: Rule) -> bool:
    """Life-like rules on the torus run packed too (VERDICT r4 item 3):
    wrap carries replace the clamped shifts' zero fill — any width, the
    partial last word included."""
    return supports_family(rule) and rule.boundary == "torus"


# --- pack / unpack ------------------------------------------------------------

def pack_np(board: np.ndarray) -> np.ndarray:
    """Host-side pack: int8[H, W] -> uint32[H, ceil(W/32)] (LSB-first).

    Packs *alive* (== 1) bits; any other state would corrupt word sums, so
    it is masked here and rejected earlier by the driver's state validation.

    Uses ``np.packbits`` (C loop) — the byte layout of LSB-first bytes read
    as native little-endian uint32 is exactly the LSB-first word layout.  On
    a big-endian host falls back to the explicit weighted-sum pack.
    """
    h, w = board.shape
    alive = board == 1
    wp = packed_width(w) * WORD
    if wp != w:
        alive = np.pad(alive, ((0, 0), (0, wp - w)))
    if _LITTLE:
        by = np.packbits(alive, axis=1, bitorder="little")
        return np.ascontiguousarray(by).view(np.uint32)
    bits = alive.astype(np.uint32).reshape(h, wp // WORD, WORD)
    weights = (_U1 << np.arange(WORD, dtype=np.uint32)).astype(np.uint32)
    return (bits * weights).sum(axis=-1, dtype=np.uint32)


def unpack_np(packed: np.ndarray, width: int) -> np.ndarray:
    """Host-side unpack: uint32[H, Wp] -> int8[H, width]."""
    h, wp = packed.shape
    if _LITTLE:
        by = np.ascontiguousarray(packed).view(np.uint8)
        bits = np.unpackbits(by, axis=1, bitorder="little")
        return bits[:, :width].astype(np.int8)
    shifts = np.arange(WORD, dtype=np.uint32)
    bits = (packed[:, :, None] >> shifts[None, None, :]) & _U1
    return bits.reshape(h, wp * WORD)[:, :width].astype(np.int8)


def pack(board: jax.Array) -> jax.Array:
    """int8[H, W] -> uint32[H, ceil(W/32)] bitboard of the alive (==1) bits."""
    h, w = board.shape
    board = (board == 1).astype(jnp.uint32)
    wp = packed_width(w) * WORD
    if wp != w:
        board = jnp.pad(board, ((0, 0), (0, wp - w)))
    bits = board.reshape(h, wp // WORD, WORD)
    weights = (_U1 << np.arange(WORD, dtype=np.uint32)).astype(np.uint32)
    return (bits * weights).sum(axis=-1, dtype=jnp.uint32)


def unpack(packed: jax.Array, width: int) -> jax.Array:
    """uint32[H, Wp] bitboard -> int8[H, width]."""
    h, wp = packed.shape
    shifts = jnp.arange(WORD, dtype=jnp.uint32)
    bits = (packed[:, :, None] >> shifts[None, None, :]) & _U1
    return bits.reshape(h, wp * WORD)[:, :width].astype(jnp.int8)


# --- the step -----------------------------------------------------------------

def _hshift_left(x: jax.Array) -> jax.Array:
    """Plane of left neighbors: L[c] = x[c-1]; clamped zero at column 0."""
    carry = jnp.pad(x[:, :-1], ((0, 0), (1, 0)))  # word j-1, zeros at j=0
    return (x << _U1) | (carry >> np.uint32(WORD - 1))


def _hshift_right(x: jax.Array) -> jax.Array:
    """Plane of right neighbors: R[c] = x[c+1]; clamped zero at last column."""
    carry = jnp.pad(x[:, 1:], ((0, 0), (0, 1)))
    return (x >> _U1) | (carry << np.uint32(WORD - 1))


def _vshift(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """(up, down) row-neighbor planes, clamped zero at board edges."""
    zero = jnp.zeros_like(x[:1])
    up = jnp.concatenate([x[1:], zero], axis=0)  # U[r] = x[r+1]
    down = jnp.concatenate([zero, x[:-1]], axis=0)  # D[r] = x[r-1]
    return up, down


def _csa(a, b, c):
    """Carry-save adder: a+b+c -> (sum bit, carry bit)."""
    ab = a ^ b
    return ab ^ c, (a & b) | (ab & c)


def make_total_planes(
    hshift_left: Callable, hshift_right: Callable, vshift: Callable
) -> Callable:
    """Build the bitplane counter over pluggable neighbor-plane shifts.

    The XLA step shifts via pad/concat (below); the Pallas kernel substitutes
    ``pltpu.roll``-based lane shifts with the board-edge carries masked —
    same adder tree, two executors.
    """

    def total_planes(x: jax.Array) -> tuple[jax.Array, ...]:
        """Bitplanes (b0, b1, b2, b3) of total = center + 8 neighbors (0..9)."""
        up, down = vshift(x)
        ones, twos = _csa(up, x, down)  # vertical 3-sum per column, 2-bit
        o_l, o_r = hshift_left(ones), hshift_right(ones)
        t_l, t_r = hshift_left(twos), hshift_right(twos)
        b0, c1 = _csa(o_l, ones, o_r)  # ones-plane horizontal sum
        s1, c2 = _csa(t_l, twos, t_r)  # twos-plane horizontal sum (weight 2)
        b1 = c1 ^ s1  # weight-2 bits
        u2 = c1 & s1  # carry into weight 4
        b2 = c2 ^ u2
        b3 = c2 & u2  # weight 8 (totals 8, 9)
        return b0, b1, b2, b3

    return total_planes


_total_planes = make_total_planes(_hshift_left, _hshift_right, _vshift)


# --- torus shifts -------------------------------------------------------------

def column_mask(width: int) -> np.ndarray:
    """uint32[ceil(width/32)] with exactly the valid-column bits set."""
    wp = packed_width(width)
    rem = width % WORD
    m = np.full(wp, 0xFFFFFFFF, np.uint32)
    if rem:
        m[-1] = np.uint32((1 << rem) - 1)
    return m


def make_torus_hshifts(width: int) -> tuple[Callable, Callable]:
    """(left, right) neighbor-plane shifts that WRAP at the logical width.

    Same in-word shift + adjacent-word carry as the clamped shifts; the
    wrap replaces the zero fill at the seam with the true opposite-edge
    bit — column W-1 is bit ``rem-1`` of the last word when the width is
    not word-aligned, so the seam carries address that bit explicitly.
    Inputs must carry ZERO padding bits (pack() and the per-step column
    re-mask guarantee it); valid output positions then depend only on
    valid input positions, because everything else in the adder tree is
    positionwise.
    """
    wp = packed_width(width)
    rem = width % WORD
    top = np.uint32((rem or WORD) - 1)  # bit index of column width-1

    def hshift_left_t(x: jax.Array) -> jax.Array:
        """L[c] = x[(c-1) mod width]."""
        if wp == 1:
            wrap = (x >> top) & _U1
            return (x << _U1) | wrap
        carry = jnp.roll(x, 1, axis=1)  # carry[j] = x[j-1]; [0] = x[wp-1]
        if rem:
            # bit rem-1 of the last word must land at bit 31 of the
            # virtual word left of word 0
            carry = carry.at[:, 0].set(x[:, -1] << np.uint32(WORD - rem))
        return (x << _U1) | (carry >> np.uint32(WORD - 1))

    def hshift_right_t(x: jax.Array) -> jax.Array:
        """R[c] = x[(c+1) mod width]."""
        if wp == 1:
            wrap = (x & _U1) << top
            return (x >> _U1) | wrap
        carry = jnp.roll(x, -1, axis=1)  # carry[j] = x[j+1]; [wp-1] = x[0]
        out = (x >> _U1) | (carry << np.uint32(WORD - 1))
        if rem:
            # last word: column width-1 (bit rem-1) receives column 0
            out = out.at[:, -1].set(
                (x[:, -1] >> _U1) | ((x[:, 0] & _U1) << top)
            )
        return out

    return hshift_left_t, hshift_right_t


def _vshift_wrap(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """(up, down) row-neighbor planes on the torus: rows wrap."""
    return jnp.roll(x, -1, axis=0), jnp.roll(x, 1, axis=0)


def make_packed_torus_step(
    rule: Rule, width: int, *, wrap_rows: bool = True
) -> Callable[[jax.Array], jax.Array]:
    """One life-like step on a packed bitboard with TORUS boundary.

    ``wrap_rows=False`` serves the sharded run: vertical neighbors come
    from halo rows the periodic ppermute ring stacked around the shard
    (clamped shifts there — the fringe the zero rows corrupt is cropped
    per block), while columns wrap in place since every 1-D-mesh shard
    holds full board rows — the packed twin of
    ``stencil.make_wrap_cols_step``.  Output padding bits are re-masked
    dead every step so they can never feed the seam carries.
    """
    if not supports_torus(rule):
        raise ValueError(
            f"packed torus path supports life-like torus rules only, got {rule}"
        )
    hl, hr = make_torus_hshifts(width)
    planes = make_total_planes(
        hl, hr, _vshift_wrap if wrap_rows else _vshift
    )
    step = make_packed_step(rule, total_planes=planes)
    cmask = column_mask(width)

    def torus_step(x: jax.Array) -> jax.Array:
        return step(x) & jnp.asarray(cmask)[None, :]

    return torus_step


@_partial(
    jax.jit, static_argnames=("rule", "steps", "width"), donate_argnums=0
)
def multi_step_packed_torus(
    x: jax.Array, *, rule: Rule, steps: int, width: int
) -> jax.Array:
    """``steps`` fused packed torus steps under one jit (single device)."""
    step = make_packed_torus_step(rule, width)
    out, _ = jax.lax.scan(lambda b, _: (step(b), None), x, None, length=steps)
    return out


def make_packed_step(
    rule: Rule, total_planes: Callable | None = None
) -> Callable[[jax.Array], jax.Array]:
    """One life-like CA step on a packed bitboard (clamped boundary).

    ``total_planes`` swaps in an alternative bitplane counter (the Pallas
    kernel's roll-based one); default is the XLA pad/concat version.

    The rule itself is applied as the Quine-McCluskey-minimized
    sum-of-products of ``alive'(b0..b3, x)`` (``tpu_life.ops.boolmin``):
    for count-rich rules this replaces one 4-bit equality mask per
    birth/survive value with a handful of wide implicants — e.g. Day &
    Night's 9 masks collapse to a few products — and the exhaustive
    truth-table check in ``rule_sop`` pins the synthesis to the original
    OR-of-equalities semantics.
    """
    if not supports_family(rule):
        raise ValueError(f"bit-sliced path supports life-like rules only, got {rule}")
    if total_planes is None:
        if rule.boundary != "clamped":
            raise ValueError(
                f"default shifts are clamped; {rule.boundary!r} boundary "
                "needs its own total_planes (make_packed_torus_step)"
            )
        total_planes = _total_planes
    from tpu_life.ops.boolmin import rule_sop

    sop = rule_sop(rule.birth, rule.survive)

    def step(x: jax.Array) -> jax.Array:
        planes = total_planes(x)
        # input bits 0..3 = total planes, bit 4 = x
        return _apply_sop(sop, (*planes, x))

    return step


def _apply_sop(
    sop: tuple[tuple[int, int], ...], literals: tuple[jax.Array, ...]
) -> jax.Array:
    """Evaluate a (mask, value) sum-of-products over literal bitplanes."""
    n = len(literals)
    inverted = [None] * n  # lazily-shared complements
    out = None
    for mask, value in sop:
        term = None
        for bit in range(n):
            if not mask & (1 << bit):
                continue
            if value & (1 << bit):
                lit = literals[bit]
            else:
                if inverted[bit] is None:
                    inverted[bit] = ~literals[bit]
                lit = inverted[bit]
            term = lit if term is None else term & lit
        if term is None:  # (0, 0): constant-true cover
            term = ~jnp.zeros_like(literals[-1])
        out = term if out is None else out | term
    return jnp.zeros_like(literals[-1]) if out is None else out


# --- bit-sliced von Neumann diamond (VERDICT r4 item 4) -----------------------

def supports_diamond(rule: Rule) -> bool:
    """2-state clamped von Neumann rules whose maximum count fits the
    4 count planes the SOP applier uses: ``2r(r+1) (+1 with center) <= 15``
    — i.e. radius <= 2, which covers the benchmarked ``NN`` rule space.
    Larger radii fall back to the int8 stencil scan."""
    if not (
        rule.states == 2
        and rule.neighborhood == "von_neumann"
        and rule.boundary == "clamped"
    ):
        return False
    count_max = 2 * rule.radius * (rule.radius + 1) + (
        1 if rule.include_center else 0
    )
    return count_max <= 15


def _hshift_left_by(x: jax.Array, k: int) -> jax.Array:
    """Plane of k-left neighbors: L[c] = x[c-k], clamped zero; 1 <= k < 32."""
    carry = jnp.pad(x[:, :-1], ((0, 0), (1, 0)))
    return (x << np.uint32(k)) | (carry >> np.uint32(WORD - k))


def _hshift_right_by(x: jax.Array, k: int) -> jax.Array:
    """Plane of k-right neighbors: R[c] = x[c+k], clamped zero; 1 <= k < 32."""
    carry = jnp.pad(x[:, 1:], ((0, 0), (0, 1)))
    return (x >> np.uint32(k)) | (carry << np.uint32(WORD - k))


def _vshift_by(x: jax.Array, dy: int) -> jax.Array:
    """Plane of row neighbors at offset dy: V[r] = x[r+dy], clamped zero."""
    if dy == 0:
        return x
    zeros = jnp.zeros_like(x[: abs(dy)])
    if dy > 0:
        return jnp.concatenate([x[dy:], zeros], axis=0)
    return jnp.concatenate([zeros, x[:dy]], axis=0)


def _reduce_planes(
    weighted: list[tuple[jax.Array, int]],
) -> tuple[jax.Array, ...]:
    """CSA-reduce (plane, weight_log2) pairs to sum bitplanes b0, b1, ...

    The generic form of the fixed Moore adder tree in
    :func:`make_total_planes`: full adders compress three same-weight
    planes into one sum + one next-weight carry until every weight holds
    a single plane.  Callers guarantee the total fits the planes they
    consume (checked by ``supports_diamond``).
    """
    levels: dict[int, list[jax.Array]] = {}
    for plane, w in weighted:
        levels.setdefault(w, []).append(plane)
    zero = jnp.zeros_like(weighted[0][0])
    out: list[jax.Array] = []
    w = 0
    while levels:
        cur = levels.pop(w, [])
        while len(cur) >= 3:
            s, carry = _csa(cur.pop(), cur.pop(), cur.pop())
            cur.append(s)
            levels.setdefault(w + 1, []).append(carry)
        if len(cur) == 2:
            a, b = cur
            cur = [a ^ b]
            levels.setdefault(w + 1, []).append(a & b)
        out.append(cur[0] if cur else zero)
        w += 1
    return tuple(out)


def make_packed_diamond_step(
    rule: Rule,
    hshift_left_by: Callable | None = None,
    hshift_right_by: Callable | None = None,
    vshift_by: Callable | None = None,
) -> Callable[[jax.Array], jax.Array]:
    """One 2-state von Neumann step on a packed bitboard (clamped).

    The diamond is a stack of 2r+1 horizontal boxes of half-width
    ``r - |dy|`` — not separable into two full box passes like Moore, but
    in the bit domain each box row is a handful of shifted planes and the
    whole count collapses into one carry-save reduction:

    - the width-(2h+1) box bitplanes of the CENTER row are built once per
      distinct half-width h (CSA as they accumulate),
    - each |dy| > 0 row reuses the box planes for its half-width,
      row-shifted (row shifts commute with the column-wise box),
    - the dy = 0 row contributes its left/right arms directly (center
      joins only for ``M1`` rules).

    ~1.5 bit-ops/cell/step where the int8 stencil scan spends O(r^2)
    byte-wide adds — this is what replaces the "diamonds aren't
    separable" fallback shrug (BASELINE.md r4, von Neumann row).
    Generalizes ``countNeighbours`` (Parallel_Life_MPI.cpp:16-35) to the
    ``NN`` neighborhood the reference never had.

    The three shift callables are pluggable exactly like
    :func:`make_total_planes`'s: defaults are the XLA pad/concat clamped
    shifts; the Pallas tile kernel substitutes ``pltpu.roll``-based lane
    shifts with board-edge carries masked — same reduction, two executors.
    """
    if not supports_diamond(rule):
        raise ValueError(
            f"packed diamond path needs a 2-state clamped von Neumann rule "
            f"with count_max <= 15, got {rule}"
        )
    if hshift_left_by is None:
        hshift_left_by = _hshift_left_by
    if hshift_right_by is None:
        hshift_right_by = _hshift_right_by
    if vshift_by is None:
        vshift_by = _vshift_by
    r = rule.radius
    count_max = 2 * r * (r + 1) + (1 if rule.include_center else 0)
    from tpu_life.ops.boolmin import membership_rule_sop

    nplanes, sop = membership_rule_sop(rule.birth, rule.survive, count_max)

    def step(x: jax.Array) -> jax.Array:
        # box planes of the center row per half-width: box[h] sums columns
        # c-h..c+h of x as (plane, weight) pairs
        box: dict[int, list[tuple[jax.Array, int]]] = {0: [(x, 0)]}
        arms: list[tuple[jax.Array, int]] = []  # L/R shifts, no center
        for k in range(1, r + 1):
            arms.append((hshift_left_by(x, k), 0))
            arms.append((hshift_right_by(x, k), 0))
            if k < r:  # box[r] would be dead: rows use half <= r-1
                box[k] = _collapse(box[k - 1] + arms[-2:])
        weighted: list[tuple[jax.Array, int]] = []
        for dy in range(-r, r + 1):
            half = r - abs(dy)
            if dy == 0:
                weighted.extend(arms)
                if rule.include_center:
                    weighted.append((x, 0))
            else:
                weighted.extend(
                    (vshift_by(p, dy), w) for p, w in box[half]
                )
        planes = _reduce_planes(weighted)
        planes = planes[:nplanes] + (jnp.zeros_like(x),) * max(
            0, nplanes - len(planes)
        )
        return _apply_sop(sop, (*planes, x))

    return step


def _collapse(
    weighted: list[tuple[jax.Array, int]],
) -> list[tuple[jax.Array, int]]:
    """CSA-compress a small (plane, weight) list without finalizing —
    keeps intermediate box sums narrow before they fan out per row."""
    return [
        (p, w)
        for w, p in enumerate(_reduce_planes(weighted))
    ]


@_partial(
    jax.jit, static_argnames=("rule", "steps", "logical_shape"), donate_argnums=0
)
def multi_step_packed_diamond(
    x: jax.Array,
    *,
    rule: Rule,
    steps: int,
    logical_shape: tuple[int, int],
) -> jax.Array:
    """``steps`` fused packed diamond steps under one jit (clamped)."""
    masked = make_masked_packed_step(
        rule, tuple(logical_shape), step=make_packed_diamond_step(rule)
    )
    out, _ = jax.lax.scan(lambda b, _: (masked(b), None), x, None, length=steps)
    return out


def make_masked_packed_step(
    rule: Rule, logical_shape: tuple[int, int], step: Callable | None = None
) -> Callable[..., jax.Array]:
    """Packed step that pins cells outside the logical board dead.

    ``row_offset`` is the global row of packed row 0, ``word_offset`` the
    global packed-word index of word column 0 (both traced inside
    shard_map; ``word_offset`` matters on 2-D meshes where the word axis is
    sharded too).  Column padding bits are masked per the global layout.
    ``step`` substitutes an alternative unmasked packed step; by default
    von Neumann rules get the bit-sliced diamond and everything else the
    life-like Moore step, so every packed caller (sharded XLA scan, gspmd)
    inherits the diamond path with no dispatch of its own.
    """
    if step is None:
        step = (
            make_packed_diamond_step(rule)
            if rule.neighborhood == "von_neumann"
            else make_packed_step(rule)
        )
    lh, lw = logical_shape
    full, rem = divmod(lw, WORD)

    def masked(
        x: jax.Array,
        row_offset: jax.Array | int = 0,
        word_offset: jax.Array | int = 0,
    ) -> jax.Array:
        h, wp = x.shape
        rows = row_offset + jnp.arange(h)
        row_ok = ((rows >= 0) & (rows < lh)).astype(jnp.uint32)[:, None]
        gw = word_offset + jnp.arange(wp)
        cmask = jnp.where(
            gw < full,
            jnp.uint32(0xFFFFFFFF),
            jnp.where(
                (gw == full) & (rem > 0),
                jnp.uint32((1 << rem) - 1),  # == 0 when rem == 0 (branch dead then)
                jnp.uint32(0),
            ),
        )[None, :]
        # negative word indices (left halo beyond the global edge) fall in
        # neither branch above only via gw < full — guard them explicitly
        cmask = jnp.where((gw >= 0)[None, :], cmask, jnp.uint32(0))
        return step(x) & (row_ok * cmask)

    return masked


@jax.jit
def live_count_packed(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Live-cell count of a packed bitboard as ``(hi, lo)`` uint32 scalars
    (count = ``(hi << 8) + lo``, combined on host by
    :func:`combine_live_count`).

    On a sharded board this is the SURVEY §5 "live-cell count via sharded
    reduction": each device popcounts and reduces its own shard, XLA inserts
    the cross-device ``psum``, and only two scalars ever reach the host — no
    board gather (contrast a host-side ``np.count_nonzero`` after a full
    gather).  The hi/lo split keeps the count exact where a single uint32 sum
    would wrap (65536² = 2**32 cells) and float32 would round: per-row
    popcounts are ≤ width, and the 8-bit split bounds each half-sum by
    ``H * W / 256`` resp. ``H * 255`` — exact up to 2**40 cells.
    """
    rows = jnp.sum(
        jax.lax.population_count(x).astype(jnp.uint32), axis=1, dtype=jnp.uint32
    )
    hi = jnp.sum(rows >> 8, dtype=jnp.uint32)
    lo = jnp.sum(rows & jnp.uint32(0xFF), dtype=jnp.uint32)
    return hi, lo


@jax.jit
def live_count_cells(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Live-cell (state == 1) count of an int8 board as ``(hi, lo)`` —
    the unpacked-domain twin of :func:`live_count_packed`, same sharded
    reduction shape."""
    rows = jnp.sum((x == 1).astype(jnp.uint32), axis=1, dtype=jnp.uint32)
    hi = jnp.sum(rows >> 8, dtype=jnp.uint32)
    lo = jnp.sum(rows & jnp.uint32(0xFF), dtype=jnp.uint32)
    return hi, lo


def combine_live_count(hi_lo: tuple[jax.Array, jax.Array]) -> int:
    """Host-side combine of the two reduction scalars into an exact int."""
    hi, lo = hi_lo
    return (int(hi) << 8) + int(lo)


@_partial(
    jax.jit,
    static_argnames=("rule", "steps", "logical_shape"),
    donate_argnums=0,
)
def multi_step_packed(
    x: jax.Array,
    *,
    rule: Rule,
    steps: int,
    logical_shape: tuple[int, int],
) -> jax.Array:
    """``steps`` fused bit-sliced CA steps under one jit (packed domain)."""
    masked = make_masked_packed_step(rule, tuple(logical_shape))
    out, _ = jax.lax.scan(lambda b, _: (masked(b), None), x, None, length=steps)
    return out
