"""Two-level boolean minimization (Quine-McCluskey) for rule synthesis.

The bit-sliced step applies a life-like rule to the count bitplanes as a
5-input boolean function ``alive'(b0, b1, b2, b3, x)`` (4 total-count bits
plus the center's state).  The naive form — an OR of 4-bit equality masks,
one per birth/survive count (``bitlife.make_packed_step``'s original
formulation) — costs ~7 VPU bit-ops per count value, which for count-rich
rules like Day & Night (B3678/S34678: 9 values) dominates the whole step.

This module instead minimizes the function once per rule at trace time:
classic Quine-McCluskey prime-implicant generation plus a greedy set cover,
with two families of don't-cares that make life-like rules minimize
unusually well:

- totals 10..15 cannot occur (center + 8 neighbors <= 9);
- total == 0 with the center alive cannot occur (the total includes it).

The result is a small sum-of-products over the 5 literals; an exhaustive
32-row truth-table check (``verify``) guards every synthesized rule, so a
minimizer bug cannot silently corrupt the step (the cross-executor
bit-identity tests then cover the integration).  The reference's analogue
of all of this is the branchy if/else chain at Parallel_Life_MPI.cpp:37-54.
"""

from __future__ import annotations

from functools import lru_cache

# An implicant is (mask, value): the product term covering exactly the
# inputs i with i & mask == value; bits outside mask are free.


def _combine(a: tuple[int, int], b: tuple[int, int]) -> tuple[int, int] | None:
    """Merge two implicants differing in one cared bit, else None."""
    if a[0] != b[0]:
        return None
    diff = a[1] ^ b[1]
    if diff and not (diff & (diff - 1)):  # exactly one bit differs
        return a[0] & ~diff, a[1] & ~diff
    return None


def prime_implicants(
    minterms: frozenset[int], dontcares: frozenset[int], nbits: int
) -> list[tuple[int, int]]:
    """All prime implicants of the (minterms + dontcares) on-set."""
    full = (1 << nbits) - 1
    current = {(full, m) for m in minterms | dontcares}
    primes: set[tuple[int, int]] = set()
    while current:
        merged: set[tuple[int, int]] = set()
        used: set[tuple[int, int]] = set()
        items = sorted(current)
        for i, a in enumerate(items):
            for b in items[i + 1 :]:
                c = _combine(a, b)
                if c is not None:
                    merged.add(c)
                    used.add(a)
                    used.add(b)
        primes |= current - used
        current = merged
    return sorted(primes)


def _covers(imp: tuple[int, int], m: int) -> bool:
    return (m & imp[0]) == imp[1]


def minimize(
    minterms: set[int] | frozenset[int],
    dontcares: set[int] | frozenset[int] = frozenset(),
    nbits: int = 5,
) -> list[tuple[int, int]]:
    """Minimal-ish SOP cover of ``minterms`` (don't-cares free to use).

    Exact prime-implicant generation + the standard essential-prime step,
    then greedy set cover for the remainder (optimal for the tiny tables
    here in practice; correctness is guaranteed by construction and
    re-checked by :func:`verify`).  Returns implicants as (mask, value).
    """
    minterms = frozenset(minterms)
    dontcares = frozenset(dontcares)
    if not minterms:
        return []
    if minterms | dontcares == frozenset(range(1 << nbits)):
        return [(0, 0)]  # constant true
    primes = prime_implicants(minterms, dontcares, nbits)
    remaining = set(minterms)
    chosen: list[tuple[int, int]] = []
    # essential primes: a minterm covered by exactly one prime
    for m in sorted(minterms):
        cover = [p for p in primes if _covers(p, m)]
        if len(cover) == 1 and cover[0] not in chosen:
            chosen.append(cover[0])
    for p in chosen:
        remaining -= {m for m in remaining if _covers(p, m)}
    while remaining:
        best = max(
            primes,
            key=lambda p: (
                len({m for m in remaining if _covers(p, m)}),
                -bin(p[0]).count("1"),  # prefer wider implicants
            ),
        )
        got = {m for m in remaining if _covers(best, m)}
        if not got:  # cannot happen for a valid prime set; guard anyway
            raise AssertionError("QM cover failed to progress")
        chosen.append(best)
        remaining -= got
    return chosen


def verify(
    implicants: list[tuple[int, int]],
    minterms: set[int] | frozenset[int],
    dontcares: set[int] | frozenset[int],
    nbits: int = 5,
) -> None:
    """Exhaustive truth-table check: the SOP must equal the spec on every
    cared input (don't-cares may fall either way)."""
    for i in range(1 << nbits):
        got = any(_covers(p, i) for p in implicants)
        if i in dontcares:
            continue
        want = i in minterms
        if got != want:
            raise AssertionError(
                f"synthesized SOP wrong at input {i:0{nbits}b}: "
                f"got {got}, want {want}"
            )


@lru_cache(maxsize=None)
def membership_rule_sop(
    birth: frozenset, survive: frozenset, count_max: int
) -> tuple[int, tuple[tuple[int, int], ...]]:
    """(n_count_bits, SOP) for ``alive'(count_b0.., x)`` over RAW counts.

    Unlike :func:`rule_sop` (life-like totals including the center), the
    count here is exactly what the rule's membership sets test — the
    neighborhood sum as ``stencil._counts`` produces it, center excluded
    unless the rule includes it — so this serves any 2-state neighborhood
    whose maximum count fits the planes (the bit-sliced von Neumann
    diamond: ``count_max = 2r(r+1)``).  Input bit layout: bits
    0..n-1 = count planes, bit n = the center cell.  Don't-cares: counts
    above ``count_max``.
    """
    nplanes = max(1, count_max.bit_length())
    nbits = nplanes + 1
    minterms, dontcares = set(), set()
    for x_bit in (0, 1):
        for count in range(1 << nplanes):
            idx = count | (x_bit << nplanes)
            if count > count_max:
                dontcares.add(idx)
            elif (count in birth) if x_bit == 0 else (count in survive):
                minterms.add(idx)
    sop = minimize(minterms, dontcares, nbits=nbits)
    verify(sop, minterms, dontcares, nbits=nbits)
    return nplanes, tuple(sop)


@lru_cache(maxsize=None)
def rule_sop(
    birth: frozenset, survive: frozenset
) -> tuple[tuple[int, int], ...]:
    """Minimal SOP for ``alive'(total_b0..b3, x)`` of a life-like rule.

    Input bit layout: bits 0..3 = the total-count bitplanes (center + 8
    neighbors, 0..9), bit 4 = the center cell.  Don't-cares: totals > 9;
    total == 0 while alive (the total includes the center); and total == 9
    while dead (9 needs all eight neighbors plus the center).
    """
    minterms, dontcares = set(), set()
    for x_bit in (0, 1):
        for total in range(16):
            idx = total | (x_bit << 4)
            if (
                total > 9
                or (x_bit == 1 and total == 0)
                or (x_bit == 0 and total == 9)
            ):
                dontcares.add(idx)
            elif (total in birth) if x_bit == 0 else ((total - 1) in survive):
                minterms.add(idx)
    sop = minimize(minterms, dontcares, nbits=5)
    verify(sop, minterms, dontcares, nbits=5)
    return tuple(sop)
