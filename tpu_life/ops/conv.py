"""MXU-native neighborhoods: the stencil as banded matmuls.

Every rule in the repo used to run the same separable shift-add box sum
(``ops.stencil``) — the one stencil shape that never touches a TPU's
matrix units.  The TPU Ising paper (PAPERS.md, arXiv:1903.11714)
computes neighbor sums as a band-matrix multiply, and the TPU
distributed-linear-algebra paper (arXiv:2112.09017) shows dense small
matmuls are where the hardware's FLOPs live.  This module is that road:

    counts = sum_i  A_i @ board @ B_i

where each ``A_i`` is a static ``(h, h)`` band matrix encoding one
rank-1 kernel factor's *row* profile and ``B_i`` a ``(w, w)`` band
encoding its *column* profile — torus bands wrap, clamped bands
truncate.  The matrices are built once per CompileKey (plain host
numpy) and ride into the compiled program as constants; the per-step
work is ``2·rank`` MXU matmuls instead of ``O(r)``–``O(r^2)`` VPU
shift-adds, so kernel radius becomes a *parameter* instead of a
hard-coded roll pattern.

Factorizations (``kernel_factors``):

- **separable one-hot** kernels (the Moore box) are exactly rank 1:
  one ``(ones, ones)`` pair, integer-exact;
- the **von Neumann diamond** decomposes exactly by rows: one one-hot
  row-shift factor per ``dy``, each paired with a contiguous column
  box — still integer-exact;
- **weighted float32** kernels (the Lenia ring, any ``Rule.kernel``)
  go through a host-side float64 SVD truncated at machine precision,
  falling back to the exact per-row decomposition when the spectrum
  does not compress — reconstruction is verified, never assumed.

Exactness contract: for integer rules every factor entry is 0/1 and
every partial sum is a small integer (bounded by ``(2r+1)^2``), which
float32 represents exactly in ANY summation order — so the matmul path
is **bit-identical** to the roll path, on numpy and under XLA.  Float
(continuous) kernels are exact up to summation order: the matmul and
roll paths agree to ``allclose`` tolerance, and the numpy roll
executor stays the pinned oracle (tests/test_conv.py).

Routing (``resolve_stencil``): ``--stencil roll|matmul|auto`` picks the
counting path per CompileKey.  ``auto`` follows the measured crossover
model — roll below :data:`CROSSOVER_RADIUS`, matmul at or above it,
always matmul for continuous (weighted-kernel) rules — except on the
numpy executors, which stay on the roll path so the ground-truth oracle
never silently moves (the ``mc_packed`` principle).  The autotune tier
carries the same choice as a measured candidate axis
(``TunedConfig.stencil``, docs/AUTOTUNE.md), so ``auto`` under
``--backend tuned`` is measured, not guessed; ``BENCH_conv``
(``bench.py --conv``) captures the crossover itself.
"""

from __future__ import annotations

import os

import numpy as np

from tpu_life.models.rules import Rule

#: The analytic ``auto`` crossover: integer rules at or above this
#: radius take the matmul path.  Bracketed by the ``BENCH_conv`` legs
#: (radii 1/3/5/10) so the model is re-verified per capture — on the
#: CPU reference BLAS wins from mid radii; on MXU hardware the measured
#: ``crossover_radius`` is expected to drop.  Override per deployment
#: with ``TPU_LIFE_STENCIL_CROSSOVER`` or pin ``--stencil`` outright.
CROSSOVER_RADIUS = int(os.environ.get("TPU_LIFE_STENCIL_CROSSOVER", 4))

#: Executor stencil modes (the CLI grammar).
STENCIL_MODES = ("auto", "roll", "matmul")

#: Relative truncation threshold for the SVD factorization of weighted
#: kernels, and the reconstruction bound the factors must meet.
_SVD_RTOL = 1e-6


def validate_stencil(mode: str) -> str:
    if mode not in STENCIL_MODES:
        raise ValueError(
            f"stencil must be one of {'|'.join(STENCIL_MODES)}, got {mode!r}"
        )
    return mode


def resolve_stencil(rule: Rule, mode: str, backend: str = "jax") -> str:
    """The per-CompileKey counting path: ``roll`` or ``matmul``.

    Explicit modes win.  ``auto`` applies the crossover model — matmul
    for continuous (weighted-kernel) rules and for integer rules with
    ``radius >= CROSSOVER_RADIUS`` — but pins the numpy executors to
    roll: they are the ground-truth oracle the matmul path is compared
    against, and an oracle that silently moves with the fast path it
    checks is no oracle (the same rule the packed Metropolis tier
    follows).  Stochastic rules have no counting stencil to route
    (ising sweeps its own checkerboard; the noisy deterministic half
    keeps the roll composition) and always resolve to roll.
    """
    validate_stencil(mode)
    if getattr(rule, "stochastic", False):
        return "roll"
    if mode != "auto":
        return mode
    if backend == "numpy":
        return "roll"
    if getattr(rule, "continuous", False):
        return "matmul"
    return "matmul" if rule.radius >= CROSSOVER_RADIUS else "roll"


# -- kernels ----------------------------------------------------------------
def rule_kernel(rule: Rule) -> np.ndarray:
    """The rule's neighborhood as a float32 ``(2r+1, 2r+1)`` kernel.

    Continuous rules carry their own weighted kernel (``rule.kernel``,
    e.g. the Lenia ring); integer rules get the one-hot Moore box or
    von Neumann diamond, with the center zeroed unless
    ``include_center`` — matching ``neighbor_counts``'s subtraction, so
    the two paths count the identical neighborhood.
    """
    own = getattr(rule, "kernel", None)
    if own is not None:
        return np.asarray(own, np.float32)
    r = rule.radius
    k = 2 * r + 1
    if rule.neighborhood == "von_neumann":
        dy, dx = np.mgrid[-r : r + 1, -r : r + 1]
        kern = (np.abs(dy) + np.abs(dx) <= r).astype(np.float32)
    else:
        kern = np.ones((k, k), np.float32)
    if not rule.include_center:
        kern[r, r] = 0.0
    return kern


def kernel_factors(kernel: np.ndarray) -> list[tuple[np.ndarray, np.ndarray]]:
    """Decompose ``kernel`` into rank-1 ``(u, v)`` pairs with
    ``kernel == sum_i outer(u_i, v_i)`` — verified, never assumed.

    One-hot kernels take exact structural decompositions (a separable
    box is one pair; anything else splits by rows, each row a one-hot
    shift times the row's weights).  Weighted kernels go through a
    float64 SVD truncated at machine precision, with the exact per-row
    split as the fallback when the spectrum does not compress below the
    row count.
    """
    kern = np.asarray(kernel, np.float64)
    if kern.ndim != 2 or kern.shape[0] != kern.shape[1] or kern.shape[0] % 2 != 1:
        raise ValueError(
            f"kernel must be odd-sided square, got shape {kern.shape}"
        )
    scale = float(np.abs(kern).max())
    if scale == 0.0:
        raise ValueError("kernel is all zeros")

    def rows() -> list[tuple[np.ndarray, np.ndarray]]:
        out = []
        for i in range(kern.shape[0]):
            if not np.any(kern[i]):
                continue
            u = np.zeros(kern.shape[0], np.float32)
            u[i] = 1.0
            out.append((u, kern[i].astype(np.float32)))
        return out

    # exact rank-1 (the Moore box, gaussian outer products): u from the
    # heaviest row's support, v the row itself — integer-exact when the
    # kernel is, unlike SVD's sqrt-scaled factors
    i0 = int(np.argmax(np.abs(kern).sum(axis=1)))
    v0 = kern[i0]
    piv = v0[int(np.argmax(np.abs(v0)))]
    if piv != 0.0:
        u0 = kern[:, int(np.argmax(np.abs(v0)))] / piv
        if np.array_equal(np.outer(u0, v0), kern):
            return [(u0.astype(np.float32), v0.astype(np.float32))]
    if np.array_equal(kern, np.rint(kern)):
        # integer kernels carry the bit-identity contract: SVD's
        # sqrt-scaled factors would trade it for a rounding budget —
        # the exact per-row split costs more matmuls, never exactness
        return rows()
    svd_u, svd_s, svd_vt = np.linalg.svd(kern)
    keep = int(np.sum(svd_s > _SVD_RTOL * svd_s[0]))
    if 0 < keep < kern.shape[0]:
        factors = [
            (
                (svd_u[:, i] * svd_s[i]).astype(np.float32),
                svd_vt[i].astype(np.float32),
            )
            for i in range(keep)
        ]
        recon = sum(
            np.outer(u.astype(np.float64), v.astype(np.float64))
            for u, v in factors
        )
        if np.abs(recon - kern).max() <= _SVD_RTOL * scale:
            return factors
    return rows()


def band_matrix(n: int, profile: np.ndarray, boundary: str) -> np.ndarray:
    """The ``(n, n)`` float32 band realizing one 1-D correlation pass:
    ``(M @ x)[i] = sum_d profile[d + r] * x[i + d]``.

    Torus bands wrap (offsets taken mod ``n``, weights of aliased
    offsets summing — the exact periodic correlation even when the
    kernel overhangs the board); clamped bands truncate at the edges
    (the zero-padding semantics of the roll path).
    """
    profile = np.asarray(profile, np.float32)
    r = (len(profile) - 1) // 2
    m = np.zeros((n, n), np.float32)
    idx = np.arange(n)
    for d in range(-r, r + 1):
        w = profile[d + r]
        if w == 0.0:
            continue
        if boundary == "torus":
            m[idx, (idx + d) % n] += w
        else:
            src = idx + d
            ok = (src >= 0) & (src < n)
            m[idx[ok], src[ok]] += w
    return m


def band_operators(
    shape: tuple[int, int], kernel: np.ndarray, boundary: str
) -> list[tuple[np.ndarray, np.ndarray]]:
    """The static per-CompileKey operator pairs: ``(A_i, B_i)`` float32
    arrays with ``conv(X) = sum_i A_i @ X @ B_i``.

    ``A_i = band(h, u_i)`` applies the factor's row profile;
    ``B_i = band(w, v_i).T`` its column profile (the transpose turns
    the row-correlation band into the right-multiplying form).
    """
    h, w = int(shape[0]), int(shape[1])
    return [
        (band_matrix(h, u, boundary), band_matrix(w, v, boundary).T)
        for u, v in kernel_factors(kernel)
    ]


def make_conv(xp, shape: tuple[int, int], kernel: np.ndarray, boundary: str):
    """``fn(X_f32) -> f32`` computing the 2-D correlation of ``X`` with
    ``kernel`` as banded matmuls.  ``xp`` is numpy or jax.numpy; under
    jnp the operators become constants of the compiled program, so XLA
    schedules them straight onto the MXU."""
    # keep the operators as HOST numpy arrays and lift them per call:
    # ``xp.asarray`` inside a traced context mints that trace's own
    # constant, so a cached conv may serve many separately-traced
    # programs (the sharded halo scan compiles one per block depth)
    # without leaking one trace's constants into another
    ops = band_operators(shape, kernel, boundary)

    def conv(x):
        out = None
        for a, b in ops:
            t = xp.matmul(xp.matmul(xp.asarray(a), x), xp.asarray(b))
            out = t if out is None else out + t
        return out

    return conv


def make_counts_matmul(xp, rule: Rule, shape: tuple[int, int]):
    """``fn(board) -> int32 counts`` — the matmul twin of
    ``stencil.neighbor_counts`` / ``reference.neighbor_counts_np``.

    Live cells lift to float32, the banded correlation runs on the MXU,
    and the result lowers back to int32.  Every value along the way is
    a small integer exactly representable in float32, so the lowering
    is exact and the counts are bit-identical to the roll path.

    Center handling mirrors the roll path: the correlation runs with
    the center cell INCLUDED — the full Moore box is exactly rank 1
    (one matmul pair), where the punctured box is rank 2 — and the
    center is subtracted afterwards when the rule excludes it.
    """
    kern = rule_kernel(rule)
    subtract_center = False
    if not getattr(rule, "continuous", False) and not rule.include_center:
        kern = kern.copy()
        kern[rule.radius, rule.radius] += 1.0
        subtract_center = True
    conv = make_conv(xp, shape, kern, rule.boundary)

    def counts(board):
        alive = (board == 1).astype(xp.float32)
        c = conv(alive).astype(xp.int32)
        if subtract_center:
            c = c - alive.astype(xp.int32)
        return c

    return counts


def neighbor_counts_matmul_np(board: np.ndarray, rule: Rule) -> np.ndarray:
    """One-shot numpy matmul counts (tests and oracles; the executors
    build :func:`make_counts_matmul` once per key instead)."""
    return make_counts_matmul(np, rule, board.shape)(board)
