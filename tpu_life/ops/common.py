"""Shared helpers for the op executors."""

from __future__ import annotations


def contiguous_ranges(values) -> list[tuple[int, int]]:
    """Collapse a set of ints into sorted inclusive ranges.

    ``{2, 3, 5, 6, 7}`` -> ``[(2, 3), (5, 7)]``.  Rule masks compile to one
    ``(lo <= c) & (c <= hi)`` pair per range — Larger-than-Life interval rules
    (e.g. ``S34..58``) cost exactly two vector compares.
    """
    vs = sorted(values)
    if not vs:
        return []
    out = []
    lo = prev = vs[0]
    for v in vs[1:]:
        if v == prev + 1:
            prev = v
            continue
        out.append((lo, prev))
        lo = prev = v
    out.append((lo, prev))
    return out
