from tpu_life.ops.reference import neighbor_counts_np, step_np
from tpu_life.ops.stencil import make_step, neighbor_counts, validity_mask

__all__ = [
    "neighbor_counts_np",
    "step_np",
    "make_step",
    "neighbor_counts",
    "validity_mask",
]
