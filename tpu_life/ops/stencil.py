"""The XLA stencil op: one CA step as a fused, branch-free jaxpr.

This is what the reference's entire compute hot loop
(``updateGrid`` + ``countNeighbours``, Parallel_Life_MPI.cpp:16-54)
collapses into on TPU:

- the 8-neighbor count becomes a *separable* box sum — (2r+1) static row
  shifts then (2r+1) static column shifts over a zero-padded array.  Static
  slices of a pad are exactly what XLA fuses into a single VPU loop; zero
  padding *is* the reference's clamped non-periodic boundary
  (Parallel_Life_MPI.cpp:21-27).
- the rule becomes compare/select chains generated from the static
  birth/survive sets (see ``tpu_life.models.rules``): no gathers, no
  data-dependent control flow, nothing XLA can't fuse into the same loop.

All intermediates are int32 (VPU-native lane width; exact for counts up to
(2r+1)^2); the board itself stays int8 in HBM, so the op is one int8 read +
one int8 write per cell per step.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp

from tpu_life.models.rules import Rule
from tpu_life.ops.common import contiguous_ranges


def neighbor_counts(
    board: jax.Array,
    radius: int = 1,
    include_center: bool = False,
    neighborhood: str = "moore",
    boundary: str = "clamped",
) -> jax.Array:
    """int32 live-neighbor counts; dead outside the array (clamped) or
    periodic (torus).

    The boundary is just the padding mode — zeros vs wrap — feeding the
    same counting body: Moore as two separable shift passes, the von
    Neumann diamond as unrolled O(r^2) shifted-slice adds; static Python
    loops over XLA slices, fully fused under jit.  Torus counting assumes
    the array IS the logical board (no physical padding); callers keep
    torus boards unpadded.
    """
    alive = (board == 1).astype(jnp.int32)
    wrap = boundary == "torus"
    return _counts(alive, radius, include_center, neighborhood, wrap, wrap)


def _counts(
    alive: jax.Array,
    radius: int,
    include_center: bool,
    neighborhood: str,
    row_wrap: bool,
    col_wrap: bool,
) -> jax.Array:
    """The shared counting body, with the boundary expressed per axis as a
    padding mode.  The mixed case (rows clamped, columns wrapped) is the
    sharded torus's per-shard substep: row neighbors arrive as real halo
    rows stacked by the exchange, column neighbors wrap in place."""
    h, w = alive.shape
    padded = jnp.pad(
        alive, ((radius, radius), (0, 0)),
        mode="wrap" if row_wrap else "constant",
    )
    padded = jnp.pad(
        padded, ((0, 0), (radius, radius)),
        mode="wrap" if col_wrap else "constant",
    )
    if neighborhood == "von_neumann":
        counts = None
        for dy in range(-radius, radius + 1):
            half = radius - abs(dy)
            row = padded[radius + dy : radius + dy + h, :]
            for dx in range(-half, half + 1):
                c = row[:, radius + dx : radius + dx + w]
                counts = c if counts is None else counts + c
    else:
        k = 2 * radius + 1
        rows = padded[0:h, :]
        for dy in range(1, k):
            rows = rows + padded[dy : dy + h, :]
        counts = rows[:, 0:w]
        for dx in range(1, k):
            counts = counts + rows[:, dx : dx + w]
    if not include_center:
        counts = counts - alive
    return counts


def make_wrap_cols_step(rule: Rule) -> Callable[[jax.Array], jax.Array]:
    """Per-shard substep for the SHARDED torus: columns wrap in place
    (each 1-D-mesh shard holds full board rows, so the east-west seam is
    local), while rows see zero padding — the real north-south neighbors
    arrive as halo rows stacked around the shard by the periodic exchange,
    and the fringe the zero rows corrupt is discarded per block."""

    def step(board: jax.Array) -> jax.Array:
        alive = (board == 1).astype(jnp.int32)
        counts = _counts(
            alive,
            rule.radius,
            rule.include_center,
            rule.neighborhood,
            row_wrap=False,
            col_wrap=True,
        )
        return apply_rule(board, counts, rule)

    return step


def _membership(counts: jax.Array, values: frozenset) -> jax.Array:
    """Branch-free ``counts in values`` as fused range compares."""
    m = jnp.zeros(counts.shape, dtype=jnp.bool_)
    for lo, hi in contiguous_ranges(values):
        if lo == hi:
            m = m | (counts == lo)
        else:
            m = m | ((counts >= lo) & (counts <= hi))
    return m


def apply_rule(board: jax.Array, counts: jax.Array, rule: Rule) -> jax.Array:
    """Next state from (state, count) — the LUT as compare/selects.

    Generic over ``board.dtype``: the XLA path runs it on int8 (HBM-resident
    boards), the Pallas kernel on int32 (keeping every select operand in the
    VPU-native 32-bit tile layout — Mosaic rejects selects that mix int8- and
    int32-derived mask layouts).
    """
    dt = board.dtype
    born = _membership(counts, rule.birth)
    survives = _membership(counts, rule.survive)
    one = jnp.asarray(1, dt)
    zero = jnp.asarray(0, dt)
    if rule.states == 2:
        alive = board == 1
        return jnp.where(alive, jnp.where(survives, one, zero),
                         jnp.where(born, one, zero))
    dying_next = jnp.where(
        board >= rule.states - 1, zero, (board + one).astype(dt)
    )
    nxt = jnp.where(
        board == 0,
        jnp.where(born, one, zero),
        jnp.where(
            board == 1,
            jnp.where(survives, one, jnp.asarray(2, dt)),
            dying_next,
        ),
    )
    return nxt.astype(dt)


def validity_mask(
    shape: tuple[int, int],
    logical_shape: tuple[int, int],
    row_offset: jax.Array | int = 0,
    col_offset: jax.Array | int = 0,
) -> jax.Array:
    """Bool mask of cells that exist on the *logical* board.

    TPU layouts want the physical array padded (rows to the shard count,
    columns toward the 128-lane width).  Padding cells must stay dead forever
    — a cell outside the logical board that flips alive would leak births
    back across the boundary, violating the reference's clamped-edge
    semantics.  ``row_offset``/``col_offset`` are the global indices of
    physical cell (0, 0) (traced, for use inside shard_map; ``col_offset``
    matters on 2-D meshes where columns are sharded too).
    """
    h, w = shape
    lh, lw = logical_shape
    grow = row_offset + jnp.arange(h)
    gcol = col_offset + jnp.arange(w)
    return ((grow >= 0) & (grow < lh))[:, None] & (
        (gcol >= 0) & (gcol < lw)
    )[None, :]


def make_step(
    rule: Rule,
    stencil: str = "roll",
    shape: tuple[int, int] | None = None,
) -> Callable[[jax.Array], jax.Array]:
    """One full-array CA step — ``int8[h, w] -> int8[h, w]`` for
    discrete rules, ``f32 -> f32`` on the continuous tier.

    ``stencil`` picks the neighborhood executor (docs/RULES.md):
    ``roll`` is the classic shift-add pass; ``matmul`` expresses the
    count as banded matmuls (``ops.conv`` — bit-identical for integer
    rules, the MXU path for large radii and weighted kernels).  The
    matmul operators are shape-static, so that path needs ``shape`` up
    front (engines and runners know it; ``None`` + matmul builds the
    operators lazily on the first call's shape).
    """
    if getattr(rule, "continuous", False):
        from tpu_life.models.lenia import make_lenia_step

        if shape is None:
            # shape-lazy wrapper: build on first call (plain-jit use)
            cache: dict = {}

            def step_cc(board: jax.Array) -> jax.Array:
                fn = cache.get(board.shape)
                if fn is None:
                    fn = make_lenia_step(jnp, rule, board.shape, stencil)
                    cache[board.shape] = fn
                return fn(board)

            return step_cc
        return make_lenia_step(jnp, rule, shape, stencil)
    if stencil == "matmul":
        from tpu_life.ops.conv import make_counts_matmul

        cache = {}

        def counts_for(board):
            fn = cache.get(board.shape)
            if fn is None:
                fn = make_counts_matmul(jnp, rule, board.shape)
                cache[board.shape] = fn
            return fn(board)

        def step_mm(board: jax.Array) -> jax.Array:
            return apply_rule(board, counts_for(board), rule)

        return step_mm

    def step(board: jax.Array) -> jax.Array:
        counts = neighbor_counts(
            board,
            rule.radius,
            rule.include_center,
            rule.neighborhood,
            rule.boundary,
        )
        return apply_rule(board, counts, rule)

    return step


def make_masked_step(
    rule: Rule, logical_shape: tuple[int, int], stencil: str = "roll"
) -> Callable[[jax.Array], jax.Array]:
    """A step that also pins physical padding cells dead (see validity_mask)."""
    if getattr(rule, "continuous", False):
        # continuous boards run unpadded (the runners stage exact
        # shapes); the int8 padding mask below would corrupt a float
        # board silently
        raise ValueError(
            "continuous rules cannot run on padded/masked boards"
        )
    if rule.boundary == "torus":
        # padding/masking would sit between the logical edges the torus
        # glues together; torus boards must run unpadded (exact shape)
        raise ValueError(
            "torus boundary cannot run on padded/masked boards; keep the "
            "board at its exact logical shape"
        )
    step = make_step(rule, stencil)

    def masked(
        board: jax.Array,
        row_offset: jax.Array | int = 0,
        col_offset: jax.Array | int = 0,
    ) -> jax.Array:
        mask = validity_mask(board.shape, logical_shape, row_offset, col_offset)
        return jnp.where(mask, step(board), jnp.int8(0))

    return masked


@partial(
    jax.jit,
    static_argnames=("rule", "steps", "logical_shape", "stencil"),
    donate_argnums=0,
)
def multi_step(
    board: jax.Array,
    *,
    rule: Rule,
    steps: int,
    logical_shape: tuple[int, int] | None = None,
    stencil: str = "roll",
) -> jax.Array:
    """``steps`` fused CA steps under one jit via ``lax.scan``.

    The epoch loop lives on-device — the analogue of the reference's
    update/exchange/barrier loop (Parallel_Life_MPI.cpp:215-221) with the
    barrier dissolved into dataflow.  ``stencil`` routes the
    neighborhood executor (roll shift-adds vs banded matmuls — both
    static args, so each (rule, shape, stencil) compiles once).
    """
    if logical_shape is None or tuple(logical_shape) == tuple(board.shape):
        step = make_step(rule, stencil, tuple(board.shape))
        body = lambda b, _: (step(b), None)
    else:
        masked = make_masked_step(rule, tuple(logical_shape), stencil)
        body = lambda b, _: (masked(b), None)
    out, _ = jax.lax.scan(body, board, None, length=steps)
    return out
