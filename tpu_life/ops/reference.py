"""Pure-NumPy reference executor — the framework's ground truth.

Plays the role the reference's nested-loop kernel plays
(Parallel_Life_MPI.cpp:16-54), but implements the *intended* B3/S23-family
semantics (the shipped binary's unconditional rule-overwrite makes its birth branch dead
code — SURVEY.md §2.2).  Every other executor (XLA stencil, sharded shard_map
step, Pallas kernel) is tested bit-identical against this one.
"""

from __future__ import annotations

import numpy as np

from tpu_life.models.rules import Rule


def neighbor_counts_np(
    board: np.ndarray,
    radius: int = 1,
    include_center: bool = False,
    neighborhood: str = "moore",
    boundary: str = "clamped",
) -> np.ndarray:
    """Live-neighbor counts; dead outside the board (clamped) or periodic
    wraparound (torus).

    The boundary is entirely a *padding mode* — zeros for clamped, wrap for
    torus — feeding one shared counting body.  Moore = the (2r+1)^2 box,
    computed separably: one pass of (2r+1) row shifts, one of (2r+1) column
    shifts — O(r) work per cell instead of the reference's O(r^2) inner
    scan (Parallel_Life_MPI.cpp:19-31).  Von Neumann = the |dx|+|dy| <= r
    diamond; not separable, so the O(r^2) shifted slices are summed
    directly.
    """
    alive = (board == 1).astype(np.int32)
    wrap = boundary == "torus"
    return _counts_np(alive, radius, include_center, neighborhood, wrap, wrap)


def _counts_np(
    alive: np.ndarray,
    radius: int,
    include_center: bool,
    neighborhood: str,
    row_wrap: bool,
    col_wrap: bool,
) -> np.ndarray:
    """The shared counting body with the boundary as a per-axis pad mode —
    the mixed case (rows clamped, columns wrapped) is the per-stripe
    substep of the torus-decomposed backends, where row neighbors arrive
    as real halo rows and the east-west seam wraps in place."""
    h, w = alive.shape
    padded = np.pad(
        alive, ((radius, radius), (0, 0)),
        mode="wrap" if row_wrap else "constant",
    )
    padded = np.pad(
        padded, ((0, 0), (radius, radius)),
        mode="wrap" if col_wrap else "constant",
    )
    counts = np.zeros((h, w), dtype=np.int32)
    if neighborhood == "von_neumann":
        for dy in range(-radius, radius + 1):
            half = radius - abs(dy)
            for dx in range(-half, half + 1):
                counts += padded[
                    radius + dy : radius + dy + h, radius + dx : radius + dx + w
                ]
    else:
        k = 2 * radius + 1
        rows = np.zeros((h, w + 2 * radius), dtype=np.int32)
        for dy in range(k):
            rows += padded[dy : dy + h, :]
        for dx in range(k):
            counts += rows[:, dx : dx + w]
    if not include_center:
        counts -= alive
    return counts


def step_np_wrap_cols(ext: np.ndarray, rule: Rule) -> np.ndarray:
    """One substep on a halo-extended stripe of a torus board: columns
    wrap in place (each stripe holds full board rows), rows see zero
    padding — the real vertical neighbors are the stacked halo rows, and
    the corrupted fringe is trimmed by the caller.  The NumPy twin of the
    sharded backend's ``make_wrap_cols_step``."""
    counts = _counts_np(
        (ext == 1).astype(np.int32),
        rule.radius,
        rule.include_center,
        rule.neighborhood,
        row_wrap=False,
        col_wrap=True,
    )
    return rule.transition_table[ext.astype(np.int64), counts]


def step_np(board: np.ndarray, rule: Rule, stencil: str = "roll") -> np.ndarray:
    """One synchronous CA step via the rule's full transition LUT.

    ``stencil`` routes the counting executor: ``roll`` (the default —
    this module IS the roll oracle) or ``matmul`` (the banded-matmul
    path of ``ops.conv``, bit-identical for integer rules).  The
    continuous tier dispatches to its own float oracle.
    """
    if getattr(rule, "continuous", False):
        from tpu_life.models import lenia

        return lenia.step_np(board, rule, stencil)
    if stencil == "matmul":
        from tpu_life.ops.conv import neighbor_counts_matmul_np

        counts = neighbor_counts_matmul_np(board, rule)
    else:
        counts = neighbor_counts_np(
            board,
            rule.radius,
            rule.include_center,
            rule.neighborhood,
            rule.boundary,
        )
    return rule.transition_table[board.astype(np.int64), counts]


def run_np(
    board: np.ndarray, rule: Rule, steps: int, stencil: str = "roll"
) -> np.ndarray:
    for _ in range(steps):
        board = step_np(board, rule, stencil)
    return board
