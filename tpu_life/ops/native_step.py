"""ctypes binding to the native compute runtime (native/life.cpp).

The native CPU counterpart of the reference's `countNeighbours`/`updateGrid`
hot loop (Parallel_Life_MPI.cpp:16-54): a pthread-parallel sliding-window
box-sum stencil driven by the same transition LUT the XLA and Pallas kernels
index.  Loads ``libtpulife_step.so`` if present (``make -C native``); callers
check :func:`available` and fall back to the NumPy executor when the library
is missing.  ``TPU_LIFE_NATIVE=0`` disables the native path outright.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from pathlib import Path

import numpy as np

from tpu_life.models.rules import Rule

_NATIVE_DIR = Path(__file__).resolve().parent.parent.parent / "native"
_LIB_NAME = "libtpulife_step.so"


def _default_threads() -> int:
    return min(16, os.cpu_count() or 1)


def _load() -> ctypes.CDLL | None:
    if os.environ.get("TPU_LIFE_NATIVE", "1") == "0":
        return None
    candidates = [
        Path(os.environ.get("TPU_LIFE_NATIVE_STEP_LIB", "")),
        _NATIVE_DIR / _LIB_NAME,
    ]
    for p in candidates:
        if p and p.is_file():
            try:
                lib = ctypes.CDLL(str(p))
            except OSError:
                continue
            lib.tl_run.restype = ctypes.c_int
            return lib
    return None


_lib = _load()


def available() -> bool:
    return _lib is not None


def build(force: bool = False) -> bool:
    """Compile the native library in-tree (requires g++); returns success."""
    global _lib
    if os.environ.get("TPU_LIFE_NATIVE", "1") == "0":
        return False  # explicitly disabled — don't compile behind the user's back
    if _lib is not None and not force:
        return True
    try:
        subprocess.run(
            ["make", "-C", str(_NATIVE_DIR), _LIB_NAME],
            check=True,
            capture_output=True,
        )
    except (subprocess.CalledProcessError, FileNotFoundError):
        return False
    _lib = _load()
    return _lib is not None


def run_native(
    board: np.ndarray, rule: Rule, steps: int, *, threads: int | None = None
) -> np.ndarray:
    """Advance ``board`` ``steps`` generations on the native threaded stepper.

    Returns a new array; the input is not modified.
    """
    if _lib is None:
        raise RuntimeError("native step library not loaded (make -C native)")
    out = np.ascontiguousarray(board, dtype=np.int8).copy()
    h, w = out.shape
    lut = np.ascontiguousarray(rule.transition_table, dtype=np.int8)
    rc = _lib.tl_run(
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int8)),
        ctypes.c_long(h),
        ctypes.c_long(w),
        lut.ctypes.data_as(ctypes.POINTER(ctypes.c_int8)),
        ctypes.c_int(rule.states),
        ctypes.c_int(rule.max_count),
        ctypes.c_int(rule.radius),
        ctypes.c_int(1 if rule.include_center else 0),
        ctypes.c_long(steps),
        ctypes.c_int(threads or _default_threads()),
    )
    if rc != 0:
        raise ValueError(f"native step failed: rc={rc}")
    return out
