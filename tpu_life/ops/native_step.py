"""ctypes binding to the native compute runtime (native/life.cpp).

The native CPU counterpart of the reference's `countNeighbours`/`updateGrid`
hot loop (Parallel_Life_MPI.cpp:16-54): a pthread-parallel sliding-window
box-sum stencil driven by the same transition LUT the XLA and Pallas kernels
index.  Loads ``libtpulife_step.so`` if present (``make -C native``); callers
check :func:`available` and fall back to the NumPy executor when the library
is missing.  ``TPU_LIFE_NATIVE=0`` disables the native path outright.
"""

from __future__ import annotations

import ctypes

import numpy as np

from tpu_life.models.rules import Rule
from tpu_life.utils import nativelib

_LIB_NAME = "libtpulife_step.so"


def _load() -> ctypes.CDLL | None:
    return nativelib.load_library(
        _LIB_NAME,
        env_override="TPU_LIFE_NATIVE_STEP_LIB",
        int_functions=["tl_run"],
    )


_lib = _load()


def available() -> bool:
    return _lib is not None


def build(force: bool = False) -> bool:
    """Compile the native library in-tree (requires g++); returns success."""
    global _lib
    if _lib is not None and not force:
        return True
    if not nativelib.build_library(_LIB_NAME):
        return False
    _lib = _load()
    return _lib is not None


def run_native(
    board: np.ndarray, rule: Rule, steps: int, *, threads: int | None = None
) -> np.ndarray:
    """Advance ``board`` ``steps`` generations on the native threaded stepper.

    Returns a new array; the input is not modified.
    """
    if _lib is None:
        raise RuntimeError("native step library not loaded (make -C native)")
    if rule.neighborhood != "moore" or rule.boundary != "clamped":
        # the C stepper's sliding-window box sum is Moore-only and clamped;
        # erroring beats silently computing the wrong semantics
        raise ValueError(
            "native backend supports clamped Moore neighborhoods only; use "
            "--backend numpy/jax for von Neumann or torus rules"
        )
    out = np.array(board, dtype=np.int8, order="C")  # exactly one fresh copy
    h, w = out.shape
    lut = np.ascontiguousarray(rule.transition_table, dtype=np.int8)
    rc = _lib.tl_run(
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int8)),
        ctypes.c_long(h),
        ctypes.c_long(w),
        lut.ctypes.data_as(ctypes.POINTER(ctypes.c_int8)),
        ctypes.c_int(rule.states),
        ctypes.c_int(rule.max_count),
        ctypes.c_int(rule.radius),
        ctypes.c_int(1 if rule.include_center else 0),
        ctypes.c_long(steps),
        ctypes.c_int(threads or nativelib.default_threads()),
    )
    if rc != 0:
        raise ValueError(f"native step failed: rc={rc}")
    return out
