"""Device mesh + sharding construction — the runtime the reference gets from MPI.

``MPI_Init``/``Comm_size``/``Comm_rank`` (Parallel_Life_MPI.cpp:195-197)
become ``jax.distributed.initialize`` + a 1-D ``jax.sharding.Mesh`` whose
axis, named ``"rows"``, carries the stripe decomposition
(README.md:6 "Devide field to stripes").  Rank and size are recovered inside
``shard_map`` via ``lax.axis_index`` — never stored in globals.
"""

from __future__ import annotations

import os

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

ROW_AXIS = "rows"
COL_AXIS = "cols"


_distributed_initialized = False


def init_distributed() -> None:
    """Join a multi-host JAX job if the environment describes one.

    The analogue of ``MPI_Init`` (Parallel_Life_MPI.cpp:195).  Controlled by
    the standard JAX cluster-environment variables; a plain single-process
    run is a no-op so the same entry point serves laptop and pod.  Idempotent
    — ``jax.distributed.initialize`` is not reentrant, and the driver calls
    this on every ``run()``.
    """
    global _distributed_initialized
    if _distributed_initialized or getattr(
        jax.distributed, "is_initialized", lambda: False
    )():
        return
    addr = os.environ.get("JAX_COORDINATOR_ADDRESS") or os.environ.get(
        "COORDINATOR_ADDRESS"
    )
    if addr:
        kwargs = {}
        num = os.environ.get("JAX_NUM_PROCESSES")
        if num is not None:
            # explicit process spec (the mpiexec -n analogue): launchers that
            # aren't a recognized cluster environment pass the coordinate
            # triple directly instead of relying on auto-detection
            pid = os.environ.get("JAX_PROCESS_ID")
            if pid is None:
                raise RuntimeError(
                    "incomplete distributed process spec: JAX_NUM_PROCESSES "
                    "is set but JAX_PROCESS_ID is not (the explicit triple is "
                    "JAX_COORDINATOR_ADDRESS + JAX_NUM_PROCESSES + "
                    "JAX_PROCESS_ID)"
                )
            kwargs = dict(
                coordinator_address=addr,
                num_processes=int(num),
                process_id=int(pid),
            )
        jax.distributed.initialize(**kwargs)
        _distributed_initialized = True


def make_mesh(num_devices: int | None = None, *, devices=None, axis: str = ROW_AXIS) -> Mesh:
    """A 1-D mesh over ``num_devices`` (default: all) devices.

    On a TPU slice the device order follows ICI topology, so the
    nearest-neighbor ``ppermute`` ring in ``tpu_life.parallel.halo`` rides
    ICI links, not DCN.
    """
    if devices is None:
        devices = jax.devices()
    if num_devices is not None:
        if num_devices > len(devices):
            raise ValueError(
                f"requested {num_devices} devices, only {len(devices)} available"
            )
        devices = devices[:num_devices]
    return Mesh(np.asarray(devices), (axis,))


def make_mesh_2d(
    shape: tuple[int, int],
    *,
    devices=None,
    axes: tuple[str, str] = (ROW_AXIS, COL_AXIS),
) -> Mesh:
    """A 2-D (rows × cols) mesh — block decomposition beyond the reference.

    The reference only stripes rows (README.md:6).  A 2-D mesh shards both
    board axes, so per-step halo traffic scales with the shard *perimeter*
    instead of its full width — the right trade on large meshes where a
    stripe would be thin.  Corner cells ride transitively: rows are
    exchanged first, then the row-extended edge columns.
    """
    r, c = shape
    if devices is None:
        devices = jax.devices()
    if r * c > len(devices):
        raise ValueError(
            f"mesh shape {shape} needs {r * c} devices, only {len(devices)} available"
        )
    return Mesh(np.asarray(devices[: r * c]).reshape(r, c), axes)


def board_sharding(mesh: Mesh, axis: str = ROW_AXIS) -> NamedSharding:
    """Stripe sharding: rows split across the mesh, columns replicated.

    The TPU-native form of the reference's block-row decomposition
    (Parallel_Life_MPI.cpp:70-81).  On a 2-D mesh (see :func:`make_mesh_2d`)
    columns shard over the second axis as well.
    """
    if COL_AXIS in mesh.shape:
        return NamedSharding(mesh, P(ROW_AXIS, COL_AXIS))
    return NamedSharding(mesh, P(axis, None))
