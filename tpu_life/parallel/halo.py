"""Halo exchange over the device mesh — the reference's L2, TPU-native.

The reference swaps one ghost row per direction per epoch with paired
``MPI_Sendrecv`` plus a global barrier
(Parallel_Life_MPI.cpp:104-145, :220).  Here the exchange is two
non-periodic ``lax.ppermute`` shifts inside ``shard_map`` — and because
``ppermute`` zero-fills destinations with no source, the mesh-edge shards
get exactly the clamped dead boundary the reference implements with index
checks (Parallel_Life_MPI.cpp:21-27).  No barrier exists anywhere: program
order inside the jitted step is the synchronization.

Two structural upgrades over the reference:

- **Deep halos / communication blocking**: exchanging a halo of depth
  ``r * k`` allows ``k`` full CA steps per exchange (the same
  compute/communication trade ring attention makes when it blocks a
  sequence axis).  ``block_steps=k`` amortizes one ppermute pair over k
  steps; edge validity is re-masked every step so out-of-board cells can
  never be born (see ``validity_mask``).
- **The whole epoch loop lives in one compiled region**: a ``lax.scan``
  over blocks *inside* ``shard_map``, so halos never leave VMEM/HBM and no
  host round-trip happens between steps (contrast the per-epoch host
  control flow at Parallel_Life_MPI.cpp:215-221).
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.6 exposes shard_map at top level
    from jax import shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

from tpu_life.models.rules import Rule
from tpu_life.ops import bitlife
from tpu_life.ops.stencil import make_masked_step, make_step, make_wrap_cols_step
from tpu_life.parallel.mesh import COL_AXIS, ROW_AXIS


def halo_depth(rule: Rule, block_steps: int) -> int:
    """Rows of halo needed to advance ``block_steps`` steps locally."""
    return rule.radius * block_steps


def make_sharded_run(
    rule: Rule,
    mesh: Mesh,
    logical_shape: tuple[int, int],
    *,
    axis: str = ROW_AXIS,
    block_steps: int = 1,
    packed: bool = False,
    stencil: str = "roll",
) -> Callable[[jax.Array, int], jax.Array]:
    """Build ``run(board, num_blocks)``: ``num_blocks * block_steps`` CA steps
    on a row-sharded board, halos exchanged once per block.

    ``board`` is the *physical* (padded) global array sharded
    ``P(axis, None)``; ``logical_shape`` is the real board extent, used to
    pin padding/out-of-board cells dead.  With ``packed=True`` the board is
    a uint32 bitboard (``tpu_life.ops.bitlife``) — the ring exchange is
    identical, just 32x narrower.  ``stencil`` routes the per-shard
    counting path (roll shift-adds vs banded matmuls, docs/RULES.md) —
    the halo exchange is identical either way.
    """
    # one builder, one halo/scan/jit scaffold: the 1-D stripe is the
    # n_cols=1 special case of the 2-D block decomposition
    return make_sharded_run_2d(
        rule,
        mesh,
        logical_shape,
        row_axis=axis,
        block_steps=block_steps,
        packed=packed,
        stencil=stencil,
    )


def make_sharded_run_torus(
    rule: Rule,
    mesh: Mesh,
    logical_shape: tuple[int, int],
    *,
    row_axis: str = ROW_AXIS,
    block_steps: int = 1,
    packed: bool = False,
) -> Callable[[jax.Array, int], jax.Array]:
    """Torus variant of the 1-D stripe run: the ``ppermute`` ring is
    CLOSED — the wrap pair the clamped exchange deliberately omits delivers
    the last shard's bottom rows as the first shard's top halo and vice
    versa — and the per-shard substep wraps columns in place
    (``make_wrap_cols_step`` / its packed twin).  The reference's MPI
    analogue would be ``MPI_Cart_create`` with ``periods=1``, the option
    its rank±1 topology never takes (Parallel_Life_MPI.cpp:105-107,121-123).

    The board must be EXACT in rows: no padding rows may sit inside the
    glued seam.  With ``packed=True`` (life-like rules, VERDICT r4 item 3)
    the board is the uint32 bitboard — the ring exchange is identical,
    32x narrower — and the last word MAY carry padding bits: the packed
    substep re-masks them dead each step and its seam carries explicitly
    address bit ``width-1``, so the column wrap is exact at any width.
    """
    n_r = mesh.shape[row_axis]
    pad = halo_depth(rule, block_steps)
    lh, lw = logical_shape
    if packed:
        step = bitlife.make_packed_torus_step(rule, lw, wrap_rows=False)
        phys_shape = (lh, bitlife.packed_width(lw))
    else:
        step = make_wrap_cols_step(rule)
        phys_shape = (lh, lw)
    fwd = [(i, (i + 1) % n_r) for i in range(n_r)]
    bwd = [((i + 1) % n_r, i) for i in range(n_r)]

    def local_block(chunk: jax.Array) -> jax.Array:
        hl, _ = chunk.shape
        if n_r > 1:
            top = lax.ppermute(chunk[hl - pad :, :], row_axis, fwd)
            bot = lax.ppermute(chunk[:pad, :], row_axis, bwd)
        else:
            # one shard: its own edges ARE the wrap neighbors
            top = chunk[hl - pad :, :]
            bot = chunk[:pad, :]
        ext = jnp.concatenate([top, chunk, bot], axis=0)
        for _ in range(block_steps):
            ext = step(ext)
        return ext[pad : pad + hl, :]

    def local_run(chunk: jax.Array, num_blocks: int) -> jax.Array:
        if chunk.shape[0] < pad:
            raise ValueError(
                f"shard height {chunk.shape[0]} smaller than halo depth "
                f"{pad}; lower block_steps or use a smaller mesh"
            )
        out, _ = lax.scan(
            lambda c, _: (local_block(c), None), chunk, None, length=num_blocks
        )
        return out

    spec = P(row_axis, None)

    @partial(jax.jit, static_argnames="num_blocks", donate_argnums=0)
    def run(board: jax.Array, num_blocks: int) -> jax.Array:
        if board.shape != phys_shape:
            # exactness IS the correctness contract here: any padding
            # rows/words beyond the canonical physical shape would sit
            # inside the glued seam (trace-time check — shapes are
            # static under jit)
            raise ValueError(
                f"torus board shape {board.shape} != physical "
                f"{phys_shape}; the torus run takes the exact unpadded "
                f"board (packed width = ceil(width/32) words when packed)"
            )
        return shard_map(
            partial(local_run, num_blocks=num_blocks),
            mesh=mesh,
            in_specs=spec,
            out_specs=spec,
        )(board)

    return run


def make_sharded_run_torus_2d(
    rule: Rule,
    mesh: Mesh,
    logical_shape: tuple[int, int],
    *,
    row_axis: str = ROW_AXIS,
    col_axis: str = COL_AXIS,
    block_steps: int = 1,
    packed: bool = True,
    stencil: str = "roll",
) -> Callable[[jax.Array, int], jax.Array]:
    """2-D block decomposition of the TORUS.

    The elegant property of the fully-sharded torus: with the board
    exactly divisible along both axes (rows by the row mesh, packed WORDS
    by the column mesh, and the width word-aligned so no partial word can
    sit on a seam), every seam — the board's outer edges included — is an
    interior seam of a closed ``ppermute`` ring.  The local substep then
    needs NO wrap logic at all: both rings deliver the true neighbors
    (corners ride the row-extended column exchange transitively, as in
    the clamped 2-D run), the plain clamped-shift packed step runs on the
    halo-extended chunk, and the zero fill at the ext edges only corrupts
    the fringe each block crops.  Contrast the 1-D torus, which wraps
    columns in-shard because each stripe holds full rows.

    A thin wrapper over the one 2-D scaffold (``make_sharded_run_2d``
    with ``torus=True``); callers guarantee exact divisibility
    (``_prepare_torus_2d`` raises the precise reason otherwise).  With
    ``packed=False`` the same construction runs multistate / wide-radius
    torus rules on the int8 board — the seam constraint is then plain
    cell divisibility, no word alignment.
    """
    lh, lw = logical_shape
    if packed and lw % bitlife.WORD:
        raise ValueError(
            f"2-D torus needs a word-aligned width (got {lw}); a partial "
            f"last word would sit inside the glued seam"
        )
    return make_sharded_run_2d(
        rule,
        mesh,
        logical_shape,
        row_axis=row_axis,
        col_axis=col_axis,
        block_steps=block_steps,
        packed=packed,
        torus=True,
        stencil=stencil,
    )


def get_clamped_twin(rule: Rule):
    """The same rule with a clamped boundary — the 2-D torus's local
    substep is boundary-free (halos carry the wrap), so it runs the plain
    clamped packed step."""
    from dataclasses import replace

    return replace(rule, boundary="clamped")


def make_sharded_run_2d(
    rule: Rule,
    mesh: Mesh,
    logical_shape: tuple[int, int],
    *,
    row_axis: str = ROW_AXIS,
    col_axis: str = COL_AXIS,
    block_steps: int = 1,
    packed: bool = False,
    torus: bool = False,
    stencil: str = "roll",
) -> Callable[[jax.Array, int], jax.Array]:
    """2-D block decomposition: halos exchanged along BOTH mesh axes.

    Beyond the reference (which only stripes rows): per-block halo traffic
    scales with the shard perimeter, the right shape for large meshes.
    Corners need no dedicated diagonal sends — rows are exchanged first,
    then the *row-extended* edge columns, so the corner cells ride the
    column exchange transitively (two hops, same as a 2-D MPI Cart shift
    would do, but expressed as two ``ppermute`` pairs XLA pipelines over
    ICI).  With ``packed=True`` the board is the uint32 bitboard
    (``tpu_life.ops.bitlife``): shard boundaries sit on word boundaries and
    the column halo is ``ceil(depth/32)`` whole words — 32x less ICI
    traffic, same exchange shape.  On a mesh without a ``col_axis`` (or
    with one shard along it) the column phase drops out and this *is* the
    1-D stripe run.

    ``torus=True`` (``make_sharded_run_torus_2d`` is the checked entry
    point): the same scaffold with the rings CLOSED on both axes and NO
    validity masking — every halo carries true wrapped neighbors
    (one-shard axes take their own edges), so the clamped twin of the
    rule runs unmasked on the ext chunk (packed bit step or plain int8
    stencil step alike) and the only invalid cells are the ext-edge
    fringe each block crops.  Callers guarantee exact divisibility along
    both axes (word-granular when packed, cell-granular for int8).
    """
    n_r = mesh.shape[row_axis]
    split_cols = col_axis in mesh.shape and mesh.shape[col_axis] > 1
    n_c = mesh.shape[col_axis] if split_cols else 1
    pad = halo_depth(rule, block_steps)
    # column-axis halo in *storage units*: cells for int8, whole words for
    # the packed bitboard (word carries propagate 1 bit/step, so ceil(pad/32)
    # words always hold the pad cells the block needs)
    pad_c = -(-pad // bitlife.WORD) if packed else pad
    if torus:
        # boundary-free local substep: the closed rings deliver every
        # neighbor, so the CLAMPED twin of the rule runs unmasked (packed
        # bit step or plain int8 stencil step alike)
        twin = get_clamped_twin(rule)
        # the local substep sees only the halo-extended chunk, so the
        # counting path is free to be the shift-add roll OR the banded
        # matmul (both shape-lazy: the ext chunk shape is static under
        # the jit trace) — PR 15's known limit discharged.  Continuous
        # rules ride the same seam: make_step routes the clamped twin to
        # the float Lenia step, whose truncated edge contributions only
        # corrupt the fringe each block crops.
        plain_step = (
            bitlife.make_packed_step(twin)
            if packed
            else make_step(twin, stencil)
        )
        masked_step = lambda ext, ro, co: plain_step(ext)  # noqa: E731
        fwd_r = [(i, (i + 1) % n_r) for i in range(n_r)]
        bwd_r = [((i + 1) % n_r, i) for i in range(n_r)]
        fwd_c = [(i, (i + 1) % n_c) for i in range(n_c)]
        bwd_c = [((i + 1) % n_c, i) for i in range(n_c)]
    else:
        masked_step = (
            bitlife.make_masked_packed_step(rule, tuple(logical_shape))
            if packed
            else make_masked_step(rule, tuple(logical_shape), stencil)
        )
        fwd_r = [(i, i + 1) for i in range(n_r - 1)]
        bwd_r = [(i + 1, i) for i in range(n_r - 1)]
        fwd_c = [(i, i + 1) for i in range(n_c - 1)]
        bwd_c = [(i + 1, i) for i in range(n_c - 1)]

    def local_block(chunk: jax.Array) -> jax.Array:
        hl, wl = chunk.shape
        ri = lax.axis_index(row_axis)
        if torus and n_r == 1:
            # one shard along the rows: its own edges ARE the wrap pair
            top = chunk[hl - pad :, :]
            bot = chunk[:pad, :]
        else:
            # clamped: ppermute zero-fills at the mesh ends = the dead
            # boundary; torus: the ring is closed, every shard has both
            top = lax.ppermute(chunk[hl - pad :, :], row_axis, fwd_r)
            bot = lax.ppermute(chunk[:pad, :], row_axis, bwd_r)
        ext = jnp.concatenate([top, chunk, bot], axis=0)
        row_offset = ri * hl - pad
        if split_cols:
            ci = lax.axis_index(col_axis)
            left = lax.ppermute(ext[:, wl - pad_c :], col_axis, fwd_c)
            right = lax.ppermute(ext[:, :pad_c], col_axis, bwd_c)
            ext = jnp.concatenate([left, ext, right], axis=1)
            col_offset = ci * wl - pad_c
        elif torus:
            # one shard along the columns: self-wrap the word columns
            left = ext[:, wl - pad_c :]
            right = ext[:, :pad_c]
            ext = jnp.concatenate([left, ext, right], axis=1)
            col_offset = -pad_c
        else:
            col_offset = 0
        for _ in range(block_steps):
            ext = masked_step(ext, row_offset, col_offset)
        col0 = pad_c if (split_cols or torus) else 0
        return ext[pad : pad + hl, col0 : col0 + wl]

    def local_run(chunk: jax.Array, num_blocks: int) -> jax.Array:
        if chunk.shape[0] < pad or (
            (split_cols or torus) and chunk.shape[1] < pad_c
        ):
            raise ValueError(
                f"shard {chunk.shape} smaller than halo depth "
                f"{(pad, pad_c)}; lower block_steps or use a smaller mesh"
            )
        out, _ = lax.scan(
            lambda c, _: (local_block(c), None), chunk, None, length=num_blocks
        )
        return out

    spec = P(row_axis, col_axis if split_cols else None)

    @partial(jax.jit, static_argnames="num_blocks", donate_argnums=0)
    def run(board: jax.Array, num_blocks: int) -> jax.Array:
        if torus:
            lh, lw = logical_shape
            phys = (lh, bitlife.packed_width(lw) if packed else lw)
            if board.shape != phys:
                # exactness IS the correctness contract: padding anywhere
                # would sit inside the glued seams (trace-time check)
                raise ValueError(
                    f"2-D torus board shape {board.shape} != physical "
                    f"{phys}; the torus run takes the exact unpadded board"
                )
        return shard_map(
            partial(local_run, num_blocks=num_blocks),
            mesh=mesh,
            in_specs=spec,
            out_specs=spec,
        )(board)

    return run
