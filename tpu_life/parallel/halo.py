"""Halo exchange over the device mesh — the reference's L2, TPU-native.

The reference swaps one ghost row per direction per epoch with paired
``MPI_Sendrecv`` plus a global barrier
(Parallel_Life_MPI.cpp:104-145, :220).  Here the exchange is two
non-periodic ``lax.ppermute`` shifts inside ``shard_map`` — and because
``ppermute`` zero-fills destinations with no source, the mesh-edge shards
get exactly the clamped dead boundary the reference implements with index
checks (Parallel_Life_MPI.cpp:21-27).  No barrier exists anywhere: program
order inside the jitted step is the synchronization.

Two structural upgrades over the reference:

- **Deep halos / communication blocking**: exchanging a halo of depth
  ``r * k`` allows ``k`` full CA steps per exchange (the same
  compute/communication trade ring attention makes when it blocks a
  sequence axis).  ``block_steps=k`` amortizes one ppermute pair over k
  steps; edge validity is re-masked every step so out-of-board cells can
  never be born (see ``validity_mask``).
- **The whole epoch loop lives in one compiled region**: a ``lax.scan``
  over blocks *inside* ``shard_map``, so halos never leave VMEM/HBM and no
  host round-trip happens between steps (contrast the per-epoch host
  control flow at Parallel_Life_MPI.cpp:215-221).
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.6 exposes shard_map at top level
    from jax import shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

from tpu_life.models.rules import Rule
from tpu_life.ops import bitlife
from tpu_life.ops.stencil import make_masked_step
from tpu_life.parallel.mesh import ROW_AXIS


def halo_depth(rule: Rule, block_steps: int) -> int:
    """Rows of halo needed to advance ``block_steps`` steps locally."""
    return rule.radius * block_steps


def make_sharded_run(
    rule: Rule,
    mesh: Mesh,
    logical_shape: tuple[int, int],
    *,
    axis: str = ROW_AXIS,
    block_steps: int = 1,
    packed: bool = False,
) -> Callable[[jax.Array, int], jax.Array]:
    """Build ``run(board, num_blocks)``: ``num_blocks * block_steps`` CA steps
    on a row-sharded board, halos exchanged once per block.

    ``board`` is the *physical* (padded) global array sharded
    ``P(axis, None)``; ``logical_shape`` is the real board extent, used to
    pin padding/out-of-board cells dead.  With ``packed=True`` the board is
    a uint32 bitboard (``tpu_life.ops.bitlife``) — the ring exchange is
    identical, just 32x narrower.
    """
    n = mesh.shape[axis]
    pad = halo_depth(rule, block_steps)
    masked_step = (
        bitlife.make_masked_packed_step(rule, tuple(logical_shape))
        if packed
        else make_masked_step(rule, tuple(logical_shape))
    )
    fwd = [(i, i + 1) for i in range(n - 1)]  # shard i's bottom rows -> i+1's top halo
    bwd = [(i + 1, i) for i in range(n - 1)]  # shard i's top rows -> i-1's bottom halo

    def local_block(chunk: jax.Array) -> jax.Array:
        h_local = chunk.shape[0]
        idx = lax.axis_index(axis)
        top_halo = lax.ppermute(chunk[h_local - pad :, :], axis, fwd)
        bot_halo = lax.ppermute(chunk[:pad, :], axis, bwd)
        ext = jnp.concatenate([top_halo, chunk, bot_halo], axis=0)
        row_offset = idx * h_local - pad
        for _ in range(block_steps):
            ext = masked_step(ext, row_offset)
        return ext[pad : pad + h_local, :]

    def local_run(chunk: jax.Array, num_blocks: int) -> jax.Array:
        if chunk.shape[0] < pad:
            raise ValueError(
                f"shard height {chunk.shape[0]} < halo depth {pad}; "
                f"lower block_steps or use fewer devices"
            )
        out, _ = lax.scan(
            lambda c, _: (local_block(c), None), chunk, None, length=num_blocks
        )
        return out

    @partial(jax.jit, static_argnames="num_blocks", donate_argnums=0)
    def run(board: jax.Array, num_blocks: int) -> jax.Array:
        return shard_map(
            partial(local_run, num_blocks=num_blocks),
            mesh=mesh,
            in_specs=P(axis, None),
            out_specs=P(axis, None),
        )(board)

    return run
