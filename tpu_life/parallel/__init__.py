from tpu_life.parallel.mesh import make_mesh, board_sharding, init_distributed
from tpu_life.parallel.halo import make_sharded_run, halo_depth

__all__ = [
    "make_mesh",
    "board_sharding",
    "init_distributed",
    "make_sharded_run",
    "halo_depth",
]
