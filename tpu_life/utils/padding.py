"""Physical-layout padding helpers.

TPU vector lanes are 128 wide and shard_map needs the sharded axis evenly
divisible by the mesh; we pad the physical array and pin padding cells dead
via ``tpu_life.ops.stencil.validity_mask`` instead of fighting XLA with
ragged shapes.
"""

from __future__ import annotations

import numpy as np

LANE = 128
SUBLANE = 8


def ceil_to(x: int, m: int) -> int:
    return -(-x // m) * m if m > 1 else x


def pad_board(board: np.ndarray, h_pad: int, w_pad: int) -> np.ndarray:
    """Zero-pad ``board`` to physical shape ``(h_pad, w_pad)``."""
    h, w = board.shape
    if (h, w) == (h_pad, w_pad):
        return np.ascontiguousarray(board, dtype=np.int8)
    out = np.zeros((h_pad, w_pad), dtype=np.int8)
    out[:h, :w] = board
    return out
