"""Shared ctypes loader for the in-tree native libraries (native/*.cpp).

Both native modules — the I/O codec (tpu_life/io/native.py) and the compute
stepper (tpu_life/ops/native_step.py) — load a shared object from
``native/``, honor the same ``TPU_LIFE_NATIVE=0`` kill switch, and build
in-tree via ``make`` on demand.  This module is that loader, once.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from pathlib import Path

NATIVE_DIR = Path(__file__).resolve().parent.parent.parent / "native"


def disabled() -> bool:
    return os.environ.get("TPU_LIFE_NATIVE", "1") == "0"


def default_threads() -> int:
    return min(16, os.cpu_count() or 1)


def load_library(
    lib_name: str, *, env_override: str, int_functions: list[str]
) -> ctypes.CDLL | None:
    """Load ``native/<lib_name>`` (or the ``env_override`` path), marking
    each named entry point as returning ``int``.  None when disabled,
    missing, or unloadable."""
    if disabled():
        return None
    candidates = [
        Path(os.environ.get(env_override, "")),
        NATIVE_DIR / lib_name,
    ]
    for p in candidates:
        if p and p.is_file():
            try:
                lib = ctypes.CDLL(str(p))
                for fn in int_functions:
                    getattr(lib, fn).restype = ctypes.c_int
            except (OSError, AttributeError):
                # AttributeError = a stale build missing newer entry points:
                # treat it as unloadable (NumPy fallback / rebuild) rather
                # than crashing every import of the binding module
                continue
            return lib
    return None


def build_library(lib_name: str) -> bool:
    """``make -C native <lib_name>``; False when disabled or the build
    fails (no compiler, make missing)."""
    if disabled():
        return False  # explicitly disabled — don't compile behind the user's back
    try:
        subprocess.run(
            ["make", "-C", str(NATIVE_DIR), lib_name],
            check=True,
            capture_output=True,
        )
    except (subprocess.CalledProcessError, FileNotFoundError):
        return False
    return True
