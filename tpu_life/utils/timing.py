"""Wall-clock timing.

The reference brackets the whole run with ``MPI_Wtime``
(Parallel_Life_MPI.cpp:199,233).  ``Timer`` does the same with
``perf_counter``; accelerated backends call ``block_until_ready`` before
reading it so async dispatch can't fake a fast run.
"""

from __future__ import annotations

import time


class Timer:
    def __init__(self):
        self.start = time.perf_counter()
        self.laps: list[float] = []

    def lap(self) -> float:
        now = time.perf_counter()
        prev = self.start + sum(self.laps)
        self.laps.append(now - prev)
        return self.laps[-1]

    @property
    def elapsed(self) -> float:
        return time.perf_counter() - self.start


def delta_seconds_per_step(
    runner, steps: int, base_steps: int, repeats: int = 3
) -> float:
    """Sustained device seconds/step of a Runner via delta timing.

    Two fused runs of different step counts are timed and differenced — the
    delta cancels the constant dispatch + readback latency, which on a
    tunneled TPU dwarfs the kernel time itself.  The first pair of calls
    warms up compilation for both step counts.  Negative deltas (timer
    noise) are discarded; if none are positive the plain per-step time of
    the long run is returned.  Single source of the methodology for both
    ``bench.py`` and ``experiments/``.
    """
    if steps <= base_steps:
        raise ValueError(f"steps {steps} must exceed base_steps {base_steps}")

    def timed(k: int) -> float:
        t0 = time.perf_counter()
        runner.advance(k)
        runner.sync()
        return time.perf_counter() - t0

    timed(base_steps)  # warmup: compile both timed step counts
    timed(steps)
    deltas = [
        (timed(steps) - timed(base_steps)) / (steps - base_steps)
        for _ in range(repeats)
    ]
    positive = [d for d in deltas if d > 0]
    return min(positive) if positive else timed(steps) / steps


def paired_delta_seconds_per_step(
    runner_a, runner_b, steps: int, base_steps: int, repeats: int = 3
) -> list[tuple[float, float]]:
    """Per-step times of two Runners, measured as back-to-back delta PAIRS.

    Each repeat times runner_a's delta then runner_b's immediately after,
    so both sit in the same throughput window of a drifting device — the
    per-pair ratio cancels window-to-window wobble that timing two
    sequential `delta_seconds_per_step` calls would soak up (the r4
    parity_ratio-1.23 artifact).  Same warmup and positive-delta policy as
    `delta_seconds_per_step`; pairs where either delta is non-positive
    (timer noise) are dropped.  Returns the surviving (a, b) pairs.
    """
    if steps <= base_steps:
        raise ValueError(f"steps {steps} must exceed base_steps {base_steps}")
    span = steps - base_steps

    def timed(runner, k: int) -> float:
        t0 = time.perf_counter()
        runner.advance(k)
        runner.sync()
        return time.perf_counter() - t0

    for r in (runner_a, runner_b):  # warmup: compile both counts, both legs
        timed(r, base_steps)
        timed(r, steps)
    pairs = []
    for _ in range(repeats):
        d_a = (timed(runner_a, steps) - timed(runner_a, base_steps)) / span
        d_b = (timed(runner_b, steps) - timed(runner_b, base_steps)) / span
        if d_a > 0 and d_b > 0:
            pairs.append((d_a, d_b))
    return pairs
