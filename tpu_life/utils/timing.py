"""Wall-clock timing.

The reference brackets the whole run with ``MPI_Wtime``
(Parallel_Life_MPI.cpp:199,233).  ``Timer`` does the same with
``perf_counter``; accelerated backends call ``block_until_ready`` before
reading it so async dispatch can't fake a fast run.
"""

from __future__ import annotations

import time


class Timer:
    def __init__(self):
        self.start = time.perf_counter()
        self.laps: list[float] = []

    def lap(self) -> float:
        now = time.perf_counter()
        prev = self.start + sum(self.laps)
        self.laps.append(now - prev)
        return self.laps[-1]

    @property
    def elapsed(self) -> float:
        return time.perf_counter() - self.start
