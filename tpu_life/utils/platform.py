"""Platform selection.

Some TPU plugin environments force themselves as the default JAX platform
regardless of ``JAX_PLATFORMS`` (observed with tunneled-TPU plugins).
``ensure_platform`` applies an explicit override via ``jax.config`` — which
does win — from a flag or the ``TPU_LIFE_PLATFORM`` env var.  Must run
before the first device query.
"""

from __future__ import annotations

import os


def ensure_platform(platform: str | None = None) -> None:
    platform = platform or os.environ.get("TPU_LIFE_PLATFORM")
    if not platform:
        return
    import jax

    jax.config.update("jax_platforms", platform)
