"""Platform selection.

Some TPU plugin environments force themselves as the default JAX platform
regardless of ``JAX_PLATFORMS`` (observed with tunneled-TPU plugins).
``ensure_platform`` applies an explicit override via ``jax.config`` — which
does win — from a flag or the ``TPU_LIFE_PLATFORM`` env var.  Must run
before the first device query.
"""

from __future__ import annotations

import os


def ensure_platform(platform: str | None = None) -> None:
    platform = platform or os.environ.get("TPU_LIFE_PLATFORM")
    if not platform:
        return
    import jax

    jax.config.update("jax_platforms", platform)


DEVICE_QUERY_TIMEOUT_S = 180.0  # first tunneled-TPU attach can take minutes


def devices_with_watchdog(timeout_s: float | None = None):
    """``jax.devices()`` that cannot hang the process forever.

    A tunneled-TPU plugin blocks indefinitely at the first device query when
    its chip grant is stale (the round-1 bench lesson, BENCH_r01.json rc=1)
    — and ``get_backend('auto')`` triggers exactly that query in-process, so
    ``python -m tpu_life run`` on a wedged machine would just hang
    (VERDICT r3 item 8).  The query runs in a daemon thread with a timeout;
    on expiry a TimeoutError with recovery guidance is raised (the stuck
    thread is abandoned — callers are expected to exit).
    """
    import threading

    if timeout_s is None:
        timeout_s = float(
            os.environ.get("TPU_LIFE_DEVICE_TIMEOUT_S", DEVICE_QUERY_TIMEOUT_S)
        )
    result: dict = {}

    def query() -> None:
        try:
            import jax

            result["devices"] = jax.devices()
        except Exception as e:  # noqa: BLE001 — re-raised on the caller side
            result["error"] = e

    t = threading.Thread(target=query, daemon=True, name="device-watchdog")
    t.start()
    t.join(timeout_s)
    if t.is_alive():
        raise TimeoutError(
            f"device query hung for {timeout_s:.0f}s — the accelerator "
            "plugin appears wedged (stale chip grant?).  Run on CPU with "
            "TPU_LIFE_PLATFORM=cpu (and PALLAS_AXON_POOL_IPS= to skip "
            "plugin registration), or retry in a few minutes once the "
            "grant expires."
        )
    if "error" in result:
        raise result["error"]
    return result["devices"]


def device_info(timeout_s: float | None = None) -> tuple[int, str]:
    """``(device_count, platform_kind)`` of the default JAX backend.

    The capacity-reporting half of per-worker placement (docs/FLEET.md):
    a gateway worker resolves what its (possibly overlaid) environment
    actually gave it — e.g. ``XLA_FLAGS=--xla_force_host_platform_
    device_count=4`` under ``JAX_PLATFORMS=cpu`` resolves to ``(4,
    "cpu")`` — and reports it in its startup line and ``/readyz`` so the
    fleet balancer can weight routing by real capacity.  Goes through
    :func:`devices_with_watchdog` (a wedged plugin must degrade the
    report, not hang the worker); any failure reports ``(1, "host")`` —
    a worker that cannot say what it owns routes as a single-chip peer.
    """
    try:
        devices = devices_with_watchdog(timeout_s)
        return len(devices), devices[0].platform
    except Exception:  # noqa: BLE001 — reporting must never kill a worker
        return 1, "host"
