from tpu_life.utils.padding import ceil_to, pad_board
from tpu_life.utils.timing import Timer

__all__ = ["ceil_to", "pad_board", "Timer"]
