"""Admission control: token buckets and queue-depth load shedding.

Two independent valves in front of ``SimulationService.submit``:

- **Rate limiting** (:class:`KeyedBuckets`): a classic token bucket per
  API key — ``rate`` tokens/second refill up to ``burst`` capacity; a
  request costs one token.  A dry bucket yields the seconds until the
  next token, which becomes the 429's ``Retry-After``.  Per-key state is
  capped (LRU eviction) so an attacker rotating keys cannot grow memory.

- **Load shedding** (:class:`LoadShedder`): reject-before-enqueue when
  the serve queue-depth gauge crosses a high-water mark.  The gauge is
  sampled each scheduling round, so this is deliberately a *soft* valve
  measuring sustained pressure; the bounded admission queue
  (``QueueFull`` -> 503) is the hard backstop for the instants between
  rounds.  Shedding at the front door keeps the continuous-batching
  scheduler saturated-but-stable instead of building an unbounded latency
  backlog — the same shape as any inference stack's traffic layer.

Both are thread-safe (the gateway's handler threads race through them)
and clock-injectable (tests run on a fake clock, no sleeps).
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict

#: Default cap on distinct API keys holding bucket state.
MAX_KEYS = 1024


class TokenBucket:
    """One key's bucket: ``acquire()`` -> 0.0 (admitted) or seconds to wait.

    ``rate <= 0`` disables the bucket (every acquire admits) — the
    "unlimited" configuration, kept here so callers never branch.
    """

    def __init__(self, rate: float, burst: float, *, clock=time.monotonic):
        if rate > 0 and burst < 1:
            raise ValueError(f"burst must be >= 1 token, got {burst}")
        self.rate = float(rate)
        self.burst = float(burst)
        self.clock = clock
        self._tokens = self.burst
        self._at = clock()

    def acquire(self, n: float = 1.0) -> float:
        """Try to spend ``n`` tokens; 0.0 on success, else seconds until
        enough tokens will have refilled (the ``Retry-After`` value)."""
        if self.rate <= 0:
            return 0.0
        now = self.clock()
        self._tokens = min(self.burst, self._tokens + (now - self._at) * self.rate)
        self._at = now
        if self._tokens >= n:
            self._tokens -= n
            return 0.0
        return (n - self._tokens) / self.rate


class KeyedBuckets:
    """Per-API-key token buckets with bounded key cardinality.

    Keys are evicted least-recently-used past ``max_keys``; an evicted
    key that returns simply starts with a full bucket — strictly more
    permissive, never a denial-of-service on memory.
    """

    def __init__(
        self,
        rate: float,
        burst: float,
        *,
        clock=time.monotonic,
        max_keys: int = MAX_KEYS,
    ):
        self.rate = float(rate)
        self.burst = float(burst)
        self.clock = clock
        self.max_keys = max_keys
        self._buckets: OrderedDict[str, TokenBucket] = OrderedDict()
        self._lock = threading.Lock()

    @property
    def enabled(self) -> bool:
        return self.rate > 0

    def acquire(self, key: str) -> float:
        """0.0 = admitted; > 0 = seconds the key must wait (429 path)."""
        if not self.enabled:
            return 0.0
        with self._lock:
            bucket = self._buckets.get(key)
            if bucket is None:
                bucket = TokenBucket(self.rate, self.burst, clock=self.clock)
                self._buckets[key] = bucket
                while len(self._buckets) > self.max_keys:
                    self._buckets.popitem(last=False)
            else:
                self._buckets.move_to_end(key)
            return bucket.acquire()


class LoadShedder:
    """Reject-before-enqueue when sustained queue depth crosses high water.

    ``depth`` is a callable returning the current queue-depth reading —
    the gateway wires it to the serve registry's ``serve_queue_depth``
    gauge, updated once per scheduling round.  ``high_water <= 0``
    disables shedding.
    """

    def __init__(self, depth, high_water: float, *, retry_after: float = 1.0):
        self.depth = depth
        self.high_water = float(high_water)
        self.retry_after = float(retry_after)

    @property
    def enabled(self) -> bool:
        return self.high_water > 0

    def check(self) -> tuple[float, float] | None:
        """None = admit; (depth, retry_after) = shed this request."""
        if not self.enabled:
            return None
        d = float(self.depth())
        if d >= self.high_water:
            return d, self.retry_after
        return None
