"""A well-behaved gateway client: urllib + retry/backoff, jax-free.

The reference implementation of the retry contract the gateway publishes:
429 and 503 responses carry ``Retry-After``; a client that honors it (and
backs off exponentially when it's absent) rides out rate limiting, load
shedding, and a draining peer without hammering the front door.  400s are
client bugs and are never retried.

Everything is stdlib + numpy (board decode) — importable from any
machine that can reach the gateway, no jax required, same spirit as the
``stats`` toolchain.
"""

from __future__ import annotations

import json
import random
import socket
import time
import urllib.error
import urllib.request

import numpy as np

from tpu_life.gateway import protocol
from tpu_life.gateway.errors import backoff_delay, parse_retry_after

#: Statuses the client retries (with Retry-After / backoff): rate limit,
#: and the 503 family (queue full / shedding / draining).
RETRYABLE = frozenset({429, 503})


class GatewayError(Exception):
    """A non-retryable (or retries-exhausted) gateway response."""

    def __init__(
        self,
        status: int,
        code: str,
        message: str,
        *,
        retry_after: float | None = None,
    ):
        super().__init__(f"[{status} {code}] {message}")
        self.status = status
        self.code = code
        self.message = message
        self.retry_after = retry_after


class GatewayClient:
    """Talk to one gateway (or a fleet router — same protocol).
    ``retries`` bounds how many times a retryable response (429/503) or a
    connection refusal is retried; ``backoff`` is the base of the
    exponential fallback used when the server sent no ``Retry-After``,
    spread by bounded ``jitter`` so N clients bounced by the same
    shedding fleet don't synchronize into retry storms (an explicit
    ``Retry-After`` always wins, un-jittered — the server asked for that
    exact pacing).  ``sleep`` and ``rng`` are injectable so tests never
    wait and never flake."""

    def __init__(
        self,
        base_url: str,
        *,
        api_key: str | None = None,
        timeout: float = 30.0,
        retries: int = 4,
        backoff: float = 0.2,
        max_backoff: float = 5.0,
        jitter: float = 0.25,
        sleep=time.sleep,
        rng: random.Random | None = None,
    ):
        self.base_url = base_url.rstrip("/")
        self.api_key = api_key
        self.timeout = timeout
        self.retries = retries
        self.backoff = backoff
        self.max_backoff = max_backoff
        if not 0.0 <= jitter < 1.0:
            raise ValueError(f"jitter must be in [0, 1), got {jitter}")
        self.jitter = jitter
        self.sleep = sleep
        self.rng = rng or random.Random()

    # -- transport ---------------------------------------------------------
    def _request(
        self,
        method: str,
        path: str,
        body: dict | None = None,
        headers: dict | None = None,
    ) -> dict:
        url = self.base_url + path
        data = None if body is None else json.dumps(body).encode()
        attempt = 0
        while True:
            req = urllib.request.Request(url, data=data, method=method)
            req.add_header("Content-Type", "application/json")
            if self.api_key:
                req.add_header("X-API-Key", self.api_key)
            for k, v in (headers or {}).items():
                req.add_header(k, v)
            try:
                with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                    return json.loads(resp.read() or b"{}")
            except urllib.error.HTTPError as e:
                payload = _error_payload(e)
                err = GatewayError(
                    e.code,
                    payload.get("code", "http_error"),
                    payload.get("message", str(e)),
                    retry_after=parse_retry_after(e.headers),
                )
                if e.code not in RETRYABLE or attempt >= self.retries:
                    raise err from None
                wait = err.retry_after
            except (urllib.error.URLError, ConnectionError, socket.timeout) as e:
                # a refused connection means the server never saw the
                # request — always safe to retry (normal during gateway
                # startup or a rolling restart).  Anything else (timeout,
                # reset mid-exchange) may have been PROCESSED: re-POSTing
                # /v1/sessions would silently create a duplicate session,
                # so only idempotent methods retry those.
                reason = getattr(e, "reason", e)
                refused = isinstance(reason, ConnectionRefusedError)
                retryable = refused or method in ("GET", "DELETE")
                if not retryable or attempt >= self.retries:
                    raise GatewayError(
                        0, "unreachable", f"{url}: {e}"
                    ) from None
                wait = None
            attempt += 1
            if wait is None:
                # no Retry-After: the shared jittered-exponential formula
                # (gateway.errors.backoff_delay — the migrator and remote
                # spill backend pace on the same curve)
                wait = backoff_delay(
                    attempt,
                    base=self.backoff,
                    cap=self.max_backoff,
                    jitter=self.jitter,
                    rng=self.rng,
                )
            self.sleep(wait)

    # -- the API -----------------------------------------------------------
    def submit(
        self,
        *,
        board: np.ndarray | None = None,
        rule: str = "conway",
        steps: int,
        timeout_s: float | None = None,
        size: int | None = None,
        height: int | None = None,
        width: int | None = None,
        seed: int | None = None,
        density: float | None = None,
        temperature: float | None = None,
        trace_id: str | None = None,
        scheduled_edits: list | None = None,
    ) -> str:
        """Create a session (inline board, or seeded geometry); returns sid.

        ``seed`` and ``temperature`` are the stochastic-tier fields
        (docs/STOCHASTIC.md): seed names the counter-based PRNG stream
        (and, for seeded geometry, the staged board); temperature is the
        per-session ising scalar.  ``trace_id`` rides the ``X-Trace-Id``
        header (docs/OBSERVABILITY.md "Distributed tracing"): the router
        honors it as the session's journey id instead of minting one —
        how a client correlates ITS request id with the fleet's trace.
        """
        req: dict = {"rule": rule, "steps": steps}
        if timeout_s is not None:
            req["timeout_s"] = timeout_s
        if temperature is not None:
            req["temperature"] = temperature
        if seed is not None:
            req["seed"] = seed
        if scheduled_edits is not None:
            # pre-scheduled steering (docs/STREAMING.md): the worker
            # applies each [step, cells] entry at exactly that step via
            # the freeze-mask seam, as if PATCHed live at that moment
            req["scheduled_edits"] = scheduled_edits
        if board is not None:
            req["board"] = board_rows(board)
        else:
            for k, v in (
                ("size", size),
                ("height", height),
                ("width", width),
                ("density", density),
            ):
                if v is not None:
                    req[k] = v
        headers = {"X-Trace-Id": trace_id} if trace_id is not None else None
        resp = self._request("POST", "/v1/sessions", req, headers=headers)
        return resp["session"]

    def poll(self, sid: str) -> dict:
        return self._request("GET", f"/v1/sessions/{sid}")

    def result(self, sid: str, fmt: str = "raw") -> dict:
        return self._request("GET", f"/v1/sessions/{sid}/result?format={fmt}")

    def result_board(self, sid: str) -> np.ndarray:
        """The finished session's board, byte-decoded from the raw payload."""
        return protocol.decode_result(self.result(sid, fmt="raw"))

    def cancel(self, sid: str) -> bool:
        return bool(self._request("DELETE", f"/v1/sessions/{sid}")["cancelled"])

    def edit_cells(self, sid: str, cells: list) -> dict:
        """Mid-run steering (docs/STREAMING.md): PATCH a list of
        ``[row, col, value]`` triples onto the running board; applied
        between chunks and recorded in the session's edit log."""
        return self._request(
            "PATCH", f"/v1/sessions/{sid}/cells", {"cells": cells}
        )

    def stream(self, sid: str, *, cursor: int = 0):
        """Watch a session live: a generator of frame dicts off the
        chunked ndjson delta stream (docs/STREAMING.md) — keyframes,
        deltas, edit markers, ``frame_gap`` resyncs, and the terminal
        ``end``.  One connection, no retries: a transport drop
        mid-stream surfaces as :class:`GatewayError` so the caller can
        reconnect with ``cursor`` set to the next sequence it needs
        (the server fast-forwards and re-keys).  Non-2xx admission
        responses (404 unknown sid, 503 watcher-buffer pressure) raise
        the usual typed error."""
        url = f"{self.base_url}/v1/sessions/{sid}/stream?cursor={int(cursor)}"
        req = urllib.request.Request(url, method="GET")
        if self.api_key:
            req.add_header("X-API-Key", self.api_key)
        try:
            resp = urllib.request.urlopen(req, timeout=self.timeout)
        except urllib.error.HTTPError as e:
            payload = _error_payload(e)
            raise GatewayError(
                e.code,
                payload.get("code", "http_error"),
                payload.get("message", str(e)),
                retry_after=parse_retry_after(e.headers),
            ) from None
        except (urllib.error.URLError, ConnectionError, socket.timeout) as e:
            raise GatewayError(0, "unreachable", f"{url}: {e}") from None
        try:
            with resp:
                for line in resp:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        yield json.loads(line)
                    except json.JSONDecodeError:
                        # a torn frame (connection died mid-line) — the
                        # reconnect-with-cursor contract, not a parse bug
                        raise GatewayError(
                            0, "stream_torn", f"{sid}: torn frame mid-stream"
                        ) from None
        except (ConnectionError, socket.timeout, OSError) as e:
            raise GatewayError(0, "stream_torn", f"{sid}: {e}") from None

    def wait(self, sid: str, *, interval: float = 0.05, timeout: float = 120.0) -> dict:
        """Poll until the session is terminal; returns the final view."""
        deadline = time.monotonic() + timeout
        while True:
            view = self.poll(sid)
            if view["finished"]:
                return view
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"session {sid} still {view['state']} after {timeout}s"
                )
            self.sleep(interval)

    def healthz(self) -> dict:
        return self._request("GET", "/healthz")

    def readyz(self) -> dict:
        """Raises :class:`GatewayError` (503, retries exhausted) while
        draining — readiness is a yes/no the LB asks, not a retry loop."""
        return self._request("GET", "/readyz")

    def metrics(self) -> str:
        req = urllib.request.Request(self.base_url + "/metrics")
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            return resp.read().decode()


def board_rows(board: np.ndarray) -> list:
    """int8 board -> rows-of-digit-strings (the compact inline encoding);
    float32 (continuous-tier) boards -> nested float lists — the wire
    shape ``parse_board`` accepts for continuous rules."""
    board = np.asarray(board)
    if board.ndim != 2:
        raise ValueError(f"board must be 2-D, got shape {board.shape}")
    if np.issubdtype(board.dtype, np.floating):
        return [[float(c) for c in row] for row in board]
    if board.min(initial=0) < 0 or board.max(initial=0) > 9:
        raise ValueError("inline boards carry digit states 0..9")
    return ["".join(str(int(c)) for c in row) for row in board]


def _error_payload(e: urllib.error.HTTPError) -> dict:
    try:
        doc = json.loads(e.read() or b"{}")
        return doc.get("error", {}) if isinstance(doc, dict) else {}
    except (json.JSONDecodeError, OSError):
        return {}
