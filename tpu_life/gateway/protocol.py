"""The gateway's JSON wire vocabulary: requests in, views and boards out.

Kept apart from the HTTP server so the contract is testable without a
socket and reusable by the client.  Never imports jax — parsing and
rendering are pure host-side work (numpy + the contract codec + RLE).

Submit request (``POST /v1/sessions``)::

    {"board": ["0110", "1001", ...],        # rows of digit strings, or
     "board": [[0,1,1,0], ...],             # nested int lists
     "rule": "conway", "steps": 64,
     "timeout_s": 5.0}                      # optional deadline

or seeded geometry instead of an inline board (the ``run --size``
shorthand over the wire — demos need no input file)::

    {"size": 256, "steps": 64}              # or "height" + "width"
    {"height": 128, "width": 96, "steps": 8, "seed": 7, "density": 0.4}

Stochastic sessions (docs/STOCHASTIC.md) add the per-session Monte-Carlo
fields — ``seed`` names the counter-based PRNG stream, ``temperature``
is the ising Metropolis scalar (required there, a typed 400 elsewhere)::

    {"size": 128, "steps": 200, "rule": "ising",
     "temperature": 2.27, "seed": 42}

Resume requests (docs/FLEET.md failover) stage a byte-exact prior state
instead of a fresh board: ``resume_b64`` is base64 of the contract-codec
board bytes (``io/codec.py`` — the spill/snapshot format), ``start_step``
the absolute steps that board has already completed, ``steps`` the
REMAINING budget.  Deterministic rules resume exactly because the board
is the whole state; stochastic rules because the counter-based key
schedule re-enters the stream at ``start_step``::

    {"resume_b64": "...", "height": 128, "width": 128,
     "rule": "ising", "steps": 120, "start_step": 80,
     "seed": 42, "temperature": 2.27}

Result payload (``GET /v1/sessions/{sid}/result?format=rle|raw``):
``rle`` is the ecosystem interchange text (``io/rle.py``); ``raw`` is
base64 of the byte-exact contract board format (``io/codec.py``) — the
format a client decodes back to the identical int8 array, which is what
the byte-equality acceptance test asserts.
"""

from __future__ import annotations

import base64
from dataclasses import dataclass

import numpy as np

from tpu_life.gateway.errors import ApiError, bad_request
from tpu_life.io.codec import decode_board, encode_board
from tpu_life.io.rle import emit_rle
from tpu_life.mc import validate_board_shape as mc_validate_board_shape
from tpu_life.mc import validate_params as mc_validate_params
from tpu_life.mc.prng import seeded_board
from tpu_life.models.rules import get_rule
from tpu_life.serve.sessions import SessionView

#: Hard cap on inline/seeded board cells — a front door must bound the
#: memory one request can demand before any engine sees it (16 Mcells is
#: a 4096^2 board: far beyond what an inline JSON board is for).
MAX_CELLS = 1 << 24

#: Default request-body bound (bytes) — pre-read admission control.
MAX_BODY = 8 << 20


@dataclass(frozen=True)
class SubmitSpec:
    """A validated submission, ready for ``SimulationService.submit``.

    ``seed``/``temperature`` are the stochastic-tier fields
    (docs/STOCHASTIC.md): the counter-based PRNG stream id and the
    per-session ising scalar.  ``seed`` is also set for seeded-geometry
    deterministic requests (it named the staged board).  ``start_step``
    is the failover-resume field: absolute steps the staged board has
    already completed (0 for fresh sessions).
    """

    board: np.ndarray
    rule: str
    steps: int
    timeout_s: float | None
    seed: int | None = None
    temperature: float | None = None
    start_step: int = 0
    #: distributed-trace context (docs/OBSERVABILITY.md "Distributed
    #: tracing"): a client- or router-supplied id for this session's
    #: cross-process journey; the ``X-Trace-Id`` header wins over the
    #: body field at the HTTP layer, and a malformed value is a typed 400
    trace_id: str | None = None
    #: steered-session resume fields (docs/STREAMING.md): ``edits`` is
    #: the APPLIED edit log (already baked into the staged board —
    #: provenance for replay), ``scheduled_edits`` the unapplied tail the
    #: service must re-apply, ``stream_seq`` the delta-stream sequence
    #: floor so a reconnected watcher's numbering stays gapless across
    #: failover.  Cell-level validation is the service's (shape- and
    #: rule-aware); here the shape of the log itself is the contract.
    edits: list | None = None
    scheduled_edits: list | None = None
    stream_seq: int = 0
    #: shard-wise mega-board resume (docs/SERVING.md "Mega-board
    #: sessions"): a shared-filesystem pointer to a spilled tile set —
    #: no board bytes ride the wire; the survivor re-gathers shard by
    #: shard at admission and ``board`` is a zeros placeholder carrying
    #: only the geometry.
    resume_tiles_dir: str | None = None


def _require_int(payload: dict, key: str, *, minimum: int = 0) -> int:
    v = payload.get(key)
    # bool is an int subclass; "steps": true must not parse as 1
    if isinstance(v, bool) or not isinstance(v, int):
        raise bad_request(
            "invalid_request", f"{key!r} must be an integer, got {v!r}"
        )
    if v < minimum:
        raise bad_request(
            "invalid_request", f"{key!r} must be >= {minimum}, got {v}"
        )
    return v


def parse_trace_id(raw) -> str | None:
    """Validate a wire trace id (body field or ``X-Trace-Id`` header):
    None passes through, anything else must match the bounded id shape
    (``obs.TRACE_ID_RE``) — a hostile value must not ride into every
    span, file name and flight event of the session's journey."""
    if raw is None:
        return None
    from tpu_life import obs

    if not obs.valid_trace_id(raw):
        raise bad_request(
            "invalid_trace_id",
            "trace id must be 1-64 characters of [A-Za-z0-9._:-] "
            "starting alphanumeric",
        )
    return raw


def _check_rule_geometry(rule, shape) -> None:
    """Kernel-vs-board geometry as a typed 400 (docs/RULES.md): a
    Larger-than-Life or continuous kernel wider than the board rejects
    HERE — ``radius_too_large`` — never as a downstream shape error."""
    from tpu_life.models.rules import GeometryError, validate_rule_geometry

    try:
        validate_rule_geometry(rule, shape)
    except GeometryError as e:
        raise bad_request("radius_too_large", str(e)) from None


def parse_board(raw, rule) -> np.ndarray:
    """Inline JSON board -> int8 (or, for continuous rules, float32)
    array, with typed 400s for every malformation.

    Discrete rules take digit-string rows or nested int lists; the
    continuous tier additionally accepts float cells (values in
    [0, 1]) — a digit-string row of 0s and 1s is legal there too.
    """
    continuous = bool(getattr(rule, "continuous", False))
    states = rule.states
    if not isinstance(raw, list) or not raw:
        raise bad_request(
            "invalid_board", "'board' must be a non-empty list of rows"
        )
    rows: list[list] = []
    width = None
    for i, row in enumerate(raw):
        if isinstance(row, str):
            # isascii() too: str.isdigit() admits Unicode digits ('¹', '٣')
            # that int() then rejects — a 500 instead of this typed 400
            if not (row.isascii() and row.isdigit()):
                raise bad_request(
                    "invalid_board",
                    f"board row {i} contains non-digit characters",
                )
            cells = [int(c) for c in row]
        elif isinstance(row, list):
            ok_types = (int, float) if continuous else (int,)
            if not all(
                isinstance(c, ok_types) and not isinstance(c, bool)
                for c in row
            ):
                raise bad_request(
                    "invalid_board",
                    f"board row {i} must hold only "
                    + ("numbers" if continuous else "integers"),
                )
            cells = row
        else:
            raise bad_request(
                "invalid_board",
                f"board row {i} must be a digit string or "
                + ("a number list" if continuous else "an int list"),
            )
        if not cells:
            raise bad_request("invalid_board", f"board row {i} is empty")
        if width is None:
            width = len(cells)
        elif len(cells) != width:
            raise bad_request(
                "invalid_board",
                f"board row {i} has {len(cells)} cells; row 0 has {width}",
            )
        rows.append(cells)
    if len(rows) * width > MAX_CELLS:
        raise bad_request(
            "board_too_large",
            f"board has {len(rows) * width} cells; the limit is {MAX_CELLS}",
        )
    if continuous:
        board = np.array(rows, dtype=np.float64)
        if not np.isfinite(board).all():
            raise bad_request(
                "invalid_board", "board contains NaN or Inf"
            )
        lo, hi = float(board.min()), float(board.max())
        if lo < 0.0 or hi > 1.0:
            raise bad_request(
                "invalid_board",
                f"board values must be in [0, 1] for continuous rule "
                f"{rule.name!r}; found {lo if lo < 0.0 else hi}",
            )
        return board.astype(np.float32)
    board = np.array(rows, dtype=np.int64)
    lo, hi = int(board.min()), int(board.max())
    if lo < 0 or hi >= states:
        raise bad_request(
            "invalid_board",
            f"board states must be 0..{states - 1} for this rule; "
            f"found {lo if lo < 0 else hi}",
        )
    return board.astype(np.int8)


def parse_resume_board(payload: dict, rule) -> np.ndarray:
    """``resume_b64`` + geometry -> the byte-exact board, with typed
    400s for malformed base64, geometry mismatch, or out-of-range
    states.  The bytes ARE the spill/snapshot contract format (the
    float32 encoding for continuous rules — ``io/codec.py``), so a
    resumed board is identical down to the byte to what the dead worker
    spilled."""
    continuous = bool(getattr(rule, "continuous", False))
    states = rule.states
    height = _require_int(payload, "height", minimum=1)
    width = _require_int(payload, "width", minimum=1)
    if height * width > MAX_CELLS:
        raise bad_request(
            "board_too_large",
            f"resume board has {height * width} cells; the limit is {MAX_CELLS}",
        )
    raw = payload["resume_b64"]
    if not isinstance(raw, str):
        raise bad_request("invalid_request", "'resume_b64' must be a string")
    try:
        buf = base64.b64decode(raw, validate=True)
    except (base64.binascii.Error, ValueError) as e:
        raise bad_request(
            "invalid_request", f"'resume_b64' is not valid base64: {e}"
        ) from None
    try:
        board = decode_board(buf, height, width)
    except ValueError as e:
        raise bad_request("invalid_board", str(e)) from None
    if continuous:
        if not np.issubdtype(board.dtype, np.floating):
            raise bad_request(
                "invalid_board",
                f"continuous rule {rule.name!r} resumes from the float32 "
                f"board encoding ({height * width * 4} bytes), got the "
                f"digit-grid encoding",
            )
        lo = float(board.min(initial=0.0))
        hi = float(board.max(initial=0.0))
        if lo < 0.0 or hi > 1.0:
            raise bad_request(
                "invalid_board",
                f"resume board values must be in [0, 1]; "
                f"found {lo if lo < 0.0 else hi}",
            )
        return board
    if np.issubdtype(board.dtype, np.floating):
        raise bad_request(
            "invalid_board",
            f"rule {rule.name!r} resumes from the digit-grid board "
            f"encoding, got the float32 encoding",
        )
    lo, hi = int(board.min(initial=0)), int(board.max(initial=0))
    if lo < 0 or hi >= states:
        raise bad_request(
            "invalid_board",
            f"resume board states must be 0..{states - 1} for this rule; "
            f"found {lo if lo < 0 else hi}",
        )
    return board


def _parse_edit_log_field(payload: dict, key: str) -> list | None:
    """Shape-check a wire edit log (``[[step, [[r, c, v], ...]], ...]``)
    as a typed 400; cell-level validation (bounds, states, the float
    range) is the service's, shape- and rule-aware, surfaced as 400 via
    the standard ValueError mapping."""
    raw = payload.get(key)
    if raw is None:
        return None
    if not isinstance(raw, list):
        raise bad_request(
            "invalid_request", f"{key!r} must be a list of [step, cells] pairs"
        )
    for i, entry in enumerate(raw):
        if (
            not isinstance(entry, (list, tuple))
            or len(entry) != 2
            or isinstance(entry[0], bool)
            or not isinstance(entry[0], int)
            or not isinstance(entry[1], list)
        ):
            raise bad_request(
                "invalid_request",
                f"{key!r} entry {i} must be a [step, cells] pair",
            )
    return raw


def parse_submit(payload) -> SubmitSpec:
    """Request JSON -> :class:`SubmitSpec`; raises :class:`ApiError` (400s)."""
    if not isinstance(payload, dict):
        raise bad_request("invalid_request", "request body must be a JSON object")
    rule_name = payload.get("rule", "conway")
    if not isinstance(rule_name, str):
        raise bad_request("invalid_request", "'rule' must be a string")
    try:
        rule = get_rule(rule_name)
    except (ValueError, KeyError) as e:
        raise bad_request("unknown_rule", str(e)) from None
    steps = _require_int(payload, "steps")
    timeout_s = payload.get("timeout_s")
    if timeout_s is not None:
        if isinstance(timeout_s, bool) or not isinstance(timeout_s, (int, float)):
            raise bad_request(
                "invalid_request", f"'timeout_s' must be a number, got {timeout_s!r}"
            )
        timeout_s = float(timeout_s)
    temperature = payload.get("temperature")
    if temperature is not None:
        if isinstance(temperature, bool) or not isinstance(
            temperature, (int, float)
        ):
            raise bad_request(
                "invalid_request",
                f"'temperature' must be a number, got {temperature!r}",
            )
        temperature = float(temperature)
    try:
        # the (rule, temperature) pairing contract (tpu_life.mc): ising
        # needs one, nothing else takes one — typed 400, not a late 500
        mc_validate_params(rule, temperature)
    except ValueError as e:
        raise bad_request("invalid_request", str(e)) from None
    seed = (
        _require_int(payload, "seed", minimum=-(1 << 63))
        if "seed" in payload
        else None
    )
    start_step = (
        _require_int(payload, "start_step") if "start_step" in payload else 0
    )
    trace_id = parse_trace_id(payload.get("trace_id"))
    edits = _parse_edit_log_field(payload, "edits")
    scheduled_edits = _parse_edit_log_field(payload, "scheduled_edits")
    stream_seq = (
        _require_int(payload, "stream_seq") if "stream_seq" in payload else 0
    )

    if "resume_tiles_dir" in payload:
        # shard-wise mega-board resume (docs/SERVING.md "Mega-board
        # sessions"): a shared-filesystem pointer to a spilled tile set.
        # No board bytes on the wire — a mega-board would not fit a
        # request body, and must never be materialized on one host; the
        # placeholder carries only geometry, the service validates the
        # pointed-at manifest against it.
        tiles_dir = payload["resume_tiles_dir"]
        if not isinstance(tiles_dir, str) or not tiles_dir:
            raise bad_request(
                "invalid_request",
                "'resume_tiles_dir' must be a non-empty path string",
            )
        height = _require_int(payload, "height", minimum=1)
        width = _require_int(payload, "width", minimum=1)
        _check_rule_geometry(rule, (height, width))
        board = np.zeros((height, width), dtype=rule.board_dtype)
        return SubmitSpec(
            board=board,
            rule=rule_name,
            steps=steps,
            timeout_s=timeout_s,
            seed=seed,
            temperature=temperature,
            start_step=start_step,
            trace_id=trace_id,
            edits=edits,
            scheduled_edits=scheduled_edits,
            stream_seq=stream_seq,
            resume_tiles_dir=tiles_dir,
        )

    if "resume_b64" in payload:
        # failover resume: byte-exact contract-codec board + the absolute
        # stream position it corresponds to (docs/FLEET.md)
        board = parse_resume_board(payload, rule)
        _check_rule_geometry(rule, board.shape)
        return SubmitSpec(
            board=board,
            rule=rule_name,
            steps=steps,
            timeout_s=timeout_s,
            seed=seed,
            temperature=temperature,
            start_step=start_step,
            trace_id=trace_id,
            edits=edits,
            scheduled_edits=scheduled_edits,
            stream_seq=stream_seq,
        )

    if "board" in payload:
        board = parse_board(payload["board"], rule)
        _check_rule_geometry(rule, board.shape)
        return SubmitSpec(
            board=board,
            rule=rule_name,
            steps=steps,
            timeout_s=timeout_s,
            seed=seed,
            temperature=temperature,
            start_step=start_step,
            trace_id=trace_id,
            edits=edits,
            scheduled_edits=scheduled_edits,
            stream_seq=stream_seq,
        )

    # seeded geometry: the self-contained demo path (run --size over HTTP);
    # explicit height/width win over the square 'size' shorthand
    size = _require_int(payload, "size", minimum=1) if "size" in payload else None
    height = (
        _require_int(payload, "height", minimum=1) if "height" in payload else size
    )
    width = (
        _require_int(payload, "width", minimum=1) if "width" in payload else size
    )
    if height is None or width is None:
        raise bad_request(
            "invalid_request",
            "provide either 'board' (inline) or geometry "
            "('size', or 'height' + 'width') for a seeded board",
        )
    if height * width > MAX_CELLS:
        raise bad_request(
            "board_too_large",
            f"seeded board has {height * width} cells; the limit is {MAX_CELLS}",
        )
    try:
        # the stochastic lattice contract (tpu_life.mc) checked BEFORE the
        # board is staged: odd ising dimensions (and, were MAX_CELLS ever
        # raised past it, the PRNG counter width) reject as a typed 400
        # instead of burning the staging work first.  The service's submit
        # re-validates with its executor's actual wide-counter capability.
        mc_validate_board_shape(rule, (height, width))
    except ValueError as e:
        raise bad_request("invalid_board", str(e)) from None
    _check_rule_geometry(rule, (height, width))
    density = payload.get("density", 0.5)
    if isinstance(density, bool) or not isinstance(density, (int, float)):
        raise bad_request("invalid_request", "'density' must be a number")
    if not 0.0 <= density <= 1.0:
        raise bad_request(
            "invalid_request", f"'density' must be in [0, 1], got {density}"
        )
    # counter-based staging (tpu_life.mc.prng): the board a seed names is
    # identical on every host, so the echoed seed fully replays the run.
    # Continuous rules stage the float twin (models/lenia.seeded_board).
    staged_seed = 0 if seed is None else seed
    if getattr(rule, "continuous", False):
        from tpu_life.models.lenia import seeded_board as lenia_seeded_board

        board = lenia_seeded_board(
            height, width, float(density), seed=staged_seed
        )
    else:
        board = seeded_board(
            height, width, float(density), states=rule.states, seed=staged_seed
        )
    return SubmitSpec(
        board=board,
        rule=rule_name,
        steps=steps,
        timeout_s=timeout_s,
        seed=staged_seed,
        temperature=temperature,
        start_step=start_step,
        trace_id=trace_id,
        edits=edits,
        scheduled_edits=scheduled_edits,
        stream_seq=stream_seq,
    )


# -- responses -------------------------------------------------------------
def render_view(view: SessionView) -> dict:
    """``poll`` response body (no board — results have their own route)."""
    out = {
        "session": view.sid,
        "state": view.state.value,
        "rule": view.rule,
        "steps": view.steps,
        "steps_done": view.steps_done,
        "progress": view.steps_done / view.steps if view.steps else 1.0,
        "finished": view.finished,
        "error": view.error,
    }
    # the replay record (docs/STOCHASTIC.md) — present only when the
    # session consumed the stochastic tier, so deterministic responses
    # keep their exact prior shape
    if view.seed is not None:
        out["seed"] = view.seed
    if view.temperature is not None:
        out["temperature"] = view.temperature
    # execution-path attribution (docs/OBSERVABILITY.md): stamped once a
    # stochastic session is admitted to an engine — True with a "lanes"
    # width on the bitplane-packed path, False on the int8 roll path
    if view.packed is not None:
        out["packed"] = view.packed
        if view.lanes is not None:
            out["lanes"] = view.lanes
    # the OOM fallback ladder's stamp (docs/SERVING.md "Resource
    # governance") — present only when the session's CompileKey degraded
    # to keep serving, so untouched sessions keep their exact prior shape
    if view.degraded_reason is not None:
        out["degraded_reason"] = view.degraded_reason
    # the distributed-trace id (docs/OBSERVABILITY.md): echoed whenever
    # the session carries one, so a client report names the exact trace
    if view.trace_id is not None:
        out["trace_id"] = view.trace_id
    # steering provenance (docs/STREAMING.md): the count of recorded
    # cell edits — present only when the session was steered, so
    # untouched sessions keep their exact prior response shape
    if view.edits:
        out["edits"] = view.edits
    # mega-board stamp (docs/SERVING.md "Mega-board sessions"): "RxC"
    # when the board runs sharded over a mesh slice — present only
    # there, so single-chip responses keep their exact prior shape
    if view.mesh is not None:
        out["mesh"] = view.mesh
    # tenant stamp (docs/SERVING.md "Tenant QoS"): the resolved tenant
    # this session was admitted under — present only when a QoS policy
    # resolved one, so policy-less responses keep their exact prior shape
    if view.tenant is not None:
        out["tenant"] = view.tenant
    return out


def render_result(board: np.ndarray, fmt: str, rule: str) -> dict:
    """Result payload in the requested encoding (``rle`` | ``raw``).

    Continuous-tier (float32) boards have no RLE form — ``raw`` is the
    byte-exact little-endian float32 contract encoding, stamped with a
    ``dtype`` field so clients (and ``decode_result``) know what the
    bytes are; asking a float board for ``rle`` is a typed 400.
    """
    h, w = board.shape
    out = {"format": fmt, "height": int(h), "width": int(w), "rule": rule}
    floating = np.issubdtype(board.dtype, np.floating)
    if fmt == "rle":
        if floating:
            raise bad_request(
                "invalid_format",
                "continuous (float32) boards have no RLE form; use "
                "format=raw",
            )
        states = max(2, int(board.max(initial=0)) + 1)
        try:
            states = get_rule(rule).states
        except (ValueError, KeyError):
            pass  # header follows board content for unregistered specs
        out["rle"] = emit_rle(board, rule=rule, states=states)
    elif fmt == "raw":
        out["b64"] = base64.b64encode(encode_board(board)).decode("ascii")
        if floating:
            out["dtype"] = "float32"
    else:
        raise bad_request(
            "invalid_format", f"format must be 'rle' or 'raw', got {fmt!r}"
        )
    return out


def decode_result(payload: dict) -> np.ndarray:
    """Client-side inverse of :func:`render_result` for ``raw`` payloads."""
    if payload.get("format") != "raw":
        raise ValueError(f"cannot decode format {payload.get('format')!r}")
    buf = base64.b64decode(payload["b64"])
    return decode_board(buf, int(payload["height"]), int(payload["width"]))


__all__ = [
    "ApiError",
    "MAX_BODY",
    "MAX_CELLS",
    "SubmitSpec",
    "decode_result",
    "parse_board",
    "parse_resume_board",
    "parse_trace_id",
    "parse_submit",
    "render_result",
    "render_view",
]
