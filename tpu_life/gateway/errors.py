"""Typed HTTP error mapping — the gateway's contract boundary.

Every failure a client can see is an :class:`ApiError`: an HTTP status, a
stable machine-readable ``code`` (the thing clients branch on — status
codes are too coarse: 503 is both "queue full, retry" and "draining, go
elsewhere"), a human message, and an optional ``Retry-After`` hint.  The
server serializes it as one JSON envelope::

    {"error": {"code": "rate_limited", "message": "..."}, "run_id": "..."}

``from_serve_error`` is the single place the serving layer's typed
exceptions (``tpu_life.serve.errors``) become HTTP semantics, so the
handler code never grows scattered ``except`` clauses with ad-hoc
status picks.
"""

from __future__ import annotations


class ApiError(Exception):
    """One client-visible failure: status + stable code + message.

    ``retry_after`` (seconds) becomes a ``Retry-After`` header when set —
    the backoff contract for 429/503 responses that
    :mod:`tpu_life.gateway.client` honors.
    """

    def __init__(
        self,
        status: int,
        code: str,
        message: str,
        *,
        retry_after: float | None = None,
        extra: dict | None = None,
    ):
        super().__init__(message)
        self.status = status
        self.code = code
        self.message = message
        self.retry_after = retry_after
        # machine-readable qualifiers beyond the code (e.g. the fleet's
        # worker_lost ``reason``) — merged into the error object so
        # clients branching on code can refine on them without parsing
        # the human message
        self.extra = extra or {}

    def body(self) -> dict:
        return {
            "error": {"code": self.code, "message": self.message, **self.extra}
        }


def fmt_retry_after(seconds: float) -> str:
    """``Retry-After`` is integer seconds; always at least 1 so a client
    that honors it literally cannot busy-spin."""
    return str(max(1, int(seconds + 0.999)))


def parse_retry_after(headers) -> float | None:
    """Inverse of :func:`fmt_retry_after` — the one place the header is
    read, shared by the client and the fleet router so the semantics
    (numeric seconds only, None on anything else) cannot drift."""
    v = headers.get("Retry-After") if headers is not None else None
    if v is None:
        return None
    try:
        return float(v)
    except ValueError:
        return None


def backoff_delay(
    attempt: int,
    *,
    base: float,
    cap: float,
    jitter: float = 0.25,
    rng=None,
) -> float:
    """The retry pause for the ``attempt``-th retry (1-based): exponential
    from ``base``, spread by bounded multiplicative ``jitter`` so N peers
    bounced together don't re-arrive in lockstep, clamped to ``cap``
    AFTER jittering (the cap is a hard bound callers size against
    deadlines; downward jitter still spreads it).  The one backoff
    formula the client, the migrator's resume retry, and the remote
    spill backend all share — an explicit ``Retry-After`` always wins
    over it, un-jittered."""
    import random

    wait = base * (2 ** (max(1, attempt) - 1))
    if jitter:
        wait *= 1.0 + (rng or random).uniform(-jitter, jitter)
    return min(cap, wait)


def bad_request(code: str, message: str) -> ApiError:
    return ApiError(400, code, message)


def not_found(message: str) -> ApiError:
    return ApiError(404, "not_found", message)


def method_not_allowed(method: str, path: str) -> ApiError:
    return ApiError(
        405, "method_not_allowed", f"{method} is not supported on {path}"
    )


def payload_too_large(length: int, limit: int) -> ApiError:
    return ApiError(
        413,
        "payload_too_large",
        f"request body is {length} bytes; the limit is {limit}",
    )


def rate_limited(retry_after: float) -> ApiError:
    return ApiError(
        429,
        "rate_limited",
        "request rate exceeds this API key's token bucket; slow down",
        retry_after=retry_after,
    )


def overloaded(depth: float, high_water: float, retry_after: float) -> ApiError:
    return ApiError(
        503,
        "overloaded",
        f"queue depth {depth:g} is past the shed threshold {high_water:g}; "
        f"the service is protecting in-flight sessions",
        retry_after=retry_after,
    )


def shed_best_effort(
    depth: float, water: float, retry_after: float, *, tenant: str
) -> ApiError:
    """The lower rung of the shed ladder (docs/SERVING.md "Tenant QoS"):
    a best-effort tenant turned away while guaranteed tenants still
    admit.  Retryable by contract — capacity may return, or the fleet
    may scale up — so the router treats it as a refusal like
    ``overloaded``."""
    return ApiError(
        503,
        "shed_best_effort",
        f"queue depth {depth:g} is past the best-effort shed threshold "
        f"{water:g}; best-effort tenant {tenant!r} is shed first so "
        f"guaranteed tenants keep admitting",
        retry_after=retry_after,
        extra={"tenant": tenant},
    )


def from_serve_error(e: Exception) -> ApiError:
    """Serving-layer exception -> HTTP semantics (the one mapping table)."""
    from tpu_life.serve.errors import (
        Draining,
        InsufficientMemory,
        QueueFull,
        QuotaExceeded,
        SessionFailed,
        UnknownSession,
    )

    if isinstance(e, QuotaExceeded):
        # the tenant's OWN declared ceiling (docs/SERVING.md "Tenant
        # QoS"), not service overload: 429 like the rate limiter, with
        # the arithmetic in the extra so clients see WHICH quota and
        # where the line is.  Retry-After is honest — the tenant's own
        # earlier work must retire before more admits.
        return ApiError(
            429,
            "quota_exceeded",
            str(e),
            retry_after=1.0,
            extra={"tenant": e.tenant, "quota": e.quota, "limit": e.limit},
        )

    if isinstance(e, InsufficientMemory):
        # the memory governor (docs/SERVING.md "Resource governance"):
        # transient pressure is a retryable 503 (other keys hold the
        # budget — come back after they drain); a session whose engine
        # can NEVER fit is a 413, not worth retrying.  One stable code
        # either way; the status and the `transient` flag carry the
        # retry semantics, the byte arithmetic rides in the extra.
        extra = {
            "transient": e.transient,
            "estimated_bytes": e.estimated_bytes,
            "budget_bytes": e.budget_bytes,
        }
        if e.transient:
            return ApiError(
                503, "insufficient_memory", str(e),
                retry_after=1.0, extra=extra,
            )
        # the never-fits 413 carries the mesh hint (docs/SERVING.md
        # "Mega-board sessions") so clients and the fleet router can
        # distinguish "resubmit to a mesh-capable fleet of >= min_devices
        # chips" from "hopeless"
        extra["mesh_eligible"] = bool(getattr(e, "mesh_eligible", False))
        if getattr(e, "min_devices", None) is not None:
            extra["min_devices"] = int(e.min_devices)
        return ApiError(413, "insufficient_memory", str(e), extra=extra)
    if isinstance(e, QueueFull):
        # backpressure: the bounded admission queue is the hard backstop
        # behind the shed threshold — same retry contract, same status
        return ApiError(503, "queue_full", str(e), retry_after=1.0)
    if isinstance(e, Draining):
        # a load-balanced client should retry against a peer, not wait here
        return ApiError(503, "draining", str(e), retry_after=1.0)
    if isinstance(e, UnknownSession):
        return ApiError(404, "unknown_session", str(e))
    if isinstance(e, SessionFailed):
        # terminal without a board (failed / cancelled): the session is
        # gone for good — 410, never retried
        return ApiError(410, "session_failed", str(e))
    from tpu_life.models.rules import GeometryError

    if isinstance(e, GeometryError):
        # kernel-vs-board geometry (docs/RULES.md): the service's
        # re-check of what parse_submit already fronts — same typed code
        return bad_request("radius_too_large", str(e))
    if isinstance(e, ValueError):
        # the service's board/steps validation speaks ValueError
        return bad_request("invalid_request", str(e))
    raise e
