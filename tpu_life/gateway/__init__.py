"""tpu_life.gateway: the HTTP front door in front of the serving core.

``tpu_life.serve`` made the repo an in-process inference stack; this
package gives it a network surface with the robustness a front door owes
the scheduler behind it: typed JSON errors, per-API-key token-bucket rate
limiting (429 + ``Retry-After``), queue-depth load shedding
(reject-before-enqueue), bounded request bodies, ``/healthz`` /
``/readyz`` / live ``/metrics``, and SIGTERM graceful drain — stop
admitting, finish in-flight sessions, flush telemetry, exit 0.

Dependency-free by design (stdlib ``http.server`` + threads): ONE
background pump thread owns all device work while handler threads call
the service's now-locked verbs, so the engine's one-compile-per-
CompileKey invariant holds under concurrent clients.

Quick start::

    from tpu_life.gateway import Gateway, GatewayConfig
    from tpu_life.serve import ServeConfig, SimulationService

    svc = SimulationService(ServeConfig(capacity=8, backend="jax"))
    gw = Gateway(svc, GatewayConfig(port=8000))
    gw.start()                      # listener + pump threads
    ...
    gw.begin_drain(); gw.wait(); gw.close()

    from tpu_life.gateway.client import GatewayClient
    c = GatewayClient("http://127.0.0.1:8000")
    sid = c.submit(size=256, steps=64)      # seeded board, no file needed
    c.wait(sid)
    board = c.result_board(sid)

See docs/GATEWAY.md for the API reference, and ``tpu-life gateway`` /
``tpu-life client`` for the CLI front-ends.
"""

from tpu_life.gateway.errors import ApiError
from tpu_life.gateway.limits import KeyedBuckets, LoadShedder, TokenBucket
from tpu_life.gateway.protocol import (
    MAX_BODY,
    MAX_CELLS,
    SubmitSpec,
    parse_submit,
    render_result,
    render_view,
)
from tpu_life.gateway.server import Gateway, GatewayConfig

__all__ = [
    "ApiError",
    "Gateway",
    "GatewayConfig",
    "KeyedBuckets",
    "LoadShedder",
    "MAX_BODY",
    "MAX_CELLS",
    "SubmitSpec",
    "TokenBucket",
    "parse_submit",
    "render_result",
    "render_view",
]
