"""The HTTP front door: admission control in front of the batch scheduler.

Dependency-free (stdlib ``http.server`` + threads), because the point is
the *shape*, not the framework: an inference-style serving stack is a
saturated continuous-batching core behind a traffic layer that admits,
sheds, and paces outside load (ISSUE: the Ising-on-TPU throughput story
only survives contact with real clients if overload turns into typed
429/503s instead of queue collapse).

Threading model — one pump, many handlers::

    handler threads (ThreadingHTTPServer, one per connection)
        │  submit / poll / result / cancel        (service verbs, locked)
        ▼
    SimulationService  ◄── ONE background pump thread (all device work)

The service's internal lock is the seam: handler threads only call the
verbs, the pump thread owns every scheduling round, so the engine's
one-compile-per-CompileKey invariant never meets concurrent device work.

Admission pipeline for ``POST /v1/sessions`` (cheapest rejection first)::

    draining? -> 503   rate limit -> 429+Retry-After   shed -> 503
    body bound -> 413   parse/validate -> typed 400s   QueueFull -> 503

Graceful drain (SIGTERM): admission closes (``/readyz`` flips to 503 so
load balancers stop routing here), in-flight sessions step to completion,
telemetry flushes (JSONL snapshot, prom file, trace), the process exits 0.
"""

from __future__ import annotations

import json
import signal
import threading
import time
from dataclasses import dataclass
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlsplit

from tpu_life import chaos
from tpu_life.gateway import errors as gw_errors
from tpu_life.gateway import protocol
from tpu_life.gateway.errors import ApiError, fmt_retry_after
from tpu_life.gateway.limits import KeyedBuckets, LoadShedder
from tpu_life.runtime.metrics import log
from tpu_life.serve.errors import Draining
from tpu_life.serve.service import SimulationService
from tpu_life.version import __version__

#: Routes get ONE bounded label each (metrics cardinality): the pattern,
#: never the concrete path (session ids are unbounded).
ROUTE_SESSIONS = "/v1/sessions"
ROUTE_SESSION = "/v1/sessions/{sid}"
ROUTE_RESULT = "/v1/sessions/{sid}/result"
#: Live-session verbs (docs/STREAMING.md): the chunked ndjson delta
#: stream and the mid-run cell-edit steering verb.
ROUTE_STREAM = "/v1/sessions/{sid}/stream"
ROUTE_CELLS = "/v1/sessions/{sid}/cells"
#: The trace drain verb (docs/OBSERVABILITY.md "Distributed tracing"):
#: each GET takes (and clears) the worker's buffered span + flight rings.
ROUTE_TRACE = "/v1/debug/trace"
#: The series scrape verb (docs/OBSERVABILITY.md "Time series"):
#: cursor-based, NON-destructive reads of the worker's bounded ring of
#: periodic metric snapshots — repeatable, unlike the trace drain.
ROUTE_SERIES = "/v1/debug/series"


@dataclass
class GatewayConfig:
    host: str = "127.0.0.1"
    port: int = 8000  # 0 = ephemeral (tests); the bound port is Gateway.port
    api_rate: float = 0.0  # token-bucket refill per API key, tokens/s (0 = off)
    api_burst: float = 10.0  # bucket capacity (max burst per key)
    # queue-depth high-water mark for load shedding; None derives 80% of
    # the service's bounded queue, 0 disables
    shed_high_water: float | None = None
    max_body: int = protocol.MAX_BODY  # request-body byte bound (413 past it)
    pump_idle_s: float = 0.01  # pump-thread nap when no session is live
    # tenant QoS (docs/SERVING.md "Tenant QoS"): usually the SAME policy
    # object as the service's ``ServeConfig.qos`` — identity resolution
    # and the tiered shed ladder run here at the front door, quotas and
    # DRR in the service.  None keeps the gateway tenant-blind.
    qos: object | None = None


class Gateway:
    """Owns the HTTP server, the pump thread, and the admission valves.

    The service's registry is shared: gateway families (per-route request
    counters, latency histograms, shed/rate-limit counters) land next to
    the serve families, so ``GET /metrics`` — and the service's own
    ``prom_file`` / JSONL snapshot — expose one coherent instrument set.
    """

    def __init__(self, service: SimulationService, config: GatewayConfig | None = None):
        self.service = service
        self.config = config or GatewayConfig()
        registry = service.registry
        self._c_requests = registry.counter(
            "gateway_requests_total",
            "HTTP requests by route / method / status",
            labels=("route", "method", "status"),
        )
        self._h_latency = registry.histogram(
            "gateway_request_seconds",
            "wall seconds per HTTP request",
            labels=("route",),
        )
        self._c_limited = registry.counter(
            "gateway_rate_limited_total",
            "submissions bounced by the per-key token bucket (429)",
        )
        self._c_shed = registry.counter(
            "gateway_shed_total",
            "submissions shed at the queue-depth high-water mark (503)",
        )
        self._c_limited.labels()
        self._c_shed.labels()
        # resolved device count of this worker's backend (docs/FLEET.md
        # "Device placement"): set on first resolution, so it lands in
        # /metrics, the prom snapshot, AND the final JSONL registry
        # snapshot — which is how `tpu-life stats` sums a fleet's
        # aggregate device count from the per-worker sinks
        self._g_devices = registry.gauge(
            "serve_devices", "devices visible to this worker's backend"
        )
        self._device_info: tuple[int, str] | None = None
        self._device_thread: threading.Thread | None = None
        self.buckets = KeyedBuckets(self.config.api_rate, self.config.api_burst)
        high_water = self.config.shed_high_water
        if high_water is None:
            high_water = 0.8 * service.config.max_queue
        # registration is idempotent, so this is the SAME gauge family the
        # service sets every scheduling round — the obs queue-depth signal
        # is the shed input, exactly as a Prometheus alert would read it
        depth_gauge = registry.gauge("serve_queue_depth")
        self.shedder = LoadShedder(lambda: depth_gauge.value, high_water)
        # the shed ladder's lower rung (docs/SERVING.md "Tenant QoS"):
        # best-effort tenants shed at a fraction of the high-water mark,
        # so overload degrades the free tier before any guaranteed
        # tenant feels it.  Policy-less gateways never build the rung.
        self.qos = self.config.qos or getattr(service.config, "qos", None)
        self.shedder_soft: LoadShedder | None = None
        self._c_tenant_shed = None
        if self.qos is not None:
            self.shedder_soft = LoadShedder(
                lambda: depth_gauge.value,
                self.qos.best_effort_water * high_water,
            )
            # the service registers this family first (idempotent): the
            # gateway's front-door sheds land next to the service's
            # quota rejections in one per-tenant counter
            self._c_tenant_shed = registry.counter(
                "tenant_shed_total",
                "typed per-tenant sheds and quota rejections by reason "
                "(quota_sessions / quota_bytes / quota_watchers / "
                "shed_best_effort)",
                labels=("tenant", "reason"),
            )
        self._server = _GatewayHTTPServer(
            (self.config.host, self.config.port), _Handler
        )
        self._server.gateway = self
        self.host, self.port = self._server.server_address[:2]
        self._wake = threading.Event()
        self._drained = threading.Event()
        self._pump_thread: threading.Thread | None = None
        self._serve_thread: threading.Thread | None = None
        self._closed = False
        self.pump_error: Exception | None = None  # set by a pump crash

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        """Start the HTTP listener thread and the single pump thread."""
        self._pump_thread = threading.Thread(
            target=self._pump_loop, name="gateway-pump", daemon=True
        )
        self._serve_thread = threading.Thread(
            target=self._server.serve_forever,
            kwargs={"poll_interval": 0.05},
            name="gateway-http",
            daemon=True,
        )
        self._pump_thread.start()
        self._serve_thread.start()
        self._device_thread = threading.Thread(
            target=self._resolve_devices, name="gateway-devices", daemon=True
        )
        self._device_thread.start()
        log.info(
            "gateway listening on http://%s:%d (run_id=%s)",
            self.host,
            self.port,
            self.service.run_id,
        )

    def begin_drain(self) -> None:
        """Stop admitting (``/readyz`` -> 503), finish in-flight sessions,
        then stop the listener.  Idempotent; returns immediately — callers
        block on :meth:`wait`."""
        self.service.begin_drain()
        self._wake.set()

    def wait(self, timeout: float | None = None) -> bool:
        """Block until the drain completed and the listener stopped.
        Joins in small slices so OS signals still reach the main thread."""
        threads = [t for t in (self._pump_thread, self._serve_thread) if t]
        deadline = None if timeout is None else _monotonic() + timeout
        for t in threads:
            while t.is_alive():
                t.join(0.1)
                if deadline is not None and _monotonic() > deadline:
                    return False
        return True

    def close(self) -> None:
        """Release the socket and flush the service's telemetry."""
        if self._closed:
            return
        self._closed = True
        if self._serve_thread is not None:
            # shutdown() blocks on serve_forever's exit handshake, so it is
            # only safe once the listener thread actually ran
            self._server.shutdown()
        self._server.server_close()
        self.service.close()

    def install_signal_handlers(self) -> None:
        """SIGTERM / SIGINT -> graceful drain (main thread only)."""

        def _drain(signum, frame):
            log.info("gateway: signal %d — draining", signum)
            self.begin_drain()

        signal.signal(signal.SIGTERM, _drain)
        signal.signal(signal.SIGINT, _drain)

    # -- the one pump ------------------------------------------------------
    def _pump_loop(self) -> None:
        """All device work lives here.  Runs rounds while sessions are
        live, naps (wakeable by submits) when idle, and exits — shutting
        the listener down — once draining AND idle."""
        svc = self.service
        while True:
            # sample draining BEFORE idle: once admission is closed, a
            # submit can no longer slip in behind an idle() observation —
            # sampled the other way around, a session admitted between the
            # two reads would be stranded at shutdown
            draining = svc.draining
            if svc.idle():
                if draining:
                    # flush before reporting empty: a chunk whose sessions
                    # were all cancelled mid-pipeline is still executing,
                    # and a drain that abandons it would race device work
                    # against interpreter teardown
                    svc.flush()
                    break
                self._wake.wait(self.config.pump_idle_s)
                self._wake.clear()
            else:
                # chaos seams (docs/CHAOS.md): a worker that dies without
                # warning (SIGKILL-grade — os._exit, no drain, no flush)
                # and one that wedges mid-round.  Both fire from the pump
                # loop because that is where a real worker death hurts:
                # sessions mid-flight, spills mid-cadence, sockets open.
                chaos.crash("worker.crash")
                hang = chaos.delay("worker.hang")
                if hang > 0:
                    log.warning("chaos: pump hanging %.1fs (worker.hang)", hang)
                    time.sleep(hang)
                try:
                    svc.pump()
                except Exception as e:
                    # a pump crash must not impersonate a healthy drain:
                    # log it, remember it (the CLI exits non-zero and the
                    # summary carries it), and shut down — a stepping-dead
                    # gateway that kept answering polls would only strand
                    # its clients more slowly.  The flight ring gets the
                    # verdict first, so the LAST capture (scrape or the
                    # close-time dump) names the cause of death.
                    from tpu_life import obs

                    obs.flight.record(
                        "pump_crash", error=f"{type(e).__name__}: {e}"
                    )
                    log.exception("gateway: pump thread crashed")
                    self.pump_error = e
                    break
        self._drained.set()
        self._server.shutdown()

    def device_info(self, wait_s: float = 0.0) -> tuple[int, str] | None:
        """``(devices, kind)`` this worker's backend resolved, or None
        while resolution is still in flight — what the startup line and
        ``/readyz`` report to a fleet supervisor.

        Resolution runs on a BACKGROUND thread kicked off by
        :meth:`start`: the first device query can take minutes on a
        slow accelerator attach (and 180 s on a wedged plugin), and
        blocking the startup line or a readiness probe on it would get
        the worker killed by its supervisor's startup timeout — the
        exact worker this seam exists to place.  Callers that can
        afford a bounded wait (the CLI's startup line) pass ``wait_s``;
        probes pass 0 and report the fields once they exist.
        """
        t = self._device_thread
        if self._device_info is None and t is not None and wait_s > 0:
            t.join(wait_s)
        return self._device_info

    def _resolve_devices(self) -> None:
        from tpu_life.utils.platform import device_info

        info = device_info()
        self._g_devices.set(float(info[0]))
        self._device_info = info

    def wake(self) -> None:
        self._wake.set()

    @property
    def drained(self) -> bool:
        return self._drained.is_set()


class _GatewayHTTPServer(ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True
    gateway: Gateway  # attached right after construction


class JsonHandler(BaseHTTPRequestHandler):
    """Shared envelope plumbing for the repo's JSON HTTP fronts — the
    gateway and the fleet router speak the same wire envelope, and the
    Content-Length / 411 / 413 hygiene must not diverge between them."""

    protocol_version = "HTTP/1.1"
    log_tag = "http"

    def log_message(self, fmt, *args):  # noqa: N802 (stdlib name)
        log.debug("%s: %s %s", self.log_tag, self.address_string(), fmt % args)

    def _send_json(
        self, status: int, body: dict, *, retry_after: float | None = None
    ) -> None:
        payload = (json.dumps(body) + "\n").encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(payload)))
        if retry_after is not None:
            self.send_header("Retry-After", fmt_retry_after(retry_after))
        self.end_headers()
        self.wfile.write(payload)

    def _send_text(self, status: int, text: str, content_type: str) -> None:
        payload = text.encode()
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def _read_sized_body(self, limit: int) -> bytes:
        """The raw request body, bounded BEFORE it is read (411/400/413)."""
        length = self.headers.get("Content-Length")
        if length is None:
            self.close_connection = True
            raise ApiError(411, "length_required", "Content-Length is required")
        try:
            n = int(length)
        except ValueError:
            self.close_connection = True
            raise ApiError(
                400, "invalid_request", f"bad Content-Length {length!r}"
            ) from None
        if n > limit:
            # the body is rejected UNREAD, so this keep-alive stream now
            # holds n bytes the next request parser would misread as a
            # request line — close instead of desyncing
            self.close_connection = True
            raise gw_errors.payload_too_large(n, limit)
        return self.rfile.read(n)


class _Handler(JsonHandler):
    server_version = f"tpu-life-gateway/{__version__}"
    log_tag = "gateway"

    @property
    def gw(self) -> Gateway:
        return self.server.gateway  # type: ignore[attr-defined]

    def _send_json(
        self, status: int, body: dict, *, retry_after: float | None = None
    ) -> None:
        body = dict(body)
        # every response carries the service's correlation id: a client
        # report ("session X was slow") joins the JSONL sink, the prom
        # snapshot and the trace file on one key
        body.setdefault("run_id", self.gw.service.run_id)
        super()._send_json(status, body, retry_after=retry_after)

    def _read_body(self) -> dict:
        raw = self._read_sized_body(self.gw.config.max_body)
        try:
            return json.loads(raw)
        except json.JSONDecodeError as e:
            raise gw_errors.bad_request(
                "invalid_json", f"request body is not valid JSON: {e}"
            ) from None

    # -- dispatch ----------------------------------------------------------
    def do_GET(self):  # noqa: N802
        self._dispatch("GET")

    def do_POST(self):  # noqa: N802
        self._dispatch("POST")

    def do_DELETE(self):  # noqa: N802
        self._dispatch("DELETE")

    def do_PATCH(self):  # noqa: N802
        self._dispatch("PATCH")

    def _dispatch(self, method: str) -> None:
        parts = urlsplit(self.path)
        path = parts.path.rstrip("/") or "/"
        # unrouted paths share ONE label: recording the raw path would let
        # any scanner mint unbounded series in the shared registry
        route, status = "unmatched", 500
        t0 = _monotonic()
        try:
            route, handler, kwargs = self._route(method, path, parts.query)
            status = handler(**kwargs)
        except ApiError as e:
            status = e.status
            try:
                self._send_json(e.status, e.body(), retry_after=e.retry_after)
            except (BrokenPipeError, ConnectionResetError):
                pass
        except (BrokenPipeError, ConnectionResetError):
            status = 499  # client went away mid-response (nginx's code)
        except Exception:
            log.exception("gateway: %s %s failed", method, path)
            status = 500
            try:
                self._send_json(
                    500,
                    {"error": {"code": "internal", "message": "internal error"}},
                )
            except (BrokenPipeError, ConnectionResetError):
                pass
        finally:
            gw = self.gw
            gw._c_requests.labels(
                route=route, method=method, status=str(status)
            ).inc()
            gw._h_latency.labels(route=route).observe(_monotonic() - t0)

    def _route(self, method: str, path: str, query: str):
        """(route label, bound handler, kwargs) — 404/405 raise here."""
        if path == "/healthz":
            if method != "GET":
                raise gw_errors.method_not_allowed(method, path)
            return "/healthz", self._healthz, {}
        if path == "/readyz":
            if method != "GET":
                raise gw_errors.method_not_allowed(method, path)
            return "/readyz", self._readyz, {}
        if path == "/metrics":
            if method != "GET":
                raise gw_errors.method_not_allowed(method, path)
            return "/metrics", self._metrics, {}
        if path == ROUTE_TRACE:
            if method != "GET":
                raise gw_errors.method_not_allowed(method, path)
            return ROUTE_TRACE, self._debug_trace, {}
        if path == ROUTE_SERIES:
            if method != "GET":
                raise gw_errors.method_not_allowed(method, path)
            raw = parse_qs(query).get("cursor", ["0"])[0]
            try:
                cursor = int(raw)
            except ValueError:
                raise gw_errors.bad_request(
                    "invalid_request", f"bad cursor {raw!r}"
                ) from None
            if cursor < 0:
                raise gw_errors.bad_request(
                    "invalid_request", "'cursor' must be >= 0"
                )
            return ROUTE_SERIES, self._debug_series, {"cursor": cursor}
        if path == ROUTE_SESSIONS:
            if method != "POST":
                raise gw_errors.method_not_allowed(method, path)
            return ROUTE_SESSIONS, self._create, {}
        if path.startswith(ROUTE_SESSIONS + "/"):
            rest = path[len(ROUTE_SESSIONS) + 1 :]
            if "/" not in rest:
                sid = rest
                if method == "GET":
                    return ROUTE_SESSION, self._poll, {"sid": sid}
                if method == "DELETE":
                    return ROUTE_SESSION, self._cancel, {"sid": sid}
                raise gw_errors.method_not_allowed(method, path)
            sid, _, tail = rest.partition("/")
            if tail == "result":
                if method != "GET":
                    raise gw_errors.method_not_allowed(method, path)
                fmt = parse_qs(query).get("format", ["rle"])[0]
                return ROUTE_RESULT, self._result, {"sid": sid, "fmt": fmt}
            if tail == "stream":
                if method != "GET":
                    raise gw_errors.method_not_allowed(method, path)
                raw = parse_qs(query).get("cursor", ["0"])[0]
                try:
                    cursor = int(raw)
                except ValueError:
                    raise gw_errors.bad_request(
                        "invalid_request", f"bad cursor {raw!r}"
                    ) from None
                if cursor < 0:
                    raise gw_errors.bad_request(
                        "invalid_request", "'cursor' must be >= 0"
                    )
                return ROUTE_STREAM, self._stream, {"sid": sid, "cursor": cursor}
            if tail == "cells":
                if method != "PATCH":
                    raise gw_errors.method_not_allowed(method, path)
                return ROUTE_CELLS, self._edit_cells, {"sid": sid}
        raise gw_errors.not_found(f"no route for {path}")

    # -- handlers (each returns the status it sent) ------------------------
    def _healthz(self) -> int:
        # liveness: the process is up and dispatching — true even while
        # draining (readiness is the signal that flips)
        self._send_json(200, {"status": "ok"})
        return 200

    def _readyz(self) -> int:
        # chaos seam: a worker that refuses its readiness probe while
        # alive and stepping — the supervisor's unready-recycle path.
        # 500 (not 503): the probe must read "unreachable", never the
        # graceful "draining" a real 503 means.
        if chaos.decide("worker.unready") is not None:
            chaos.record_fire("worker.unready", "refuse")
            raise ApiError(500, "chaos_unready", "chaos: injected unready probe")
        svc = self.gw.service
        wedged = getattr(svc, "wedged", None)
        if wedged is not None:
            # the wedge watchdog tripped (docs/SERVING.md "Resource
            # governance"): a settle window outlived its deadline.  500
            # with the machine-readable verdict — a supervisor probe
            # reads "unreachable" (never the graceful "draining") and
            # its unready-recycle + migration path rescues the sessions.
            raise ApiError(
                500,
                "engine_wedged",
                f"a device settle blocked past "
                f"{wedged.get('deadline_s')}s; this worker must be "
                f"recycled",
                extra=wedged,
            )
        if svc.draining:
            self._send_json(
                503,
                {
                    "ready": False,
                    "draining": True,
                    # the probe's yes/no plus the standard envelope, so a
                    # client library reports "draining", not a bare 503
                    "error": {"code": "draining", "message": "service is draining"},
                },
                retry_after=1.0,
            )
            return 503
        body = {"ready": True, "draining": False}
        info = self.gw.device_info()  # non-blocking: None while resolving
        if info is not None:
            # capacity feedback for a fleet supervisor: what THIS
            # worker's backend resolved (docs/FLEET.md placement).  The
            # fields appear once resolution lands — readiness must never
            # block behind a slow (or wedged) accelerator attach.
            body["devices"], body["device_kind"] = info
        self._send_json(200, body)
        return 200

    def _metrics(self) -> int:
        # live Prometheus text straight off the shared registry — the same
        # renderer --prom-file snapshots, now scrapeable over HTTP
        text = self.gw.service.registry.prom_text()
        self._send_text(200, text, "text/plain; version=0.0.4")
        return 200

    def _debug_trace(self) -> int:
        # the fleet trace-collection seam (docs/OBSERVABILITY.md): drain
        # this worker's buffered span + flight events to the scraper.
        # Destructive by design — each scrape is an increment, so the
        # supervisor's per-tick collection never duplicates an event.
        self._send_json(200, self.gw.service.drain_trace())
        return 200

    def _debug_series(self, cursor: int) -> int:
        # the fleet series-scrape seam (docs/OBSERVABILITY.md "Time
        # series"): snapshots with seq >= cursor off the worker's bounded
        # ring.  Non-destructive — the SCRAPER owns the cursor, so a
        # replayed or concurrent scrape reads the same snapshots.
        self._send_json(200, self.gw.service.read_series(cursor))
        return 200

    def _create(self) -> int:
        gw = self.gw
        svc = gw.service
        if svc.draining:
            raise gw_errors.from_serve_error(
                Draining("service is draining: no new sessions are admitted")
            )
        api_key = self.headers.get("X-API-Key", "anonymous")
        wait = gw.buckets.acquire(api_key)
        if wait > 0:
            gw._c_limited.inc()
            raise gw_errors.rate_limited(wait)
        # tenant identity (docs/SERVING.md "Tenant QoS"): the API key
        # resolves to a named tenant once, here — the name then rides
        # submit -> session -> view as a typed field
        tenant = None
        tenant_spec = None
        if gw.qos is not None:
            tenant_spec = gw.qos.resolve(api_key)
            tenant = tenant_spec.name
        # the shed ladder: best-effort tenants meet the lower rung
        # first, so guaranteed tenants only ever see the full high-water
        # shed (and an autoscaling fleet gets the reaction window the
        # lower rung buys)
        if (
            gw.shedder_soft is not None
            and tenant_spec is not None
            and not tenant_spec.guaranteed
        ):
            shed = gw.shedder_soft.check()
            if shed is not None:
                gw._c_shed.inc()
                gw._c_tenant_shed.labels(
                    tenant=tenant_spec.label, reason="shed_best_effort"
                ).inc()
                raise gw_errors.shed_best_effort(
                    shed[0],
                    gw.shedder_soft.high_water,
                    shed[1],
                    tenant=tenant,
                )
        shed = gw.shedder.check()
        if shed is not None:
            gw._c_shed.inc()
            raise gw_errors.overloaded(shed[0], gw.shedder.high_water, shed[1])
        spec = protocol.parse_submit(self._read_body())
        # distributed-trace context (docs/OBSERVABILITY.md): the header
        # (what the fleet router forwards) wins over the body field (what
        # a resume request carries); with neither, the gateway mints one
        # — every HTTP-submitted session has a journey id from birth
        trace_id = protocol.parse_trace_id(self.headers.get("X-Trace-Id"))
        if trace_id is None:
            trace_id = spec.trace_id
        if trace_id is None:
            from tpu_life import obs

            trace_id = obs.new_trace_id()
        try:
            sid = svc.submit(
                spec.board,
                spec.rule,
                spec.steps,
                timeout_s=spec.timeout_s,
                seed=spec.seed,
                temperature=spec.temperature,
                start_step=spec.start_step,
                trace_id=trace_id,
                edits=spec.edits,
                scheduled_edits=spec.scheduled_edits,
                stream_seq=spec.stream_seq,
                mesh_resume_dir=spec.resume_tiles_dir,
                tenant=tenant,
            )
        except Exception as e:  # typed serve errors -> typed HTTP
            raise gw_errors.from_serve_error(e) from e
        gw.wake()  # the pump may be napping — new work just arrived
        view = svc.poll(sid)
        body = protocol.render_view(view)
        self._send_json(201, body)
        return 201

    def _poll(self, sid: str) -> int:
        try:
            view = self.gw.service.poll(sid)
        except Exception as e:
            raise gw_errors.from_serve_error(e) from e
        self._send_json(200, protocol.render_view(view))
        return 200

    def _result(self, sid: str, fmt: str) -> int:
        svc = self.gw.service
        try:
            view = svc.poll(sid)
        except Exception as e:
            raise gw_errors.from_serve_error(e) from e
        if not view.finished:
            raise ApiError(
                409,
                "not_finished",
                f"session {sid} is {view.state.value} "
                f"({view.steps_done}/{view.steps} steps); poll until done",
                retry_after=0.1,
            )
        try:
            board = svc.result(sid)
        except Exception as e:
            raise gw_errors.from_serve_error(e) from e
        body = protocol.render_result(board, fmt, view.rule)
        body["session"] = sid
        self._send_json(200, body)
        return 200

    def _stream(self, sid: str, cursor: int) -> int:
        """``GET /v1/sessions/{sid}/stream`` — the chunked ndjson delta
        stream (docs/STREAMING.md).  Subscribe is the admission point
        (404 unknown, 503 when the governor refuses the watcher
        buffer); after the 200 header the connection belongs to the
        frame grammar until ``end`` (or a ``stream.reset`` chaos drop).
        The handler thread only ever blocks on the hub's condition —
        never on the service lock — so a slow reader cannot stall the
        pump."""
        svc = self.gw.service
        try:
            svc.stream_subscribe(sid, cursor=cursor)
        except Exception as e:
            raise gw_errors.from_serve_error(e) from e
        try:
            self.send_response(200)
            self.send_header("Content-Type", "application/x-ndjson")
            # chunkless streaming: no Content-Length, so the connection
            # cannot be reused — say so and mean it
            self.send_header("Connection", "close")
            self.end_headers()
            self.close_connection = True
            while True:
                frames, cursor, eof = svc.stream_read(sid, cursor, timeout=0.25)
                for frame in frames:
                    line = (json.dumps(frame) + "\n").encode()
                    if chaos.decide("stream.reset") is not None:
                        # mid-FRAME connection drop: half a line, then a
                        # hard close — the client's resync path, not its
                        # happy path, is what this exercises
                        chaos.record_fire("stream.reset", "reset")
                        self.wfile.write(line[: max(1, len(line) // 2)])
                        self.wfile.flush()
                        raise BrokenPipeError("chaos: stream.reset")
                    self.wfile.write(line)
                if frames:
                    self.wfile.flush()
                if eof:
                    break
                if not frames and self.gw.drained:
                    # the pump exited (drain or crash): no frame will
                    # ever arrive again — release the watcher instead of
                    # spinning on an empty ring
                    break
            return 200
        finally:
            svc.stream_unsubscribe(sid)

    def _edit_cells(self, sid: str) -> int:
        """``PATCH /v1/sessions/{sid}/cells`` — mid-run steering
        (docs/STREAMING.md): a validated cell mask applied between
        chunks via the freeze-mask seam and recorded in the session's
        edit log."""
        gw = self.gw
        svc = gw.service
        body = self._read_body()
        if not isinstance(body, dict):
            raise gw_errors.bad_request(
                "invalid_request", "request body must be a JSON object"
            )
        cells = body.get("cells")
        if not isinstance(cells, list):
            raise gw_errors.bad_request(
                "invalid_request",
                "'cells' must be a list of [row, col, value] triples",
            )
        try:
            view = svc.edit_cells(sid, cells)
        except Exception as e:
            raise gw_errors.from_serve_error(e) from e
        gw.wake()  # the pump may be napping — the edit needs a round
        self._send_json(200, protocol.render_view(view))
        return 200

    def _cancel(self, sid: str) -> int:
        svc = self.gw.service
        try:
            stopped = svc.cancel(sid)
            view = svc.poll(sid)
        except Exception as e:
            raise gw_errors.from_serve_error(e) from e
        self._send_json(
            200,
            {"session": sid, "cancelled": stopped, "state": view.state.value},
        )
        return 200


def _monotonic() -> float:
    return time.monotonic()
