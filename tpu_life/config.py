"""Run configuration: the reference's 3-int config file plus a real flag system.

The reference's entire config surface is ``grid_size_data.txt`` = ``h w
epochs`` read by every rank, with hard-coded filenames and zero CLI arguments
(Parallel_Life_MPI.cpp:201-209, :63, :166).  That file remains the default
source of truth (bit-compat mode); everything else is a flag that overrides
it (SURVEY.md §5 "Config / flag system").
"""

from __future__ import annotations

from dataclasses import dataclass
from pathlib import Path

from tpu_life.io.codec import read_config


@dataclass
class RunConfig:
    # board geometry + steps; None -> taken from config_file
    height: int | None = None
    width: int | None = None
    steps: int | None = None

    # I/O contract files (reference defaults: Parallel_Life_MPI.cpp:63, :201, :170)
    config_file: str = "grid_size_data.txt"
    input_file: str = "data.txt"
    output_file: str = "output.txt"

    # rule + semantics
    rule: str = "conway"
    bug_compat: bool = False  # replicate the shipped binary's effective B/S2 rule
    # stochastic tier (tpu_life.mc, docs/STOCHASTIC.md): the counter-based
    # PRNG seed — names the whole trajectory for stochastic rules AND the
    # staged board for seeded exploratory runs (stamped into RunResult so
    # every run is replayable from its telemetry record)
    seed: int = 0
    # per-run Metropolis temperature; required by (and only valid for) the
    # ising rule
    temperature: float | None = None

    # execution
    # "tuned" resolves backend + perf knobs through tpu_life.autotune
    # (cache hit -> tuned config; miss -> analytic cost model / measured
    # search per tune_mode below)
    backend: str = "auto"  # auto | tuned | numpy | native | jax | sharded | stripes | mpi | pallas
    # autotune resolution mode for backend="tuned": "off" = cost model only
    # (no cache I/O), "cache" = cache hit else cost model (never measures),
    # "measure" = cache hit else run the measured search now and persist it
    tune_mode: str = "cache"  # off | cache | measure
    num_devices: int | None = None
    mesh_shape: tuple[int, int] | None = None  # 2-D rows x cols mesh (sharded)
    # CA steps per halo exchange / HBM pass (deep halos); None keeps each
    # backend's own default (sharded: 1, pallas: 8)
    block_steps: int | None = None
    partition_mode: str = "shard_map"  # shard_map | gspmd
    # per-shard stepper of the sharded backend: the Pallas deep-halo stripe
    # kernel (single-chip-fast) or the XLA bitlife/stencil scan.  auto =
    # Pallas on TPU 1-D packed meshes, XLA everywhere else
    local_kernel: str = "auto"  # auto | xla | pallas
    sync_every: int = 0  # steps per host sync chunk; 0 = one fused run
    # per-shard streaming file I/O (sharded backend, 1-D mesh): the board is
    # never materialized whole on one host.  None = auto (on for big boards)
    stream_io: bool | None = None
    pad_lanes: bool = True  # pad width to the 128-lane TPU tile
    bitpack: bool = True  # bit-sliced fast path for life-like rules
    # the neighborhood-counting path (docs/RULES.md): "roll" shift-adds,
    # "matmul" banded matmuls (bit-identical for integer rules; the MXU
    # path for large radii and the continuous tier), "auto" = the
    # crossover model (ops.conv.resolve_stencil; numpy stays the roll
    # oracle, and --backend tuned consults the measured cache axis)
    stencil: str = "auto"  # auto | roll | matmul

    # aux subsystems
    snapshot_every: int = 0
    snapshot_dir: str = "snapshots"
    # retention: keep only the newest N snapshots (0 = keep all); pruning
    # happens after each successful snapshot publish
    keep_snapshots: int = 0
    resume: str | None = None
    # elastic recovery: on a recoverable device failure mid-run (RuntimeError
    # from a blocked step — preemption, device loss), rebuild the backend and
    # resume from the newest snapshot (or the original input when none exists
    # yet), at most this many times.  0 = fail fast (the reference's model:
    # any rank failure kills the job, SURVEY.md §5)
    max_restarts: int = 0
    # fault injection drill: raise a simulated device failure when the fused
    # loop crosses this absolute step, fault_count times in a row (recovery
    # rewinds below fault_at, so the drill re-fires until spent — the
    # multi-failure / budget-exhaustion path).  0 = off
    fault_at: int = 0
    fault_count: int = 1
    # seconds to wait before each recovery attempt — a real device loss can
    # take a while to clear; 0 keeps drills and tests instant
    restart_wait_s: float = 0.0
    profile: str | None = None  # jax.profiler trace directory
    # Chrome trace-event JSON file (Perfetto-loadable): host-phase spans —
    # config-resolve, compile, staging, each host-sync chunk, snapshots,
    # recovery — stamped with the run's correlation id (docs/OBSERVABILITY.md)
    trace_events: str | None = None
    verbose: bool = False
    metrics: bool = False  # per-chunk live-cell counts + throughput
    # append each metrics record as a JSON line here (implies metrics)
    metrics_file: str | None = None

    def resolved_geometry(self) -> tuple[int, int, int]:
        """(height, width, steps), reading the config file for any None."""
        h, w, s = self.height, self.width, self.steps
        if h is None or w is None or s is None:
            if not Path(self.config_file).exists():
                raise FileNotFoundError(
                    f"config file {self.config_file!r} not found and geometry "
                    f"not fully specified by flags"
                )
            fh, fw, fs = read_config(self.config_file)
            h = fh if h is None else h
            w = fw if w is None else w
            s = fs if s is None else s
        return h, w, s

    def effective_rule(self) -> str:
        return "reference_bug_compat" if self.bug_compat else self.rule
