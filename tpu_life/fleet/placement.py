"""Per-worker device placement: disjoint device slices as env overlays.

The MPMD seam (docs/FLEET.md "Device placement"): every fleet worker is an
independently-compiled gateway process, so giving each one its OWN device
subset turns ``--workers N`` on a multi-chip host from "N claimants
fighting over the same chips" into N single-owner programs behind the
thin router — the many-workers-one-coordinator shape the TPU-cluster
Ising work scales by.

The planner never touches jax (the fleet front tier stays jax-free): a
placement is just an **environment overlay** the supervisor applies when
spawning the worker — the worker's own jax init resolves it, and the
worker reports what it actually got back through its startup line and
``/readyz`` (the capacity-feedback half, ``fleet.balancer``).

Overlay semantics by platform kind:

=========  ====================================================  =========
kind       overlay                                               disjoint?
=========  ====================================================  =========
``cpu``    ``JAX_PLATFORMS=cpu`` +                               synthetic
           ``XLA_FLAGS=--xla_force_host_platform_device_count=K``
``tpu``    ``JAX_PLATFORMS=tpu`` + ``TPU_VISIBLE_DEVICES=i,...``  real ids
``gpu``    ``JAX_PLATFORMS=cuda`` + ``CUDA_VISIBLE_DEVICES=...``  real ids
=========  ====================================================  =========

CPU placement forces K *host* devices per worker (XLA's fake-device
platform) — there is nothing to collide on, so any K per worker is
valid and the whole multi-"chip" seam is testable on CPU CI.  TPU/GPU
placement slices real integer device ids ``0..total_devices-1`` into
disjoint contiguous runs, worker order; an explicit per-worker request
that oversubscribes the host is a :class:`PlacementError` at PLAN time —
before any process is spawned — because respawning into the same bad env
can never succeed (the fail-fast contract ``fleet --max-restarts``
relies on).
"""

from __future__ import annotations

from dataclasses import dataclass

#: The XLA flag that fakes K host devices on the CPU platform — the knob
#: that makes multi-"chip" placement fully testable on CPU CI.
HOST_DEVICE_FLAG = "--xla_force_host_platform_device_count"

#: Platform kind -> (JAX_PLATFORMS value, visible-device env var).  CPU is
#: special-cased (synthetic devices via XLA_FLAGS, no visibility var).
_ACCEL_ENV = {
    "tpu": ("tpu", "TPU_VISIBLE_DEVICES"),
    "gpu": ("cuda", "CUDA_VISIBLE_DEVICES"),
    "cuda": ("cuda", "CUDA_VISIBLE_DEVICES"),
}


class PlacementError(ValueError):
    """A device-placement plan that can never come up healthy: wrong
    worker/device arithmetic, an oversubscribed host, or an unknown
    platform kind.  Raised at PLAN time (fleet construction) so the
    supervisor never burns its restart budget respawning a worker into
    an env that is deterministically broken."""


@dataclass(frozen=True)
class Placement:
    """One worker's planned slice: how many devices, which kind, which
    concrete ids (None for CPU's synthetic host devices), and the env
    overlay that realizes it in the spawned process."""

    worker: str
    devices: int
    kind: str
    device_ids: tuple[int, ...] | None
    env: dict


def parse_devices_per_worker(spec: str | None, workers: int) -> tuple[int, ...] | None:
    """``--devices-per-worker`` parser: ``"4"`` = 4 for every worker,
    ``"1,4"`` = per-worker counts (length must equal ``workers``)."""
    if spec is None:
        return None
    try:
        counts = tuple(int(part) for part in str(spec).split(","))
    except ValueError:
        raise PlacementError(
            f"--devices-per-worker must be an int or comma list, got {spec!r}"
        ) from None
    if any(c < 1 for c in counts):
        raise PlacementError(
            f"every per-worker device count must be >= 1, got {spec!r}"
        )
    if len(counts) == 1:
        return counts * workers
    if len(counts) != workers:
        raise PlacementError(
            f"--devices-per-worker lists {len(counts)} counts for "
            f"{workers} workers (give one count, or exactly one per worker)"
        )
    return counts


def plan_placements(
    workers: int,
    *,
    platform: str = "cpu",
    devices_per_worker: tuple[int, ...] | None = None,
    total_devices: int | None = None,
) -> list[Placement]:
    """Assign every worker a disjoint device subset; raises
    :class:`PlacementError` for any plan that cannot come up healthy.

    ``devices_per_worker`` is per-worker (already normalized — see
    :func:`parse_devices_per_worker`); None auto-splits.  CPU auto is one
    forced host device each; accelerator auto splits ``total_devices``
    evenly with the remainder going to the first workers (so a 10-chip
    host under 4 workers plans 3/3/2/2 — no chip idles).  Explicit
    accelerator counts may undersubscribe (spare chips stay unassigned
    for other tenants) but never oversubscribe.
    """
    if workers < 1:
        raise PlacementError(f"workers must be >= 1, got {workers}")
    if devices_per_worker is not None and len(devices_per_worker) != workers:
        raise PlacementError(
            f"devices_per_worker has {len(devices_per_worker)} entries "
            f"for {workers} workers"
        )
    if platform == "cpu":
        counts = devices_per_worker or (1,) * workers
        return [
            Placement(
                worker=f"w{i}",
                devices=k,
                kind="cpu",
                device_ids=None,
                env={
                    "JAX_PLATFORMS": "cpu",
                    "XLA_FLAGS": f"{HOST_DEVICE_FLAG}={k}",
                },
            )
            for i, k in enumerate(counts)
        ]
    if platform not in _ACCEL_ENV:
        raise PlacementError(
            f"unknown placement platform {platform!r} "
            f"(expected cpu, tpu, or gpu)"
        )
    if total_devices is None or total_devices < 1:
        raise PlacementError(
            f"{platform} placement needs --total-devices (the fleet front "
            f"tier is jax-free and cannot count the host's chips itself)"
        )
    if devices_per_worker is None:
        base, extra = divmod(total_devices, workers)
        if base == 0:
            raise PlacementError(
                f"{workers} workers over {total_devices} {platform} "
                f"device(s): every worker needs at least one — use fewer "
                f"workers or --placement none"
            )
        counts = tuple(base + (1 if i < extra else 0) for i in range(workers))
    else:
        counts = devices_per_worker
        if sum(counts) > total_devices:
            raise PlacementError(
                f"devices_per_worker={counts} oversubscribes the host: "
                f"{sum(counts)} requested, {total_devices} available"
            )
    jax_platform, visible_var = _ACCEL_ENV[platform]
    plans: list[Placement] = []
    cursor = 0
    for i, k in enumerate(counts):
        ids = tuple(range(cursor, cursor + k))
        cursor += k
        plans.append(
            Placement(
                worker=f"w{i}",
                devices=k,
                kind=platform,
                device_ids=ids,
                env={
                    "JAX_PLATFORMS": jax_platform,
                    visible_var: ",".join(str(d) for d in ids),
                },
            )
        )
    return plans


def apply_env_overlay(env: dict, overlay: dict) -> dict:
    """Merge a placement overlay into a spawn environment, in place.

    ``XLA_FLAGS`` is additive by contract (a space-separated flag list an
    operator may already be using), so the overlay's flags are APPENDED —
    after stripping any existing forced-host-device-count token, which
    the overlay owns.  Every other overlay var replaces the inherited
    value outright (a worker's visible-device set must be exactly its
    slice, not a merge with whatever the parent had).
    """
    for key, value in overlay.items():
        if key == "XLA_FLAGS":
            inherited = [
                tok
                for tok in env.get("XLA_FLAGS", "").split()
                if not tok.startswith(HOST_DEVICE_FLAG + "=")
            ]
            env[key] = " ".join(inherited + [value]) if inherited else value
        else:
            env[key] = value
    return env
