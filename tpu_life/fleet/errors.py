"""Fleet-level typed errors — the router's additions to the gateway contract.

The router speaks the exact gateway error envelope
(:class:`tpu_life.gateway.errors.ApiError`), so an unmodified
``GatewayClient`` sees fleet failures as the same typed JSON it already
handles.  The fleet adds failure modes a single gateway cannot have:

- ``worker_lost`` (410): the worker holding a pinned session died (crash,
  SIGKILL, restart) and the session could NOT be migrated.  Terminal and
  never retried; the ``reason`` field says why durability didn't cover
  it — ``never_snapshotted`` (death before the first spill),
  ``spill_corrupt`` (every snapshot failed the CRC/size intact check),
  ``migration_failed`` (no survivor could take it), or
  ``spill_disabled`` (the fleet runs without a spill dir, so every
  worker death is terminal for its sessions — the pre-durability
  behavior).
- ``migrating`` (409): the pinned worker died but its spilled sessions
  are being resumed on a survivor — retry after ``Retry-After`` and the
  original sid keeps working.  (Plain GET polls are answered with a
  synthetic in-progress view instead, so a poll-until-done client rides
  straight through the kill.)
- ``fleet_unavailable`` (503): every worker refused the submission
  (shedding, queue-full, or draining).  Retryable with ``Retry-After`` —
  the fleet-wide twin of a single gateway's ``overloaded``.
- ``upstream_error`` (502): a worker failed *mid-exchange* (timeout,
  reset) so the request may have been processed.  NOT retried by the
  router — re-forwarding a submit that may already have created a session
  would silently duplicate it (the same no-duplicate rule the PR 4 client
  applies to its own retries).
"""

from __future__ import annotations

from tpu_life.gateway.errors import ApiError


def worker_lost(worker: str, sid: str, reason: str = "spill_disabled") -> ApiError:
    return ApiError(
        410,
        "worker_lost",
        f"session {sid} was pinned to worker {worker}, which is gone, and "
        f"could not be recovered ({reason}); its in-flight state is lost — "
        f"resubmit to start over",
        extra={"reason": reason},
    )


def migrating(sid: str, retry_after: float = 0.5) -> ApiError:
    return ApiError(
        409,
        "migrating",
        f"session {sid} is being migrated from a dead worker to a "
        f"survivor; retry shortly — the same session id stays valid",
        retry_after=retry_after,
    )


def fleet_unavailable(tried: int, retry_after: float = 1.0) -> ApiError:
    return ApiError(
        503,
        "fleet_unavailable",
        f"all {tried} ready workers refused the submission (shedding or "
        f"draining); the fleet is protecting in-flight sessions",
        retry_after=retry_after,
    )


def no_ready_workers(total: int) -> ApiError:
    return ApiError(
        503,
        "fleet_unavailable",
        f"no ready workers ({total} supervised); retry shortly",
        retry_after=1.0,
    )


def upstream_error(worker: str, detail: str) -> ApiError:
    return ApiError(
        502,
        "upstream_error",
        f"worker {worker} failed mid-request ({detail}); the request may "
        f"or may not have been processed — not retried to avoid duplicates",
    )


def unknown_session(sid: str) -> ApiError:
    return ApiError(404, "unknown_session", f"no session {sid!r} in this fleet")
