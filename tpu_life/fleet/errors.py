"""Fleet-level typed errors — the router's additions to the gateway contract.

The router speaks the exact gateway error envelope
(:class:`tpu_life.gateway.errors.ApiError`), so an unmodified
``GatewayClient`` sees fleet failures as the same typed JSON it already
handles.  The fleet adds failure modes a single gateway cannot have:

- ``worker_lost`` (410): the worker holding a pinned session died (crash,
  SIGKILL, restart) and the session could NOT be migrated.  Terminal and
  never retried; the ``reason`` field says why durability didn't cover
  it — ``never_snapshotted`` (death before the first spill),
  ``spill_corrupt`` (every snapshot failed the CRC/size intact check),
  ``migration_failed`` (no survivor could take it), or
  ``spill_disabled`` (the fleet runs without a spill dir, so every
  worker death is terminal for its sessions — the pre-durability
  behavior).
- ``migrating`` (409): the pinned worker died but its spilled sessions
  are being resumed on a survivor — retry after ``Retry-After`` and the
  original sid keeps working.  (Plain GET polls are answered with a
  synthetic in-progress view instead, so a poll-until-done client rides
  straight through the kill.)
- ``fleet_unavailable`` (503): every worker refused the submission
  (shedding, queue-full, or draining).  Retryable with ``Retry-After`` —
  the fleet-wide twin of a single gateway's ``overloaded``.
- ``upstream_error`` (502): a worker failed *mid-exchange* (timeout,
  reset) so the request may have been processed.  NOT retried by the
  router — re-forwarding a submit that may already have created a session
  would silently duplicate it (the same no-duplicate rule the PR 4 client
  applies to its own retries).
"""

from __future__ import annotations

from tpu_life.gateway.errors import ApiError


def worker_lost(worker: str, sid: str, reason: str = "spill_disabled") -> ApiError:
    return ApiError(
        410,
        "worker_lost",
        f"session {sid} was pinned to worker {worker}, which is gone, and "
        f"could not be recovered ({reason}); its in-flight state is lost — "
        f"resubmit to start over",
        extra={"reason": reason},
    )


def migrating(sid: str, retry_after: float = 0.5) -> ApiError:
    return ApiError(
        409,
        "migrating",
        f"session {sid} is being migrated from a dead worker to a "
        f"survivor; retry shortly — the same session id stays valid",
        retry_after=retry_after,
    )


def fleet_unavailable(tried: int, retry_after: float = 1.0) -> ApiError:
    return ApiError(
        503,
        "fleet_unavailable",
        f"all {tried} ready workers refused the submission (shedding or "
        f"draining); the fleet is protecting in-flight sessions",
        retry_after=retry_after,
    )


def no_ready_workers(total: int) -> ApiError:
    return ApiError(
        503,
        "fleet_unavailable",
        f"no ready workers ({total} supervised); retry shortly",
        retry_after=1.0,
    )


def upstream_error(worker: str, detail: str) -> ApiError:
    return ApiError(
        502,
        "upstream_error",
        f"worker {worker} failed mid-request ({detail}); the request may "
        f"or may not have been processed — not retried to avoid duplicates",
    )


def unknown_session(sid: str) -> ApiError:
    return ApiError(404, "unknown_session", f"no session {sid!r} in this fleet")


def lease_expired(worker: str, generation: int) -> ApiError:
    """The generation fence (docs/FLEET.md "Cross-host topology"): a
    heartbeat from a ``(worker, generation)`` whose lease already expired
    is REFUSED — its sessions were (or are being) rescued onto survivors,
    and accepting the heartbeat would re-admit a partitioned-but-alive
    worker into a fleet that re-homed its work: split-brain double
    execution.  410 (terminal for that incarnation): the worker's
    recourse is to drop its adopted state and re-register fresh."""
    return ApiError(
        410,
        "lease_expired",
        f"the lease of {worker} generation {generation} expired and its "
        f"sessions were re-homed; this incarnation is fenced — drop local "
        f"state and re-register for a fresh generation",
        extra={"worker": worker, "generation": generation},
    )


def draining(worker: str) -> ApiError:
    """The drain answer to a remote worker's heartbeat: the control plane
    is going away, but — unlike :func:`lease_expired` — the worker's
    sessions were NOT rescued anywhere.  Cancelling them would lose
    accepted work on a clean drain; the worker's correct move is to keep
    serving them to completion and re-register when (if) a control plane
    returns.  503 (retryable), so the generic transient path handles it."""
    return ApiError(
        503,
        "draining",
        f"this control plane is draining; {worker}'s lease is revoked but "
        f"its sessions were not re-homed — finish them and re-register "
        f"elsewhere (or here, after a restart)",
        retry_after=5.0,
    )


def peer_unreachable(peer: str, detail: str) -> ApiError:
    """A transient failure on the control-plane-to-peer link while
    proxying a pinned request (docs/FLEET.md "Cross-host topology").
    Unlike :func:`upstream_error`, every proxied request is an idempotent
    GET/DELETE — re-asking cannot duplicate anything — so this is a
    retryable 503, and an unmodified poll-until-done client rides through
    a link blip (or a healing partition) the same way it rides through a
    migration."""
    return ApiError(
        503,
        "peer_unreachable",
        f"peer control plane {peer} unreachable ({detail}); the session "
        f"may be running fine there — retry shortly",
        retry_after=0.5,
    )


def unknown_worker(worker: str) -> ApiError:
    return ApiError(
        404, "unknown_worker", f"no registered worker {worker!r} in this fleet"
    )


def bad_registration(message: str) -> ApiError:
    return ApiError(400, "bad_registration", message)
