"""Worker supervision: spawn, health-check, restart, breaker, drain.

The supervisor owns N gateway worker *subprocesses* (``tpu-life gateway``
on distinct ephemeral ports — every worker binds port 0 and the bound
port is read back from its startup JSON line, so no port can collide
under parallel CI).  A monitor thread ticks every ``probe_interval_s``:

- **liveness**: ``proc.poll()`` — a dead process is a crash (unless the
  fleet is draining, when exits are the goal);
- **readiness**: ``GET /readyz`` — 200 is READY, 503 is DRAINING, and a
  process that stays unreachable while alive past a threshold is wedged
  and gets killed into the restart path;
- **restart**: crashed workers respawn (a fresh generation, a fresh
  port) after exponential backoff; a worker that keeps dying young —
  ``breaker_threshold`` consecutive failures, each before
  ``healthy_after_s`` of uptime — opens its circuit breaker and is marked
  FAILED, never respawned (a config that crashes on boot must not turn
  the supervisor into a fork bomb).  Surviving ``healthy_after_s`` resets
  the count;
- **drain**: ``begin_drain()`` forwards SIGTERM to every live worker —
  each gateway finishes its in-flight sessions and exits 0 — and stops
  restarting; ``drained()`` turns true once every process is reaped.

With ``placement="auto"`` the supervisor also owns the **per-worker
device seam** (docs/FLEET.md "Device placement"): a planner assigns each
worker a disjoint device slice as an env overlay
(``fleet.placement``), applied at every spawn — so a restart or recycle
re-enters the dead worker's exact slice — and each worker's startup line
reports the device count/kind its own jax init actually resolved, which
feeds the capacity-weighted balancer.  A placed worker that dies without
EVER becoming ready fails fast (typed :class:`PlacementError`, breaker
OPEN) instead of burning the restart budget respawning into the same
deterministically bad env.

Everything is injectable (``spawn``, ``probe``, ``clock``) so the restart
and breaker logic unit-test with fake processes and a fake clock; the
default implementations spawn real ``sys.executable -m tpu_life gateway``
subprocesses and probe over real HTTP.
"""

from __future__ import annotations

import enum
import json
import os
import re
import signal
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request
from collections import OrderedDict
from dataclasses import dataclass, field
from pathlib import Path

from tpu_life import chaos, obs
from tpu_life.fleet.placement import (
    PlacementError,
    apply_env_overlay,
    plan_placements,
)
from tpu_life.gateway import protocol
from tpu_life.runtime.metrics import log

#: Bound on remembered lease-expiry fences (a months-running control
#: plane with a flapping remote worker must not grow without bound).
#: Evicting the OLDEST fence is safe: its generation is long superseded,
#: so ``heartbeat``'s generation-mismatch arm answers the same typed 410.
MAX_FENCES = 10_000


class WorkerState(enum.Enum):
    STARTING = "starting"  # spawned, startup line / first readyz pending
    READY = "ready"  # /readyz answered 200 — in the routing rotation
    DRAINING = "draining"  # /readyz answered 503 (worker-side drain)
    DOWN = "down"  # process exited; restart scheduled (or drain done)
    FAILED = "failed"  # circuit breaker open — never respawned
    STANDBY = "standby"  # parked capacity — no process, out of rotation,
    # recruitable by the autoscaler (docs/FLEET.md "Autoscaling")


@dataclass
class FleetConfig:
    workers: int = 2
    host: str = "127.0.0.1"
    port: int = 0  # router port; 0 = ephemeral (read Fleet.port back)
    #: extra argv appended to ``gateway --host H --port 0`` for every worker
    worker_args: tuple[str, ...] = ()
    #: per-worker JSONL metrics sinks land at <metrics_dir>/<name>.jsonl
    metrics_dir: str | None = None
    #: per-worker stdout+stderr logs (default: a fresh temp dir)
    log_dir: str | None = None
    probe_interval_s: float = 0.25
    startup_timeout_s: float = 30.0  # spawn -> startup line + first readyz
    backoff_base_s: float = 0.5  # restart delay doubles from here
    backoff_max_s: float = 10.0
    breaker_threshold: int = 5  # consecutive fast failures -> FAILED
    healthy_after_s: float = 5.0  # uptime that resets the failure count
    unready_threshold: int = 20  # failed probes while alive -> kill+restart
    depth_ttl_s: float = 0.5  # balancer metrics-scrape cache TTL
    forward_timeout_s: float = 30.0  # router -> worker per-request bound
    max_body: int = protocol.MAX_BODY  # router request-body bound (413)
    max_pins: int = 100_000  # session-registry LRU cap
    #: durable sessions (docs/FLEET.md failover): the spill root.  Each
    #: worker incarnation spills its live sessions under
    #: ``<spill_dir>/<name>g<generation>``; on worker death the migrator
    #: resumes the intact spills on a survivor under the SAME fleet sid.
    #: None = durability off (worker death answers 410 worker_lost).
    spill_dir: str | None = None
    spill_every: int = 4  # rounds between worker spill passes
    #: remote spill store (docs/FLEET.md "Cross-host topology"): workers
    #: spill through this HTTP store instead of a local directory, under
    #: per-incarnation namespaces (``<site><name>g<gen>``), so migration
    #: reads work when the rescuer shares no filesystem with the victim.
    #: Mutually exclusive with ``spill_dir``.
    spill_url: str | None = None
    #: this control plane's namespace prefix in a SHARED spill store (two
    #: fleets sharing one store must not collide on ``w0g1``); also the
    #: orphan-sweep scope — a fleet only ever reaps its own site's
    #: namespaces.  Letters/digits/dash, e.g. ``"a-"``.
    site: str = ""
    #: peer control planes (router URLs): when every LOCAL survivor
    #: refuses a rescue, the migrator re-submits the spilled session to a
    #: peer fleet — cross-host failure masking (docs/FLEET.md).
    peers: tuple[str, ...] = ()
    #: lease TTL for wire-registered workers; their heartbeats renew it,
    #: and an un-renewed lease fires the same migration hook a local
    #: process death does, then FENCES the generation (typed
    #: ``lease_expired`` on reconnect — never split-brain re-admission)
    lease_ttl_s: float = 15.0
    migrate_timeout_s: float = 30.0  # per-session resume budget on death
    #: stuck-MIGRATING watchdog (docs/CHAOS.md): a sid still answering
    #: "migrating" this long after its run activated (or after the
    #: rescue-imminent fallback first covered it) settles to a terminal
    #: 410 ``migration_failed`` — a dead migrator thread must not leave
    #: clients polling synthetic progress forever
    migrate_stuck_after_s: float = 120.0
    #: device placement (docs/FLEET.md "Device placement"): ``"none"``
    #: keeps today's shared spawning env byte-for-byte; ``"auto"`` plans a
    #: disjoint device slice per worker and applies it as an env overlay
    #: at every spawn (restarts re-apply the dead worker's slice)
    placement: str = "none"
    #: per-worker device counts for the planner (normalized: one entry
    #: per worker); None = auto split (one forced host device each on
    #: cpu, an even slice of ``total_devices`` on accelerators)
    devices_per_worker: tuple[int, ...] | None = None
    #: how many real devices the host has (tpu/gpu placement only — the
    #: jax-free front tier cannot count chips itself)
    total_devices: int | None = None
    #: platform kind the planner targets (cpu / tpu / gpu)
    placement_platform: str = "cpu"
    #: fleet trace collection (docs/OBSERVABILITY.md "Distributed
    #: tracing"): when set, every worker runs with an active tracer
    #: (``--trace-events <trace_dir>/<name>g<gen>.trace.json``) and the
    #: monitor tick DRAINS each worker's span + flight rings over
    #: ``GET /v1/debug/trace`` into ``<trace_dir>/<name>.jsonl`` (one
    #: scrape record per line, with a handshake-estimated clock offset),
    #: plus this control plane's own flight ring into ``control.jsonl``
    #: — the capture set ``tpu-life trace merge`` fuses into one
    #: Perfetto timeline.  None = no collection (zero new requests).
    trace_dir: str | None = None
    #: fleet series collection (docs/OBSERVABILITY.md "Time series"):
    #: the monitor tick scrapes every live worker's snapshot ring over
    #: ``GET /v1/debug/series?cursor=`` into a per-(worker, generation)
    #: store — the SLO engine's data plane — at most once per this many
    #: seconds, and samples the fleet's own registry (the control
    #: series: router/lease/shed counters) on the same cadence.  With
    #: ``trace_dir`` set the scrapes also land in ``<name>.series.jsonl``
    #: capture files for offline replay.  0 disables collection.
    series_every_s: float = 1.0
    #: declarative SLO specs (docs/OBSERVABILITY.md "SLOs and burn
    #: rates"): a JSON/TOML spec file evaluated with multi-window burn
    #: rates on the monitor tick; None = the built-in defaults.  A bad
    #: spec file raises at construction, before any process exists.
    slo_file: str | None = None
    #: standby pool (docs/FLEET.md "Autoscaling"): this many EXTRA worker
    #: slots created parked — no process, out of the routing rotation —
    #: that ``recruit()`` launches on demand and ``release()`` returns
    #: capacity to.  Under placement auto the plan covers the full
    #: ``workers + standby`` set, so a recruit enters a reserved slice.
    standby: int = 0
    #: the autoscaling policy (an ``AutoscaleConfig``); None = no control
    #: loop (standby stays parked unless an operator recruits by hand)
    autoscale: object | None = None


@dataclass
class Worker:
    """One supervised gateway: process + bound URL + health state."""

    name: str
    log_path: Path
    generation: int = 0
    proc: subprocess.Popen | None = None
    url: str | None = None
    run_id: str | None = None
    state: WorkerState = WorkerState.DOWN
    started_at: float = 0.0
    restart_at: float = 0.0
    failures: int = 0  # consecutive fast failures (breaker input)
    unready: int = 0  # consecutive failed probes while alive
    #: machine-readable reason from the last 500 /readyz answer (e.g.
    #: ``engine_wedged:settle_deadline`` from the serve wedge watchdog,
    #: docs/SERVING.md "Resource governance") — surfaced in /healthz and
    #: the fleet summary so an unready-recycle names WHY it fired; None
    #: for plain unreachability, cleared on the next ready/draining probe
    unready_reason: str | None = None
    log_offset: int = 0  # startup line scan starts here (per generation)
    exit_codes: list[int] = field(default_factory=list)
    #: placement env overlay applied at every spawn of this worker —
    #: stable across generations, so a restart re-enters the SAME slice
    env_overlay: dict = field(default_factory=dict)
    #: resolved device count/kind, reported by the worker's startup line
    #: (planned values until the first report lands)
    devices: int | None = None
    device_kind: str | None = None
    #: True once ANY generation answered ready — the placed-worker
    #: fail-fast gate (a slice that never came up is presumed invalid)
    ever_ready: bool = False
    #: True while the SUPERVISOR is killing this worker (startup timeout,
    #: unready recycle): that exit is self-inflicted — possibly just a
    #: slow attach — and must ride the restart budget, never the
    #: placement fail-fast
    recycling: bool = False
    #: wire-registered membership (docs/FLEET.md "Cross-host topology"):
    #: True for workers the control plane did NOT spawn — they registered
    #: over HTTP, hold a heartbeat-renewed lease, and are never respawned
    #: by us (a fresh registration IS their respawn)
    remote: bool = False
    lease_expires_at: float = 0.0
    #: the lease expired (or the fleet drained): this incarnation is
    #: fenced — terminal until the worker re-registers a new generation
    lease_dead: bool = False
    #: standby-pool membership (docs/FLEET.md "Autoscaling"): this slot
    #: parks at STANDBY instead of respawning after a release — set at
    #: construction for the ``--standby`` tail, and stamped onto any
    #: worker ``release()`` drains (a released base worker IS returned
    #: capacity; recruit can bring it back)
    standby: bool = False
    #: a per-worker scale-down drain is in flight: the next exit re-parks
    #: this slot at STANDBY instead of scheduling a restart
    released: bool = False

    @property
    def alive(self) -> bool:
        if self.remote:
            # a remote worker is "alive" exactly while its lease stands:
            # there is no process to poll, only the claim it keeps renewing
            return self.url is not None and not self.lease_dead
        return self.proc is not None and self.proc.poll() is None


class Supervisor:
    """Owns the workers and the monitor thread; exposes the routing view
    (:meth:`ready_workers`) and the drain choreography."""

    def __init__(
        self,
        config: FleetConfig,
        registry,
        *,
        spawn=None,
        probe=None,
        clock=time.monotonic,
    ):
        self.config = config
        if config.spill_url is not None and config.spill_dir is not None:
            raise ValueError(
                "spill_dir and spill_url are mutually exclusive (a fleet "
                "spills locally OR through the remote store, never both)"
            )
        if not re.fullmatch(r"(?:[A-Za-z0-9][A-Za-z0-9-]*)?", config.site):
            raise ValueError(
                f"site must be letters/digits/dash starting with an "
                f"alphanumeric (a spill-namespace prefix), got {config.site!r}"
            )
        self.clock = clock
        self.spawn = spawn or self._default_spawn
        self.probe = probe or self._default_probe
        self._lock = threading.RLock()
        self._draining = False
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        log_dir = Path(config.log_dir or tempfile.mkdtemp(prefix="tpu-life-fleet-"))
        log_dir.mkdir(parents=True, exist_ok=True)
        self.log_dir = log_dir
        self.workers = [
            Worker(name=f"w{i}", log_path=log_dir / f"w{i}.log")
            for i in range(config.workers + max(0, config.standby))
        ]
        # the standby tail parks at construction: no process, out of the
        # rotation, waiting for recruit() (docs/FLEET.md "Autoscaling")
        for w in self.workers[config.workers:]:
            w.standby = True
            w.state = WorkerState.STANDBY
        # device placement (docs/FLEET.md): plan ONCE, at construction —
        # an invalid plan (oversubscribed slice, unknown platform) raises
        # the typed PlacementError here, before any process exists, so a
        # deterministically broken env never burns the restart budget
        self.placements = None
        if config.placement == "auto":
            # the plan covers the standby tail too: a recruit must enter
            # a RESERVED disjoint slice, not squat on a live worker's
            self.placements = plan_placements(
                config.workers + max(0, config.standby),
                platform=config.placement_platform,
                devices_per_worker=config.devices_per_worker,
                total_devices=config.total_devices,
            )
            for w, p in zip(self.workers, self.placements):
                w.env_overlay = dict(p.env)
                w.devices = p.devices  # planned; startup line overwrites
                w.device_kind = p.kind
        elif config.placement != "none":
            raise PlacementError(
                f"unknown placement policy {config.placement!r} "
                f"(expected auto or none)"
            )
        #: worker-death callback: ``cb(name, generation)`` fires (under
        #: the supervisor lock — keep it fast) for every non-drain exit
        #: AND every lease expiry; the fleet wires the migrator's spill
        #: rescue here
        self.on_worker_exit = None
        #: fenced incarnations (docs/FLEET.md "Cross-host topology"): a
        #: (name, generation) whose lease expired after its sessions were
        #: re-homed — its heartbeats are refused with the typed 410
        #: ``lease_expired``, never silently re-admitted.  Insertion-
        #: ordered and bounded (a months-running plane with a flapping
        #: remote worker must not grow without bound): an evicted fence
        #: is generations-superseded, and ``heartbeat``'s generation-
        #: mismatch arm still answers it the same typed 410
        self._fenced: OrderedDict[tuple[str, int], None] = OrderedDict()
        #: fences created by begin_drain rather than a lease expiry: the
        #: worker's sessions were NOT re-homed, so its heartbeats answer
        #: the typed 503 ``draining`` (finish your sessions, re-register
        #: later) instead of the 410 that tells it to drop everything
        self._drain_fenced: set[tuple[str, int]] = set()
        #: chaos-injection retention (docs/CHAOS.md): last-seen
        #: ``chaos_injections_total`` per (worker, generation, point,
        #: outcome), scraped continuously while a plan is armed — a dead
        #: worker's counters no longer die with its registry, so drill
        #: accounting is per-incarnation exact instead of a pre-kill floor
        self._injections: dict[tuple[str, int, str, str], float] = {}
        self._g_workers = registry.gauge(
            "fleet_workers", "supervised workers by state", labels=("state",)
        )
        self._c_restarts = registry.counter(
            "fleet_restarts_total", "worker respawns after a crash"
        )
        self._c_restarts.labels()
        # the lease instruments (docs/FLEET.md "Cross-host topology")
        self._c_lease_expired = registry.counter(
            "fleet_lease_expired_total",
            "remote-worker leases expired un-renewed (fires migration)",
        )
        self._c_lease_expired.labels()
        self._c_lease_refused = registry.counter(
            "fleet_lease_refusals_total",
            "heartbeats refused because the (worker, generation) is fenced",
        )
        self._c_lease_refused.labels()
        self._c_registrations = registry.counter(
            "fleet_registrations_total", "wire registrations accepted"
        )
        self._c_registrations.labels()
        self._g_injections = registry.gauge(
            "fleet_chaos_injections",
            "last-seen chaos_injections_total per worker (survives death)",
            labels=("worker", "point", "outcome"),
        )
        self._g_devices = registry.gauge(
            "fleet_worker_devices",
            "devices resolved by each worker (planned until reported)",
            labels=("worker",),
        )
        # fleet trace collection (docs/OBSERVABILITY.md): capture-file
        # appends come from the monitor thread and close() — serialized
        # here.  _doomed carries (worker, generation, url) recycle
        # victims whose kill is DEFERRED past the lock so their final
        # trace scrape (bounded HTTP) never stalls the routing hot path.
        self._capture_lock = threading.Lock()
        self._doomed: list[tuple] = []
        # fleet series collection + the SLO engine (docs/OBSERVABILITY.md
        # "Time series" / "SLOs and burn rates"): the per-(worker,
        # generation) snapshot store the tick scrapes into, the cursors
        # it owns (the worker's ring read is non-destructive), this
        # process's own registry ring (the control series), and the burn-
        # rate engine judging the store every collection pass.  A bad
        # --slo file raises HERE, before any process exists — like a bad
        # placement plan.
        self._registry = registry
        self.series_store = obs.timeseries.SeriesStore()
        self._series_cursors: dict[tuple[str, int], int] = {}
        self._control_series = obs.timeseries.SeriesRing()
        self._series_next = 0.0
        specs = (
            obs.slo.load_specs(config.slo_file)
            if config.slo_file is not None
            else obs.slo.default_specs()
        )
        self.slo_engine = obs.slo.SloEngine(specs, self.series_store)
        # demand-driven autoscaling (docs/FLEET.md "Autoscaling"): the
        # control loop joins the monitor tick at the series cadence —
        # its data plane IS the series store the tick already fills
        self.autoscaler = None
        if config.autoscale is not None:
            from tpu_life.fleet.autoscaler import Autoscaler

            self.autoscaler = Autoscaler(config.autoscale, self)
        for st in WorkerState:
            self._g_workers.labels(state=st.value).set(0.0)

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        self._sweep_orphan_spills()
        with self._lock:
            for w in self.workers:
                if w.state is not WorkerState.STANDBY:
                    self._spawn_worker(w, first=True)
            self._update_gauges()
        self._thread = threading.Thread(
            target=self._monitor, name="fleet-monitor", daemon=True
        )
        self._thread.start()

    def _sweep_orphan_spills(self) -> None:
        """Startup sweep: delete spill directories left by dead
        generations of a PREVIOUS supervisor run.  This supervisor's
        generations all start fresh (and get fresh per-generation dirs),
        so at start every existing subdirectory is an orphan — without
        this, a crashed worker's directory would sit on disk forever
        (in-run orphans are deleted by the migrator after each rescue)."""
        if self.config.spill_url is not None:
            # the remote twin: reap THIS SITE's namespaces from the shared
            # store.  An empty site would sweep every fleet sharing the
            # store, so the sweep is gated on a non-empty prefix (a solo
            # fleet that wants the reap names a site; docs/FLEET.md).
            if not self.config.site:
                log.debug("fleet: no site prefix — skipping remote spill sweep")
                return
            from tpu_life.serve.spill_http import (
                delete_remote_namespace,
                list_remote_namespaces,
            )

            try:
                spaces = list_remote_namespaces(self.config.spill_url)
            except OSError as e:
                # the store may simply not be up yet: durability degrades,
                # the fleet must still come up
                log.warning("fleet: remote spill sweep skipped: %s", e)
                return
            for ns in spaces:
                if ns.startswith(self.config.site):
                    log.info("fleet: sweeping orphan remote namespace %s", ns)
                    delete_remote_namespace(self.config.spill_url, ns)
            return
        if self.config.spill_dir is None:
            return
        root = Path(self.config.spill_dir)
        if not root.is_dir():
            return
        import shutil

        for child in root.iterdir():
            if child.is_dir():
                log.info("fleet: sweeping orphan spill dir %s", child)
                shutil.rmtree(child, ignore_errors=True)

    def begin_drain(self) -> None:
        """Fleet-wide graceful drain: SIGTERM every live worker (each
        gateway finishes in-flight sessions and exits 0) and stop
        restarting.  Idempotent — but a repeat call re-TERMs anything
        still alive, so a signal that raced a worker spawn (or a second
        SIGTERM from an impatient operator) is never silently dropped.
        Callers block on :meth:`wait`."""
        with self._lock:
            first = not self._draining
            self._draining = True
            for w in self.workers:
                if w.remote:
                    # not ours to signal: revoke the lease and fence the
                    # generation — but as a DRAIN fence, so a late
                    # heartbeat gets the typed 503 ``draining`` (its
                    # sessions were not re-homed; it must finish them,
                    # not drop them) rather than the 410 fence
                    if not w.lease_dead:
                        self._fence_locked(w)
                        self._drain_fenced.add((w.name, w.generation))
                        w.lease_dead = True
                        w.state = WorkerState.DOWN
                    continue
                if w.alive:
                    if first:
                        log.info("fleet: draining %s (pid %d)", w.name, w.proc.pid)
                    w.proc.terminate()

    @property
    def draining(self) -> bool:
        return self._draining

    def drained(self) -> bool:
        """True once every worker process is gone (reaped or never up)."""
        with self._lock:
            return all(not w.alive for w in self.workers)

    def finished(self) -> bool:
        """True when this supervisor will never run another worker: a
        requested drain completed, OR every worker opened its circuit
        breaker (a fleet that crash-loops to all-FAILED must surface as
        exit 1, not hang serving 503s until someone signals it)."""
        with self._lock:
            if self.workers and all(
                w.state is WorkerState.FAILED for w in self.workers
            ):
                return True
            return self._draining and all(not w.alive for w in self.workers)

    def wait(self, timeout: float | None = None) -> bool:
        """Block (in signal-friendly slices) until :meth:`finished`."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while not self.finished():
            if deadline is not None and time.monotonic() > deadline:
                return False
            time.sleep(0.05)
        return True

    def close(self) -> None:
        """Stop the monitor and hard-kill anything still alive."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        if self.config.trace_dir is not None or self.config.series_every_s > 0:
            # last evidence pass: whatever the workers buffered since the
            # final monitor tick, plus this process's own flight tail
            with self._lock:
                targets = [
                    (w, w.generation, w.url)
                    for w in self.workers
                    if w.url is not None and w.alive
                ]
            if self.config.trace_dir is not None:
                self._scrape_traces(targets)
                self._scrape_control()
            if self.config.series_every_s > 0:
                self._scrape_series(targets)
                self._sample_control_series()
        with self._lock:
            for w in self.workers:
                if w.proc is not None and w.proc.poll() is None:
                    w.proc.kill()
            for w in self.workers:
                if w.proc is not None:
                    try:
                        w.proc.wait(timeout=5)
                    except subprocess.TimeoutExpired:  # pragma: no cover
                        log.warning("fleet: %s did not die on SIGKILL", w.name)

    # -- the routing view --------------------------------------------------
    def ready_workers(self) -> list[Worker]:
        # liveness-checked on read: a freshly dead worker leaves the
        # rotation immediately, not at the monitor's next tick
        with self._lock:
            return [
                w
                for w in self.workers
                if w.state is WorkerState.READY and w.alive
            ]

    def get(self, name: str) -> Worker | None:
        for w in self.workers:
            if w.name == name:
                return w
        return None

    def states(self) -> dict[str, str]:
        with self._lock:
            out = {}
            for w in self.workers:
                st = w.state
                if (
                    st
                    not in (
                        WorkerState.DOWN,
                        WorkerState.FAILED,
                        WorkerState.STANDBY,  # parked: no process BY DESIGN
                    )
                    and not w.alive
                ):
                    st = WorkerState.DOWN  # dead but not yet reaped by a tick
                out[w.name] = st.value
            return out

    def unready_reasons(self) -> dict[str, str]:
        """Workers currently refusing their probe WITH a typed reason
        (``code[:reason]``, e.g. ``engine_wedged:settle_deadline``) — the
        why behind an in-flight unready-recycle (docs/FLEET.md)."""
        with self._lock:
            return {
                w.name: w.unready_reason
                for w in self.workers
                if w.unready_reason is not None
            }

    def capacities(self) -> dict:
        """Per-worker capacity view for ``/healthz`` / ``stats``: resolved
        (or planned) device count + kind, and the routing weight the
        balancer normalizes queue depth by."""
        with self._lock:
            return {
                w.name: {
                    "devices": w.devices,
                    "device_kind": w.device_kind,
                    "weight": worker_weight(w),
                }
                for w in self.workers
            }

    def devices_total(self) -> int:
        """The fleet's aggregate device count — the capacity-planning
        number.  Slices are disjoint only under placement auto, so only
        then do per-worker counts SUM; under the shared spawning env
        (placement none) every worker co-claims ONE device set, and the
        honest aggregate is that set's size (the max report), not
        workers x it."""
        with self._lock:
            values = [w.devices or 0 for w in self.workers]
        if self.placements is not None:
            return sum(values)
        return max(values, default=0)

    def restarts(self) -> float:
        return self._c_restarts.value

    # -- demand-driven scaling (docs/FLEET.md "Autoscaling") ----------------
    def scale_counts(self) -> tuple[int, int]:
        """``(active, standby)``: slots currently deployed (ready,
        starting, draining, or local-and-restarting) vs parked slots a
        :meth:`recruit` could launch right now."""
        with self._lock:
            active = standby = 0
            for w in self.workers:
                if w.state is WorkerState.STANDBY:
                    if not w.remote or not w.lease_dead:
                        standby += 1
                elif w.state in (
                    WorkerState.STARTING,
                    WorkerState.READY,
                    WorkerState.DRAINING,
                ):
                    active += 1
                elif w.state is WorkerState.DOWN and not w.remote:
                    # a local DOWN worker has a restart scheduled: still
                    # a deployed slot, just mid-bounce
                    active += 1
            return active, standby

    def recruit(self) -> str | None:
        """Launch one parked standby into the fleet: spawn it (local) or
        start probing it (a pre-registered remote standby — its gateway
        is already up, parked out of rotation).  Returns the worker's
        name, or None when the pool is empty / the fleet is draining /
        the ``scale.recruit.fail`` chaos point says the launch failed —
        the caller (the autoscaler) holds and retries next evaluation."""
        with self._lock:
            if self._draining:
                return None
            cands = [
                w
                for w in self.workers
                if w.state is WorkerState.STANDBY
                and (not w.remote or not w.lease_dead)
            ]
            if not cands:
                return None
            if chaos.decide("scale.recruit.fail") is not None:
                # the "standby failed to launch" drill: no spawn, no
                # state change — deterministic, and the next evaluation
                # simply tries again
                chaos.record_fire("scale.recruit.fail", "refuse")
                log.warning("fleet: recruit refused (chaos scale.recruit.fail)")
                return None
            w = cands[0]
            if w.remote:
                # the parked gateway is live and leased: recruiting is
                # just re-entering the probe rotation
                w.state = WorkerState.STARTING
                w.started_at = self.clock()
                w.unready = 0
            else:
                self._spawn_worker(w)
            obs.flight.record(
                "scale.recruit",
                worker=w.name,
                generation=w.generation,
                remote=w.remote,
            )
            self._update_gauges()
            return w.name

    def release(self, name: str) -> bool:
        """Drain ONE worker out of the fleet and return its slot to the
        standby pool: the graceful per-worker twin of
        :meth:`begin_drain` — SIGTERM a local worker (its gateway
        finishes accepted sessions, then exits; the exit re-parks the
        slot) or drain-fence a remote one (typed 503 heartbeats tell it
        to finish its sessions and re-register later).  Mesh-slice
        reservations and sid pins are respected for free: the worker
        itself retires them as its sessions complete."""
        with self._lock:
            w = self.get(name)
            if w is None or self._draining:
                return False
            if w.remote:
                if w.lease_dead or w.state is WorkerState.STANDBY:
                    return False
                self._fence_locked(w)
                self._drain_fenced.add((w.name, w.generation))
                w.lease_dead = True
                w.standby = True
                w.state = WorkerState.DOWN
            else:
                if not w.alive or w.released:
                    return False
                w.released = True
                w.proc.terminate()
            obs.flight.record(
                "scale.release",
                worker=w.name,
                generation=w.generation,
                remote=w.remote,
            )
            self._update_gauges()
            return True

    # -- the monitor -------------------------------------------------------
    def _monitor(self) -> None:
        while not self._stop.is_set():
            try:
                self.tick()
            except Exception:  # pragma: no cover - monitor must not die
                log.exception("fleet: monitor tick failed")
            if self.finished():
                # reaping is done (or every breaker opened); keep gauges
                # truthful and stop ticking
                with self._lock:
                    self._update_gauges()
                return
            self._stop.wait(self.config.probe_interval_s)

    def tick(self) -> None:
        """One monitor pass (public so unit tests drive it directly).

        Two phases around the lock: process lifecycle (exits, respawns,
        startup-line reads — fast and local) runs under it; the ``/readyz``
        HTTP probes (up to 1 s each against a wedged-but-alive worker) run
        OUTSIDE it, so a slow worker can never stall the router's
        ``ready_workers()`` / ``states()`` hot path for the probe's
        duration.  Probe answers are re-validated against the generation
        before applying — the world may have moved while we waited.
        """
        # chaos seam (docs/CHAOS.md): the monitor's clock reads skewed by
        # a bounded, seeded amount — the "NTP stepped the clock" drill.
        # Every deadline decision this tick makes (startup timeout,
        # backoff expiry, healthy-uptime reset) sees the same skew, and
        # the fleet must stay consistent: a skew-provoked kill is
        # supervisor-initiated and rides the normal restart budget.
        now = self.clock() + chaos.skew("probe.skew")
        to_probe: list[tuple[Worker, int]] = []
        with self._lock:
            for w in self.workers:
                if self._tick_liveness(w, now):
                    to_probe.append((w, w.generation))
            self._update_gauges()
        if to_probe:
            results = self._probe_all(to_probe)
            with self._lock:
                for w, gen, status in results:
                    if (
                        w.generation != gen
                        or not w.alive
                        or w.state in (WorkerState.DOWN, WorkerState.FAILED)
                    ):
                        continue  # stale answer: the next tick sees the truth
                    self._apply_probe(w, status, now)
                self._update_gauges()
        self._reap_doomed()
        # fleet trace collection (docs/OBSERVABILITY.md): drain every
        # live worker's span + flight rings into the capture dir —
        # continuous, like the PR 11 chaos-counter scrape, so a SIGKILL
        # loses at most one tick's events.  Runs OUTSIDE the lock.
        if self.config.trace_dir is not None:
            with self._lock:
                targets = [
                    (w, w.generation, w.url)
                    for w in self.workers
                    if w.url is not None and w.alive
                ]
            self._scrape_traces(targets)
            self._scrape_control()
        # fleet series collection + SLO evaluation (docs/OBSERVABILITY.md):
        # scrape every live worker's snapshot ring, sample the fleet's own
        # registry, then judge the store's windows — rate-limited to one
        # pass per series_every_s whatever the probe cadence is.  Runs
        # OUTSIDE the lock like the trace scrape (max, not sum, latency).
        if self.config.series_every_s > 0 and now >= self._series_next:
            self._series_next = now + self.config.series_every_s
            with self._lock:
                targets = [
                    (w, w.generation, w.url)
                    for w in self.workers
                    if w.url is not None and w.alive
                ]
            self._scrape_series(targets)
            self._sample_control_series()
            try:
                self.slo_engine.evaluate()
            except Exception:  # pragma: no cover - alerting must not kill ticks
                log.exception("fleet: slo evaluation failed")
            # the autoscaler rides the same cadence: its inputs are the
            # windows this very pass just refreshed
            if self.autoscaler is not None and not self._draining:
                try:
                    self.autoscaler.evaluate(now)
                except Exception:  # pragma: no cover - scaling must not kill ticks
                    log.exception("fleet: autoscale evaluation failed")

    def slo_status(self) -> dict:
        """The live burn gauges (``/healthz`` ``slo`` section, ``top``)."""
        return self.slo_engine.status()

    def _probe_all(self, targets: list[tuple[Worker, int]]) -> list[tuple]:
        """Probe workers CONCURRENTLY: tick latency must be max(probe),
        not sum(probe) — with several wedged workers each burning their
        full HTTP timeout, sequential probes would stretch every tick by
        the sum and lag healthy workers' state transitions behind it."""
        if len(targets) == 1:
            w, gen = targets[0]
            return [(w, gen, self.probe(w))]
        results: list = [None] * len(targets)

        def one(i: int, w: Worker, gen: int) -> None:
            results[i] = (w, gen, self.probe(w))

        threads = [
            threading.Thread(target=one, args=(i, w, gen), daemon=True)
            for i, (w, gen) in enumerate(targets)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()  # bounded: the probe itself carries an HTTP timeout
        return [r for r in results if r is not None]

    def _tick_liveness(self, w: Worker, now: float) -> bool:
        """Lifecycle transitions under the lock; True = probe this worker
        over HTTP (it is alive with a bound URL)."""
        if w.state is WorkerState.FAILED:
            return False
        if w.state is WorkerState.STANDBY:
            # parked capacity: no process to reap, no probe to run.  A
            # REMOTE standby still holds a heartbeat-renewed lease; one
            # that goes silent leaves the pool (fenced, so a zombie
            # reconnect is refused typed) — but held no sessions, so no
            # migration fires
            if w.remote and not w.lease_dead and now > w.lease_expires_at:
                log.warning(
                    "fleet: standby %s gen %d stopped heartbeating — "
                    "leaving the pool",
                    w.name,
                    w.generation,
                )
                self._fence_locked(w)
                w.lease_dead = True
                self._c_lease_expired.inc()
                obs.flight.record(
                    "lease.expired",
                    worker=w.name,
                    generation=w.generation,
                    standby=True,
                )
            return False
        if w.remote:
            # wire-registered: liveness is the lease, not a process.  An
            # un-renewed lease is this tier's "the process exited" — same
            # hook, same migration, plus the generation fence.
            if w.lease_dead:
                return False
            if now > w.lease_expires_at:
                self._expire_lease_locked(w)
                return False
            return w.url is not None
        if w.proc is not None and w.proc.poll() is not None:
            self._on_exit(w, now)
            return False
        if w.state is WorkerState.DOWN:
            if not self._draining and now >= w.restart_at:
                self._spawn_worker(w)
            return False  # freshly spawned: startup line read next tick
        if w.state is WorkerState.STARTING and w.url is None:
            doc = self._read_startup(w)
            if doc is None:
                if now - w.started_at > self.config.startup_timeout_s:
                    log.warning(
                        "fleet: %s produced no startup line in %.0fs; killing",
                        w.name,
                        self.config.startup_timeout_s,
                    )
                    w.recycling = True
                    w.proc.kill()
                return False
            w.url = doc["url"]
            w.run_id = doc.get("run_id")
            # the capacity-feedback half of placement: what the worker's
            # OWN jax init resolved wins over the planner's intent — the
            # balancer weights by what the chips actually came up as
            if doc.get("devices"):
                w.devices = int(doc["devices"])
                w.device_kind = doc.get("device_kind") or w.device_kind
            log.info(
                "fleet: %s gen %d at %s (%s device(s), kind %s)",
                w.name,
                w.generation,
                w.url,
                w.devices if w.devices is not None else "?",
                w.device_kind or "?",
            )
        return True

    def _apply_probe(self, w: Worker, status, now: float) -> None:
        # the default probe answers ("ready", <readyz doc>) so capacity
        # reported AFTER the startup line (device resolution is async in
        # the worker — a slow attach must not block its readiness) still
        # reaches the balancer; injected fakes may answer plain strings
        info = None
        if isinstance(status, tuple):
            status, info = status
        if isinstance(info, dict) and "_chaos_injections" in info:
            # the piggybacked injection scrape (docs/CHAOS.md): fold it
            # into the per-incarnation retention whatever the readiness
            # verdict was — evidence is evidence
            self._record_injections_locked(w, info.pop("_chaos_injections"))
        if status == "ready":
            was_ready = w.state is WorkerState.READY
            w.state = WorkerState.READY
            w.ever_ready = True
            w.unready = 0
            w.unready_reason = None
            if not was_ready:
                # the recovery-time SLO's closing edge: a name that had
                # an open outage just answered ready again
                self.slo_engine.note_worker_ready(
                    w.name, w.generation, time.time()
                )
            if isinstance(info, dict) and info.get("devices"):
                w.devices = int(info["devices"])
                w.device_kind = info.get("device_kind") or w.device_kind
            if w.failures and now - w.started_at >= self.config.healthy_after_s:
                w.failures = 0  # survived long enough: breaker resets
        elif status == "draining":
            w.state = WorkerState.DRAINING
            w.unready = 0
            w.unready_reason = None
        else:  # unreachable
            # a reasoned refusal (the worker answered 500 with a typed
            # body — e.g. the serve wedge watchdog's engine_wedged) is
            # still UNREACHABLE for recycle purposes, but the reason is
            # retained so /healthz and the summary name why the recycle
            # fired instead of showing an anonymous probe failure
            if isinstance(info, dict) and info.get("unready_reason"):
                w.unready_reason = str(info["unready_reason"])
            if w.state is WorkerState.STARTING:
                if now - w.started_at > self.config.startup_timeout_s:
                    log.warning("fleet: %s never became ready; killing", w.name)
                    if w.remote:
                        # no process to kill: revoke the lease — the
                        # worker re-registers when (if) it can reach us
                        self._expire_lease_locked(w)
                    else:
                        self._kill_for_recycle_locked(w)
                return
            w.unready += 1
            if w.unready >= self.config.unready_threshold:
                log.warning(
                    "fleet: %s unresponsive for %d probes; killing for restart",
                    w.name,
                    w.unready,
                )
                if w.remote:
                    self._expire_lease_locked(w)
                else:
                    self._kill_for_recycle_locked(w)

    def _on_exit(self, w: Worker, now: float) -> None:
        rc = w.proc.poll()
        w.exit_codes.append(rc)
        w.proc = None
        w.url = None
        w.unready = 0
        # the journey's kill marker: a worker incarnation left the fleet
        # (crash, SIGKILL, recycle, or drain exit) — what the doctor
        # anchors a migration gap's left edge on
        obs.flight.record(
            "worker.exit",
            worker=w.name,
            generation=w.generation,
            rc=rc,
            draining=self._draining,
            recycling=w.recycling,
            released=w.released,
        )
        if not self._draining and not w.released:
            # the recovery-time SLO's clock starts at the death edge (a
            # drain exit — fleet-wide or a scale-down release — is the
            # goal, not an outage)
            self.slo_engine.note_worker_exit(w.name, w.generation, time.time())
        if self._draining:
            w.state = WorkerState.DOWN
            log.info("fleet: %s exited rc=%s (drain)", w.name, rc)
            return
        if self.on_worker_exit is not None:
            # the durability hook: hand this incarnation's spills to the
            # migrator BEFORE any respawn bumps the generation (the hook
            # only records state and spawns a thread — it must stay fast,
            # we hold the supervisor lock).  A released worker gets it
            # too, as a safety net: a graceful release finishes its
            # sessions (nothing to rescue), but one that died MID-drain
            # leaves spills the migrator must still re-home.
            try:
                self.on_worker_exit(w.name, w.generation)
            except Exception:  # pragma: no cover - the hook must not kill reaping
                log.exception("fleet: worker-exit hook failed for %s", w.name)
        if w.released:
            # a scale-down release completing: the slot returns to the
            # standby pool (docs/FLEET.md "Autoscaling") — recruitable
            # again, never auto-respawned, breaker history cleared (an
            # intentional exit is not a crash)
            w.released = False
            w.standby = True
            w.failures = 0
            w.unready_reason = None
            w.state = WorkerState.STANDBY
            log.info(
                "fleet: %s exited rc=%s (released to standby pool)", w.name, rc
            )
            return
        if w.env_overlay and not w.ever_ready and not w.recycling:
            # a PLACED worker that died ON ITS OWN without ever answering
            # ready: its device slice is presumed invalid
            # (oversubscription the planner could not see, a hostile
            # visible-device var, ...).  The overlay is re-applied
            # verbatim on every respawn, so retrying is deterministic
            # failure — fail fast with the typed placement error instead
            # of burning the restart budget respawning into the same bad
            # env.  A supervisor-initiated kill (startup timeout, unready
            # recycle — ``recycling``) is excluded: that may be nothing
            # more than a slow device attach, and it takes the normal
            # restart/backoff/breaker path like an unplaced worker.
            w.failures += 1
            w.state = WorkerState.FAILED
            err = PlacementError(
                f"worker {w.name} exited rc={rc} before ever becoming "
                f"ready under placement overlay {w.env_overlay!r} — the "
                f"device slice appears invalid; not respawning"
            )
            log.error("fleet: %s circuit breaker OPEN (placement): %s", w.name, err)
            return
        uptime = now - w.started_at
        w.failures = w.failures + 1 if uptime < self.config.healthy_after_s else 1
        if w.failures >= self.config.breaker_threshold:
            w.state = WorkerState.FAILED
            log.error(
                "fleet: %s circuit breaker OPEN after %d consecutive fast "
                "failures (last rc=%s) — not restarting",
                w.name,
                w.failures,
                rc,
            )
            return
        delay = min(
            self.config.backoff_max_s,
            self.config.backoff_base_s * 2 ** (w.failures - 1),
        )
        w.restart_at = now + delay
        w.state = WorkerState.DOWN
        log.warning(
            "fleet: %s exited rc=%s after %.1fs; restart %d in %.1fs",
            w.name,
            rc,
            uptime,
            w.failures,
            delay,
        )

    # -- wire-registered membership (docs/FLEET.md "Cross-host topology") --
    def _expire_lease_locked(self, w: Worker) -> None:
        """A remote worker's lease ran out (or it wedged): this
        incarnation is dead to the fleet.  Fires the SAME migration hook
        a local process exit does, then fences the generation — a
        partitioned-but-alive worker that reconnects is refused typed,
        never silently re-admitted over its rescued sessions."""
        log.warning(
            "fleet: lease of %s gen %d expired — fencing and migrating "
            "its sessions",
            w.name,
            w.generation,
        )
        self._fence_locked(w)
        w.lease_dead = True
        w.state = WorkerState.DOWN
        w.unready = 0
        self._c_lease_expired.inc()
        obs.flight.record(
            "lease.expired", worker=w.name, generation=w.generation
        )
        if self._draining:
            return
        # a lease expiry is this tier's worker death: same recovery clock
        self.slo_engine.note_worker_exit(w.name, w.generation, time.time())
        if self.on_worker_exit is not None:
            try:
                self.on_worker_exit(w.name, w.generation)
            except Exception:  # pragma: no cover - the hook must not kill the tick
                log.exception("fleet: worker-exit hook failed for %s", w.name)

    def register_worker(self, doc: dict) -> dict:
        """Admit a wire-registered worker; ``doc`` is its startup JSON
        line (the existing contract IS the handshake).  Returns the
        grant: assigned name, fresh generation, lease TTL, heartbeat
        cadence, and — when the fleet spills remotely — the spill
        namespace this incarnation must write.

        A re-registration claiming a known remote name bumps that slot's
        generation (exactly a local respawn); if the prior generation's
        lease was still standing, it is expired first — re-registration
        is an admission that the old incarnation is gone, and its
        sessions need rescuing like any death."""
        from tpu_life.fleet import errors as fl_errors
        from tpu_life.fleet.membership import heartbeat_every

        url = doc.get("url")
        if not isinstance(url, str) or not url.startswith("http"):
            raise fl_errors.bad_registration(
                f"registration needs the worker's bound url, got {url!r}"
            )
        # every wire field is validated BEFORE any slot mutation: a typed
        # 400 must leave no half-registered ghost behind (a slot with a
        # bumped generation and a zero lease would fence-and-migrate an
        # incarnation that never existed)
        devices: int | None = None
        if doc.get("devices"):
            try:
                devices = int(doc["devices"])
            except (TypeError, ValueError):
                raise fl_errors.bad_registration(
                    f"registration devices must be an integer, "
                    f"got {doc['devices']!r}"
                ) from None
        with self._lock:
            if self._draining:
                raise fl_errors.no_ready_workers(len(self.workers))
            claimed = doc.get("worker")
            w = self.get(claimed) if isinstance(claimed, str) else None
            if w is not None and not w.remote:
                raise fl_errors.bad_registration(
                    f"{claimed!r} is a locally supervised worker; remote "
                    f"registration cannot claim it"
                )
            if w is None:
                # honor a well-formed unclaimed name: two workers
                # re-registering after a control-plane restart must keep
                # their DISTINCT old identities, not collide on one
                # auto-minted slot and fence each other in a ping-pong
                if isinstance(claimed, str) and re.fullmatch(r"w\d+", claimed):
                    name = claimed
                else:
                    taken = {x.name for x in self.workers}
                    idx = len(self.workers)
                    while f"w{idx}" in taken:
                        idx += 1
                    name = f"w{idx}"
                w = Worker(
                    name=name,
                    log_path=self.log_dir / f"{name}.log",
                    remote=True,
                )
                self.workers.append(w)
            else:
                if not w.lease_dead and w.url is not None:
                    self._expire_lease_locked(w)
                # a slot re-claim is the SAME worker process carrying
                # cumulative chaos counters into its next generation: its
                # fresh scrapes SUPERSEDE the old generation's retention
                # (keeping both would double-count every prior injection)
                self._injections = {
                    k: v for k, v in self._injections.items() if k[0] != w.name
                }
            w.remote = True
            w.generation += 1
            w.proc = None
            w.url = url
            w.run_id = doc.get("run_id")
            if devices is not None:
                w.devices = devices
                w.device_kind = doc.get("device_kind") or w.device_kind
            w.lease_dead = False
            w.lease_expires_at = self.clock() + self.config.lease_ttl_s
            w.started_at = self.clock()
            w.unready = 0
            w.ever_ready = False
            standby = bool(doc.get("standby"))
            if standby:
                # a pre-registered standby (docs/FLEET.md "Autoscaling"):
                # parked out of the rotation, lease kept warm by its
                # heartbeats, launched by recruit() when demand calls
                w.standby = True
                w.state = WorkerState.STANDBY
            else:
                w.standby = False
                w.state = WorkerState.STARTING
            self._c_registrations.inc()
            obs.flight.record(
                "register",
                worker=w.name,
                generation=w.generation,
                url=url,
                standby=standby,
            )
            self._update_gauges()
            grant = {
                "worker": w.name,
                "generation": w.generation,
                "lease_ttl_s": self.config.lease_ttl_s,
                "heartbeat_every_s": heartbeat_every(self.config.lease_ttl_s),
            }
            if standby:
                grant["standby"] = True
            if self.config.spill_url is not None:
                grant["spill"] = {
                    "url": self.config.spill_url,
                    "namespace": self.spill_namespace(w.name, w.generation),
                }
            log.info(
                "fleet: registered remote worker %s gen %d at %s",
                w.name,
                w.generation,
                url,
            )
            return grant

    def heartbeat(self, name: str, generation: int) -> dict:
        """Renew a remote worker's lease; the typed 410 ``lease_expired``
        for a fenced (or superseded) incarnation is the generation fence
        the split-brain guarantee rests on."""
        from tpu_life.fleet import errors as fl_errors

        generation = int(generation)
        with self._lock:
            w = self.get(name)
            if w is None or not w.remote:
                raise fl_errors.unknown_worker(name)
            if (name, generation) in self._drain_fenced:
                # a drain fence, not a lease-expiry fence: the worker's
                # sessions were never rescued, so it must NOT drop them —
                # typed 503, and the refusal counter (fence evidence)
                # stays untouched
                raise fl_errors.draining(name)
            if (
                (name, generation) in self._fenced
                or w.generation != generation
                or w.lease_dead
            ):
                self._c_lease_refused.inc()
                raise fl_errors.lease_expired(name, generation)
            w.lease_expires_at = self.clock() + self.config.lease_ttl_s
            return {
                "worker": name,
                "generation": generation,
                "lease_ttl_s": self.config.lease_ttl_s,
            }

    def _fence_locked(self, w: Worker) -> None:
        """Record the generation fence for ``w``'s current incarnation
        (caller holds the lock), evicting the oldest fence past the
        :data:`MAX_FENCES` bound."""
        self._fenced[(w.name, w.generation)] = None
        obs.flight.record("fence", worker=w.name, generation=w.generation)
        while len(self._fenced) > MAX_FENCES:
            self._fenced.popitem(last=False)

    def is_fenced(self, name: str, generation: int) -> bool:
        with self._lock:
            return (name, int(generation)) in self._fenced

    def spill_namespace(self, name: str, generation: int) -> str:
        """Where one worker incarnation spills in the REMOTE store: the
        site-prefixed twin of ``worker_spill_dir`` (two fleets sharing a
        store stay disjoint by site)."""
        return f"{self.config.site}{name}g{generation}"

    # -- fleet trace collection (docs/OBSERVABILITY.md) ---------------------
    def _scrape_traces(self, targets: list[tuple]) -> None:
        """Drain each target worker's trace + flight rings concurrently
        (tick latency must be max(scrape), not sum — the probe rule)."""
        if not targets:
            return
        if len(targets) == 1:
            self._scrape_one(*targets[0])
            return
        threads = [
            threading.Thread(
                target=self._scrape_one, args=t, daemon=True
            )
            for t in targets
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

    def _scrape_one(self, w: Worker, generation: int, url: str) -> None:
        """One best-effort drain of a worker's ``/v1/debug/trace``,
        appended as a scrape record to ``<trace_dir>/<name>.jsonl``.
        The clock offset is handshake-estimated: the worker's reported
        ``now`` against the midpoint of our request window — on one
        machine it reads ~0, across hosts it absorbs the wall-clock
        delta so the merge can place both rings on the collector clock."""
        t0 = time.time()
        try:
            req = urllib.request.Request(url + "/v1/debug/trace")
            with urllib.request.urlopen(req, timeout=2.0) as resp:
                doc = json.loads(resp.read())
        except Exception:
            return  # unreachable/dying worker: evidence stays best-effort
        t1 = time.time()
        if not isinstance(doc, dict):
            return
        events = doc.get("events") or []
        flights = doc.get("flight") or []
        if not events and not flights:
            return  # nothing new this tick: no capture line
        now = doc.get("now")
        offset = (
            float(now) - (t0 + t1) / 2.0
            if isinstance(now, (int, float))
            else 0.0
        )
        self._append_capture(
            f"{w.name}.jsonl",
            {
                "worker": w.name,
                "generation": generation,
                "pid": doc.get("pid"),
                "run_id": doc.get("run_id"),
                "wall_t0": doc.get("wall_t0"),
                "offset_s": offset,
                "scraped_at": t1,
                "dropped": doc.get("dropped", 0),
                "events": events,
                "flight": flights,
            },
        )

    def _scrape_control(self) -> None:
        """Drain THIS process's flight ring (router pins, migrations,
        the supervisor's own lifecycle verdicts) into ``control.jsonl``
        — the control plane is a process in the journey too."""
        flights = obs.flight.drain()
        if not flights:
            return
        self._append_capture(
            "control.jsonl",
            {
                "worker": "control",
                "generation": 0,
                "pid": os.getpid(),
                "run_id": None,
                "wall_t0": None,
                "offset_s": 0.0,  # the collector IS the reference clock
                "scraped_at": time.time(),
                "dropped": 0,
                "events": [],
                "flight": flights,
            },
        )

    def _scrape_series(self, targets: list[tuple]) -> None:
        """Read each target worker's snapshot ring concurrently (the
        probe rule: pass latency is max(scrape), not sum)."""
        if not targets:
            return
        if len(targets) == 1:
            self._scrape_series_one(*targets[0])
            return
        threads = [
            threading.Thread(
                target=self._scrape_series_one, args=t, daemon=True
            )
            for t in targets
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

    def _scrape_series_one(self, w: Worker, generation: int, url: str) -> None:
        """One best-effort cursor read of a worker's
        ``/v1/debug/series``: new snapshots land in the per-(worker,
        generation) store (the SLO engine's window substrate) and — with
        ``trace_dir`` set — in ``<name>.series.jsonl`` for offline
        replay.  The cursor is per INCARNATION: a respawned worker's
        ring restarts at seq 0 under a new generation key, so a counter
        reset reads as a new series, never a negative rate."""
        key = (w.name, generation)
        cursor = self._series_cursors.get(key, 0)
        t0 = time.time()
        try:
            req = urllib.request.Request(f"{url}/v1/debug/series?cursor={cursor}")
            with urllib.request.urlopen(req, timeout=2.0) as resp:
                doc = json.loads(resp.read())
        except Exception:
            return  # unreachable/dying worker: collection stays best-effort
        if not isinstance(doc, dict):
            return
        snapshots = doc.get("snapshots") or []
        dropped = int(doc.get("dropped") or 0)
        next_cursor = doc.get("next_cursor")
        if isinstance(next_cursor, int) and next_cursor >= cursor:
            self._series_cursors[key] = next_cursor
        if not snapshots and not dropped:
            return  # nothing new this pass: no store growth, no capture line
        self.series_store.extend(w.name, generation, snapshots, dropped=dropped)
        if self.config.trace_dir is not None:
            self._append_capture(
                f"{w.name}.series.jsonl",
                {
                    "worker": w.name,
                    "generation": generation,
                    "pid": doc.get("pid"),
                    "run_id": doc.get("run_id"),
                    "scraped_at": time.time(),
                    "latency_s": round(time.time() - t0, 6),
                    "cursor": cursor,
                    "next_cursor": next_cursor,
                    "dropped": dropped,
                    "snapshots": snapshots,
                },
            )

    def _sample_control_series(self) -> None:
        """Snapshot the fleet's OWN registry (router routes, leases,
        restarts, ``watcher_shed_total`` — the control plane's signals)
        into its ring and the store under the ``control`` series."""
        snap = self._control_series.sample(self._registry)
        self.series_store.extend("control", 0, [snap])
        if self.config.trace_dir is not None:
            self._append_capture(
                "control.series.jsonl",
                {
                    "worker": "control",
                    "generation": 0,
                    "pid": os.getpid(),
                    "run_id": None,
                    "scraped_at": time.time(),
                    "cursor": snap["seq"],
                    "next_cursor": snap["seq"] + 1,
                    "dropped": 0,
                    "snapshots": [snap],
                },
            )

    def _append_capture(self, fname: str, rec: dict) -> None:
        root = Path(self.config.trace_dir)
        try:
            with self._capture_lock:
                root.mkdir(parents=True, exist_ok=True)
                with open(root / fname, "a") as f:
                    f.write(json.dumps(rec) + "\n")
        except OSError:
            log.warning("fleet: could not append trace capture %s", fname)

    def _kill_for_recycle_locked(self, w: Worker) -> None:
        """Kill a recycle victim (startup timeout / unready threshold).
        With trace collection on and a scrapeable URL, the kill is
        DEFERRED to the tick's unlocked tail so a best-effort final
        drain of the victim's rings (the PR 11 chaos-counter-scrape
        discipline: evidence leaves the process before the process
        leaves) never runs HTTP under the supervisor lock — the routing
        hot path (ready_workers/get) takes this lock on every request.
        Untraced fleets kill inline, byte-for-byte the prior behavior."""
        w.recycling = True
        if self.config.trace_dir is not None and w.url is not None:
            self._doomed.append((w, w.generation, w.url))
        else:
            w.proc.kill()

    def _reap_doomed(self) -> None:
        """The tick's unlocked tail: final-scrape each deferred recycle
        victim, then deliver its kill (re-validated under the lock — the
        generation must still be the condemned one and the process still
        alive; a self-exit meanwhile already took the _on_exit path)."""
        with self._lock:
            doomed, self._doomed = self._doomed, []
        for w, gen, url in doomed:
            self._scrape_one(w, gen, url)
            if self.config.series_every_s > 0:
                self._scrape_series_one(w, gen, url)
            with self._lock:
                if (
                    w.generation == gen
                    and w.proc is not None
                    and w.proc.poll() is None
                ):
                    w.proc.kill()

    # -- chaos-injection retention (docs/CHAOS.md) --------------------------
    def _record_injections_locked(self, w: Worker, series: dict) -> None:
        """Fold one scrape of a worker's ``chaos_injections_total`` into
        the per-(worker, generation) last-seen view.  Monotone max per
        incarnation: a counter reset (respawn) starts a NEW generation
        key instead of silently shrinking the old one."""
        totals: dict[tuple[str, str, str], float] = {}
        for key, v in series.items():
            point, _, outcome = key.partition("|")
            k = (w.name, w.generation, point, outcome)
            self._injections[k] = max(self._injections.get(k, 0.0), float(v))
        for (name, _gen, point, outcome), v in self._injections.items():
            if name == w.name:
                tk = (name, point, outcome)
                totals[tk] = totals.get(tk, 0.0) + v
        for (name, point, outcome), v in totals.items():
            self._g_injections.labels(
                worker=name, point=point, outcome=outcome
            ).set(v)

    def injection_totals(self) -> dict:
        """``point -> outcome -> count`` summed over every worker
        incarnation ever seen — the drill's exact accounting (a dead
        worker's last-seen counters are retained here, not lost with its
        registry)."""
        with self._lock:
            out: dict[str, dict[str, float]] = {}
            for (_name, _gen, point, outcome), v in self._injections.items():
                bucket = out.setdefault(point, {})
                bucket[outcome] = bucket.get(outcome, 0.0) + v
            return out

    def _spawn_worker(self, w: Worker, *, first: bool = False) -> None:
        if self._draining:
            # a SIGTERM can land between installing handlers and start()'s
            # spawn loop (the handler interleaves — the lock is reentrant
            # on this thread): a worker spawned AFTER the drain began would
            # never receive its SIGTERM and the drain would hang forever
            w.state = WorkerState.DOWN
            return
        w.generation += 1
        w.started_at = self.clock()
        w.url = None
        w.run_id = None
        w.unready = 0
        w.recycling = False
        w.state = WorkerState.STARTING
        if not first:
            self._c_restarts.inc()
        self.spawn(w)

    def _update_gauges(self) -> None:
        counts = {st: 0 for st in WorkerState}
        for w in self.workers:
            counts[w.state] += 1
            if w.devices is not None:
                self._g_devices.labels(worker=w.name).set(float(w.devices))
        for st, n in counts.items():
            self._g_workers.labels(state=st.value).set(float(n))

    # -- default process plumbing -----------------------------------------
    def worker_argv(self, w: Worker) -> list[str]:
        argv = [
            sys.executable,
            "-m",
            "tpu_life",
            "gateway",
            "--host",
            self.config.host,
            "--port",
            "0",
            *self.config.worker_args,
        ]
        if self.config.metrics_dir is not None:
            sink = Path(self.config.metrics_dir) / f"{w.name}.jsonl"
            argv += ["--metrics-file", str(sink)]
        if self.config.spill_dir is not None:
            # per-incarnation spill dir: a respawn must never read (or
            # clobber) its predecessor's sessions — the migrator owns those
            from tpu_life.fleet.migrate import worker_spill_dir

            argv += [
                "--spill-dir",
                str(worker_spill_dir(self.config.spill_dir, w.name, w.generation)),
                "--spill-every",
                str(self.config.spill_every),
            ]
        elif self.config.spill_url is not None:
            # the remote twin: same per-incarnation isolation, expressed
            # as a namespace in the shared store instead of a directory
            argv += [
                "--spill-url",
                self.config.spill_url,
                "--spill-namespace",
                self.spill_namespace(w.name, w.generation),
                "--spill-every",
                str(self.config.spill_every),
            ]
        if self.config.trace_dir is not None:
            # fleet trace collection: an ACTIVE tracer per incarnation —
            # the scrape drains its ring live, and a graceful exit writes
            # whatever was never drained to the per-generation file the
            # merge also reads (a respawn must not clobber its
            # predecessor's undrained tail)
            argv += [
                "--trace-events",
                str(
                    Path(self.config.trace_dir)
                    / f"{w.name}g{w.generation}.trace.json"
                ),
            ]
        return argv

    def _default_spawn(self, w: Worker) -> None:
        # the package may be import-from-checkout rather than installed:
        # make sure the child can `python -m tpu_life` regardless of cwd
        env = dict(os.environ)
        pkg_root = str(Path(__file__).resolve().parents[2])
        env["PYTHONPATH"] = (
            pkg_root + os.pathsep + env["PYTHONPATH"]
            if env.get("PYTHONPATH")
            else pkg_root
        )
        if w.env_overlay:
            # the placement seam: this worker's device slice, re-applied
            # verbatim at every spawn so a restart re-enters the SAME env
            apply_env_overlay(env, w.env_overlay)
        w.log_offset = w.log_path.stat().st_size if w.log_path.exists() else 0
        with open(w.log_path, "ab") as logf:
            w.proc = subprocess.Popen(
                self.worker_argv(w),
                stdout=logf,
                stderr=subprocess.STDOUT,
                env=env,
                # detached session: a ^C at the fleet CLI must reach the
                # workers as a supervised drain, not a raw group SIGINT
                start_new_session=True,
            )
        log.debug("fleet: spawned %s gen %d pid %d", w.name, w.generation, w.proc.pid)

    def _read_startup(self, w: Worker) -> dict | None:
        """Scan the worker's log (from this generation's offset) for the
        gateway startup JSON line; returns the parsed line (url, run_id,
        resolved devices/device_kind, ...) or None."""
        try:
            with open(w.log_path, "rb") as f:
                f.seek(w.log_offset)
                data = f.read()
        except OSError:
            return None
        for raw in data.split(b"\n")[:-1]:  # complete lines only
            raw = raw.strip()
            if not raw.startswith(b"{"):
                continue
            try:
                doc = json.loads(raw)
            except json.JSONDecodeError:
                continue
            if doc.get("mode") == "gateway" and "url" in doc:
                return doc
        return None

    def _default_probe(self, w: Worker):
        if w.url is None:
            return "unreachable"
        try:
            req = urllib.request.Request(w.url + "/readyz")
            with urllib.request.urlopen(req, timeout=1.0) as resp:
                try:
                    doc = json.loads(resp.read())
                except (json.JSONDecodeError, OSError):
                    doc = {}
            # the injection-retention scrape (docs/CHAOS.md): while a
            # chaos plan is armed in THIS process (a drill), every probe
            # also folds the worker's chaos_injections_total into the
            # fleet registry — so a dead worker's counters no longer die
            # with its own registry, and drill accounting is exact
            # rather than a pre-kill floor
            if chaos.armed():
                series = _scrape_injection_series(w.url)
                if series:
                    doc["_chaos_injections"] = series
            # carry the readyz body: it grows devices/device_kind
            # once the worker's async device resolution lands
            return ("ready", doc)
        except urllib.error.HTTPError as e:
            if e.code == 503:
                return "draining"
            reason = _unready_reason(e)
            if reason:
                # a TYPED refusal (the serve wedge watchdog's 500
                # engine_wedged): unreachable for recycle purposes, but
                # the machine-readable reason rides along
                return ("unreachable", {"unready_reason": reason})
            return "unreachable"
        except Exception:
            return "unreachable"


def _unready_reason(e) -> str | None:
    """``code[:reason]`` from a refused probe's JSON error envelope, or
    None when the body is unreadable/untyped — reason extraction must
    never turn a readable refusal into a probe crash."""
    try:
        doc = json.loads(e.read() or b"{}")
        err = doc.get("error") or {}
        code = err.get("code")
        if not code:
            return None
        reason = err.get("reason")
        return f"{code}:{reason}" if reason else str(code)
    except Exception:
        return None


def _scrape_injection_series(url: str) -> dict[str, float] | None:
    """One best-effort scrape of a worker's ``chaos_injections_total``
    series: ``{"point|outcome": value}``, or None when the worker (or its
    exposition) is unreadable — evidence collection must never fail a
    probe that already answered ready."""
    try:
        req = urllib.request.Request(url + "/metrics")
        with urllib.request.urlopen(req, timeout=1.0) as resp:
            text = resp.read().decode()
    except Exception:
        return None
    series: dict[str, float] = {}
    for line in text.splitlines():
        if not line.startswith("chaos_injections_total{"):
            continue
        head, _, value = line.rpartition(" ")
        inner = head[head.find("{") + 1 : head.rfind("}")]
        point = outcome = ""
        for part in inner.split(","):
            k, _, v = part.partition("=")
            if k == "point":
                point = v.strip('"')
            elif k == "outcome":
                outcome = v.strip('"')
        if not point:
            continue
        try:
            series[f"{point}|{outcome}"] = float(value)
        except ValueError:
            continue
    return series or None


def worker_weight(w: Worker) -> float:
    """The capacity weight weighted-least-depth routing normalizes queue
    depth by: the worker's resolved device count (planned until its
    startup line reports), never below 1 — a worker that has not said
    what it owns routes as a single-chip peer, not as zero capacity."""
    return float(max(1, w.devices or 1))


def propagate_signals(on_signal) -> None:
    """SIGTERM / SIGINT -> the fleet-wide drain (main thread only)."""

    def _handler(signum, frame):
        log.info("fleet: signal %d — draining the fleet", signum)
        on_signal()

    signal.signal(signal.SIGTERM, _handler)
    signal.signal(signal.SIGINT, _handler)
