"""Wire-registered fleet membership: leases, fencing, the registrar.

The supervisor's original worker model is *ownership*: it spawns a
subprocess, so liveness is ``proc.poll()`` and identity is implicit.
Cross-host fleets break that — a worker on another machine registers
over HTTP instead of being spawned, and the control plane's knowledge of
it is only ever as fresh as its last message.  Membership therefore
becomes a **lease**:

- **Registration** (``POST /v1/fleet/register``): the worker sends
  exactly its startup JSON line (the ``mode: gateway`` document with its
  bound ``url`` / ``run_id`` / resolved ``devices``) — the contract that
  already existed *is* the handshake.  The control plane admits it as a
  fresh ``(worker, generation)``, grants a lease, and assigns the spill
  namespace that incarnation must write (so a later rescue knows where
  to read).
- **Heartbeats** (``POST /v1/fleet/heartbeat``): renew the lease.  A
  lease that expires un-renewed fires the SAME worker-exit hook a local
  process death does — the migrator rescues the spills — and the
  ``(worker, generation)`` is **fenced**.
- **Fencing**: a fenced incarnation's heartbeat is refused with the
  typed 410 ``lease_expired``, never silently re-admitted: its sessions
  were re-homed, and letting a partitioned-but-alive worker carry on
  would be split-brain double execution.  The namespaced ``wNgM-sK`` sid
  encoding makes the fence checkable end to end — every pin names the
  exact incarnation it trusts.

Locally-spawned workers keep working unchanged: the supervisor admits
their startup line through the same accounting (they hold a lease too,
renewed by its own liveness probes), so one code path decides membership
regardless of who started the process.

This module holds the **worker-side** :class:`Registrar` (a small
background client any ``tpu-life gateway --register URL`` runs) and the
shared helpers; the control-plane half lives on the
:class:`~tpu_life.fleet.supervisor.Supervisor` (``register_worker`` /
``heartbeat``), wired to HTTP by the router.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request

from tpu_life import chaos
from tpu_life.gateway.errors import backoff_delay
from tpu_life.runtime.metrics import log

#: Default lease TTL granted to wire-registered workers.  Heartbeats run
#: at a third of it, so a lease survives two lost beats and the third
#: fences — responsive enough to matter, lazy enough not to flap.
LEASE_TTL_S = 15.0

ROUTE_REGISTER = "/v1/fleet/register"
ROUTE_HEARTBEAT = "/v1/fleet/heartbeat"


def heartbeat_every(ttl_s: float) -> float:
    return max(0.05, ttl_s / 3.0)


class Registrar:
    """The worker's membership client: register, heartbeat, re-register
    when fenced.

    Runs on a daemon thread beside the gateway.  The loop is two nested
    phases: acquire a grant (retrying refusals on the shared jittered
    backoff — the ``lease.register.reset`` chaos point fires here), then
    heartbeat until the control plane refuses.  On the typed 410
    ``lease_expired`` the worker's sessions were rescued elsewhere, so
    the registrar calls ``on_fenced`` (the gateway wires it to
    ``service.cancel_live`` — finishing the local copies would double-
    execute re-homed trajectories) and re-registers for a fresh
    generation, re-binding the spill namespace from the new grant.

    Everything is injectable (``http``, ``clock``, ``sleep``) so the
    state machine unit-tests without sockets.
    """

    def __init__(
        self,
        control_url: str,
        *,
        self_url: str,
        run_id: str | None = None,
        device_info=None,  # callable -> (devices, kind) | None
        on_grant=None,  # callable(grant dict) — spill-namespace rebinding
        on_fenced=None,  # callable(reason str) — drop re-homed sessions
        standby: bool = False,  # park in the standby pool, not the rotation
        timeout_s: float = 5.0,
        backoff_s: float = 0.2,
        max_backoff_s: float = 5.0,
        clock=time.monotonic,
        sleep=time.sleep,
        http=None,
    ):
        self.control_url = control_url.rstrip("/")
        self.self_url = self_url
        self.run_id = run_id
        self.device_info = device_info
        self.on_grant = on_grant
        self.on_fenced = on_fenced
        #: standby membership (docs/FLEET.md "Autoscaling"): registered
        #: and leased, but PARKED — the control plane keeps us out of
        #: the rotation until its autoscaler recruits the slot
        self.standby = standby
        self.timeout_s = timeout_s
        self.backoff_s = backoff_s
        self.max_backoff_s = max_backoff_s
        self.clock = clock
        self.sleep = sleep
        self.http = http or self._default_http
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        #: the current grant: None until the first registration lands
        self.worker: str | None = None
        self.generation: int | None = None
        self.lease_ttl_s: float = LEASE_TTL_S
        #: observability for drills/tests: how often we were fenced
        self.fenced_count = 0
        self.registrations = 0

    # -- plumbing ------------------------------------------------------------
    def _default_http(self, path: str, body: dict) -> tuple[int, dict]:
        req = urllib.request.Request(
            self.control_url + path,
            data=json.dumps(body).encode(),
            method="POST",
        )
        req.add_header("Content-Type", "application/json")
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
                return resp.status, _parse(resp.read())
        except urllib.error.HTTPError as e:
            return e.code, _parse(e.read())

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._loop, name="fleet-registrar", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    # -- the state machine ----------------------------------------------------
    def _loop(self) -> None:
        while not self._stop.is_set():
            grant = self._register_until_granted()
            if grant is None:
                return  # stopped
            self._heartbeat_until_fenced(grant)

    def _register_until_granted(self) -> dict | None:
        attempt = 0
        while not self._stop.is_set():
            doc = {
                "mode": "gateway",
                "url": self.self_url,
                "run_id": self.run_id,
            }
            if self.standby:
                doc["standby"] = True
            if self.worker is not None:
                # a re-registration claims the prior name: the control
                # plane bumps the generation on the same slot, exactly
                # like a local respawn
                doc["worker"] = self.worker
            if self.device_info is not None:
                info = self.device_info()
                if info is not None:
                    doc["devices"], doc["device_kind"] = info
            try:
                # chaos seam: the registration POST is reset before the
                # control plane ever sees it — the worker's only correct
                # move is to retry (registration is idempotent: the CP
                # mints the generation, so a lost answer costs a fenced
                # ghost generation, never a duplicate identity)
                if chaos.decide("lease.register.reset") is not None:
                    chaos.record_fire("lease.register.reset", "reset")
                    raise ConnectionResetError("chaos: register reset")
                if chaos.partitioned("registrar", self.control_url):
                    raise ConnectionRefusedError("chaos: net partition")
                status, body = self.http(ROUTE_REGISTER, doc)
            except Exception as e:  # noqa: BLE001 - transport noise: retry
                log.debug("registrar: register attempt failed: %s", e)
                status, body = 0, {}
            if status == 400 and self.worker is not None:
                # the claim itself was refused (e.g. a restarted control
                # plane now runs a LOCAL worker under our old name):
                # retrying the same claim forever would orphan us — drop
                # it and register fresh for whatever name is granted
                log.warning(
                    "registrar: registration claiming %s refused (%s); "
                    "dropping the stale claim",
                    self.worker,
                    _code(body),
                )
                self.worker = None
                self.generation = None
                continue
            if status == 200 and isinstance(body.get("worker"), str):
                self.worker = body["worker"]
                self.generation = int(body.get("generation", 0))
                self.lease_ttl_s = float(body.get("lease_ttl_s", LEASE_TTL_S))
                self.registrations += 1
                log.info(
                    "registrar: registered as %s gen %d (lease %.1fs)",
                    self.worker,
                    self.generation,
                    self.lease_ttl_s,
                )
                if self.on_grant is not None:
                    try:
                        self.on_grant(body)
                    except Exception:
                        log.exception("registrar: on_grant hook failed")
                return body
            attempt += 1
            self._nap(
                backoff_delay(
                    attempt, base=self.backoff_s, cap=self.max_backoff_s
                )
            )
        return None

    def _heartbeat_until_fenced(self, grant: dict) -> None:
        every = heartbeat_every(self.lease_ttl_s)
        while not self._stop.is_set():
            self._nap(every)
            if self._stop.is_set():
                return
            # chaos seam: the heartbeat is dropped on the floor — the
            # asymmetric partition where the worker believes it is fine
            # while the control plane hears silence.  Enough consecutive
            # drops expire the lease and the next delivered heartbeat
            # meets the fence.
            if chaos.decide("lease.heartbeat.drop") is not None:
                chaos.record_fire("lease.heartbeat.drop", "drop")
                continue
            if chaos.partitioned("registrar", self.control_url):
                continue
            try:
                status, body = self.http(
                    ROUTE_HEARTBEAT,
                    {"worker": self.worker, "generation": self.generation},
                )
            except Exception as e:  # noqa: BLE001 - transient: the lease
                # has slack for lost beats; a real partition ends at the
                # fence, not here
                log.debug("registrar: heartbeat failed: %s", e)
                continue
            if status == 200:
                continue
            if status == 410 and _code(body) == "lease_expired":
                self.fenced_count += 1
                log.warning(
                    "registrar: FENCED — %s gen %s lease expired and its "
                    "sessions were re-homed; dropping local state and "
                    "re-registering",
                    self.worker,
                    self.generation,
                )
                if self.on_fenced is not None:
                    try:
                        self.on_fenced("lease_expired")
                    except Exception:
                        log.exception("registrar: on_fenced hook failed")
                return  # back to registration with a fresh generation
            if status == 404:
                # the control plane has no record of us at all (it
                # restarted): nothing was rescued, so local sessions are
                # kept — but the lease is gone and only a fresh
                # registration restores capacity; looping here would
                # orphan the worker forever
                log.warning(
                    "registrar: control plane no longer knows %s gen %s "
                    "(%s); re-registering",
                    self.worker,
                    self.generation,
                    _code(body),
                )
                return
            log.debug(
                "registrar: heartbeat answered %s %s", status, _code(body)
            )

    def _nap(self, seconds: float) -> None:
        """Sleep in stop-aware slices (sleep is injectable for tests)."""
        if self.sleep is not time.sleep:
            self.sleep(seconds)
            return
        self._stop.wait(seconds)


def _parse(raw: bytes) -> dict:
    try:
        doc = json.loads(raw or b"{}")
        return doc if isinstance(doc, dict) else {}
    except json.JSONDecodeError:
        return {}


def _code(doc: dict) -> str | None:
    err = doc.get("error")
    return err.get("code") if isinstance(err, dict) else None
