"""Session migration: a dead worker's spilled sessions resume elsewhere.

The failure-masking half of durability (docs/FLEET.md).  Each worker
spills its live sessions through the checkpoint contract
(``serve.spill``) into a per-generation directory the supervisor chose
for it.  When a worker dies, the supervisor's exit hook hands the death
to a :class:`Migrator`, which:

1. marks the dead ``(worker, generation)`` MIGRATING — the router
   answers the victim's pinned sids with a typed 409 ``migrating`` (+
   ``Retry-After``) or a synthetic in-progress poll view, never a 410 —
2. reads the victim's intact spills (CRC-verified; a corrupt-but-right-
   sized snapshot demotes to its predecessor, a session with no intact
   snapshot is recorded ``spill_corrupt``),
3. re-submits each as a **resume request** (``resume_b64`` +
   ``start_step`` + remaining budget + seed/temperature) to a survivor —
   refusal-only retry, exactly the router's own no-duplicate rule, and
   through the same capacity-WEIGHTED balancer, so a rescued batch
   lands on survivors in proportion to their device slices — and
4. re-pins the ORIGINAL fleet sid onto the survivor's session (a STICKY
   pin: LRU churn evicts ordinary pins around it, because the sid
   string encodes the DEAD home and a parse-fallback would answer a
   spurious 410), so the unmodified PR 4 client polls straight through
   the kill.

Placement interplay (docs/FLEET.md "Device placement"): the supervisor
re-applies the dead worker's env overlay verbatim when it respawns, so
the fresh generation re-enters the SAME device slice while its former
sessions finish on survivors — capacity returns without re-planning.

Bit-identity is inherited, not re-proven: deterministic rules are pure
functions of the board, and the MC tier's ``(seed, step, cell,
substream)`` key schedule plus ``start_step`` makes a mid-stream restart
re-enter the exact stream.

Double death: when a survivor dies mid- or post-migration, the sessions
it adopted migrate again — the ``alias`` map remembers which original
fleet sid each adopted session answers to, so a second hop re-pins the
sid the client actually holds.

Sessions that were never spilled (death between admission and the first
spill pass) stay lost: once the migration run completes, their sids
answer 410 ``worker_lost`` with ``reason: never_snapshotted`` — the
documented recovery-point bound of a K-round spill cadence.

Everything is injectable (``forward``, ``clock``, ``sleep``) so the
state machine unit-tests on fakes; the real wiring (``tpu_life.fleet``)
hands it the router's forwarder and balancer.
"""

from __future__ import annotations

import base64
import json
import shutil
import threading
import time
from collections import OrderedDict
from pathlib import Path

from tpu_life import chaos, obs
from tpu_life.fleet.registry import fleet_sid
from tpu_life.fleet.router import (
    REFUSAL_CODES,
    WorkerUnreachable,
    _error_code,
    _json_body as _json,
)
from tpu_life.gateway.errors import backoff_delay
from tpu_life.gateway.server import ROUTE_SESSIONS
from tpu_life.io.codec import encode_board
from tpu_life.runtime.metrics import log
from tpu_life.serve.spill import (
    MeshSpillRecord,
    SpillRecord,
    read_mesh_sessions,
    read_spill_sessions,
)

#: Peer-router 503 codes that mean "definitively not admitted" — the
#: worker refusal set plus the router's own fleet-level refusal.
PEER_REFUSAL_CODES = REFUSAL_CODES | {"fleet_unavailable"}

#: Bound on remembered per-sid outcomes / aliases (a months-running
#: router must not grow without bound; an evicted outcome degrades to
#: ``never_snapshotted`` — still a truthful 410).
MAX_OUTCOMES = 100_000



def worker_spill_dir(root: str, name: str, generation: int) -> Path:
    """Where one worker incarnation spills: per-generation, so a respawn
    can never read (or clobber) its predecessor's sessions."""
    return Path(root) / f"{name}g{generation}"


def resume_request(rec: SpillRecord) -> dict:
    """The wire body that resumes one spilled session on a survivor."""
    body = {
        "rule": rec.rule,
        "steps": rec.remaining,
        "start_step": rec.step,
        "resume_b64": base64.b64encode(encode_board(rec.board)).decode("ascii"),
        "height": rec.height,
        "width": rec.width,
    }
    if rec.seed is not None:
        body["seed"] = rec.seed
    if rec.temperature is not None:
        body["temperature"] = rec.temperature
    if rec.timeout_s is not None:
        body["timeout_s"] = rec.timeout_s
    if rec.trace_id is not None:
        # trace continuity (docs/OBSERVABILITY.md "Distributed tracing"):
        # the manifest-persisted id rides the resume wire body, so the
        # survivor's session CONTINUES the dead worker's trace — one
        # trace_id across generations and hosts
        body["trace_id"] = rec.trace_id
    # steered-session continuity (docs/STREAMING.md): the applied edit
    # log (provenance — already baked into the spilled board), the
    # unapplied scheduled tail (the survivor re-applies it at the
    # recorded steps), and the delta-stream sequence floor (a
    # reconnected watcher's numbering stays gapless across the failover)
    if rec.edits:
        body["edits"] = rec.edits
    if rec.scheduled_edits:
        body["scheduled_edits"] = rec.scheduled_edits
    if rec.stream_seq:
        body["stream_seq"] = rec.stream_seq
    return body


def mesh_resume_request(rec: MeshSpillRecord) -> dict:
    """The wire body that resumes one mega-board tile-set session (docs/
    SERVING.md "Mega-board sessions"): a ``resume_tiles_dir`` POINTER
    instead of ``resume_b64`` — the board never rides the wire, the
    survivor re-gathers it shard by shard from the shared spill root.
    This is why mesh rescues are local-plane only: a peer control plane
    on another host cannot see the directory."""
    body = {
        "rule": rec.rule,
        "steps": rec.remaining,
        "start_step": rec.step,
        "resume_tiles_dir": str(rec.root),
        "height": rec.height,
        "width": rec.width,
    }
    if rec.timeout_s is not None:
        body["timeout_s"] = rec.timeout_s
    if rec.trace_id is not None:
        body["trace_id"] = rec.trace_id
    if rec.scheduled_edits:
        body["scheduled_edits"] = rec.scheduled_edits
    if rec.stream_seq:
        body["stream_seq"] = rec.stream_seq
    return body


class Migrator:
    """Owns the migration state machine and the per-death worker threads."""

    def __init__(
        self,
        *,
        spill_root: str | None = None,
        supervisor,
        sessions,
        registry,
        balancer,
        forward,
        clock=time.monotonic,
        sleep=time.sleep,
        timeout_s: float = 30.0,
        retry_pause_s: float = 0.5,
        max_retry_pause_s: float = 5.0,
        stuck_after_s: float = 120.0,
        spill_url: str | None = None,
        site: str = "",
        peers: tuple[str, ...] = (),
    ):
        self.spill_root = spill_root
        #: remote spill store (docs/FLEET.md "Cross-host topology"): read
        #: a dead worker's sessions out of the shared HTTP store instead
        #: of a local directory — the rescue works when the survivor is
        #: on another machine.  ``site`` prefixes this control plane's
        #: namespaces in a SHARED store.
        self.spill_url = spill_url
        self.site = site
        #: peer control planes: when every LOCAL survivor refuses a
        #: resume, re-submit to a peer fleet's router — the session then
        #: answers its ORIGINAL sid through the peer proxy.
        self.peers = tuple(peers)
        self.supervisor = supervisor
        self.sessions = sessions
        self.balancer = balancer
        self.forward = forward
        self.clock = clock
        self.sleep = sleep
        self.timeout_s = timeout_s
        self.retry_pause_s = retry_pause_s
        self.max_retry_pause_s = max_retry_pause_s
        # the stuck-MIGRATING watchdog (docs/CHAOS.md): a migration run
        # that neither finishes nor fails — its thread died, or the exit
        # hook never fired — must not leave sids answering synthetic
        # in-progress views forever.  Past this deadline WITHOUT
        # PROGRESS (a live run heartbeats after every record it settles,
        # so the clock bounds one record's stall, not the whole run —
        # keep it comfortably above ``timeout_s``) a still-pending sid
        # settles to a terminal 410 ``migration_failed``.
        self.stuck_after_s = stuck_after_s
        self._lock = threading.Lock()
        # (worker, generation) -> when its migration run was activated
        self._active: dict[tuple[str, int], float] = {}
        self._completed: set[tuple[str, int]] = set()
        # fsid -> when the no-record "rescue imminent" fallback first
        # answered migrating for it (the watchdog's clock for deaths
        # whose exit hook never arrives)
        self._pending_since: OrderedDict[str, float] = OrderedDict()
        # fsid -> terminal non-migrated reason (spill_corrupt / migration_failed)
        self._failed: OrderedDict[str, str] = OrderedDict()
        # (worker, generation, worker-sid) -> the ORIGINAL fleet sid a
        # client holds — consulted on double death so a second hop
        # re-pins the sid that is actually out there
        self._alias: OrderedDict[tuple[str, int, str], str] = OrderedDict()
        # fsid -> (peer router url, peer fleet sid): sessions rescued
        # onto a PEER control plane; the router proxies these
        self._peer_pins: OrderedDict[str, tuple[str, str]] = OrderedDict()
        # fsid -> (steps_total, steps_done) from the spill manifest, for
        # synthetic poll views while the migration is in flight
        self._progress: dict[str, tuple[int, int]] = {}
        self._threads: list[threading.Thread] = []
        self._c_migrations = registry.counter(
            "fleet_migrations_total",
            "sessions handled by worker-death migration, by outcome",
            labels=("outcome",),
        )
        for outcome in ("migrated", "peer", "corrupt", "failed", "disabled"):
            self._c_migrations.labels(outcome=outcome)

    # -- the supervisor hook (called under its lock: must be fast) ----------
    def worker_exit(self, name: str, generation: int) -> None:
        key = (name, generation)
        with self._lock:
            if key in self._active or key in self._completed:
                return
            self._active[key] = self.clock()
        # chaos seam (docs/CHAOS.md): the migration thread dies before it
        # ever runs — the run is recorded ACTIVE but nothing will finish
        # it.  Without the stuck watchdog this leaves every victim sid
        # answering synthetic in-progress views forever; the drill arms
        # this point and asserts they settle to 410 migration_failed.
        if chaos.decide("migrate.die") is not None:
            chaos.record_fire("migrate.die", "die")
            log.error(
                "chaos: migration thread for %s gen %d killed at birth",
                name,
                generation,
            )
            return
        t = threading.Thread(
            target=self._run,
            args=(name, generation),
            name=f"fleet-migrate-{name}g{generation}",
            daemon=True,
        )
        # prune finished runs: a months-running fleet with restart churn
        # must not retain one dead Thread object per worker death
        self._threads = [x for x in self._threads if x.is_alive()]
        self._threads.append(t)
        t.start()

    # -- the router's view --------------------------------------------------
    def status(self, fsid: str, pin, *, pending_ok: bool = True) -> tuple[str, ...]:
        """What a request for a sid whose pinned home is gone should get:
        ``("migrating",)`` or ``("lost", reason)``.

        ``pending_ok`` narrows the no-record fallback: True only when the
        pin targets the worker's CURRENT generation (the just-died,
        exit-hook-not-yet-fired window, where a rescue is imminent).  A
        pin into an unknown PAST generation — a sid from a previous fleet
        process, or a forged generation — has no rescue coming and must
        settle to a terminal 410, never poll as migrating forever.

        Both migrating answers carry the stuck watchdog: an ACTIVE run
        older than ``stuck_after_s`` (its thread died mid-flight), or a
        pending-fallback sid that has waited that long for an exit hook
        that never came, settles to a terminal 410 ``migration_failed``
        instead of polling as migrating until the end of time."""
        now = self.clock()
        with self._lock:
            reason = self._failed.get(fsid)
            if reason is not None:
                return ("lost", reason)
            key = (pin.worker, pin.generation)
            started = self._active.get(key)
            if started is not None:
                if now - started <= self.stuck_after_s:
                    return ("migrating",)
            elif key in self._completed:
                # the run finished and neither re-pinned nor failed this
                # sid: it was never spilled before the death
                return ("lost", "never_snapshotted")
            elif not pending_ok:
                return ("lost", "never_snapshotted")
            else:
                # the death has not reached the supervisor's exit hook yet
                # (the monitor tick is on its way): migration is imminent —
                # but start (and bound) the watchdog clock for this sid
                first = self._pending_since.setdefault(fsid, now)
                while len(self._pending_since) > MAX_OUTCOMES:
                    self._pending_since.popitem(last=False)
                if now - first <= self.stuck_after_s:
                    return ("migrating",)
        # the watchdog tripped: whatever was meant to settle this sid is
        # presumed dead — record the terminal verdict (outside the lock;
        # _record_failure re-acquires it) so every later request is a
        # fast, consistent 410
        log.warning(
            "fleet: migration of %s stuck past %.0fs; settling to "
            "migration_failed (watchdog)",
            fsid,
            self.stuck_after_s,
        )
        self._record_failure(fsid, "migration_failed")
        return ("lost", "migration_failed")

    def progress(self, fsid: str) -> tuple[int, int] | None:
        with self._lock:
            return self._progress.get(fsid)

    def peer_of(self, fsid: str) -> tuple[str, str] | None:
        """``(peer router url, peer fleet sid)`` for a session rescued
        onto a peer control plane, else None — the router's proxy seam."""
        with self._lock:
            return self._peer_pins.get(fsid)

    def wait_idle(self, timeout: float = 30.0) -> bool:
        """Block until every migration thread finished (tests, drains)."""
        deadline = time.monotonic() + timeout
        for t in list(self._threads):
            t.join(max(0.0, deadline - time.monotonic()))
            if t.is_alive():
                return False
        return True

    # -- one worker-death migration run -------------------------------------
    def _run(self, name: str, generation: int) -> None:
        remote_ns = None
        d = None
        if self.spill_url is not None:
            # the wire read path (docs/FLEET.md "Cross-host topology"):
            # the victim's namespace in the shared store, site-prefixed —
            # identical triage to the directory read, CRC re-checked on
            # the downloaded bytes
            remote_ns = f"{self.site}{name}g{generation}"
        else:
            d = worker_spill_dir(self.spill_root, name, generation)
        cleanup = True
        try:
            try:
                if remote_ns is not None:
                    from tpu_life.serve.spill_http import read_remote_sessions

                    # mesh tile sets never reach the remote store (the
                    # HTTP backend has no shard-wise contract — the
                    # worker marked those sessions spill-disabled, which
                    # lands in ``disabled`` below): nothing extra to read
                    records, corrupt, disabled = read_remote_sessions(
                        self.spill_url, remote_ns
                    )
                else:
                    records, corrupt, disabled = read_spill_sessions(d)
                    mrecs, mcorrupt, mdisabled = read_mesh_sessions(d)
                    records = list(records) + list(mrecs)
                    corrupt = list(corrupt) + list(mcorrupt)
                    disabled = list(disabled) + list(mdisabled)
            except Exception:
                # a read failure must not delete bytes nobody looked at
                log.exception("fleet: cannot read spills of %s gen %d", name,
                              generation)
                records, corrupt, disabled, cleanup = [], [], [], False
            log.info(
                "fleet: migrating %d session(s) from dead %s gen %d "
                "(%d corrupt, %d spill-disabled)",
                len(records),
                name,
                generation,
                len(corrupt),
                len(disabled),
            )
            obs.flight.record(
                "migrate.start",
                worker=name,
                generation=generation,
                sessions=len(records),
                corrupt=len(corrupt),
                disabled=len(disabled),
            )
            for sid in corrupt:
                self._record_failure(
                    self._target_fsid(name, generation, sid),
                    "spill_corrupt",
                    counter="corrupt",
                )
            for sid in disabled:
                # the worker itself degraded this session (a spill write
                # failed — ENOSPC): the truthful reason is the
                # degradation, not the misleading never_snapshotted
                self._record_failure(
                    self._target_fsid(name, generation, sid),
                    "spill_disabled",
                    counter="disabled",
                )
            # resolve every record's client-facing fsid and publish its
            # last-known progress BEFORE any resume runs: synthetic poll
            # views never regress to 0/0 while a session waits its turn
            targets = [
                (self._target_fsid(name, generation, rec.sid), rec)
                for rec in records
            ]
            with self._lock:
                for fsid, rec in targets:
                    self._progress[fsid] = (rec.steps_total, rec.step)
            # per-record isolation: a crash resuming record 3 must neither
            # abort records 4..N unattempted nor mislabel them
            # never_snapshotted — every session's fate gets recorded
            for fsid, rec in targets:
                # the watchdog may have settled this sid to a terminal
                # 410 while it waited its turn (behind a stalled
                # predecessor record): the client was TOLD it is lost
                # and the documented recourse is a fresh resubmission —
                # resuming it now would run the trajectory twice.  The
                # terminal answer is sticky; honor it.
                with self._lock:
                    settled = fsid in self._failed
                if settled:
                    log.warning(
                        "fleet: %s settled by the stuck watchdog before "
                        "its resume could run; not resuming", fsid,
                    )
                    continue
                try:
                    self._migrate_one(fsid, rec)
                except Exception:
                    log.exception("fleet: resume of %s crashed", fsid)
                    self._record_failure(fsid, "migration_failed")
                if isinstance(rec, MeshSpillRecord) and rec.root.exists():
                    with self._lock:
                        lost = fsid in self._failed
                    if not lost:
                        # the survivor admitted the resume but could NOT
                        # adopt the tiles by rename (no local spill store
                        # of its own): it will re-gather from THIS
                        # directory at admission, so the victim dir must
                        # outlive the run — a bounded disk leak, never a
                        # truncated re-gather
                        log.warning(
                            "fleet: %s resumed without tile adoption; "
                            "keeping victim spill dir %s", fsid, d,
                        )
                        cleanup = False
                # progress heartbeat: a LIVE run refreshes its watchdog
                # clock after every record it settles, so stuck_after_s
                # bounds one record's stall — never the wall time of a
                # many-session rescue
                with self._lock:
                    if (name, generation) in self._active:
                        self._active[(name, generation)] = self.clock()
        finally:
            with self._lock:
                self._active.pop((name, generation), None)
                self._completed.add((name, generation))
            if cleanup:
                # the victim's spills are orphaned now: every session
                # either lives on a survivor (which spills it under its
                # OWN namespace) or is terminally lost — either way these
                # bytes must not be resumed a second time
                if remote_ns is not None:
                    from tpu_life.serve.spill_http import delete_remote_namespace

                    delete_remote_namespace(self.spill_url, remote_ns)
                else:
                    shutil.rmtree(d, ignore_errors=True)

    def _target_fsid(self, name: str, generation: int, sid: str) -> str:
        with self._lock:
            return self._alias.pop((name, generation, sid), None) or fleet_sid(
                name, generation, sid
            )

    def _migrate_one(self, fsid: str, rec) -> None:
        is_mesh = isinstance(rec, MeshSpillRecord)
        body = json.dumps(
            mesh_resume_request(rec) if is_mesh else resume_request(rec)
        ).encode()
        deadline = self.clock() + self.timeout_s
        attempt = 0
        while True:
            ready = self.supervisor.ready_workers()
            outcome, hint = self._try_candidates(
                fsid, body, ready, rec.trace_id
            )
            if outcome == "refused" and self.peers and not is_mesh:
                # every LOCAL survivor definitively declined (or none is
                # ready): re-home across the host boundary — the peer
                # control plane's router speaks the same protocol, and the
                # original sid keeps answering through the peer proxy
                outcome, peer_hint = self._try_peers(fsid, body, rec.trace_id)
                hint = max(hint, peer_hint)
            if outcome in ("migrated", "peer", "failed"):
                break
            # everyone refused: capacity pressure, not a verdict — pace
            # on the shared jittered-exponential curve (an explicit
            # Retry-After hint wins, un-jittered: the refuser TOLD us
            # when) and retry until the budget runs out.  Jitter matters
            # here specifically: a mass rescue runs one of these loops
            # per session, and a briefly-overloaded survivor must not be
            # re-hammered by all of them in lockstep.
            if self.clock() >= deadline:
                self._record_failure(fsid, "migration_failed")
                return
            attempt += 1
            self.sleep(
                max(
                    hint,
                    backoff_delay(
                        attempt,
                        base=self.retry_pause_s,
                        cap=self.max_retry_pause_s,
                    ),
                )
            )
        if outcome == "failed":
            self._record_failure(fsid, "migration_failed")
        else:
            with self._lock:
                self._progress.pop(fsid, None)
                self._pending_since.pop(fsid, None)
            self._c_migrations.labels(
                outcome="peer" if outcome == "peer" else "migrated"
            ).inc()

    def _try_candidates(
        self, fsid: str, body: bytes, ready, trace_id: str | None = None
    ) -> tuple[str, float]:
        """One pass over the ready workers: ``('migrated' | 'failed' |
        'refused', retry_after_hint)`` — 'failed' is ambiguous or a
        protocol rejection (do not retry); 'refused' means every candidate
        definitively declined (safe to retry), with the largest
        ``Retry-After`` any refuser volunteered as the pacing hint."""
        hint = 0.0
        for worker in self.balancer.candidates(ready):
            # capture BEFORE the round-trip (the route_submit rule): a
            # crash+respawn mid-forward must not alias the wrong life
            target_gen = worker.generation
            try:
                status, retry_after, doc = self.forward(
                    worker, "POST", ROUTE_SESSIONS, body=body
                )
            except WorkerUnreachable as e:
                if e.refused or not worker.alive:
                    self.balancer.invalidate(worker)
                    continue
                # mid-exchange on a live worker: the resume may exist
                # there — re-submitting could duplicate the trajectory
                log.warning(
                    "fleet: resume of %s on %s ambiguous (%s); not retried",
                    fsid,
                    worker.name,
                    e.cause,
                )
                return "failed", 0.0
            if retry_after:
                hint = max(hint, retry_after)
            if status == 201:
                wsid = doc.get("session")
                if not isinstance(wsid, str):
                    return "failed", 0.0
                self.sessions.repin(fsid, worker.name, target_gen, wsid)
                with self._lock:
                    self._alias[(worker.name, target_gen, wsid)] = fsid
                    while len(self._alias) > MAX_OUTCOMES:
                        self._alias.popitem(last=False)
                self.balancer.invalidate(worker)
                log.info(
                    "fleet: %s resumed on %s gen %d as %s",
                    fsid,
                    worker.name,
                    target_gen,
                    wsid,
                )
                obs.flight.record(
                    "migrate.resumed",
                    sid=fsid,
                    trace_id=trace_id,
                    worker=worker.name,
                    generation=target_gen,
                    worker_sid=wsid,
                )
                return "migrated", 0.0
            code = _error_code(doc)
            if status == 503 and code in REFUSAL_CODES:
                self.balancer.invalidate(worker)
                continue
            if status == 429:
                # rate-limited: the token bucket rejects BEFORE anything
                # is stored, so the session definitively was not created —
                # retryable capacity pressure, never a terminal verdict
                # (resumes share the workers' anonymous bucket)
                self.balancer.invalidate(worker)
                continue
            # a protocol rejection (400 family) of a spill-derived resume
            # is deterministic: failing N more times adds nothing
            log.error(
                "fleet: resume of %s rejected by %s: %s %s", fsid,
                worker.name, status, code,
            )
            return "failed", 0.0
        return "refused", hint

    def _try_peers(
        self, fsid: str, body: bytes, trace_id: str | None = None
    ) -> tuple[str, float]:
        """One pass over the peer control planes: ``('peer' | 'failed' |
        'refused', hint)``.  The same no-ambiguous-retry discipline as the
        worker pass — a mid-exchange failure against a peer router may
        have created the session over there, and re-submitting anywhere
        would run the trajectory twice."""
        import socket
        import urllib.error
        import urllib.request

        hint = 0.0
        for peer in self.peers:
            if chaos.partitioned("migrate", peer):
                log.warning(
                    "fleet: peer %s unreachable for %s (partition)", peer, fsid
                )
                continue
            req = urllib.request.Request(
                peer.rstrip("/") + ROUTE_SESSIONS, data=body, method="POST"
            )
            req.add_header("Content-Type", "application/json")
            if trace_id is not None:
                # trace continuity across the HOST boundary: the peer's
                # ROUTER honors X-Trace-Id — without it, the peer would
                # mint a fresh id (the header wins over the body field at
                # the worker), severing the journey exactly on the
                # cross-host rescue the trace exists to show
                req.add_header("X-Trace-Id", trace_id)
            try:
                try:
                    with urllib.request.urlopen(
                        req, timeout=self.timeout_s
                    ) as resp:
                        status, retry_after, doc = resp.status, None, _json(resp)
                except urllib.error.HTTPError as e:
                    from tpu_life.gateway.errors import parse_retry_after

                    status, retry_after, doc = (
                        e.code, parse_retry_after(e.headers), _json(e)
                    )
            except (urllib.error.URLError, ConnectionError, socket.timeout, TimeoutError) as e:
                reason = getattr(e, "reason", e)
                refused = isinstance(reason, ConnectionRefusedError) or isinstance(
                    e, ConnectionRefusedError
                )
                if refused:
                    continue  # the peer never saw it: safe to try the next
                log.warning(
                    "fleet: resume of %s on peer %s ambiguous (%s); not retried",
                    fsid,
                    peer,
                    e,
                )
                return "failed", 0.0
            if retry_after:
                hint = max(hint, retry_after)
            if status == 201:
                peer_sid = doc.get("session")
                if not isinstance(peer_sid, str):
                    return "failed", 0.0
                with self._lock:
                    self._peer_pins[fsid] = (peer.rstrip("/"), peer_sid)
                    while len(self._peer_pins) > MAX_OUTCOMES:
                        self._peer_pins.popitem(last=False)
                log.info(
                    "fleet: %s resumed on PEER %s as %s (cross-host rescue)",
                    fsid,
                    peer,
                    peer_sid,
                )
                obs.flight.record(
                    "migrate.peer",
                    sid=fsid,
                    trace_id=trace_id,
                    peer=peer,
                    peer_sid=peer_sid,
                )
                return "peer", 0.0
            code = _error_code(doc)
            if status in (429, 503) and (
                status == 429 or code in PEER_REFUSAL_CODES
            ):
                continue  # definitively not admitted over there: next peer
            log.error(
                "fleet: resume of %s rejected by peer %s: %s %s",
                fsid,
                peer,
                status,
                code,
            )
            return "failed", 0.0
        return "refused", hint

    def _record_failure(
        self, fsid: str, reason: str, *, counter: str = "failed"
    ) -> None:
        obs.flight.record("migrate.failed", sid=fsid, reason=reason)
        with self._lock:
            self._failed[fsid] = reason
            while len(self._failed) > MAX_OUTCOMES:
                self._failed.popitem(last=False)
            self._progress.pop(fsid, None)
            self._pending_since.pop(fsid, None)
        self._c_migrations.labels(outcome=counter).inc()
        log.warning("fleet: session %s not recovered (%s)", fsid, reason)

