"""tpu_life.fleet — multi-worker router with supervision and failover.

The horizontal-scale tier (docs/FLEET.md): a :class:`Supervisor` keeps N
``tpu-life gateway`` worker subprocesses alive (readyz health checks,
exponential-backoff restarts, a circuit breaker for crash loops) while a
:class:`Router` speaks the exact gateway HTTP protocol to clients and
routes each session to the least-loaded worker, pinning sid -> worker in
a :class:`SessionRegistry` so polls and results land on the right
backend.  One worker dying takes out only its own in-flight sessions
(typed ``worker_lost`` errors); everything else keeps completing, and the
restarted worker rejoins the rotation.

With a ``spill_dir`` configured, worker death is *masked*, not merely
isolated: each worker spills its live sessions through the checkpoint
contract, and a :class:`~tpu_life.fleet.migrate.Migrator` resumes a dead
worker's intact spills on a survivor under the SAME fleet sid — the
unmodified client polls straight through a SIGKILL and the finished
board is byte-identical to the uninterrupted run (docs/FLEET.md
"durability").

:class:`Fleet` wires the pieces together and owns the drain choreography:
SIGTERM -> the router stops admitting, every worker drains gracefully,
processes are reaped, and the CLI exits 0.

With ``placement="auto"`` each worker also owns a DISJOINT device slice
(env overlay via the planner in ``fleet.placement``; restarts re-enter
the same slice), reports its resolved capacity back, and the router
weights least-depth routing by it — so total capacity is ``sum(per-worker
chips x per-worker batch capacity)`` and a multi-chip host is saturated
by one fleet; the ROADMAP's "heavy traffic" story is this tier stamped
out behind a real load balancer.
"""

from __future__ import annotations

import time

from tpu_life import obs
from tpu_life.fleet.balancer import LeastDepthBalancer
from tpu_life.fleet.migrate import Migrator
from tpu_life.fleet.placement import (
    Placement,
    PlacementError,
    parse_devices_per_worker,
    plan_placements,
)
from tpu_life.fleet.registry import SessionRegistry
from tpu_life.fleet.router import Router, merge_prom_texts
from tpu_life.fleet.supervisor import (
    FleetConfig,
    Supervisor,
    Worker,
    WorkerState,
    propagate_signals,
)
from tpu_life.runtime.metrics import log


class Fleet:
    """The assembled tier: supervisor + router + session registry, on one
    shared metrics registry (``fleet_workers`` / ``fleet_restarts_total``
    / ``fleet_routed_total`` / ``fleet_retry_total``)."""

    def __init__(self, config: FleetConfig | None = None):
        self.config = config or FleetConfig()
        self.run_id = obs.new_run_id()
        self.registry = obs.MetricsRegistry()
        # chaos observability (docs/CHAOS.md): router/supervisor/migrator
        # injections fired in THIS process surface in the merged /metrics
        from tpu_life import chaos

        chaos.bind_registry(self.registry)
        self.supervisor = Supervisor(self.config, self.registry)
        self.sessions = SessionRegistry(self.config.max_pins)
        self.router = Router(
            self.config, self.supervisor, self.sessions, self.registry
        )
        self.migrator = None
        if (
            self.config.spill_dir is not None
            or self.config.spill_url is not None
        ):
            self.migrator = Migrator(
                spill_root=self.config.spill_dir,
                spill_url=self.config.spill_url,
                site=self.config.site,
                peers=self.config.peers,
                supervisor=self.supervisor,
                sessions=self.sessions,
                registry=self.registry,
                balancer=self.router.balancer,
                forward=self.router.forward,
                timeout_s=self.config.migrate_timeout_s,
                stuck_after_s=self.config.migrate_stuck_after_s,
            )
            self.router.migrator = self.migrator
            self.supervisor.on_worker_exit = self.migrator.worker_exit
        self.host, self.port = self.router.host, self.router.port

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        self.supervisor.start()
        self.router.start()
        log.info(
            "fleet: %d workers behind http://%s:%d (run_id=%s)",
            self.config.workers,
            self.host,
            self.port,
            self.run_id,
        )

    def wait_ready(self, timeout: float = 60.0, min_workers: int = 1) -> bool:
        """Block until at least ``min_workers`` workers answer ready."""
        deadline = time.monotonic() + timeout
        while len(self.supervisor.ready_workers()) < min_workers:
            if time.monotonic() > deadline:
                return False
            time.sleep(0.05)
        return True

    def begin_drain(self) -> None:
        """Fleet-wide graceful drain: stop admitting at the router, then
        SIGTERM every worker (each finishes in-flight sessions, exits 0).
        Idempotent; block on :meth:`wait`."""
        self.router.begin_drain()
        self.supervisor.begin_drain()

    def wait(self, timeout: float | None = None) -> bool:
        """Block until the drain completed and every worker is reaped.
        The router keeps forwarding polls/results while workers finish."""
        return self.supervisor.wait(timeout)

    def close(self) -> None:
        self.router.close()
        self.supervisor.close()

    def install_signal_handlers(self) -> None:
        propagate_signals(self.begin_drain)

    # -- introspection -----------------------------------------------------
    def stats(self) -> dict:
        routed = {
            labels["worker"]: inst.value
            for labels, inst in self.registry.counter(
                "fleet_routed_total", labels=("worker",)
            ).series()
        }
        capacity = self.supervisor.capacities()
        out = {
            "run_id": self.run_id,
            "workers": self.supervisor.states(),
            "generations": {w.name: w.generation for w in self.supervisor.workers},
            "restarts": self.supervisor.restarts(),
            "routed": routed,
            "retries": self.registry.counter("fleet_retry_total").value,
            "sessions_pinned": len(self.sessions),
            # device placement (docs/FLEET.md): per-worker resolved
            # devices/kind + routing weight, and the aggregate chip
            # count (sums only when placement makes slices disjoint)
            "capacity": capacity,
            "devices_total": self.supervisor.devices_total(),
            # typed probe-refusal reasons (docs/FLEET.md): why any
            # in-flight unready-recycle fired (e.g. engine_wedged)
            "unready_reasons": self.supervisor.unready_reasons(),
        }
        if self.migrator is not None:
            out["migrations"] = {
                labels["outcome"]: inst.value
                for labels, inst in self.registry.counter(
                    "fleet_migrations_total", labels=("outcome",)
                ).series()
            }
        # autoscaling evidence (docs/FLEET.md "Autoscaling") — present
        # only when the loop (or a standby pool) is configured, so
        # classic fleets keep their summary shape byte-stable
        if self.config.standby or self.supervisor.autoscaler is not None:
            active, standby = self.supervisor.scale_counts()
            out["scale"] = {"active": active, "standby": standby}
            if self.supervisor.autoscaler is not None:
                out["scale"]["decisions"] = self.supervisor.autoscaler.decisions
        return out


__all__ = [
    "Fleet",
    "FleetConfig",
    "LeastDepthBalancer",
    "Migrator",
    "Placement",
    "PlacementError",
    "Router",
    "SessionRegistry",
    "Supervisor",
    "Worker",
    "WorkerState",
    "merge_prom_texts",
    "parse_devices_per_worker",
    "plan_placements",
]
