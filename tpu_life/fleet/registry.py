"""Session pinning: which worker (and which incarnation of it) owns a sid.

Worker gateways mint session ids independently (every worker starts at
``s000000``, and a RESTARTED worker starts at ``s000000`` again), so the
fleet id namespaces them by worker *and generation*: ``w1g2-s000042`` is
session ``s000042`` on the second incarnation of worker ``w1``.  Baking
the generation into the id is load-bearing: a pin into a dead generation
must resolve to a typed ``worker_lost``, never to the (identically
numbered) session the successor process mints — and a sid that merely
namespaced the worker name would be silently re-pinned onto the new
generation's session the moment the restarted worker reused it.

Pins are LRU-capped so a long-lived router cannot grow memory without
bound; an evicted pin degrades gracefully — the fleet sid encodes the
full pin, so resolution falls back to parsing it.
"""

from __future__ import annotations

import re
import threading
from collections import OrderedDict
from dataclasses import dataclass

#: Default cap on live pins (sessions the router can route back to).
MAX_PINS = 100_000

_FLEET_SID = re.compile(r"(?P<worker>w\d+)g(?P<gen>\d+)-(?P<sid>.+)")


@dataclass(frozen=True)
class Pin:
    worker: str  # worker name, e.g. "w0"
    generation: int  # worker incarnation at submit time
    sid: str  # the worker's own session id


def fleet_sid(worker: str, generation: int, sid: str) -> str:
    return f"{worker}g{generation}-{sid}"


def parse_fleet_sid(fsid: str) -> Pin | None:
    """Recover the pin from the sid itself — the fallback when an LRU-
    evicted pin comes back (the encoding carries the whole pin)."""
    m = _FLEET_SID.fullmatch(fsid)
    if m is None:
        return None
    return Pin(
        worker=m.group("worker"),
        generation=int(m.group("gen")),
        sid=m.group("sid"),
    )


class SessionRegistry:
    """Thread-safe fleet-sid -> :class:`Pin` map with LRU eviction."""

    def __init__(self, max_pins: int = MAX_PINS):
        self.max_pins = max_pins
        self._pins: OrderedDict[str, Pin] = OrderedDict()
        self._lock = threading.Lock()

    def pin(self, worker: str, generation: int, sid: str) -> str:
        """Record the mapping; returns the fleet sid clients will use."""
        fsid = fleet_sid(worker, generation, sid)
        with self._lock:
            self._pins[fsid] = Pin(worker=worker, generation=generation, sid=sid)
            self._pins.move_to_end(fsid)
            while len(self._pins) > self.max_pins:
                self._pins.popitem(last=False)
        return fsid

    def repin(self, fsid: str, worker: str, generation: int, sid: str) -> None:
        """Point an EXISTING fleet sid at a new home (session migration:
        the dead worker's session resumed on a survivor under the
        survivor's own sid).  The fleet sid string keeps encoding the
        ORIGINAL pin — that is what clients hold — so a migrated sid must
        stay in the map to resolve; LRU eviction degrades it to the
        encoded (dead) home and a typed 410, which resolution accepts as
        the bounded-memory trade."""
        with self._lock:
            self._pins[fsid] = Pin(worker=worker, generation=generation, sid=sid)
            self._pins.move_to_end(fsid)
            while len(self._pins) > self.max_pins:
                self._pins.popitem(last=False)

    def resolve(self, fsid: str) -> Pin | None:
        """The pin for a fleet sid; falls back to prefix parsing when the
        pin was LRU-evicted.  None = not a fleet sid at all (404)."""
        with self._lock:
            pin = self._pins.get(fsid)
            if pin is not None:
                self._pins.move_to_end(fsid)
                return pin
        return parse_fleet_sid(fsid)

    def forget(self, fsid: str) -> None:
        with self._lock:
            self._pins.pop(fsid, None)

    def __len__(self) -> int:
        with self._lock:
            return len(self._pins)
