"""Session pinning: which worker (and which incarnation of it) owns a sid.

Worker gateways mint session ids independently (every worker starts at
``s000000``, and a RESTARTED worker starts at ``s000000`` again), so the
fleet id namespaces them by worker *and generation*: ``w1g2-s000042`` is
session ``s000042`` on the second incarnation of worker ``w1``.  Baking
the generation into the id is load-bearing: a pin into a dead generation
must resolve to a typed ``worker_lost``, never to the (identically
numbered) session the successor process mints — and a sid that merely
namespaced the worker name would be silently re-pinned onto the new
generation's session the moment the restarted worker reused it.

Pins are LRU-capped so a long-lived router cannot grow memory without
bound; an evicted pin degrades gracefully — the fleet sid encodes the
full pin, so resolution falls back to parsing it.  MIGRATED sids are the
exception (the PR 8 known limit, fixed here): a re-pointed pin is the
ONLY record of where the session went — the sid string still encodes the
dead home, so falling back to parsing it would answer a spurious 410 for
a session that is alive and well on a survivor.  ``repin`` therefore
marks its entry *sticky*: eviction takes non-sticky pins first, and only
reaches sticky ones when the registry holds more migrated sessions than
``max_pins`` — the memory bound still wins, but a rescue is never
un-done by routine traffic churn.
"""

from __future__ import annotations

import re
import threading
from collections import OrderedDict
from dataclasses import dataclass

#: Default cap on live pins (sessions the router can route back to).
MAX_PINS = 100_000

_FLEET_SID = re.compile(r"(?P<worker>w\d+)g(?P<gen>\d+)-(?P<sid>.+)")


@dataclass(frozen=True)
class Pin:
    worker: str  # worker name, e.g. "w0"
    generation: int  # worker incarnation at submit time
    sid: str  # the worker's own session id


def fleet_sid(worker: str, generation: int, sid: str) -> str:
    return f"{worker}g{generation}-{sid}"


def parse_fleet_sid(fsid: str) -> Pin | None:
    """Recover the pin from the sid itself — the fallback when an LRU-
    evicted pin comes back (the encoding carries the whole pin)."""
    m = _FLEET_SID.fullmatch(fsid)
    if m is None:
        return None
    return Pin(
        worker=m.group("worker"),
        generation=int(m.group("gen")),
        sid=m.group("sid"),
    )


class SessionRegistry:
    """Thread-safe fleet-sid -> :class:`Pin` map with LRU eviction."""

    def __init__(self, max_pins: int = MAX_PINS):
        self.max_pins = max_pins
        self._pins: OrderedDict[str, Pin] = OrderedDict()
        self._sticky: set[str] = set()  # migrated sids: evicted LAST
        self._lock = threading.Lock()

    def _evict_locked(self) -> None:
        """LRU eviction, non-sticky pins first: a migrated sid's pin is
        the only record of its survivor home (the encoded prefix is the
        DEAD home), so routine churn must never evict it.  Only when the
        map is all-sticky and still over cap does the oldest sticky pin
        go — the absolute memory bound outranks even rescues."""
        while len(self._pins) > self.max_pins:
            victim = next(
                (k for k in self._pins if k not in self._sticky), None
            )
            if victim is None:
                victim = next(iter(self._pins))
                self._sticky.discard(victim)
            del self._pins[victim]

    def pin(self, worker: str, generation: int, sid: str) -> str:
        """Record the mapping; returns the fleet sid clients will use."""
        fsid = fleet_sid(worker, generation, sid)
        with self._lock:
            self._pins[fsid] = Pin(worker=worker, generation=generation, sid=sid)
            self._pins.move_to_end(fsid)
            self._evict_locked()
        return fsid

    def repin(self, fsid: str, worker: str, generation: int, sid: str) -> None:
        """Point an EXISTING fleet sid at a new home (session migration:
        the dead worker's session resumed on a survivor under the
        survivor's own sid).  The fleet sid string keeps encoding the
        ORIGINAL pin — that is what clients hold — and resolution's
        parse-the-sid fallback would therefore answer the dead home with
        a spurious 410, so a re-pointed pin is marked STICKY: ordinary
        pins evict around it and a rescued session stays reachable for
        its whole life (``forget`` — terminal retirement — releases it)."""
        with self._lock:
            self._pins[fsid] = Pin(worker=worker, generation=generation, sid=sid)
            self._pins.move_to_end(fsid)
            self._sticky.add(fsid)
            self._evict_locked()

    def resolve(self, fsid: str) -> Pin | None:
        """The pin for a fleet sid; falls back to prefix parsing when the
        pin was LRU-evicted.  None = not a fleet sid at all (404)."""
        with self._lock:
            pin = self._pins.get(fsid)
            if pin is not None:
                self._pins.move_to_end(fsid)
                return pin
        return parse_fleet_sid(fsid)

    def forget(self, fsid: str) -> None:
        with self._lock:
            self._pins.pop(fsid, None)
            self._sticky.discard(fsid)

    def __len__(self) -> int:
        with self._lock:
            return len(self._pins)
