"""The fleet router: one HTTP front speaking the gateway protocol for N
workers.

Clients talk to the router exactly as they would to a single gateway —
the unmodified ``GatewayClient`` works against it — and the router
forwards:

- ``POST /v1/sessions``: pick a worker (weighted least queue depth —
  depth normalized by the worker's resolved device count — from a
  TTL-cached ``/metrics`` scrape, ties spread by smooth weighted
  round-robin), forward the body verbatim, pin the
  returned sid in the session registry, and answer with the namespaced
  fleet sid (``w1g2-s000042`` — worker, generation, worker's own sid).  A worker that *refuses* — connection
  refused (the request was never seen) or a typed 503 (shedding /
  queue-full / draining: the session was definitively not created) — is
  retried on the next candidate.  A worker that fails *mid-exchange*
  (timeout, reset) is NOT retried: the session may exist, and
  re-forwarding would silently duplicate it (the PR 4 client's own
  no-duplicate-session rule, applied server-side).  503
  ``fleet_unavailable`` only when every candidate refused.
- ``GET/DELETE /v1/sessions/{fleet-sid}[...]``: resolve the pin and hit
  the exact worker generation that owns the session; a pin into a dead
  worker or a stale generation is a typed 410 ``worker_lost``.

Fleet endpoints aggregate the tier: ``/healthz`` (router liveness +
worker states), ``/readyz`` (503 unless ≥1 worker is ready), and
``/metrics`` (the fleet's own families plus every live worker's registry,
merged with a ``worker`` label so per-worker series never collide).
"""

from __future__ import annotations

import json
import socket
import threading
import time
import urllib.error
import urllib.request
from http.server import ThreadingHTTPServer
from urllib.parse import urlsplit

from tpu_life import chaos, obs
from tpu_life.fleet import errors as fl_errors
from tpu_life.fleet.balancer import LeastDepthBalancer, prom_value
from tpu_life.fleet.fanout import FanoutHub
from tpu_life.fleet.membership import ROUTE_HEARTBEAT, ROUTE_REGISTER
from tpu_life.fleet.registry import SessionRegistry
from tpu_life.fleet.supervisor import (
    FleetConfig,
    Supervisor,
    Worker,
    WorkerState,
    worker_weight,
)
from tpu_life.gateway import errors as gw_errors
from tpu_life.gateway.errors import ApiError, parse_retry_after
from tpu_life.gateway.server import ROUTE_SESSIONS, JsonHandler
from tpu_life.runtime.metrics import log
from tpu_life.version import __version__

#: Worker 503 codes that mean "definitively not admitted" — safe to retry
#: the submission on the next candidate without risking a duplicate.
REFUSAL_CODES = frozenset(
    {"overloaded", "queue_full", "draining", "shed_best_effort"}
)

#: Socket read timeout on an upstream worker stream: frames arrive every
#: scheduling round while a session runs, so a read that blocks this
#: long means the link (or the worker) is gone — the fan-out puller
#: reconnects with its cursor and the survivor re-keys.
STREAM_READ_TIMEOUT_S = 30.0

#: How long a fan-out upstream open waits on a 409 ``migrating`` answer
#: before treating the sid as lost — failover replay is seconds, not
#: minutes.
STREAM_MIGRATE_WAIT_S = 30.0


class WorkerUnreachable(Exception):
    """Transport-level forward failure; ``refused`` means the connection
    was refused outright (the worker never saw the request)."""

    def __init__(self, worker: Worker, refused: bool, cause: Exception):
        super().__init__(f"{worker.name}: {cause}")
        self.worker = worker
        self.refused = refused
        self.cause = cause


class Router:
    """Owns the HTTP listener, the balancer, and the session pins."""

    def __init__(
        self,
        config: FleetConfig,
        supervisor: Supervisor,
        sessions: SessionRegistry,
        registry,
    ):
        self.config = config
        self.supervisor = supervisor
        self.sessions = sessions
        # weighted least-depth (docs/FLEET.md "Device placement"): depth
        # is normalized by the worker's resolved device count, so a
        # 4-chip worker absorbs ~4x the sessions of a 1-chip peer
        self.balancer = LeastDepthBalancer(
            self._fetch_depth, ttl_s=config.depth_ttl_s, weight=worker_weight
        )
        self._c_routed = registry.counter(
            "fleet_routed_total", "sessions routed, by worker", labels=("worker",)
        )
        self._c_retry = registry.counter(
            "fleet_retry_total",
            "submissions retried on another worker after a refusal",
        )
        self._c_retry.labels()
        self.registry = registry
        # the durability seam (docs/FLEET.md): set by the fleet when a
        # spill dir is configured.  With a migrator, a dead worker's
        # pinned sids answer 409 ``migrating`` (or a synthetic running
        # view on plain polls) until the migration run settles them;
        # without one, worker death stays terminal (410, reason
        # ``spill_disabled``).
        self.migrator = None
        # the watcher fan-out tier (docs/STREAMING.md): N watchers of one
        # sid share ONE upstream worker stream; the shed counter and the
        # live-watcher gauge land in the fleet registry
        self.fanout = FanoutHub(open_upstream=self._open_upstream, registry=registry)
        self._server = _RouterHTTPServer((config.host, config.port), _Handler)
        self._server.router = self
        self.host, self.port = self._server.server_address[:2]
        self._serve_thread: threading.Thread | None = None
        self._draining = False
        self._closed = False

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        self._serve_thread = threading.Thread(
            target=self._server.serve_forever,
            kwargs={"poll_interval": 0.05},
            name="fleet-router",
            daemon=True,
        )
        self._serve_thread.start()
        log.info("fleet: router listening on http://%s:%d", self.host, self.port)

    def begin_drain(self) -> None:
        """Stop admitting (``/readyz`` -> 503, submits -> 503 draining);
        poll/result/cancel keep forwarding while workers finish."""
        self._draining = True

    @property
    def draining(self) -> bool:
        return self._draining

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self.fanout.close()
        if self._serve_thread is not None:
            self._server.shutdown()
        self._server.server_close()

    # -- worker I/O --------------------------------------------------------
    def _fetch_depth(self, worker: Worker) -> float:
        text = self._fetch_text(worker, "/metrics", timeout=2.0)
        v = prom_value(text, "serve_queue_depth")
        if v is None:
            raise ValueError(f"{worker.name}: no serve_queue_depth sample")
        return v

    def _fetch_text(self, worker: Worker, path: str, timeout: float) -> str:
        req = urllib.request.Request(worker.url + path)
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.read().decode()

    def forward(
        self,
        worker: Worker,
        method: str,
        path: str,
        *,
        body: bytes | None = None,
        api_key: str | None = None,
        trace_id: str | None = None,
    ) -> tuple[int, float | None, dict]:
        """One proxied request; returns (status, retry_after, json body).
        HTTP error statuses return normally (they are protocol answers to
        relay); transport failures raise :class:`WorkerUnreachable`."""
        if worker.url is None:
            raise WorkerUnreachable(
                worker, True, ConnectionRefusedError("worker has no bound URL")
            )
        # chaos seam (docs/CHAOS.md): a socket reset BEFORE the request is
        # written.  The worker never saw it, so the honest classification
        # is a refusal — submits retry the next candidate (no duplicate is
        # possible), exactly the path a NIC hiccup at connect exercises.
        if method == "POST" and chaos.decide("router.submit.reset") is not None:
            chaos.record_fire("router.submit.reset", "reset")
            raise WorkerUnreachable(
                worker, True, ConnectionResetError("chaos: pre-send reset")
            )
        # chaos seam: the seeded per-peer connectivity mask severs THIS
        # router->worker link (docs/CHAOS.md ``net.partition``).  The
        # honest transport shape is a connect that never establishes —
        # a refusal, so submits retry the next candidate and pinned
        # requests consult the migrator exactly as a real partition would.
        # The site prefix keeps the pair label unique when two control
        # planes share one process (the cross-host drill): without it,
        # plane A's and plane B's links to same-named workers would share
        # one draw counter and the per-link schedule would depend on
        # thread interleaving instead of the seed alone.
        if chaos.partitioned(f"{self.config.site}router", worker.name):
            raise WorkerUnreachable(
                worker, True, ConnectionRefusedError("chaos: net partition")
            )
        poll_fault = (
            chaos.decide("router.poll.reset")
            if method in ("GET", "DELETE")
            else None
        )
        req = urllib.request.Request(worker.url + path, data=body, method=method)
        if body is not None:
            req.add_header("Content-Type", "application/json")
        if api_key is not None:
            req.add_header("X-API-Key", api_key)
        if trace_id is not None:
            # cross-process trace propagation (docs/OBSERVABILITY.md):
            # the worker stamps this id onto the session it creates
            req.add_header("X-Trace-Id", trace_id)
        try:
            try:
                with urllib.request.urlopen(
                    req, timeout=self.config.forward_timeout_s
                ) as resp:
                    status, retry_after, doc = resp.status, None, _json_body(resp)
            except urllib.error.HTTPError as e:
                # an error STATUS is still a completed exchange — the
                # injected resets below apply to it exactly as to a 200
                # (a 409/410 answer can be lost on the wire too)
                status, retry_after, doc = (
                    e.code, parse_retry_after(e.headers), _json_body(e)
                )
            if poll_fault is not None:
                chaos.record_fire("router.poll.reset", poll_fault.fault.mode)
                if poll_fault.fault.mode == "mid_exchange":
                    # the exchange completed but the answer is lost on the
                    # wire: ambiguous — the handlers must treat it as a
                    # maybe-processed failure, never silently retry a POST
                    raise WorkerUnreachable(
                        worker,
                        False,
                        ConnectionResetError("chaos: mid-exchange reset"),
                    )
                # mid_body: the response truncated — the body parses empty
                doc = {}
            return status, retry_after, doc
        except (urllib.error.URLError, ConnectionError, socket.timeout, TimeoutError) as e:
            reason = getattr(e, "reason", e)
            refused = isinstance(reason, ConnectionRefusedError) or isinstance(
                e, ConnectionRefusedError
            )
            raise WorkerUnreachable(worker, refused, e) from None

    # -- routing -----------------------------------------------------------
    def route_submit(
        self, body: bytes, api_key: str | None, trace_id: str | None = None
    ) -> tuple[int, float | None, dict]:
        """The submit pipeline: candidates by least depth, refusal-only
        retry, pin on 201.  Returns (status, retry_after, response doc).

        ``trace_id`` is the distributed-trace context this router MINTS
        per submitted session (honoring a client-supplied ``X-Trace-Id``
        — the handler validates and passes it): forwarded to the chosen
        worker on the wire, recorded with the pin's flight event, and
        carried by the session through every later hop (spill, kill,
        migration) so the whole journey joins on one id."""
        if self._draining:
            raise ApiError(
                503,
                "draining",
                "the fleet is draining: no new sessions are admitted",
                retry_after=1.0,
            )
        ready = self.supervisor.ready_workers()
        if not ready:
            raise fl_errors.no_ready_workers(len(self.supervisor.workers))
        hint = 1.0
        mesh_retried = False
        shed_relay = None  # last typed best-effort shed seen on the walk
        for i, worker in enumerate(self.balancer.candidates(ready)):
            if i > 0:
                self._c_retry.inc()
            # capture the generation BEFORE the round-trip: if the worker
            # crashes and respawns mid-forward, pinning the (dead) session
            # under the successor's generation would hand its sid numbers
            # to the wrong tenant — the exact confusion the generation
            # namespace exists to prevent
            generation = worker.generation
            try:
                status, retry_after, doc = self.forward(
                    worker,
                    "POST",
                    ROUTE_SESSIONS,
                    body=body,
                    api_key=api_key,
                    trace_id=trace_id,
                )
            except WorkerUnreachable as e:
                if e.refused or not worker.alive:
                    # refused = the worker never saw the request; dead = even
                    # if it did, the session died with the process and can
                    # never be observed — either way the next candidate
                    # cannot produce a duplicate.  Only a mid-exchange
                    # failure on a LIVE worker is ambiguous (502 below).
                    log.warning(
                        "fleet: %s unreachable on submit; trying next", worker.name
                    )
                    self.balancer.invalidate(worker)
                    continue
                raise fl_errors.upstream_error(worker.name, str(e.cause)) from None
            if status == 201:
                return 201, None, self._finish_submit(
                    worker, generation, doc, trace_id
                )
            if status == 503 and _error_code(doc) in REFUSAL_CODES:
                # a definitive refusal — the session was not created
                log.info(
                    "fleet: %s refused submit (%s); trying next",
                    worker.name,
                    _error_code(doc),
                )
                self.balancer.invalidate(worker)
                if retry_after:
                    hint = max(hint, retry_after)
                if _error_code(doc) == "shed_best_effort":
                    doc.setdefault("worker", worker.name)
                    shed_relay = doc
                continue
            # a mesh-eligible 413 (docs/SERVING.md "Mega-board sessions")
            # is the one protocol rejection the router does NOT relay
            # blindly: the refuser volunteered the minimum slice size, so
            # one targeted retry against the largest ready worker whose
            # reserved slice clears it is acting on the hint, not the
            # N-fold deterministic-400 replay the verbatim rule forbids
            if status == 413 and not mesh_retried:
                target = self._mesh_candidate(doc, ready, worker)
                if target is not None:
                    mesh_retried = True
                    out = self._mesh_retry(
                        target, body, api_key, trace_id, worker, doc
                    )
                    if out is not None:
                        return out
            # any other answer (400/413/429/...) is the worker speaking the
            # protocol: relay it verbatim — retrying a deterministic 400 on
            # another worker would just fail N times instead of once
            doc.setdefault("worker", worker.name)
            return status, retry_after, doc
        if shed_relay is not None:
            # the QoS shed ladder stays TYPED end to end (docs/SERVING.md
            # "Tenant QoS"): a best-effort submit shed by every candidate
            # relays a worker's own ``shed_best_effort`` envelope — a
            # generic ``fleet_unavailable`` would erase the tier the
            # client's documented recourse (sleep Retry-After, resubmit)
            # keys on, and only best-effort tenants can draw this code
            return 503, hint, shed_relay
        raise fl_errors.fleet_unavailable(len(ready), retry_after=hint)

    def _finish_submit(
        self, worker: Worker, generation: int, doc: dict, trace_id: str | None
    ) -> dict:
        """The 201 bookkeeping shared by the depth-ranked path and the
        mesh retry: pin the sid under the generation captured BEFORE the
        round-trip, stamp the trace, and invalidate the now-staler depth
        reading."""
        sid = doc.get("session")
        if isinstance(sid, str):
            doc["session"] = self.sessions.pin(worker.name, generation, sid)
            # the journey's first control-plane event: which
            # fleet sid this trace was routed as, and to whom —
            # the join key `tpu-life doctor --sid` resolves with
            obs.flight.record(
                "route.submit",
                sid=doc["session"],
                worker_sid=sid,
                trace_id=trace_id,
                worker=worker.name,
                generation=generation,
            )
        if trace_id is not None:
            doc.setdefault("trace_id", trace_id)
        doc["worker"] = worker.name
        self._c_routed.labels(worker=worker.name).inc()
        # this worker's queue just grew: re-scrape before routing
        # the next submit rather than trusting the stale reading
        self.balancer.invalidate(worker)
        return doc

    def _mesh_candidate(
        self, doc: dict, ready: list, rejected_by: Worker
    ) -> Worker | None:
        """The worker a mesh-eligible 413 should be retried on: the
        LARGEST ready slice (most resolved devices) that clears the
        refuser's ``min_devices`` hint — biggest first, because a board
        at the edge of one worker's budget fits with the most headroom on
        the widest mesh.  None when the 413 carries no mesh hint or no
        ready worker's slice is big enough."""
        err = doc.get("error")
        if not isinstance(err, dict) or not err.get("mesh_eligible"):
            return None
        need = err.get("min_devices")
        need = int(need) if isinstance(need, (int, float)) else 2
        best = None
        for w in ready:
            if w is rejected_by:
                continue
            dev = getattr(w, "devices", None) or 1
            if dev >= need and (
                best is None or dev > (getattr(best, "devices", None) or 1)
            ):
                best = w
        return best

    def _mesh_retry(
        self,
        target: Worker,
        body: bytes,
        api_key: str | None,
        trace_id: str | None,
        rejected_by: Worker,
        reject_doc: dict,
    ) -> tuple[int, float | None, dict] | None:
        """One targeted re-forward of a mesh-eligible 413 to ``target``.
        Returns the answer to send the client, or None to fall through to
        relaying the original 413 (the target never saw the request, so
        no duplicate is possible)."""
        err = reject_doc.get("error") or {}
        obs.flight.record(
            "route.mesh_retry",
            trace_id=trace_id,
            rejected_by=rejected_by.name,
            worker=target.name,
            devices=getattr(target, "devices", None),
            min_devices=err.get("min_devices"),
        )
        generation = target.generation
        try:
            status, retry_after, doc = self.forward(
                target,
                "POST",
                ROUTE_SESSIONS,
                body=body,
                api_key=api_key,
                trace_id=trace_id,
            )
        except WorkerUnreachable as e:
            if e.refused or not target.alive:
                # the slice never saw it (or died with it): the honest
                # answer is the original 413 — fall through to the relay
                self.balancer.invalidate(target)
                return None
            raise fl_errors.upstream_error(target.name, str(e.cause)) from None
        if status == 201:
            self._c_retry.inc()
            return 201, None, self._finish_submit(
                target, generation, doc, trace_id
            )
        # the big slice ALSO said no: ITS answer (a 413 with its own
        # numbers, or a refusal) supersedes the first worker's
        doc.setdefault("worker", target.name)
        return status, retry_after, doc

    def resolve(self, fsid: str) -> tuple[Worker, str]:
        """Fleet sid -> (live worker of the pinned generation, worker sid);
        typed 404 / 409 migrating / 410+reason otherwise.

        A migrated sid's pin was re-pointed at its survivor, so it
        resolves like any live pin.  A pin whose home is gone consults
        the migrator: still being rescued -> 409 ``migrating`` (retry
        later, same sid); settled without a rescue -> 410 with a
        ``reason`` (never_snapshotted / spill_corrupt /
        migration_failed); no migrator at all -> 410, reason
        ``spill_disabled`` (the pre-durability contract: the successor
        mints the same sid NUMBERS for new tenants — the generation in
        the pin is what keeps them apart)."""
        pin = self.sessions.resolve(fsid)
        if pin is None:
            raise fl_errors.unknown_session(fsid)
        worker = self.supervisor.get(pin.worker)
        if worker is None:
            raise fl_errors.unknown_session(fsid)
        if (
            worker.generation == pin.generation
            and worker.alive
            and worker.state not in (WorkerState.DOWN, WorkerState.FAILED)
        ):
            return worker, pin.sid
        # the pinned incarnation is gone (dead, reaped, or replaced)
        raise self._gone_error(fsid, pin)

    def _gone_error(self, fsid: str, pin) -> ApiError:
        """The typed answer for a sid whose pinned worker incarnation is
        gone: 409 ``migrating`` while (or until) the migrator rescues it,
        410 + reason once its fate is settled (or durability is off)."""
        if self.migrator is not None:
            # the "rescue imminent" fallback only applies when the pin
            # targets the worker's CURRENT generation (a death the
            # monitor tick hasn't processed yet) — a pin into an unknown
            # past generation has no migration coming and must settle
            w = self.supervisor.get(pin.worker)
            pending_ok = w is not None and w.generation == pin.generation
            st = self.migrator.status(fsid, pin, pending_ok=pending_ok)
            if st[0] == "migrating":
                return fl_errors.migrating(fsid)
            return fl_errors.worker_lost(pin.worker, fsid, reason=st[1])
        return fl_errors.worker_lost(pin.worker, fsid)

    def _open_upstream(self, fsid: str, cursor: int):
        """One upstream worker stream for the fan-out tier (runs on a
        fan's puller thread): resolve the pin FRESH — after a failover it
        names the survivor — waiting bounded through a 409 ``migrating``
        window, then consume the worker's ndjson frames starting at
        ``cursor``.  Transport failures (and torn frames) raise; the
        :class:`FanoutHub` reconnects with the next cursor it needs."""
        deadline = time.monotonic() + STREAM_MIGRATE_WAIT_S
        while True:
            try:
                worker, sid = self.resolve(fsid)
                break
            except ApiError as e:
                if e.code == "migrating" and time.monotonic() < deadline:
                    time.sleep(0.1)
                    continue
                raise
        if chaos.partitioned(f"{self.config.site}router", worker.name):
            raise ConnectionRefusedError("chaos: net partition")
        url = f"{worker.url}{ROUTE_SESSIONS}/{sid}/stream?cursor={int(cursor)}"
        req = urllib.request.Request(url)
        with urllib.request.urlopen(req, timeout=STREAM_READ_TIMEOUT_S) as resp:
            for line in resp:
                line = line.strip()
                if not line:
                    continue
                try:
                    yield json.loads(line)
                except json.JSONDecodeError:
                    # a torn frame: the worker died mid-write — the
                    # reconnect-with-cursor contract, not a parse bug
                    raise ConnectionError(
                        f"{fsid}: torn frame on upstream stream"
                    ) from None

    def route_pinned(
        self,
        method: str,
        fsid: str,
        tail: str,
        api_key: str | None,
        body: bytes | None = None,
    ) -> tuple[int, float | None, dict]:
        # a session rescued onto a PEER control plane (docs/FLEET.md
        # "Cross-host topology") answers under its original sid: the pin
        # still names the dead local home, so the peer map is consulted
        # first and the request proxies to the peer router, which speaks
        # the exact same protocol
        peer = self.migrator.peer_of(fsid) if self.migrator is not None else None
        if peer is not None:
            return self._route_peer(method, fsid, peer, tail, api_key, body=body)
        worker, sid = self.resolve(fsid)
        try:
            status, retry_after, doc = self.forward(
                worker,
                method,
                f"{ROUTE_SESSIONS}/{sid}{tail}",
                api_key=api_key,
                body=body,
            )
        except WorkerUnreachable as e:
            dead = e.refused or not worker.alive
            if not dead and method in ("GET", "DELETE"):
                # a SIGKILL closes the worker's sockets a beat before the
                # process becomes waitable: a poll reset in that window
                # would misread as a 502.  GET/DELETE are idempotent, so
                # re-checking liveness after a grace beat is safe (POST
                # never reaches this path — pinned routes are GET/DELETE).
                time.sleep(0.05)
                dead = not worker.alive
            if dead:
                # no listener on the pinned port, or the process itself is
                # dead (a freshly SIGKILLed worker answers with a reset
                # before the supervisor reaps it): the session's state died
                # with the process — typed, not a 502.  A restart binds a
                # fresh ephemeral port, so this can never reach the
                # successor generation by accident.  Re-resolve the pin for
                # the migrator consult: with durability on, this freshly
                # observed death answers 409 migrating, not 410.
                pin = self.sessions.resolve(fsid)
                if pin is not None:
                    raise self._gone_error(fsid, pin) from None
                raise fl_errors.worker_lost(worker.name, fsid) from None
            raise fl_errors.upstream_error(worker.name, str(e.cause)) from None
        if isinstance(doc.get("session"), str):
            doc["session"] = fsid
        doc["worker"] = worker.name
        return status, retry_after, doc

    def _route_peer(
        self,
        method: str,
        fsid: str,
        peer: tuple[str, str],
        tail: str,
        api_key: str | None,
        body: bytes | None = None,
    ) -> tuple[int, float | None, dict]:
        """Proxy one pinned request to the peer control plane that adopted
        the session; the client keeps its original fleet sid."""
        peer_url, peer_sid = peer
        if chaos.partitioned(f"{self.config.site}router", peer_url):
            raise fl_errors.peer_unreachable(
                peer_url, "net partition to peer control plane"
            )
        req = urllib.request.Request(
            f"{peer_url}{ROUTE_SESSIONS}/{peer_sid}{tail}", data=body, method=method
        )
        if body is not None:
            req.add_header("Content-Type", "application/json")
        if api_key is not None:
            req.add_header("X-API-Key", api_key)
        try:
            try:
                with urllib.request.urlopen(
                    req, timeout=self.config.forward_timeout_s
                ) as resp:
                    status, retry_after, doc = resp.status, None, _json_body(resp)
            except urllib.error.HTTPError as e:
                status, retry_after, doc = (
                    e.code, parse_retry_after(e.headers), _json_body(e)
                )
        except (urllib.error.URLError, ConnectionError, socket.timeout, TimeoutError) as e:
            # the peer plane is unreachable, never a 410 — the session may
            # be running fine over there.  Proxied requests are all
            # idempotent GET/DELETE, so unlike the mid-exchange 502 this
            # is a retryable 503: a poll loop rides through a link blip.
            raise fl_errors.peer_unreachable(peer_url, str(e)) from None
        if isinstance(doc.get("session"), str):
            doc["session"] = fsid
        doc["peer"] = peer_url
        return status, retry_after, doc

    def migrating_view(self, fsid: str) -> dict:
        """A synthetic in-progress poll body for a sid mid-migration, so
        an unmodified poll-until-done client (``GatewayClient.wait``)
        rides straight through a worker kill: ``finished`` stays false,
        progress is the last spilled position when the manifest has been
        read, and the next poll after the re-pin lands on the survivor.
        Only plain GET polls get this — result/cancel answer the typed
        409 ``migrating`` (+ Retry-After) instead, because "here is a
        board" and "it is cancelled" cannot be synthesized truthfully.
        Progress comes from the spill manifest (published for every
        record before any resume runs); in the short window before the
        manifests are read, the progress keys are OMITTED rather than
        reported as a regressed 0/0 — steps_done must only ever grow."""
        view = {
            "session": fsid,
            "state": "running",
            "migrating": True,
            "finished": False,
            "error": None,
            "fleet": True,
        }
        progress = self.migrator.progress(fsid) if self.migrator else None
        if progress is not None:
            total, done = progress
            view["steps"] = total
            view["steps_done"] = done
            view["progress"] = done / total if total else 0.0
        return view

    # -- fleet endpoints ---------------------------------------------------
    def merged_metrics(self) -> str:
        """The fleet registry plus every reachable worker's registry, each
        worker's samples tagged ``worker="<name>"``.  Workers are scraped
        CONCURRENTLY: the endpoint's latency is the slowest single scrape,
        so one wedged worker burning its timeout cannot push the whole
        fleet's exposition past a scraper's deadline."""
        workers = [
            w for w in self.supervisor.workers if w.url is not None and w.alive
        ]
        texts: list[str | None] = [None] * len(workers)

        def scrape(i: int, w: Worker) -> None:
            try:
                texts[i] = self._fetch_text(w, "/metrics", timeout=2.0)
            except Exception:
                log.debug("fleet: metrics scrape of %s failed", w.name)

        threads = [
            threading.Thread(target=scrape, args=(i, w), daemon=True)
            for i, w in enumerate(workers)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        sources: list[tuple[str | None, str]] = [
            (None, self.registry.prom_text())
        ]
        sources += [
            (w.name, text) for w, text in zip(workers, texts) if text is not None
        ]
        return merge_prom_texts(sources)


class _RouterHTTPServer(ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True
    router: Router  # attached right after construction


class _Handler(JsonHandler):
    server_version = f"tpu-life-fleet/{__version__}"
    log_tag = "fleet"

    @property
    def rt(self) -> Router:
        return self.server.router  # type: ignore[attr-defined]

    def _read_body(self) -> bytes:
        """The raw request body, bounded — the router forwards it verbatim
        (workers own the JSON validation), but the byte bound is admission
        control and belongs at the front."""
        return self._read_sized_body(self.rt.config.max_body)

    def _read_json(self) -> dict:
        """A bounded JSON object body for the fleet's OWN endpoints
        (registration / heartbeat) — typed 400 on garbage."""
        try:
            doc = json.loads(self._read_body() or b"{}")
        except json.JSONDecodeError as e:
            raise fl_errors.bad_registration(f"body is not JSON: {e}") from None
        if not isinstance(doc, dict):
            raise fl_errors.bad_registration("body must be a JSON object")
        return doc

    # -- dispatch ----------------------------------------------------------
    def do_GET(self):  # noqa: N802
        self._dispatch("GET")

    def do_POST(self):  # noqa: N802
        self._dispatch("POST")

    def do_DELETE(self):  # noqa: N802
        self._dispatch("DELETE")

    def do_PATCH(self):  # noqa: N802
        self._dispatch("PATCH")

    def _dispatch(self, method: str) -> None:
        parts = urlsplit(self.path)
        path = parts.path.rstrip("/") or "/"
        try:
            self._route(method, path, parts.query)
        except ApiError as e:
            try:
                body = e.body()
                body["fleet"] = True  # who answered: the router, not a worker
                self._send_json(e.status, body, retry_after=e.retry_after)
            except (BrokenPipeError, ConnectionResetError):
                pass
        except (BrokenPipeError, ConnectionResetError):
            pass
        except Exception:
            log.exception("fleet: %s %s failed", method, path)
            try:
                self._send_json(
                    500,
                    {"error": {"code": "internal", "message": "internal error"}},
                )
            except (BrokenPipeError, ConnectionResetError):
                pass

    def _route(self, method: str, path: str, query: str) -> None:
        rt = self.rt
        api_key = self.headers.get("X-API-Key")
        if path == "/healthz":
            self._require(method, "GET", path)
            capacity = rt.supervisor.capacities()
            self._send_json(
                200,
                {
                    "status": "ok",
                    "workers": rt.supervisor.states(),
                    # per-worker resolved devices + routing weight, and
                    # the fleet's aggregate device count — the capacity-
                    # planning numbers (docs/FLEET.md "Device placement";
                    # per-worker counts SUM only when placement makes the
                    # slices disjoint — shared-env workers co-claim one
                    # device set and report its size, once)
                    "capacity": capacity,
                    "devices_total": rt.supervisor.devices_total(),
                    # workers refusing their probe with a TYPED reason
                    # (e.g. the serve wedge watchdog's engine_wedged) —
                    # why an unready-recycle is in flight, not just that
                    "unready_reasons": rt.supervisor.unready_reasons(),
                    # the SLO engine's live burn gauges (docs/
                    # OBSERVABILITY.md "SLOs and burn rates") — what
                    # `tpu-life top` paints its breach table from
                    "slo": rt.supervisor.slo_status(),
                },
            )
            return
        if path == "/readyz":
            self._require(method, "GET", path)
            ready = rt.supervisor.ready_workers()
            if rt.draining or not ready:
                code = "draining" if rt.draining else "no_ready_workers"
                self._send_json(
                    503,
                    {
                        "ready": False,
                        "draining": rt.draining,
                        "workers_ready": len(ready),
                        "error": {"code": code, "message": f"fleet is {code}"},
                    },
                    retry_after=1.0,
                )
            else:
                self._send_json(
                    200,
                    {
                        "ready": True,
                        "draining": False,
                        "workers_ready": len(ready),
                    },
                )
            return
        if path == "/metrics":
            self._require(method, "GET", path)
            self._send_text(200, rt.merged_metrics(), "text/plain; version=0.0.4")
            return
        if path == ROUTE_REGISTER:
            # wire registration (docs/FLEET.md "Cross-host topology"):
            # the body is the worker's startup JSON line — the contract
            # that already existed IS the handshake
            self._require(method, "POST", path)
            self._send_json(200, rt.supervisor.register_worker(self._read_json()))
            return
        if path == ROUTE_HEARTBEAT:
            self._require(method, "POST", path)
            doc = self._read_json()
            worker = doc.get("worker")
            if not isinstance(worker, str):
                raise fl_errors.bad_registration(
                    f"heartbeat needs a worker name, got {worker!r}"
                )
            try:
                generation = int(doc.get("generation"))
            except (TypeError, ValueError):
                raise fl_errors.bad_registration(
                    f"heartbeat needs an integer generation, got "
                    f"{doc.get('generation')!r}"
                ) from None
            self._send_json(200, rt.supervisor.heartbeat(worker, generation))
            return
        if path == ROUTE_SESSIONS:
            self._require(method, "POST", path)
            body = self._read_body()
            # the router MINTS the per-session trace id (honoring a
            # client-supplied X-Trace-Id, validated typed) — the root of
            # the session's cross-process journey
            from tpu_life.gateway.protocol import parse_trace_id

            trace_id = parse_trace_id(self.headers.get("X-Trace-Id"))
            if trace_id is None:
                trace_id = obs.new_trace_id()
            status, retry_after, doc = rt.route_submit(body, api_key, trace_id)
            self._send_json(status, doc, retry_after=retry_after)
            return
        if path.startswith(ROUTE_SESSIONS + "/"):
            rest = path[len(ROUTE_SESSIONS) + 1 :]
            if "/" not in rest:
                if method not in ("GET", "DELETE"):
                    raise gw_errors.method_not_allowed(method, path)
                try:
                    status, retry_after, doc = rt.route_pinned(
                        method, rest, "", api_key
                    )
                except ApiError as e:
                    if method == "GET" and e.code == "migrating":
                        # a plain poll mid-migration answers 200 with a
                        # synthetic running view — the poll-until-done
                        # client loop never sees the failover at all
                        self._send_json(200, rt.migrating_view(rest))
                        return
                    raise
                self._send_json(status, doc, retry_after=retry_after)
                return
            fsid, _, tail = rest.partition("/")
            if tail == "result":
                self._require(method, "GET", path)
                suffix = "/result" + (f"?{query}" if query else "")
                status, retry_after, doc = rt.route_pinned(
                    method, fsid, suffix, api_key
                )
                self._send_json(status, doc, retry_after=retry_after)
                return
            if tail == "cells":
                # mid-run steering (docs/STREAMING.md): forward the cell
                # mask verbatim to the exact worker that owns the session
                self._require(method, "PATCH", path)
                body = self._read_body()
                status, retry_after, doc = rt.route_pinned(
                    "PATCH", fsid, "/cells", api_key, body=body
                )
                self._send_json(status, doc, retry_after=retry_after)
                return
            if tail == "stream":
                self._require(method, "GET", path)
                self._stream(rt, fsid, query)
                return
        raise gw_errors.not_found(f"no route for {path}")

    def _stream(self, rt: Router, fsid: str, query: str) -> None:
        """``GET /v1/sessions/{fsid}/stream`` — one watcher on the
        fan-out tier (docs/STREAMING.md): frames come off the sid's
        shared broadcast buffer, never a dedicated worker connection.
        Admission errors (404 / 409 migrating / 410) answer typed BEFORE
        the 200; after the header the connection belongs to the frame
        grammar."""
        from urllib.parse import parse_qs

        raw = parse_qs(query).get("cursor", ["0"])[0]
        try:
            cursor = int(raw)
        except ValueError:
            raise gw_errors.bad_request(
                "invalid_request", f"bad cursor {raw!r}"
            ) from None
        if cursor < 0:
            raise gw_errors.bad_request("invalid_request", "'cursor' must be >= 0")
        rt.resolve(fsid)  # typed 404/409/410 while an answer is still JSON
        self.send_response(200)
        self.send_header("Content-Type", "application/x-ndjson")
        self.send_header("Connection", "close")
        self.end_headers()
        self.close_connection = True
        gen = rt.fanout.watch(fsid, cursor)
        try:
            for frame in gen:
                # chaos seam (docs/CHAOS.md ``watch.slow_reader``): a
                # seeded stall in THIS watcher's write loop — its cursor
                # falls behind the broadcast buffer and the shed path,
                # not the pump or its peer watchers, absorbs the damage
                stall = chaos.delay("watch.slow_reader")
                if stall > 0:
                    time.sleep(stall)
                self.wfile.write((json.dumps(frame) + "\n").encode())
                self.wfile.flush()
        finally:
            gen.close()

    def _require(self, method: str, expected: str, path: str) -> None:
        if method != expected:
            raise gw_errors.method_not_allowed(method, path)


# -- prometheus merging ------------------------------------------------------
def merge_prom_texts(sources: list[tuple[str | None, str]]) -> str:
    """Merge Prometheus text expositions into one valid document.

    ``sources`` is ``[(worker_label, text), ...]``; every sample from a
    labeled source gains ``worker="<label>"`` (a ``None`` label — the
    fleet's own registry — passes through untouched).  Samples are
    regrouped by family so each family appears once, under one ``# TYPE``
    line, with all workers' series contiguous — the exposition-format
    contract a real scraper enforces.
    """
    fams: dict[str, dict] = {}

    def fam_entry(name: str) -> dict:
        return fams.setdefault(
            name, {"help": None, "type": None, "samples": []}
        )

    def family_of(sample: str) -> str:
        for suffix in ("_bucket", "_sum", "_count"):
            if sample.endswith(suffix) and sample[: -len(suffix)] in fams:
                return sample[: -len(suffix)]
        return sample

    for label, text in sources:
        for line in text.splitlines():
            if not line.strip():
                continue
            if line.startswith("# HELP "):
                parts = line.split(" ", 3)
                entry = fam_entry(parts[2])
                if entry["help"] is None and len(parts) > 3:
                    entry["help"] = parts[3]
            elif line.startswith("# TYPE "):
                parts = line.split(" ", 3)
                entry = fam_entry(parts[2])
                if entry["type"] is None and len(parts) > 3:
                    entry["type"] = parts[3]
            elif line.startswith("#"):
                continue
            else:
                head, _, value = line.rpartition(" ")
                if not head:
                    continue
                brace = head.find("{")
                if brace >= 0:
                    name, labelpart = head[:brace], head[brace + 1 : -1]
                else:
                    name, labelpart = head, ""
                if label is not None:
                    worker_label = f'worker="{label}"'
                    labelpart = (
                        f"{worker_label},{labelpart}" if labelpart else worker_label
                    )
                fam_entry(family_of(name))["samples"].append(
                    (name, labelpart, value)
                )
    lines: list[str] = []
    for fam, entry in fams.items():
        if not entry["samples"]:
            continue
        if entry["help"] is not None:
            lines.append(f"# HELP {fam} {entry['help']}")
        if entry["type"] is not None:
            lines.append(f"# TYPE {fam} {entry['type']}")
        for name, labelpart, value in entry["samples"]:
            series = f"{name}{{{labelpart}}}" if labelpart else name
            lines.append(f"{series} {value}")
    return "\n".join(lines) + ("\n" if lines else "")


def _json_body(resp) -> dict:
    try:
        doc = json.loads(resp.read() or b"{}")
        return doc if isinstance(doc, dict) else {"value": doc}
    except (json.JSONDecodeError, OSError):
        return {}


def _error_code(doc: dict) -> str | None:
    err = doc.get("error")
    return err.get("code") if isinstance(err, dict) else None
