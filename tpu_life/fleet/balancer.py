"""Worker choice: weighted least queue depth, scraped from /metrics.

The routing signal is the same one the gateway's own load shedder uses —
the ``serve_queue_depth`` gauge the service updates every scheduling
round — read over HTTP from the worker's live ``/metrics`` endpoint.
Depth readings are cached with a short TTL: one scrape per worker per TTL
window bounds the metrics traffic no matter the submit rate, at the cost
of routing on a slightly stale signal (the router's refusal-retry and the
worker's own shed valve catch what staleness misses).

With per-worker device placement (docs/FLEET.md), workers are no longer
interchangeable: a 4-chip worker drains its queue ~4x faster than a
1-chip one, so raw least-depth would leave the big worker starved and the
small one swamped.  The balancer therefore routes by **capacity-
normalized depth** — ``depth / weight``, where the weight is the
worker's resolved device count — and breaks ties by **smooth weighted
round-robin** (the nginx algorithm: every worker accrues credit
proportional to its weight, the richest goes first and pays the total
back), so an IDLE heterogeneous fleet already spreads sessions in
capacity ratio (~1:4 for 1-chip vs 4-chip) instead of alternating 1:1.
Unweighted fleets degenerate to the old behavior: equal weights make the
normalization a no-op and the credit rotation a plain round-robin.
"""

from __future__ import annotations

import threading
import time

#: Depth assigned to a worker whose metrics could not be scraped — sorts
#: last, but stays a candidate (the submit-path retry skips it if dead).
UNKNOWN_DEPTH = float("inf")


def prom_value(text: str, name: str) -> float | None:
    """First sample value of an (unlabeled) metric in Prometheus text."""
    for line in text.splitlines():
        if line.startswith(name):
            rest = line[len(name) :]
            if rest.startswith(" "):
                try:
                    return float(rest.strip())
                except ValueError:
                    return None
    return None


class LeastDepthBalancer:
    """Order candidates by capacity-normalized cached queue depth, ties
    broken by smooth weighted round-robin.

    ``fetch`` takes a worker and returns its current queue depth (raising
    on failure); the router wires it to a ``/metrics`` scrape.  The cache
    is keyed by (worker name, generation) so a restarted worker never
    inherits its predecessor's reading.  ``weight`` takes a worker and
    returns its capacity weight (the router wires it to the resolved
    device count); None — or a weight that errors / is non-positive —
    means 1.0, the homogeneous pre-placement behavior.
    """

    def __init__(
        self, fetch, ttl_s: float = 0.5, *, clock=time.monotonic, weight=None
    ):
        self.fetch = fetch
        self.weight = weight
        self.ttl_s = ttl_s
        self.clock = clock
        self._cache: dict[tuple[str, int], tuple[float, float]] = {}
        #: smooth-WRR credit per worker NAME (not generation: capacity is
        #: a property of the slice, which survives restarts)
        self._credits: dict[str, float] = {}
        self._lock = threading.Lock()

    def _weight(self, worker) -> float:
        if self.weight is None:
            return 1.0
        try:
            w = float(self.weight(worker))
        except Exception:
            return 1.0
        return w if w > 0 else 1.0

    def depth(self, worker) -> float:
        """The worker's queue depth (cached within the TTL)."""
        return self.depths([worker])[worker.name]

    def depths(self, workers: list) -> dict:
        """name -> depth for all ``workers``, scraping STALE entries
        concurrently: a submit that lands on a cold cache must pay the
        slowest single scrape, not the sum of them (one wedged worker
        burning its timeout would otherwise stall every admission for a
        whole TTL window)."""
        now = self.clock()
        out: dict = {}
        stale: list = []
        with self._lock:
            for w in workers:
                hit = self._cache.get((w.name, w.generation))
                if hit is not None and now - hit[0] < self.ttl_s:
                    out[w.name] = hit[1]
                else:
                    stale.append(w)
        if stale:
            values: list = [None] * len(stale)

            def one(i: int, w) -> None:
                try:
                    values[i] = float(self.fetch(w))
                except Exception:
                    values[i] = UNKNOWN_DEPTH

            if len(stale) == 1:
                one(0, stale[0])
            else:
                threads = [
                    threading.Thread(target=one, args=(i, w), daemon=True)
                    for i, w in enumerate(stale)
                ]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()  # bounded: fetch carries its own HTTP timeout
            with self._lock:
                for w, d in zip(stale, values):
                    key = (w.name, w.generation)
                    # drop readings from this worker's dead generations:
                    # restarts are unbounded over a router's lifetime, and
                    # a per-restart orphan entry would be a slow leak
                    for k in [
                        k for k in self._cache if k[0] == w.name and k != key
                    ]:
                        del self._cache[k]
                    self._cache[key] = (now, d)
                    out[w.name] = d
        return out

    def candidates(self, workers: list) -> list:
        """Workers ordered by weighted least depth (``depth / weight``);
        equal normalized depths follow the smooth-WRR credit order, so an
        idle heterogeneous fleet spreads in capacity ratio and an
        unweighted one round-robins as before."""
        if not workers:
            return []
        depths = self.depths(workers)
        weights = {w.name: self._weight(w) for w in workers}
        with self._lock:
            # credits belong to the CURRENT candidate set: a worker that
            # left the rotation (dead, draining) forfeits its balance
            # rather than leaking an entry per departed name
            live = {w.name for w in workers}
            for stale_name in [n for n in self._credits if n not in live]:
                del self._credits[stale_name]
            for w in workers:
                self._credits[w.name] = (
                    self._credits.get(w.name, 0.0) + weights[w.name]
                )
            keyed = [
                (
                    depths[w.name] / weights[w.name],
                    -self._credits[w.name],
                    i,
                    w,
                )
                for i, w in enumerate(workers)
            ]
            keyed.sort(key=lambda t: t[:3])
            # the CREDIT LEADER pays the whole round back (nginx smooth
            # WRR — at equal depths the leader IS the routed winner, so
            # over K idle picks each worker leads weight/total of them).
            # Charging the depth-selected winner instead would let
            # credits diverge without bound while a depth imbalance pins
            # routing to one worker, then burst-invert the spread once
            # depths re-equalize; paying the leader keeps every credit
            # inside one round's total regardless of depth weather.
            leader = max(workers, key=lambda w: self._credits[w.name])
            self._credits[leader.name] -= sum(weights.values())
        return [w for *_, w in keyed]

    def invalidate(self, worker) -> None:
        """Drop a worker's cached reading (e.g. right after routing to it,
        or after it refused — the next choice should re-scrape)."""
        with self._lock:
            self._cache.pop((worker.name, worker.generation), None)
