"""Worker choice: least queue depth, scraped from each worker's /metrics.

The routing signal is the same one the gateway's own load shedder uses —
the ``serve_queue_depth`` gauge the service updates every scheduling
round — read over HTTP from the worker's live ``/metrics`` endpoint.
Depth readings are cached with a short TTL: one scrape per worker per TTL
window bounds the metrics traffic no matter the submit rate, at the cost
of routing on a slightly stale signal (the router's refusal-retry and the
worker's own shed valve catch what staleness misses).

Equal depths tie-break by rotation so an idle fleet spreads sessions
round-robin instead of piling onto the first worker until the cache
expires.
"""

from __future__ import annotations

import threading
import time

#: Depth assigned to a worker whose metrics could not be scraped — sorts
#: last, but stays a candidate (the submit-path retry skips it if dead).
UNKNOWN_DEPTH = float("inf")


def prom_value(text: str, name: str) -> float | None:
    """First sample value of an (unlabeled) metric in Prometheus text."""
    for line in text.splitlines():
        if line.startswith(name):
            rest = line[len(name) :]
            if rest.startswith(" "):
                try:
                    return float(rest.strip())
                except ValueError:
                    return None
    return None


class LeastDepthBalancer:
    """Order candidate workers by cached queue depth, ties rotated.

    ``fetch`` takes a worker and returns its current queue depth (raising
    on failure); the router wires it to a ``/metrics`` scrape.  The cache
    is keyed by (worker name, generation) so a restarted worker never
    inherits its predecessor's reading.
    """

    def __init__(self, fetch, ttl_s: float = 0.5, *, clock=time.monotonic):
        self.fetch = fetch
        self.ttl_s = ttl_s
        self.clock = clock
        self._cache: dict[tuple[str, int], tuple[float, float]] = {}
        self._rr = 0
        self._lock = threading.Lock()

    def depth(self, worker) -> float:
        """The worker's queue depth (cached within the TTL)."""
        return self.depths([worker])[worker.name]

    def depths(self, workers: list) -> dict:
        """name -> depth for all ``workers``, scraping STALE entries
        concurrently: a submit that lands on a cold cache must pay the
        slowest single scrape, not the sum of them (one wedged worker
        burning its timeout would otherwise stall every admission for a
        whole TTL window)."""
        now = self.clock()
        out: dict = {}
        stale: list = []
        with self._lock:
            for w in workers:
                hit = self._cache.get((w.name, w.generation))
                if hit is not None and now - hit[0] < self.ttl_s:
                    out[w.name] = hit[1]
                else:
                    stale.append(w)
        if stale:
            values: list = [None] * len(stale)

            def one(i: int, w) -> None:
                try:
                    values[i] = float(self.fetch(w))
                except Exception:
                    values[i] = UNKNOWN_DEPTH

            if len(stale) == 1:
                one(0, stale[0])
            else:
                threads = [
                    threading.Thread(target=one, args=(i, w), daemon=True)
                    for i, w in enumerate(stale)
                ]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()  # bounded: fetch carries its own HTTP timeout
            with self._lock:
                for w, d in zip(stale, values):
                    key = (w.name, w.generation)
                    # drop readings from this worker's dead generations:
                    # restarts are unbounded over a router's lifetime, and
                    # a per-restart orphan entry would be a slow leak
                    for k in [
                        k for k in self._cache if k[0] == w.name and k != key
                    ]:
                        del self._cache[k]
                    self._cache[key] = (now, d)
                    out[w.name] = d
        return out

    def candidates(self, workers: list) -> list:
        """Workers ordered least-depth-first; equal depths rotate so an
        idle fleet round-robins instead of always hitting index 0."""
        if not workers:
            return []
        with self._lock:
            self._rr += 1
            rr = self._rr
        n = len(workers)
        depths = self.depths(workers)
        keyed = [
            (depths[w.name], (i - rr) % n, w) for i, w in enumerate(workers)
        ]
        keyed.sort(key=lambda t: (t[0], t[1]))
        return [w for _, _, w in keyed]

    def invalidate(self, worker) -> None:
        """Drop a worker's cached reading (e.g. right after routing to it,
        or after it refused — the next choice should re-scrape)."""
        with self._lock:
            self._cache.pop((worker.name, worker.generation), None)
