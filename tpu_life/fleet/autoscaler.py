"""Demand-driven autoscaling (docs/FLEET.md "Autoscaling").

The supervisor already knows how to spawn, probe, drain, and fence
workers; the series store already knows what the fleet's load looks
like.  This module closes the loop: a control function that reads the
store's windows (queue depth, queue age, admission refusals, memory
pressure) plus the SLO engine's burn verdicts, and answers scale UP
(recruit a parked standby through the existing spawn/registration
machinery), scale DOWN (drain-and-release an idle worker — accepted
sessions finish, nothing is dropped), or HOLD.

Design rules, in order:

- **The decision is a pure function.**  :func:`decide` maps (signals,
  control state, policy, now) to a :class:`Decision` with no I/O — the
  unit tests drive it with synthetic signals and a fake clock, and every
  hysteresis/cooldown/flap property is provable without a process tree.
- **Flap resistance is structural, not tuned.**  Scale-up and scale-down
  trigger on DIFFERENT thresholds (``depth_high`` vs ``depth_low``, the
  classic hysteresis band), scale-down additionally requires the fleet
  to have LOOKED idle continuously for ``idle_grace_s``, and each
  direction carries its own cooldown — a burst that ends the moment we
  scaled up cannot bounce the fleet back down inside the grace window.
- **Every decision is evidence.**  Ups and downs (and the first hold of
  each distinct reason) land in the flight recorder as typed
  ``scale.up`` / ``scale.down`` / ``scale.hold`` events carrying the
  signal snapshot that justified them, so ``tpu-life doctor --scale``
  can replay the whole sequence from a trace capture and answer "why
  did we have 40 workers at 14:02".

Pure stdlib, no jax/numpy (the fleet-tier contract).  No imports from
:mod:`tpu_life.fleet.supervisor` — the supervisor imports *us* (the
:class:`Autoscaler` takes it duck-typed), never the reverse.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace

from tpu_life import chaos
from tpu_life.obs import flight
from tpu_life.runtime.metrics import log

#: Series keys whose windowed rates sum into the "demand is being turned
#: away" signal: the serve tier's hard refusals plus the gateway's sheds.
#: Per-tenant quota rejections are deliberately absent — a tenant at its
#: own declared ceiling is not fleet pressure.
DEFAULT_REJECT_KEYS = (
    "serve_admission_rejected_total{reason=queue_full}",
    "serve_admission_rejected_total{reason=overloaded}",
    "gateway_shed_total",
)


@dataclass(frozen=True)
class AutoscaleConfig:
    """The declarative scaling policy (``fleet --autoscale``)."""

    #: never drain below this many deployed workers
    min_workers: int = 1
    #: never recruit past this many deployed workers; None = bounded
    #: only by the standby pool
    max_workers: int | None = None
    #: mean queue depth per READY worker at/above which demand exceeds
    #: capacity — the scale-up edge of the hysteresis band
    depth_high: float = 4.0
    #: mean queue depth per READY worker at/below which the fleet is
    #: idle enough to shrink — the scale-down edge (must sit strictly
    #: below ``depth_high`` or the band is a flap generator)
    depth_low: float = 0.5
    #: oldest queued session older than this -> scale up even at modest
    #: depth (a stuck queue is demand the depth gauge understates)
    queue_age_high_s: float = 5.0
    #: fleet-wide refusal rate (sheds + queue_full, per second) that
    #: counts as demand being turned away -> scale up
    reject_rate_high: float = 0.5
    #: summed ``serve_estimated_bytes`` over summed budget at/above
    #: which the fleet is memory-bound -> scale up
    bytes_fraction_high: float = 0.85
    #: rate window for the refusal signal
    window_s: float = 30.0
    #: minimum seconds between consecutive scale-ups
    cooldown_up_s: float = 5.0
    #: minimum seconds between consecutive scale-downs (and between a
    #: scale-up and the next scale-down)
    cooldown_down_s: float = 30.0
    #: the fleet must look idle CONTINUOUSLY this long before any
    #: scale-down — the structural flap guard
    idle_grace_s: float = 10.0
    #: ignore a worker's gauges when its newest snapshot is older than
    #: this (a wedged worker's stale queue depth is not demand)
    gauge_max_age_s: float = 10.0
    #: a breaching SLO (fast+slow burn past threshold) counts as a
    #: scale-up signal when True
    scale_on_burn: bool = True
    #: the refusal-rate series keys (overridable for bespoke stacks)
    reject_keys: tuple[str, ...] = DEFAULT_REJECT_KEYS

    def __post_init__(self):
        if self.min_workers < 0:
            raise ValueError(
                f"min_workers must be >= 0, got {self.min_workers}"
            )
        if self.max_workers is not None and self.max_workers < max(
            1, self.min_workers
        ):
            raise ValueError(
                f"max_workers must be >= max(1, min_workers), "
                f"got {self.max_workers}"
            )
        if not self.depth_low < self.depth_high:
            raise ValueError(
                f"need depth_low < depth_high (the hysteresis band), "
                f"got {self.depth_low} vs {self.depth_high}"
            )
        for name in ("window_s", "idle_grace_s", "gauge_max_age_s"):
            if getattr(self, name) <= 0:
                raise ValueError(f"{name} must be > 0")
        for name in ("cooldown_up_s", "cooldown_down_s"):
            if getattr(self, name) < 0:
                raise ValueError(f"{name} must be >= 0")


@dataclass(frozen=True)
class Signals:
    """One evaluation's input: what the fleet looks like *right now*.
    Pure data — the unit tests build these by hand."""

    active: int  # deployed slots (ready + starting + restarting)
    standby: int  # parked, recruitable slots
    ready: int  # workers actually in the routing rotation
    depth: float  # fleet-summed serve_queue_depth
    queue_age_s: float  # max per-worker serve_queue_age_oldest_seconds
    reject_rate: float  # summed refusal rate over the window (per s)
    mem_fraction: float | None  # est bytes / budget, None when unknown
    breaching: bool  # any SLO breaching right now
    per_worker_depth: dict = field(default_factory=dict)

    @property
    def depth_per_ready(self) -> float:
        return self.depth / max(1, self.ready)


@dataclass
class ControlState:
    """The loop's memory between evaluations (mutable, clock-stamped
    with whatever clock the caller passes to :func:`decide`)."""

    last_up_at: float | None = None
    last_down_at: float | None = None
    #: when the fleet FIRST looked idle in the current idle stretch;
    #: None while any demand signal is up
    low_since: float | None = None


@dataclass(frozen=True)
class Decision:
    action: str  # "up" | "down" | "hold"
    reason: str
    worker: str | None = None
    signals: dict = field(default_factory=dict)


def _signal_doc(sig: Signals) -> dict:
    doc = {
        "active": sig.active,
        "standby": sig.standby,
        "ready": sig.ready,
        "depth": round(sig.depth, 3),
        "depth_per_ready": round(sig.depth_per_ready, 3),
        "queue_age_s": round(sig.queue_age_s, 3),
        "reject_rate": round(sig.reject_rate, 4),
        "breaching": sig.breaching,
    }
    if sig.mem_fraction is not None:
        doc["mem_fraction"] = round(sig.mem_fraction, 4)
    return doc


def decide(
    sig: Signals, state: ControlState, cfg: AutoscaleConfig, now: float
) -> Decision:
    """The pure control function: signals + memory + policy -> verdict.
    Mutates ``state`` (the idle timer) but touches nothing else."""
    doc = _signal_doc(sig)

    # which way is demand pushing?
    up_reason = None
    if sig.ready > 0 and sig.depth_per_ready >= cfg.depth_high:
        up_reason = "queue_depth"
    elif sig.queue_age_s >= cfg.queue_age_high_s:
        up_reason = "queue_age"
    elif sig.reject_rate >= cfg.reject_rate_high:
        up_reason = "rejections"
    elif (
        sig.mem_fraction is not None
        and sig.mem_fraction >= cfg.bytes_fraction_high
    ):
        up_reason = "memory_pressure"
    elif cfg.scale_on_burn and sig.breaching:
        up_reason = "slo_burn"
    elif sig.active < cfg.min_workers:
        up_reason = "below_min"

    idle = (
        up_reason is None
        and sig.depth_per_ready <= cfg.depth_low
        and sig.queue_age_s < cfg.queue_age_high_s
        and sig.reject_rate <= 0.0
        # an operator who disabled burn-driven scaling gets burn-blind
        # downs too — SLO state then neither grows nor pins the fleet
        and not (cfg.scale_on_burn and sig.breaching)
    )

    if up_reason is not None:
        state.low_since = None  # any demand restarts the idle clock
        if sig.standby <= 0:
            return Decision("hold", "no_standby", signals=doc)
        if (
            cfg.max_workers is not None
            and sig.active >= cfg.max_workers
            and up_reason != "below_min"
        ):
            return Decision("hold", "at_max", signals=doc)
        if (
            state.last_up_at is not None
            and now - state.last_up_at < cfg.cooldown_up_s
        ):
            return Decision("hold", "cooldown_up", signals=doc)
        return Decision("up", up_reason, signals=doc)

    if not idle:
        # in the hysteresis band: neither edge tripped — hold, and the
        # idle clock does NOT accumulate (idle must be continuous)
        state.low_since = None
        return Decision("hold", "steady", signals=doc)

    if sig.active <= cfg.min_workers:
        return Decision("hold", "at_min", signals=doc)
    if state.low_since is None:
        state.low_since = now
    if now - state.low_since < cfg.idle_grace_s:
        return Decision("hold", "settling", signals=doc)
    # a fresh scale-up also arms the down cooldown: a burst that ended
    # the moment we grew must not bounce straight back
    moves = [t for t in (state.last_down_at, state.last_up_at) if t is not None]
    last_move = max(moves) if moves else None
    if last_move is not None and now - last_move < cfg.cooldown_down_s:
        return Decision("hold", "cooldown_down", signals=doc)
    return Decision("down", "idle", signals=doc)


class Autoscaler:
    """The live loop: gathers :class:`Signals` from a supervisor's
    series store / SLO engine / membership view, runs :func:`decide`,
    executes the verdict through ``supervisor.recruit()`` /
    ``supervisor.release()``, and records every decision as flight
    evidence.  Driven from the supervisor's monitor tick at the series
    cadence; all its own state lives in :class:`ControlState`."""

    def __init__(self, cfg: AutoscaleConfig, supervisor):
        self.cfg = cfg
        self.sup = supervisor
        self.state = ControlState()
        self.decisions = 0
        #: the last hold reason recorded (holds only land in the flight
        #: ring on a reason EDGE — a steady fleet must not flood the
        #: ring the postmortem depends on)
        self._last_hold: str | None = None

    # -- signal gathering --------------------------------------------------
    def collect(self) -> Signals:
        store = self.sup.series_store
        active, standby = self.sup.scale_counts()
        ready = len(self.sup.ready_workers())
        depth = 0.0
        per_worker: dict = {}
        g = store.fleet_gauge(
            "serve_queue_depth", max_age_s=self.cfg.gauge_max_age_s
        )
        if g is not None:
            depth, per_worker = g
        age = 0.0
        g = store.fleet_gauge(
            "serve_queue_age_oldest_seconds",
            max_age_s=self.cfg.gauge_max_age_s,
        )
        if g is not None and g[1]:
            age = max(g[1].values())
        reject = 0.0
        for key in self.cfg.reject_keys:
            r = store.fleet_rate(key, self.cfg.window_s)
            if r is not None:
                reject += r[0]
        mem_fraction = None
        est = store.fleet_gauge(
            "serve_estimated_bytes", max_age_s=self.cfg.gauge_max_age_s
        )
        budget = store.fleet_gauge(
            "serve_memory_budget_bytes", max_age_s=self.cfg.gauge_max_age_s
        )
        if est is not None and budget is not None and budget[0] > 0:
            mem_fraction = est[0] / budget[0]
        breaching = any(
            st.get("breaching") for st in self.sup.slo_engine.status().values()
        )
        return Signals(
            active=active,
            standby=standby,
            ready=ready,
            depth=depth,
            queue_age_s=age,
            reject_rate=reject,
            mem_fraction=mem_fraction,
            breaching=breaching,
            per_worker_depth=per_worker,
        )

    # -- the loop body -----------------------------------------------------
    def evaluate(self, now: float) -> Decision:
        sig = self.collect()
        d = decide(sig, self.state, self.cfg, now)
        if d.action == "up":
            name = self.sup.recruit()
            if name is None:
                # the standby refused to launch (or chaos said it did):
                # hold, leave the cooldown unarmed so the next pass
                # retries immediately
                d = replace(d, action="hold", reason="recruit_failed")
            else:
                self.state.last_up_at = now
                d = replace(d, worker=name)
                log.info(
                    "fleet: scale up -> %s (%s, depth/worker %.1f)",
                    name,
                    d.reason,
                    sig.depth_per_ready,
                )
        elif d.action == "down":
            victim = self._pick_victim(sig)
            if victim is None or not self.sup.release(victim):
                d = replace(d, action="hold", reason="no_victim")
            else:
                self.state.last_down_at = now
                self.state.low_since = None
                d = replace(d, worker=victim)
                log.info("fleet: scale down -> releasing %s (idle)", victim)
        self._record(d)
        return d

    def _pick_victim(self, sig: Signals) -> str | None:
        """The idlest READY worker (lowest reported queue depth; a
        worker with no fresh gauge counts as idle).  The
        ``scale.release.race`` chaos point inverts the choice — the
        drain races live load, and graceful release must STILL lose no
        session (accepted work finishes before the worker exits)."""
        ready = self.sup.ready_workers()
        if not ready:
            return None
        d = chaos.decide("scale.release.race")
        if d is not None:
            chaos.record_fire("scale.release.race", "race")
            busiest = max(
                ready, key=lambda w: sig.per_worker_depth.get(w.name, 0.0)
            )
            return busiest.name
        idlest = min(
            ready, key=lambda w: sig.per_worker_depth.get(w.name, 0.0)
        )
        return idlest.name

    def _record(self, d: Decision) -> None:
        self.decisions += 1
        if d.action == "hold":
            if d.reason == self._last_hold:
                return  # steady state: the edge was already recorded
            self._last_hold = d.reason
        else:
            self._last_hold = None
        ev = dict(d.signals)
        ev["reason"] = d.reason
        if d.worker is not None:
            ev["worker"] = d.worker
        flight.record(f"scale.{d.action}", **ev)


# -- the doctor join ------------------------------------------------------
#: Flight-event names (as they appear in a merged trace capture) that
#: belong to the scaling story, in the order the report narrates them.
_SCALE_NAMES = (
    "flight.scale.up",
    "flight.scale.down",
    "flight.scale.hold",
    "flight.scale.recruit",
    "flight.scale.release",
)


def scale_report(doc: dict) -> dict:
    """Reconstruct the full scaling decision sequence from a merged
    trace capture (``tpu-life doctor --scale CAPTURE``): every typed
    ``scale.*`` flight event, time-ordered, each carrying the signal
    snapshot that justified it — the audit trail behind "why did we
    have 40 workers at 14:02"."""
    events = [
        ev
        for ev in doc.get("traceEvents", [])
        if isinstance(ev, dict)
        and ev.get("name") in _SCALE_NAMES
        and "ts" in ev
        and isinstance(ev.get("args"), dict)
    ]
    events.sort(key=lambda e: float(e["ts"]))
    decisions = []
    counts: dict[str, int] = {}
    for ev in events:
        action = ev["name"].rsplit(".", 1)[1]
        args = ev["args"]
        counts[action] = counts.get(action, 0) + 1
        decisions.append(
            {
                "t_s": round(float(ev["ts"]) / 1e6, 6),
                "action": action,
                "reason": args.get("reason"),
                "worker": args.get("worker"),
                "active": args.get("active"),
                "standby": args.get("standby"),
                "signals": {
                    k: v
                    for k, v in args.items()
                    if k not in ("reason", "worker", "trace_id")
                },
            }
        )
    return {"decisions": decisions, "counts": counts, "ok": True}


def render_scale_report(report: dict) -> str:
    lines = []
    for d in report["decisions"]:
        sig = d["signals"]
        parts = [f"{d['t_s']:.3f}s", d["action"].upper()]
        if d.get("worker"):
            parts.append(d["worker"])
        # recruit/release are action events with no reason — fall back
        # to their signal snapshot (generation, remote) for the audit line
        detail = d.get("reason") or ", ".join(
            f"{k}={v}" for k, v in sig.items() if not isinstance(v, (dict, list))
        ) or "?"
        if d.get("active") is not None:
            detail += (
                f" (active {d['active']}, standby {d['standby']}"
                f", depth/worker {sig.get('depth_per_ready', '?')})"
            )
        parts.append("— " + detail)
        lines.append(" ".join(parts))
    c = report["counts"]
    lines.append(
        f"verdict: {len(report['decisions'])} decision(s) — "
        f"{c.get('up', 0)} up, {c.get('down', 0)} down, "
        f"{c.get('hold', 0)} hold"
        if report["decisions"]
        else "no scale decisions in the capture (autoscaling off, or the "
        "fleet never left steady state)"
    )
    return "\n".join(lines)
