"""The watcher fan-out tier: N watchers of one session, ONE upstream.

A popular session must not multiply load on the worker that computes it
(docs/STREAMING.md "Fan-out topology").  The router multiplexes: per
watched fleet sid it keeps exactly one upstream stream (a puller thread
consuming the worker's ndjson delta frames) feeding a bounded broadcast
buffer; every watcher is just a cursor into that buffer.  10 000
watchers of one sid cost the worker exactly what one watcher costs — the
multiplexer test proves it by counting upstream opens.

Backpressure is the router's problem, never the worker's: the buffer is
bounded, and when it overflows the SLOWEST watcher is shed typed (a
``{"type": "shed", "reason": "slow_reader"}`` frame, then the stream
ends; ``watcher_shed_total{reason}`` counts it) — one wedged client
cannot grow router memory or stall its peers.

Failover continuity rides the cursor: the upstream is opened with the
next sequence number the buffer needs, so when a worker dies mid-stream
and the migrator re-pins the sid to a survivor (which replays the delta
log from the spilled manifest), the puller's reconnect resumes at the
exact seq where the dead worker stopped — watchers observe a keyframe
re-sync with GAPLESS sequence numbers, same trace, no torn state.

``open_upstream`` is injectable (``(fsid, cursor) -> frame iterator``):
the router binds it to pin-resolution + a worker HTTP stream; tests bind
counting fakes, so the fan-out contract is provable without sockets.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque

from tpu_life.runtime.metrics import log

#: Default broadcast-buffer bound, in frames, per watched sid.  Deltas
#: are small (run-length masks); 512 frames of slack absorbs a multi-
#: second stall before the slowest watcher is shed.
BUFFER_FRAMES = 512

#: The one shed reason this tier emits today; the label is open for a
#: future policy (e.g. an admission cap shedding newest-first).
SHED_SLOW_READER = "slow_reader"


class _Fan:
    """Per-sid broadcast state.  All fields are guarded by the hub lock;
    ``cond`` shares that lock so pullers wake watchers directly."""

    __slots__ = (
        "fsid",
        "frames",
        "start",
        "next_seq",
        "out_next",
        "watchers",
        "sheds",
        "cond",
        "done",
        "closed",
        "opens",
    )

    def __init__(self, fsid: str, cursor: int, lock: threading.Lock):
        self.fsid = fsid
        self.frames: deque = deque()
        self.start = 0  # ordinal of frames[0] since this fan was born
        self.next_seq = cursor  # upstream seq to request on (re)connect
        # the DENSE outgoing sequence (what watchers see): upstream seqs
        # may jump across a failover (frames the dead worker produced
        # but never delivered still consumed its numbering; the survivor
        # re-keys past them) — the fan renumbers every broadcast frame
        # so reconnected watcher seqs are gapless by construction
        self.out_next = cursor
        self.watchers: dict[int, int] = {}  # watcher id -> ordinal cursor
        self.sheds: set[int] = set()  # watchers marked for typed shed
        self.cond = threading.Condition(lock)
        self.done = False
        self.closed = False
        self.opens = 0  # upstream opens (reconnects included) — test seam

    @property
    def end(self) -> int:
        return self.start + len(self.frames)


class FanoutHub:
    """The subscription multiplexer (one per router).

    ``open_upstream(fsid, cursor)`` must return an iterator of frame
    dicts starting at sequence ``cursor`` and may raise on transport
    failure — the hub reconnects with the next cursor it needs, up to
    ``max_reconnects`` consecutive failures, then ends the fan with a
    synthetic ``{"type": "end", "state": "lost"}`` so watchers terminate
    typed instead of hanging.
    """

    def __init__(
        self,
        *,
        open_upstream,
        buffer_frames: int = BUFFER_FRAMES,
        registry=None,
        max_reconnects: int = 8,
        sleep=time.sleep,
    ):
        if buffer_frames < 2:
            raise ValueError(f"buffer_frames must be >= 2, got {buffer_frames}")
        self._open_upstream = open_upstream
        self.buffer_frames = buffer_frames
        self._max_reconnects = max_reconnects
        self._sleep = sleep
        self._lock = threading.Lock()
        self._fans: dict[str, _Fan] = {}
        self._ids = itertools.count(1)
        self.shed_total = 0
        self._c_shed = None
        self._g_watchers = None
        if registry is not None:
            self._c_shed = registry.counter(
                "watcher_shed_total",
                "stream watchers shed by the fan-out tier, by reason",
                labels=("reason",),
            )
            self._c_shed.labels(reason=SHED_SLOW_READER)
            self._g_watchers = registry.gauge(
                "fleet_stream_watchers",
                "live stream watchers across the fan-out tier",
            )

    # -- the watcher side --------------------------------------------------
    def watch(self, fsid: str, cursor: int = 0):
        """A generator of frame dicts for one watcher of ``fsid``.

        The FIRST watcher of a sid creates the fan and its puller (the
        one upstream); later watchers join the broadcast buffer at its
        most recent keyframe (or, when the buffer holds none, after a
        synthetic ``frame_gap`` so the client knows to wait for the next
        re-key).  Ends on the upstream's ``end`` frame, or early with a
        typed ``shed`` frame when this watcher is the slowest under
        overflow.
        """
        with self._lock:
            fan = self._fans.get(fsid)
            if fan is None:
                fan = _Fan(fsid, cursor, self._lock)
                self._fans[fsid] = fan
                t = threading.Thread(
                    target=self._pull,
                    args=(fan,),
                    name=f"fanout-{fsid}",
                    daemon=True,
                )
                t.start()
            wid = next(self._ids)
            pos, keywait = self._join_pos(fan, cursor)
            fan.watchers[wid] = pos
            self._set_watcher_gauge()
        try:
            if keywait:
                # the buffer holds no keyframe (overflow ate it): tell the
                # client to hold reconstruction until the next re-key
                yield {
                    "type": "frame_gap",
                    "seq": max(0, fan.out_next - 1),
                    "dropped": -1,
                }
            while True:
                with self._lock:
                    while (
                        wid not in fan.sheds
                        and pos >= fan.end
                        and not fan.done
                        and not fan.closed
                    ):
                        fan.cond.wait(0.25)
                    if wid in fan.sheds:
                        self.shed_total += 1
                        if self._c_shed is not None:
                            self._c_shed.labels(reason=SHED_SLOW_READER).inc()
                        shed = {
                            "type": "shed",
                            "reason": SHED_SLOW_READER,
                            # the oldest still-broadcastable outgoing seq
                            # — where a reconnecting client could resume
                            "seq": fan.out_next - len(fan.frames),
                        }
                        batch, ended = [shed], True
                    elif pos < fan.start:
                        # fell behind while outside the wait (mid-yield):
                        # same verdict, recorded the same way
                        fan.sheds.add(wid)
                        continue
                    else:
                        batch = list(
                            itertools.islice(
                                fan.frames, pos - fan.start, len(fan.frames)
                            )
                        )
                        pos = fan.end
                        fan.watchers[wid] = pos
                        ended = fan.done and pos >= fan.end
                        if fan.closed and not batch:
                            return
                # yield OUTSIDE the lock: a slow consumer blocks only its
                # own generator, never the puller or its peers
                for frame in batch:
                    if keywait and frame.get("type") == "delta":
                        continue  # unreconstructable until the next key
                    if frame.get("type") == "key":
                        keywait = False
                    yield frame
                if ended:
                    return
        finally:
            self._unsubscribe(fsid, wid)

    def watcher_count(self) -> int:
        with self._lock:
            return sum(len(f.watchers) for f in self._fans.values())

    def upstream_opens(self, fsid: str) -> int:
        """Upstream connections opened for ``fsid`` so far (test seam —
        the fan-out sublinearity proof counts these)."""
        with self._lock:
            fan = self._fans.get(fsid)
            return fan.opens if fan is not None else 0

    def close(self) -> None:
        """End every fan: watchers drain what is buffered and return;
        pullers notice ``closed`` at their next frame and exit."""
        with self._lock:
            for fan in self._fans.values():
                fan.closed = True
                fan.cond.notify_all()

    # -- internals ---------------------------------------------------------
    def _join_pos(self, fan: _Fan, cursor: int) -> tuple[int, bool]:
        """(ordinal to start at, keyframe-wait flag) for a new watcher.

        A reconnecting watcher whose outgoing-seq ``cursor`` still falls
        inside the buffer resumes exactly there — its own stream stays
        dense across its reconnect.  Otherwise: the latest buffered
        keyframe when one exists; the buffer head (frame 0 IS the
        worker's first keyframe) when nothing was ever dropped; else the
        tail, flagged to wait for a re-key."""
        out_base = fan.out_next - len(fan.frames)
        if cursor and out_base <= cursor <= fan.out_next:
            return fan.start + (cursor - out_base), False
        for i in range(len(fan.frames) - 1, -1, -1):
            if fan.frames[i].get("type") == "key":
                return fan.start + i, False
        if fan.start == 0:
            return 0, False
        return fan.end, True

    def _unsubscribe(self, fsid: str, wid: int) -> None:
        with self._lock:
            fan = self._fans.get(fsid)
            if fan is None:
                return
            fan.watchers.pop(wid, None)
            fan.sheds.discard(wid)
            if not fan.watchers:
                # last watcher gone: tear the fan down — the puller sees
                # ``closed`` and drops the upstream, releasing the
                # worker-side watcher-buffer governor charge with it
                fan.closed = True
                fan.cond.notify_all()
                self._fans.pop(fsid, None)
            self._set_watcher_gauge()

    def _set_watcher_gauge(self) -> None:
        if self._g_watchers is not None:
            self._g_watchers.set(
                float(sum(len(f.watchers) for f in self._fans.values()))
            )

    def _append(self, fan: _Fan, frame: dict) -> None:
        """Buffer one upstream frame (hub lock held): bound the buffer,
        mark the slowest watchers for typed shed on overflow, and
        renumber into the fan's dense outgoing sequence (the upstream
        seq only advances the reconnect cursor)."""
        if len(fan.frames) >= self.buffer_frames:
            fan.frames.popleft()
            fan.start += 1
            for wid, c in fan.watchers.items():
                if c < fan.start and wid not in fan.sheds:
                    fan.sheds.add(wid)
        seq = frame.get("seq")
        if isinstance(seq, int):
            fan.next_seq = seq + 1
        out = dict(frame)
        out["seq"] = fan.out_next
        fan.out_next += 1
        fan.frames.append(out)
        fan.cond.notify_all()

    def _pull(self, fan: _Fan) -> None:
        """The one upstream consumer for this fan.  Reconnects with the
        next needed cursor on transport failure — the failover-continuity
        path — and converts exhaustion into a typed terminal frame."""
        attempts = 0
        while True:
            with self._lock:
                if fan.done or fan.closed:
                    return
                cursor = fan.next_seq
                fan.opens += 1
            try:
                for frame in self._open_upstream(fan.fsid, cursor):
                    with self._lock:
                        if fan.closed:
                            return
                        self._append(fan, frame)
                        attempts = 0
                        if frame.get("type") == "end":
                            fan.done = True
                            fan.cond.notify_all()
                            return
                # iterator ended without an "end" frame: the stream tore
                # gracefully (worker drained / connection closed) — same
                # reconnect path as an exception
                raise ConnectionError("upstream stream ended without 'end'")
            except Exception as e:
                with self._lock:
                    if fan.done or fan.closed:
                        return
                attempts += 1
                if attempts > self._max_reconnects:
                    log.warning(
                        "fanout: %s upstream lost after %d attempts: %s",
                        fan.fsid,
                        attempts,
                        e,
                    )
                    with self._lock:
                        self._append(
                            fan,
                            {
                                "type": "end",
                                "seq": fan.next_seq,
                                "state": "lost",
                            },
                        )
                        fan.done = True
                        fan.cond.notify_all()
                    return
                log.debug(
                    "fanout: %s upstream dropped (%s); reconnect %d at seq %d",
                    fan.fsid,
                    e,
                    attempts,
                    fan.next_seq,
                )
                self._sleep(min(0.05 * (2**attempts), 1.0))


__all__ = ["BUFFER_FRAMES", "FanoutHub", "SHED_SLOW_READER"]
