"""Command-line interface.

The reference takes zero CLI arguments — config is a 3-int file and
filenames are hard-coded (Parallel_Life_MPI.cpp:195, :201, :63).  Running
``python -m tpu_life run`` with no flags reproduces exactly that contract
(reads ``grid_size_data.txt`` + ``data.txt``, writes ``output.txt``, prints
``Total time = <s>``); every flag is an override on top.
"""

from __future__ import annotations

import argparse
import sys

from tpu_life.config import RunConfig


def _add_stencil_arg(p) -> None:
    """The neighborhood-counting knob (docs/RULES.md) — shared by every
    front that steps boards (run / serve / sweep / gateway; the fleet
    forwards it per worker)."""
    p.add_argument(
        "--stencil", default="auto", choices=["auto", "roll", "matmul"],
        help="neighborhood-counting path: roll = shift-add stencil, "
             "matmul = banded matmuls on the MXU (bit-identical for "
             "integer rules; the large-radius / continuous-kernel path), "
             "auto = the measured crossover model (numpy executors stay "
             "on the roll oracle)")


def _add_governor_args(p) -> None:
    """The serve-tier resource-governor knobs (docs/SERVING.md "Resource
    governance") — shared by every front that constructs a ServeConfig
    (serve / sweep / gateway) and forwarded per worker by the fleet."""
    p.add_argument(
        "--memory-budget-bytes", type=int, default=None, metavar="BYTES",
        help="admission memory budget for estimated engine footprints; a "
             "CompileKey that would overflow it is a typed rejection "
             "instead of a mid-round XLA OOM (default: devices x "
             "per-kind default from device_info(); 0 disables)")
    p.add_argument(
        "--engine-max-restarts", type=int, default=3, metavar="N",
        help="in-place engine recoveries per CompileKey (rebuild+replay, "
             "OOM halve-chunk -> host-demotion ladder) before a chunk "
             "fault falls back to the typed per-key failure (0 = pure "
             "failure isolation)")
    p.add_argument(
        "--settle-deadline", type=float, default=None, metavar="SECONDS",
        help="wedge watchdog: a pipelined settle window still blocked "
             "after this many seconds marks the service wedged — "
             "finishers salvaged, /readyz answers 500 engine_wedged so a "
             "supervisor recycles the worker (default: off)")
    p.add_argument(
        "--mesh-devices", type=int, default=0, metavar="N",
        help="mega-board tier (docs/SERVING.md): reserve an N-device "
             "slice so a board the governor would reject as never-fits "
             "is placed on a sharded 2-D torus mesh instead of 413'd "
             "(0 = tier off; needs >= 2)")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tpu_life", description="TPU-native cellular-automaton framework"
    )
    sub = p.add_subparsers(dest="command")

    r = sub.add_parser("run", help="run a simulation (default command)")
    _add_run_args(r)

    info = sub.add_parser("info", help="show devices, rules and version")
    info.set_defaults(command="info")

    pat = sub.add_parser(
        "pattern",
        help="RLE pattern interchange: import/export boards, stamp named "
        "patterns",
    )
    pat.add_argument(
        "action",
        choices=["import", "export", "list"],
        help="import: RLE/named pattern -> contract board+config; "
        "export: contract board -> RLE; list: named patterns",
    )
    pat.add_argument("--rle", default=None, metavar="FILE",
                     help="RLE file (import source / export destination; "
                     "export defaults to stdout)")
    pat.add_argument("--name", default=None,
                     help="named pattern to import (see `pattern list`)")
    pat.add_argument("--height", type=int, default=None)
    pat.add_argument("--width", type=int, default=None)
    pat.add_argument("--at", default=None, metavar="R,C",
                     help="top-left placement of the pattern (default: centered)")
    pat.add_argument("--input-file", default="data.txt")
    pat.add_argument("--config-file", default="grid_size_data.txt")
    pat.add_argument("--steps", type=int, default=100,
                     help="steps written to the config file on import")
    pat.add_argument("--rule", default="B3/S23",
                     help="rule string stamped into the exported RLE header "
                     "(record what the board was actually evolved under)")

    t = sub.add_parser(
        "tune",
        help="measured autotuning: search the (backend, block_steps, "
        "local_kernel, bitpack) space for this device + rule + board "
        "shape and persist the winner to the autotune cache",
    )
    t.add_argument("--size", type=int, default=4096,
                   help="square board edge for the trial workload")
    t.add_argument("--height", type=int, default=None,
                   help="trial board height (overrides --size)")
    t.add_argument("--width", type=int, default=None,
                   help="trial board width (overrides --size)")
    t.add_argument("--rule", default="conway")
    t.add_argument("--backend-set", default=None, metavar="B1,B2",
                   help="comma list of backends to search (default: "
                   "jax,sharded,pallas on TPU; jax,sharded elsewhere)")
    t.add_argument("--trials", type=int, default=3,
                   help="timed repetitions per candidate (median wins)")
    t.add_argument("--steps", type=int, default=None,
                   help="steps per timed trial (default: platform-scaled)")
    t.add_argument("--warmup-steps", type=int, default=None,
                   help="untimed steps absorbing compilation per candidate")
    t.add_argument("--dry-run", action="store_true",
                   help="enumerate candidates and rank by the analytic "
                   "cost model only — no measurement, nothing persisted "
                   "(the CI smoke path)")
    t.add_argument("--cache-file", default=None, metavar="JSON",
                   help="autotune cache location (default "
                   "~/.cache/tpu_life/autotune.json or "
                   "$TPU_LIFE_AUTOTUNE_CACHE)")
    t.add_argument("--platform", default=None,
                   help="force a JAX platform (cpu/tpu), like `run --platform`")

    b = sub.add_parser(
        "bench",
        help="quick throughput measurement: cells/s/chip vs the 1e11 target",
    )
    # steps/base-steps match bench.py's delta methodology — the timed delta
    # must hold far more compute than the tunnel's per-dispatch jitter, or
    # the number is noise (a 90-step delta at 4096^2 is ~0.7 ms of compute
    # against ~ms jitter).  size/repeats are smaller than bench.py's
    # (16384 / 6 on an accelerator): this is the quick check, not the
    # armored capture.
    b.add_argument("--size", type=int, default=4096)
    b.add_argument("--steps", type=int, default=1000)
    b.add_argument("--base-steps", type=int, default=100)
    b.add_argument("--repeats", type=int, default=3)
    b.add_argument("--rule", default="conway")
    b.add_argument("--backend", default="auto")
    b.add_argument("--platform", default=None,
                   help="force a JAX platform (cpu/tpu), like `run --platform`")
    b.add_argument("--block-steps", type=int, default=None)
    b.add_argument("--local-kernel", default=None,
                   help="sharded backend only (ignored elsewhere, and "
                   "recorded as null in the JSON)")

    srv = sub.add_parser(
        "serve",
        help="multi-tenant batched serving: run every request in a JSONL "
        "spool file through the continuous-batching service",
    )
    srv.add_argument(
        "--requests",
        default="serve_requests.jsonl",
        metavar="JSONL",
        help="request spool file (one JSON object per line; see "
        "`tpu-life submit` and docs/SERVING.md)",
    )
    srv.add_argument(
        "--output-dir",
        default="serve_out",
        help="where results land for requests without an output_file "
        "(<output-dir>/<session-id>.txt, contract board format)",
    )
    srv.add_argument("--capacity", type=int, default=8,
                     help="batch slots per compile key")
    srv.add_argument("--chunk-steps", type=int, default=16,
                     help="device steps per scheduling round")
    srv.add_argument("--max-queue", type=int, default=64,
                     help="bounded admission queue (backpressure threshold)")
    srv.add_argument(
        "--serve-backend",
        default="jax",
        choices=["jax", "tuned", "numpy", "sharded", "stripes", "pallas", "native"],
        help="engine executor: jax/numpy run a true batch axis, the rest "
        "loop over slots (one Runner per session); tuned resolves per "
        "CompileKey through the autotune cache (read path only — an "
        "untuned key takes the cost-model pick, never a measurement)",
    )
    srv.add_argument("--sync-pump", action="store_true",
                     help="run the host-synchronous scheduling round "
                     "instead of the default pipelined (double-buffered) "
                     "pump — the bit-identical oracle shape, for "
                     "debugging and baseline timing (docs/SERVING.md)")
    srv.add_argument("--no-bitpack", action="store_true",
                     help="pin stochastic (ising) batches to the int8 "
                     "roll engines instead of the default bitplane-packed "
                     "path — bit-identical, the packed path's oracle "
                     "(docs/STOCHASTIC.md)")
    _add_stencil_arg(srv)
    srv.add_argument("--timeout", type=float, default=None, metavar="SECONDS",
                     help="default per-request deadline")
    srv.add_argument("--spill-dir", default=None, metavar="DIR",
                     help="durable sessions: spill every live session's "
                     "board + manifest here through the checkpoint "
                     "contract so a killed process's work is resumable "
                     "(docs/SERVING.md durability)")
    srv.add_argument("--spill-every", type=int, default=4, metavar="K",
                     help="rounds between spill passes (recovery point = "
                     "the last spilled chunk)")
    srv.add_argument("--metrics-file", default=None, metavar="JSONL",
                     help="append per-round serve metrics as JSON lines")
    srv.add_argument("--trace-events", default=None, metavar="FILE",
                     help="write Chrome trace-event JSON (Perfetto): round "
                     "spans (admit/dispatch/collect/retire; step-chunk "
                     "under --sync-pump) + per-session queue-wait "
                     "intervals, run_id-correlated with the metrics sink")
    srv.add_argument("--prom-file", default=None, metavar="FILE",
                     help="write a Prometheus text-exposition snapshot of "
                     "the serve metrics registry at shutdown")
    _add_governor_args(srv)
    srv.add_argument("--platform", default=None,
                     help="force a JAX platform (cpu/tpu), like `run --platform`")
    srv.add_argument("--profile", default=None, metavar="TRACE_DIR")
    srv.add_argument("--verbose", "-v", action="store_true")

    sw = sub.add_parser(
        "sweep",
        help="temperature sweep (docs/STOCHASTIC.md): fan a temperature "
        "grid into one ising session per temperature through the "
        "continuous-batching service — mixed temperatures share ONE "
        "compiled vmapped step",
    )
    sw.add_argument("--size", type=int, default=None,
                    help="square lattice edge (or --height/--width)")
    sw.add_argument("--height", type=int, default=None)
    sw.add_argument("--width", type=int, default=None)
    sw.add_argument("--steps", type=int, required=True,
                    help="Metropolis sweeps per session")
    sw.add_argument("--rule", default="ising",
                    help="stochastic rule to sweep (ising)")
    sw.add_argument(
        "--temps",
        default="1.5:3.0:8",
        metavar="SPEC",
        help="temperature grid: comma list 'T1,T2,...' or range 'lo:hi:n' "
        "(n points, endpoints included; default 1.5:3.0:8 brackets the "
        "Onsager critical point T~2.269)",
    )
    sw.add_argument("--seed", type=int, default=0,
                    help="counter-based PRNG seed shared by every session "
                    "(the temperature is the only thing that varies)")
    sw.add_argument("--density", type=float, default=0.5,
                    help="seeded initial-board density")
    sw.add_argument(
        "--serve-backend",
        default="jax",
        choices=["jax", "numpy"],
        help="engine executor (stochastic rules run on the executors "
        "implementing the counter-based key schedule)",
    )
    sw.add_argument("--capacity", type=int, default=None,
                    help="batch slots (default: one per temperature, so "
                    "the whole grid runs as one batch)")
    sw.add_argument("--chunk-steps", type=int, default=16)
    sw.add_argument("--sync-pump", action="store_true",
                    help="host-synchronous rounds instead of the pipelined "
                    "pump (same semantics as `serve --sync-pump`)")
    sw.add_argument("--no-bitpack", action="store_true",
                    help="sweep on the int8 roll engines instead of the "
                    "default bitplane-packed Metropolis path — "
                    "bit-identical, the packed path's oracle")
    _add_stencil_arg(sw)
    _add_governor_args(sw)
    sw.add_argument("--output-dir", default=None, metavar="DIR",
                    help="also write each final lattice to "
                    "DIR/<session-id>.txt (contract board format)")
    sw.add_argument("--metrics-file", default=None, metavar="JSONL",
                    help="append per-round serve metrics as JSON lines")
    sw.add_argument("--platform", default=None,
                    help="force a JAX platform (cpu/tpu), like `run --platform`")
    sw.add_argument("--verbose", "-v", action="store_true")

    gw = sub.add_parser(
        "gateway",
        help="HTTP front door over the batched simulation service: JSON "
        "API with rate limiting, load shedding and graceful drain "
        "(docs/GATEWAY.md)",
    )
    gw.add_argument("--host", default="127.0.0.1")
    gw.add_argument("--port", type=int, default=8000,
                    help="listen port (0 = ephemeral; the bound port is "
                    "printed in the startup JSON line)")
    gw.add_argument("--capacity", type=int, default=8,
                    help="batch slots per compile key")
    gw.add_argument("--chunk-steps", type=int, default=16,
                    help="device steps per scheduling round")
    gw.add_argument("--max-queue", type=int, default=64,
                    help="bounded admission queue (backpressure threshold)")
    gw.add_argument(
        "--serve-backend",
        default="jax",
        choices=["jax", "tuned", "numpy", "sharded", "stripes", "pallas", "native"],
        help="engine executor (same semantics as `serve --serve-backend`)",
    )
    gw.add_argument("--sync-pump", action="store_true",
                    help="host-synchronous rounds instead of the pipelined "
                    "pump (same semantics as `serve --sync-pump`)")
    gw.add_argument("--no-bitpack", action="store_true",
                    help="pin stochastic (ising) batches to the int8 roll "
                    "engines (same semantics as `serve --no-bitpack`)")
    _add_stencil_arg(gw)
    gw.add_argument("--timeout", type=float, default=None, metavar="SECONDS",
                    help="default per-request deadline")
    gw.add_argument("--spill-dir", default=None, metavar="DIR",
                    help="durable sessions: spill live sessions here so a "
                    "supervisor can migrate them after a kill "
                    "(docs/FLEET.md failover; same semantics as "
                    "`serve --spill-dir`)")
    gw.add_argument("--spill-every", type=int, default=4, metavar="K",
                    help="rounds between spill passes")
    gw.add_argument("--spill-url", default=None, metavar="URL",
                    help="remote spill store (docs/FLEET.md cross-host "
                    "topology): spill through this `tpu-life spill-store` "
                    "HTTP store instead of a local directory, so a "
                    "migrator on another machine can read the rescue; "
                    "mutually exclusive with --spill-dir")
    gw.add_argument("--spill-namespace", default=None, metavar="NAME",
                    help="this worker incarnation's namespace in the "
                    "remote store (default: the run_id; a registered "
                    "worker rebinds to the namespace its lease grant "
                    "names)")
    gw.add_argument("--register", default=None, metavar="URL",
                    help="wire registration (docs/FLEET.md cross-host "
                    "topology): register with this fleet control plane "
                    "instead of being spawned by one — hold a heartbeat-"
                    "renewed lease, rebind the spill namespace per grant, "
                    "and on a lease_expired fence drop the re-homed "
                    "sessions and re-register fresh")
    gw.add_argument("--standby", action="store_true",
                    help="with --register: park in the control plane's "
                    "standby pool (docs/FLEET.md autoscaling) — leased "
                    "but out of the rotation until its autoscaler "
                    "recruits the slot")
    gw.add_argument("--qos", default=None, metavar="FILE",
                    help="tenant QoS policy (docs/SERVING.md tenant QoS): "
                    "a JSON or TOML file of per-tenant quotas, weights "
                    "and tiers — X-API-Key resolves to a tenant, the "
                    "scheduler interleaves tenants weighted-fair, and "
                    "best-effort tenants shed before guaranteed ones "
                    "feel pressure")
    gw.add_argument("--spill-replicas", type=int, default=1, metavar="N",
                    help="replicated spill (docs/FLEET.md durability): "
                    "fan every --spill-dir write through N replica "
                    "stores so a torn or lost replica never loses the "
                    "rescue; reads take the newest intact copy "
                    "(local-directory spill only)")
    _add_governor_args(gw)
    gw.add_argument("--api-rate", type=float, default=0.0, metavar="TOKENS/S",
                    help="per-API-key token-bucket refill rate; 0 disables "
                    "rate limiting (the X-API-Key header names the key)")
    gw.add_argument("--api-burst", type=float, default=10.0,
                    help="token-bucket capacity (max burst per key)")
    gw.add_argument("--shed-high-water", type=float, default=None,
                    metavar="DEPTH",
                    help="queue-depth load-shedding threshold (default: "
                    "80%% of --max-queue; 0 disables)")
    gw.add_argument("--max-body", type=int, default=None, metavar="BYTES",
                    help="request-body size bound (413 past it)")
    gw.add_argument("--metrics-file", default=None, metavar="JSONL",
                    help="append per-round serve metrics as JSON lines")
    gw.add_argument("--prom-file", default=None, metavar="FILE",
                    help="atomically rewrite a Prometheus text snapshot "
                    "every scheduling round (file-scraper twin of the "
                    "live GET /metrics)")
    gw.add_argument("--trace-events", default=None, metavar="FILE",
                    help="write Chrome trace-event JSON for the serve "
                    "rounds (docs/OBSERVABILITY.md)")
    gw.add_argument("--series-every", type=float, default=1.0,
                    metavar="SECONDS",
                    help="metric time-series sampling cadence "
                    "(docs/OBSERVABILITY.md time series): the pump "
                    "snapshots the registry into a bounded ring this "
                    "often, scraped via GET /v1/debug/series?cursor=; "
                    "0 disables the ring entirely")
    gw.add_argument("--platform", default=None,
                    help="force a JAX platform (cpu/tpu), like `run --platform`")
    gw.add_argument("--verbose", "-v", action="store_true")

    fl = sub.add_parser(
        "fleet",
        help="multi-worker front tier (docs/FLEET.md): supervise N gateway "
        "worker subprocesses and route session traffic across them by "
        "least queue depth, with health-checked failover",
    )
    fl.add_argument("--workers", type=int, default=2,
                    help="gateway worker subprocesses to supervise")
    fl.add_argument("--host", default="127.0.0.1")
    fl.add_argument("--port", type=int, default=8000,
                    help="router listen port (0 = ephemeral; the bound "
                    "port is printed in the startup JSON line; workers "
                    "always bind port 0 and are read back)")
    fl.add_argument("--capacity", type=int, default=8,
                    help="batch slots per compile key, per worker (fleet "
                    "capacity = workers x this)")
    fl.add_argument("--chunk-steps", type=int, default=16)
    fl.add_argument("--max-queue", type=int, default=64,
                    help="bounded admission queue per worker")
    fl.add_argument(
        "--serve-backend",
        default="jax",
        choices=["jax", "tuned", "numpy", "sharded", "stripes", "pallas", "native"],
        help="engine executor for every worker (same semantics as "
        "`gateway --serve-backend`)",
    )
    fl.add_argument("--sync-pump", action="store_true",
                    help="workers run host-synchronous rounds instead of "
                    "the pipelined pump (forwarded to every gateway)")
    _add_stencil_arg(fl)
    fl.add_argument("--spill-dir", default=None, metavar="DIR",
                    help="durable sessions (docs/FLEET.md): workers spill "
                    "live sessions under per-generation subdirs here; on "
                    "worker death the fleet resumes the intact spills on "
                    "a survivor under the SAME session id — a SIGKILLed "
                    "worker loses zero accepted work")
    fl.add_argument("--spill-every", type=int, default=4, metavar="K",
                    help="rounds between worker spill passes (recovery "
                    "point = the last spilled chunk)")
    fl.add_argument("--spill-url", default=None, metavar="URL",
                    help="remote spill store (docs/FLEET.md cross-host "
                    "topology): workers spill through this `tpu-life "
                    "spill-store` HTTP store under per-incarnation "
                    "namespaces, so migration reads work when the "
                    "survivor is on another machine; mutually exclusive "
                    "with --spill-dir")
    fl.add_argument("--site", default="", metavar="PREFIX",
                    help="this control plane's namespace prefix in a "
                    "SHARED spill store (e.g. 'a-'); two fleets sharing "
                    "one store must use distinct sites")
    fl.add_argument("--peer", action="append", default=None, metavar="URL",
                    dest="peers",
                    help="peer control-plane router URL (repeatable): when "
                    "every local survivor refuses a rescue, the migrator "
                    "re-homes the session onto a peer fleet — it keeps "
                    "answering its ORIGINAL session id through this router")
    fl.add_argument("--lease-ttl", type=float, default=15.0, metavar="SECONDS",
                    help="lease TTL for wire-registered workers (gateway "
                    "--register); an un-renewed lease fires the same "
                    "migration a worker death does, then fences the "
                    "generation")
    fl.add_argument("--standby", type=int, default=0, metavar="N",
                    help="standby pool (docs/FLEET.md autoscaling): plan "
                    "N extra worker slots that stay PARKED — no process, "
                    "no routing — until the autoscaler (or a wire-"
                    "registered `gateway --standby`) fills them")
    fl.add_argument("--autoscale", action="store_true",
                    help="demand-driven autoscaling (docs/FLEET.md "
                    "autoscaling): a control loop on the monitor tick "
                    "reads the fleet series store (queue depth/age, "
                    "refusal rates, memory pressure) plus SLO burn and "
                    "recruits standby workers under load / drains idle "
                    "ones back to the pool, every decision a typed "
                    "scale.* flight event `tpu-life doctor --scale` "
                    "replays")
    fl.add_argument("--scale-min", type=int, default=1, metavar="N",
                    help="autoscale floor: never drain below N deployed "
                    "workers")
    fl.add_argument("--scale-max", type=int, default=None, metavar="N",
                    help="autoscale ceiling: never recruit past N "
                    "deployed workers (default: bounded by the pool)")
    fl.add_argument("--scale-up-depth", type=float, default=4.0,
                    metavar="DEPTH",
                    help="mean queue depth per ready worker at which the "
                    "fleet scales up (the hysteresis band's upper edge)")
    fl.add_argument("--scale-down-depth", type=float, default=0.5,
                    metavar="DEPTH",
                    help="mean queue depth per ready worker at or below "
                    "which the fleet counts as idle (the band's lower "
                    "edge; must sit below --scale-up-depth)")
    fl.add_argument("--scale-idle-grace", type=float, default=10.0,
                    metavar="SECONDS",
                    help="the fleet must look idle continuously this long "
                    "before any scale-down (the structural flap guard)")
    fl.add_argument("--scale-cooldown-up", type=float, default=5.0,
                    metavar="SECONDS",
                    help="minimum seconds between consecutive scale-ups")
    fl.add_argument("--scale-cooldown-down", type=float, default=30.0,
                    metavar="SECONDS",
                    help="minimum seconds between a scale move and the "
                    "next scale-down")
    fl.add_argument("--qos", default=None, metavar="FILE",
                    help="tenant QoS policy file forwarded to every "
                    "worker (docs/SERVING.md tenant QoS): per-tenant "
                    "quotas, weighted-fair scheduling, tiered shedding "
                    "(the router already forwards X-API-Key)")
    fl.add_argument("--spill-replicas", type=int, default=1, metavar="N",
                    help="replicated spill for every worker (docs/"
                    "FLEET.md durability): writes fan through N replica "
                    "stores under each worker's spill dir; requires "
                    "--spill-dir")
    _add_governor_args(fl)
    fl.add_argument("--timeout", type=float, default=None, metavar="SECONDS",
                    help="default per-request deadline (per worker)")
    fl.add_argument("--api-rate", type=float, default=0.0, metavar="TOKENS/S",
                    help="per-API-key token bucket, enforced per worker "
                    "(the router forwards X-API-Key)")
    fl.add_argument("--api-burst", type=float, default=10.0)
    fl.add_argument("--metrics-dir", default=None, metavar="DIR",
                    help="per-worker JSONL sinks at DIR/wN.jsonl — read "
                    "them back merged with `tpu-life stats DIR/*.jsonl`")
    fl.add_argument("--trace-dir", default=None, metavar="DIR",
                    help="fleet trace collection (docs/OBSERVABILITY.md "
                    "distributed tracing): workers run with active "
                    "tracers and the supervisor drains their span + "
                    "flight rings into per-worker capture files here on "
                    "every monitor tick; fuse them with `tpu-life trace "
                    "merge DIR` and read one session's journey back with "
                    "`tpu-life doctor DIR --sid SID`")
    fl.add_argument("--series-every", type=float, default=1.0,
                    metavar="SECONDS",
                    help="fleet series collection cadence "
                    "(docs/OBSERVABILITY.md time series): the monitor "
                    "tick scrapes each worker's snapshot ring and "
                    "samples the fleet's own registry this often — the "
                    "SLO engine's data plane; with --trace-dir the "
                    "scrapes also land in *.series.jsonl capture files; "
                    "0 disables collection")
    fl.add_argument("--slo", default=None, metavar="FILE", dest="slo_file",
                    help="declarative SLO specs (docs/OBSERVABILITY.md "
                    "SLOs and burn rates): a JSON or TOML file of "
                    "objectives evaluated with multi-window burn rates "
                    "on the monitor tick; a breach fires a typed "
                    "slo.breach flight event `tpu-life doctor --slo` "
                    "joins to its cause (default: the built-in specs)")
    fl.add_argument("--log-dir", default=None, metavar="DIR",
                    help="per-worker stdout+stderr logs at DIR/wN.log "
                    "(default: a fresh temp dir)")
    fl.add_argument("--restart-backoff", type=float, default=0.5,
                    metavar="SECONDS",
                    help="base of the exponential restart backoff after "
                    "a worker crash")
    fl.add_argument("--max-restarts", type=int, default=5, metavar="N",
                    help="restart a crash-looping worker at most this many "
                    "times (one more consecutive fast failure opens its "
                    "circuit breaker and leaves it down; 0 = fail fast, "
                    "matching `run --max-restarts`)")
    fl.add_argument("--probe-interval", type=float, default=0.25,
                    metavar="SECONDS",
                    help="health-check cadence (liveness + /readyz)")
    fl.add_argument("--platform", default=None,
                    help="force a JAX platform in every worker (cpu/tpu)")
    fl.add_argument("--placement", default="none", choices=["auto", "none"],
                    help="per-worker device placement (docs/FLEET.md): "
                    "auto assigns each worker a DISJOINT device slice as "
                    "an env overlay (JAX_PLATFORMS + visible-device vars; "
                    "on cpu, forced host device counts — fully testable "
                    "without chips) so an N-worker accelerator fleet "
                    "stops fighting over one device set; none keeps "
                    "today's shared spawning env byte-for-byte")
    fl.add_argument("--devices-per-worker", default=None, metavar="K[,K...]",
                    help="devices per worker for --placement auto: one "
                    "count for all workers, or a comma list with exactly "
                    "one count per worker (e.g. 1,4 for a heterogeneous "
                    "pair); default: an even split")
    fl.add_argument("--total-devices", type=int, default=None, metavar="N",
                    help="how many devices the host has (tpu/gpu "
                    "placement only — the jax-free fleet front cannot "
                    "count chips itself); oversubscribing it is a typed "
                    "placement error at startup, before any worker spawns")
    fl.add_argument("--verbose", "-v", action="store_true")

    ss = sub.add_parser(
        "spill-store",
        help="host a remote spill store (docs/FLEET.md cross-host "
        "topology): a CRC-checked, atomically-published HTTP object "
        "store workers spill through and migrators read rescues from — "
        "stdlib only, any fleet process can carry it",
    )
    ss.add_argument("--root", required=True, metavar="DIR",
                    help="directory the store publishes namespaces under")
    ss.add_argument("--host", default="127.0.0.1")
    ss.add_argument("--port", type=int, default=0,
                    help="listen port (0 = ephemeral; the bound port is "
                    "printed in the startup JSON line)")
    ss.add_argument("--verbose", "-v", action="store_true")

    ch = sub.add_parser(
        "chaos",
        help="seeded chaos drill (docs/CHAOS.md): drive a real N-worker "
        "CPU fleet under a deterministic fault schedule (spill ENOSPC, "
        "snapshot bit-flips, socket resets, engine faults, SIGKILLs) "
        "and machine-verify the failure-masking invariants",
    )
    ch.add_argument("--seed", type=int, default=0,
                    help="the chaos seed: the fault schedule (and the "
                    "kill schedule) is a pure function of it — a failed "
                    "drill replays verbatim from its printed seed")
    ch.add_argument("--workers", type=int, default=2)
    ch.add_argument("--sessions", type=int, default=6,
                    help="deterministic (conway) sessions in the mix")
    ch.add_argument("--ising-sessions", type=int, default=2,
                    help="stochastic (ising) sessions in the mix")
    ch.add_argument("--size", type=int, default=20,
                    help="deterministic board edge (ising runs 16x16)")
    ch.add_argument("--steps", type=int, default=900,
                    help="base step budget; staggered downward per session")
    ch.add_argument("--kills", type=int, default=1,
                    help="drill-driven SIGKILLs of session-owning workers "
                    "(must be 1 with --cross-host: its choreography "
                    "performs exactly one adopter kill)")
    ch.add_argument("--plan", default=None, metavar="JSON",
                    help="chaos point spec as JSON (the plan's 'points' "
                    "object; default: the documented drill mix — spill "
                    "ENOSPC, snapshot bit-flip, submit/poll resets, one "
                    "engine fault)")
    ch.add_argument("--backend", default="numpy",
                    choices=["numpy", "jax"],
                    help="worker engine executor (numpy keeps the drill "
                    "CPU-cheap; jax exercises the device engines)")
    ch.add_argument("--capacity", type=int, default=4)
    ch.add_argument("--chunk-steps", type=int, default=2)
    ch.add_argument("--spill-every", type=int, default=1)
    ch.add_argument("--recovery-bound", type=float, default=60.0,
                    metavar="SECONDS",
                    help="per-kill bound on fleet recovery to full ready "
                    "strength (the recovery_bounded invariant)")
    ch.add_argument("--wait-timeout", type=float, default=180.0,
                    metavar="SECONDS",
                    help="per-session bound on reaching a terminal state "
                    "(the all_terminal invariant)")
    ch.add_argument("--workdir", default=None, metavar="DIR",
                    help="where spill/ and logs/ land (default: a fresh "
                    "temp dir)")
    ch.add_argument("--governor", action="store_true",
                    help="the resource-governor drill (docs/SERVING.md "
                         "'Resource governance'): arm engine.oom + "
                         "engine.wedge, run workers with the wedge "
                         "watchdog, and verify OOMs are MASKED (no worker "
                         "death) while wedges are rescued via the "
                         "unready-recycle + migration path")
    ch.add_argument("--settle-deadline", type=float, default=1.0,
                    metavar="SECONDS",
                    help="worker wedge-watchdog deadline for --governor "
                         "(forwarded as each worker's --settle-deadline)")
    ch.add_argument("--surge", action="store_true",
                    help="the autoscaling + tenant-QoS drill (docs/"
                    "CHAOS.md surge): a 2-worker fleet with a standby "
                    "pool and an autoscaler rides a 10x admission burst "
                    "from a guaranteed and a best-effort tenant — the "
                    "drill verifies the fleet scaled up through the "
                    "burst and released back after it, every shed was "
                    "typed and landed on the best-effort tenant only, "
                    "and the standard durability invariants held; "
                    "recruit/release chaos points fire on the seed")
    ch.add_argument("--surge-factor", type=int, default=10, metavar="N",
                    help="--surge only: burst size as a multiple of "
                    "--sessions (the trickle baseline)")
    ch.add_argument("--surge-standby", type=int, default=2, metavar="N",
                    help="--surge only: parked standby slots the "
                    "autoscaler recruits through the burst")
    ch.add_argument("--qos-p99-bound", type=float, default=5.0,
                    metavar="SECONDS",
                    help="--surge only: bound on the guaranteed tenant's "
                    "admission-latency p99 through the burst (the qos "
                    "invariant)")
    ch.add_argument("--stream", action="store_true",
                    help="the live-session stream drill (docs/STREAMING.md): "
                         "every session carries pre-scheduled mid-run edits "
                         "and live watchers on the fan-out tier; arms "
                         "stream.reset + watch.slow_reader and verifies "
                         "gapless watcher seqs across the SIGKILL, watcher "
                         "agreement, and reconstruction == the "
                         "replay_edit_log oracle")
    ch.add_argument("--lenia-sessions", type=int, default=1,
                    help="--stream only: continuous-tier (lenia) sessions "
                         "in the watched mix (oracle compare is allclose "
                         "at FLOAT_ATOL)")
    ch.add_argument("--watchers", type=int, default=2,
                    help="--stream only: live watchers per session")
    ch.add_argument("--cross-host", action="store_true",
                    help="the two-control-plane drill (docs/FLEET.md "
                    "cross-host topology): two supervisors with disjoint "
                    "worker sets sharing one remote spill store, a wire-"
                    "registered worker, SIGKILLs + lease expiries + "
                    "seeded partitions + remote-spill faults in one "
                    "seeded run")
    ch.add_argument("--lease-ttl", type=float, default=8.0, metavar="SECONDS",
                    help="cross-host drill: lease TTL for the wire-"
                    "registered worker")
    ch.add_argument("--summary-file", default=None, metavar="JSONL",
                    help="append the drill summary as one JSON line")
    ch.add_argument("--verbose", "-v", action="store_true")

    cl = sub.add_parser(
        "client",
        help="talk to a running gateway: submit boards, poll, fetch "
        "results, cancel (jax-free; retries 429/503 with backoff)",
    )
    cl.add_argument(
        "action",
        choices=["submit", "poll", "result", "cancel", "health"],
    )
    cl.add_argument("--url", default="http://127.0.0.1:8000",
                    help="gateway base URL")
    cl.add_argument("--api-key", default=None,
                    help="sent as X-API-Key (the rate-limiting identity)")
    cl.add_argument("--session", default=None, metavar="SID",
                    help="session id for poll/result/cancel")
    cl.add_argument("--input-file", default=None, metavar="BOARD",
                    help="contract-format board to submit inline (geometry "
                    "from --height/--width or --config-file)")
    cl.add_argument("--config-file", default="grid_size_data.txt",
                    help="geometry fallback when --input-file is used "
                    "without explicit --height/--width/--steps")
    cl.add_argument("--size", type=int, default=None,
                    help="square seeded board: submit with no input file "
                    "at all (the server seeds it)")
    cl.add_argument("--height", type=int, default=None)
    cl.add_argument("--width", type=int, default=None)
    cl.add_argument("--steps", type=int, default=None)
    cl.add_argument("--rule", default="conway")
    cl.add_argument("--seed", type=int, default=None,
                    help="seed for a server-seeded board")
    cl.add_argument("--density", type=float, default=None)
    cl.add_argument("--temperature", type=float, default=None, metavar="T",
                    help="Metropolis temperature for --rule ising")
    cl.add_argument("--timeout", type=float, default=None, metavar="SECONDS",
                    help="per-request deadline submitted with the session")
    cl.add_argument("--wait", action="store_true",
                    help="submit: block (polling) until the session is "
                    "terminal; with --output-file also fetch the result")
    cl.add_argument("--output-file", default=None,
                    help="result: write the board in contract format "
                    "(default: RLE to stdout)")
    cl.add_argument("--format", default="rle", choices=["rle", "raw"],
                    help="result payload encoding when printing")
    cl.add_argument("--retries", type=int, default=4,
                    help="retry budget for 429/503/unreachable responses")

    st = sub.add_parser(
        "stats",
        help="summarize a metrics JSONL file (run or serve): throughput "
        "aggregates, histogram quantiles, occupancy, rejection rate",
    )
    st.add_argument("metrics_file", metavar="JSONL", nargs="+",
                    help="sink(s) written by `run --metrics-file`, `serve "
                    "--metrics-file`, or a fleet's per-worker sinks — "
                    "multiple files merge keyed by run_id into one report")
    st.add_argument("--json", action="store_true",
                    help="emit the summary as one JSON object instead of "
                    "the human table")
    st.add_argument("--watch", type=float, default=None, metavar="SECONDS",
                    help="re-read and re-render every N seconds (the "
                    "`top` refresh loop) until ^C; without this flag the "
                    "single-shot output is unchanged")

    tp = sub.add_parser(
        "top",
        help="live fleet console (docs/OBSERVABILITY.md top): per-worker "
        "throughput, queue depth, governor bytes vs budget, "
        "packed/matmul fractions, stream watchers, and SLO burn-rate "
        "gauges with breach highlighting, over GET /metrics + /healthz",
    )
    tp.add_argument("--url", default="http://127.0.0.1:8000",
                    help="fleet router (or single gateway) base URL")
    tp.add_argument("--interval", type=float, default=2.0, metavar="SECONDS",
                    help="refresh cadence")
    tp.add_argument("--once", action="store_true",
                    help="paint one frame and exit (two samples one "
                    "interval apart, so the rates are real)")
    tp.add_argument("--json", action="store_true",
                    help="with --once: emit the view as one JSON object — "
                    "the scripting/autoscaler input contract")

    tr = sub.add_parser(
        "trace",
        help="distributed-trace tooling (docs/OBSERVABILITY.md): fuse a "
        "fleet capture directory (`fleet --trace-dir`) into one "
        "Perfetto-loadable timeline",
    )
    tr_sub = tr.add_subparsers(dest="trace_command", required=True)
    trm = tr_sub.add_parser(
        "merge",
        help="merge per-worker capture files into one Chrome-trace JSON "
        "with per-worker process tracks and handshake-estimated clock "
        "offsets applied",
    )
    trm.add_argument("capture_dir", metavar="DIR",
                     help="the `fleet --trace-dir` capture directory")
    trm.add_argument("-o", "--output", default=None, metavar="FILE",
                     help="merged trace path (default: DIR/merged.trace.json)")

    dr = sub.add_parser(
        "doctor",
        help="flight-recorder postmortem (docs/OBSERVABILITY.md doctor): "
        "reconstruct one session's causal journey — submit, rounds, "
        "injections, kill, migration, resume, done — across workers "
        "from a trace capture, with typed findings and anomaly checks",
    )
    dr.add_argument("capture", metavar="CAPTURE",
                    help="a capture directory (`fleet --trace-dir`), a "
                    "merged trace (`tpu-life trace merge`), or a single "
                    "written trace file")
    dr.add_argument("--sid", default=None,
                    help="the session id to reconstruct (fleet sid like "
                    "w0g1-s000003, or a worker-local sid)")
    dr.add_argument("--trace-id", default=None,
                    help="reconstruct by trace id directly (skips the "
                    "sid -> trace resolution)")
    dr.add_argument("--max-gap", type=float, default=None, metavar="SECONDS",
                    help="bound on the kill -> resumed-on-survivor gap "
                    "before the doctor flags migration_gap_exceeded "
                    "(default 60)")
    dr.add_argument("--json", action="store_true",
                    help="emit the machine-readable journey report as "
                    "one JSON object")
    dr.add_argument("--slo", action="store_true",
                    help="SLO postmortem instead of a session journey "
                    "(docs/OBSERVABILITY.md): join every slo.breach "
                    "flight event in the capture to its plausible cause "
                    "— a kill, a lease expiry, an injection — with typed "
                    "findings; needs no --sid")
    dr.add_argument("--scale", action="store_true",
                    help="autoscaling postmortem (docs/FLEET.md "
                    "autoscaling): replay the fleet's full scale.* "
                    "decision sequence from the capture — every up/"
                    "down/hold with the signal snapshot that justified "
                    "it ('why did we have 40 workers at 14:02'); needs "
                    "no --sid")

    sm = sub.add_parser(
        "submit",
        help="append one simulation request to the serve spool file "
        "(board + rule + step budget)",
    )
    sm.add_argument("--requests", default="serve_requests.jsonl", metavar="JSONL")
    sm.add_argument("--input-file", default="data.txt")
    sm.add_argument("--config-file", default="grid_size_data.txt",
                    help="geometry fallback for unset --height/--width/--steps")
    sm.add_argument("--size", type=int, default=None,
                    help="square board: shorthand for --height N --width N "
                    "(explicit --height/--width win); with --steps and no "
                    "input file, queues a seeded random board — like "
                    "`run --size`, no pre-existing files needed")
    sm.add_argument("--height", type=int, default=None)
    sm.add_argument("--width", type=int, default=None)
    sm.add_argument("--steps", type=int, default=None)
    sm.add_argument("--seed", type=int, default=0,
                    help="seed for the no-input-file random board")
    sm.add_argument("--rule", default="conway")
    sm.add_argument("--temperature", type=float, default=None, metavar="T",
                    help="Metropolis temperature for --rule ising "
                    "(per-session; rides the spool line)")
    sm.add_argument("--output-file", default=None,
                    help="where `serve` writes this request's result "
                    "(default: <output-dir>/<session-id>.txt)")
    sm.add_argument("--timeout", type=float, default=None, metavar="SECONDS")
    sm.add_argument("--id", default=None, help="client request tag echoed in the summary")

    g = sub.add_parser("gen", help="generate a random board + config")
    g.add_argument("--height", type=int, required=True)
    g.add_argument("--width", type=int, required=True)
    g.add_argument("--steps", type=int, default=100)
    g.add_argument("--density", type=float, default=0.5)
    g.add_argument("--states", type=int, default=2)
    g.add_argument("--seed", type=int, default=0)
    g.add_argument("--input-file", default="data.txt")
    g.add_argument("--config-file", default="grid_size_data.txt")

    return p


def _add_run_args(r: argparse.ArgumentParser) -> None:
    r.add_argument("--config-file", default="grid_size_data.txt")
    r.add_argument("--input-file", default="data.txt")
    r.add_argument("--output-file", default="output.txt")
    r.add_argument("--size", type=int, default=None,
                   help="square board: shorthand for --height N --width N "
                   "(explicit --height/--width win); with --steps and no "
                   "input file, runs a seeded random board")
    r.add_argument("--height", type=int, default=None)
    r.add_argument("--width", type=int, default=None)
    r.add_argument("--steps", type=int, default=None)
    r.add_argument("--rule", default="conway", help="name or B/S / LtL spec")
    r.add_argument(
        "--seed",
        type=int,
        default=0,
        help="counter-based PRNG seed (docs/STOCHASTIC.md): names the "
        "whole trajectory for stochastic rules (ising / noisy:*) and the "
        "staged board for seeded exploratory runs; stamped into the run "
        "record so any run is replayable",
    )
    r.add_argument(
        "--temperature",
        type=float,
        default=None,
        metavar="T",
        help="Metropolis temperature for --rule ising (required there, "
        "invalid elsewhere); the Onsager critical point is T~2.269",
    )
    r.add_argument(
        "--bug-compat",
        action="store_true",
        help="replicate the reference binary's effective (buggy) B/S2 rule",
    )
    r.add_argument(
        "--backend",
        default="auto",
        choices=["auto", "tuned", "numpy", "native", "jax", "sharded", "stripes", "mpi", "pallas"],
        help="tuned resolves backend + perf knobs through the autotune "
        "cache (see `tpu-life tune` and --tune-mode); "
        "mpi is EXPERIMENTAL and thread-simulated only: mpiexec/mpi4py "
        "are absent from this image (libmpi alone ships no launcher), so "
        "its per-rank logic has only ever run against an injected fake "
        "communicator; real cross-process messaging is covered by the "
        "jax.distributed backend tests",
    )
    r.add_argument("--num-devices", type=int, default=None)
    r.add_argument(
        "--mesh-shape",
        default=None,
        metavar="R,C",
        help="2-D rows,cols device mesh for the sharded backend "
        "(block decomposition; halo traffic ~ shard perimeter)",
    )
    r.add_argument(
        "--platform",
        default=None,
        help="force a JAX platform (cpu/tpu); also via TPU_LIFE_PLATFORM env",
    )
    r.add_argument(
        "--block-steps",
        type=int,
        default=None,
        help="CA steps per halo exchange / HBM pass; unset keeps the backend default",
    )
    r.add_argument(
        "--partition-mode", default="shard_map", choices=["shard_map", "gspmd"]
    )
    r.add_argument(
        "--local-kernel",
        default="auto",
        choices=["auto", "xla", "pallas"],
        help="per-shard stepper of the sharded backend: Pallas deep-halo "
        "kernels vs the XLA scan.  auto on TPU picks the bit-sliced stripe "
        "kernel (life-like rules, 1-D meshes) or the int8 2-D-tiled kernel "
        "(Larger-than-Life / Generations, any mesh); explicit pallas on a "
        "2-D mesh runs life-like rules through the int8 kernel unpacked",
    )
    r.add_argument(
        "--tune-mode",
        default="cache",
        choices=["off", "cache", "measure"],
        help="autotune resolution for --backend tuned: off = analytic "
        "cost model only; cache = cache hit else cost model (never "
        "measures); measure = cache hit else run the measured search now "
        "and persist it",
    )
    r.add_argument("--sync-every", type=int, default=0)
    r.add_argument(
        "--stream-io",
        action="store_true",
        default=None,
        help="per-shard streaming file I/O (sharded backend, 1-D mesh): the "
        "board is never materialized whole on one host; auto-enabled for "
        "big boards",
    )
    r.add_argument(
        "--no-stream-io", dest="stream_io", action="store_false", help=""
    )
    r.add_argument("--no-pad-lanes", action="store_true")
    r.add_argument(
        "--no-bitpack",
        action="store_true",
        help="disable the bit-sliced fast paths: the life-like bitplane "
        "adder tree AND the packed Metropolis engine for --rule ising "
        "(both bit-identical to their int8 twins)",
    )
    _add_stencil_arg(r)
    r.add_argument("--snapshot-every", type=int, default=0)
    r.add_argument("--snapshot-dir", default="snapshots")
    r.add_argument(
        "--keep-snapshots",
        type=int,
        default=0,
        metavar="N",
        help="retain only the newest N snapshots (0 = keep all)",
    )
    r.add_argument("--resume", default=None)
    r.add_argument(
        "--max-restarts",
        type=int,
        default=0,
        help="elastic recovery: on a recoverable device failure, rebuild the "
        "backend and resume from the newest snapshot (pair with "
        "--snapshot-every) at most this many times; 0 fails fast",
    )
    r.add_argument(
        "--fault-at",
        type=int,
        default=0,
        metavar="STEP",
        help="fault-injection drill: simulate a device failure the first "
        "time the run crosses STEP (exercises the --max-restarts path)",
    )
    r.add_argument(
        "--fault-count",
        type=int,
        default=1,
        help="how many times the --fault-at drill fires (recovery rewinds "
        "below the fault step, so it re-fires until spent)",
    )
    r.add_argument(
        "--restart-wait",
        type=float,
        default=0.0,
        metavar="SECONDS",
        help="wait this long before each recovery attempt (device losses "
        "take time to clear)",
    )
    r.add_argument("--profile", default=None, metavar="TRACE_DIR")
    r.add_argument(
        "--trace-events",
        default=None,
        metavar="FILE",
        help="write Chrome trace-event JSON (Perfetto-loadable): host-phase "
        "spans — config-resolve, compile, staging, each host-sync chunk, "
        "snapshots, recovery — stamped with the run's correlation id "
        "(docs/OBSERVABILITY.md)",
    )
    r.add_argument("--metrics", action="store_true")
    r.add_argument(
        "--metrics-file",
        default=None,
        metavar="JSONL",
        help="append each metrics record as a JSON line (implies --metrics)",
    )
    r.add_argument("--verbose", "-v", action="store_true")


def _parse_mesh_shape(parser, spec: str | None) -> tuple[int, int] | None:
    if spec is None:
        return None
    try:
        parts = tuple(int(v) for v in spec.split(","))
    except ValueError:
        parts = ()
    if len(parts) != 2 or min(parts) < 1:
        parser.error(f"--mesh-shape must be two positive ints 'R,C', got {spec!r}")
    return parts


def main(argv: list[str] | None = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    parser = build_parser()
    if not argv or argv[0].startswith("-"):
        argv = ["run", *argv]  # default command
    args = parser.parse_args(argv)

    # deterministic fault injection (docs/CHAOS.md): arm once, at entry,
    # from TPU_LIFE_CHAOS when present — this is how a chaos drill arms
    # the gateway worker subprocesses a fleet spawns (they inherit the
    # exported spec).  Unset (the overwhelmingly common case), this is
    # one dict lookup; a malformed spec fails loudly here, typed.
    from tpu_life import chaos

    try:
        chaos.maybe_arm_from_env()
    except chaos.ChaosError as e:
        print(f"tpu_life: bad {chaos.ENV_VAR}: {e}", file=sys.stderr)
        return 2

    if args.command == "info":
        return _info()
    if args.command == "gen":
        return _gen(args)
    if args.command == "pattern":
        return _pattern(parser, args)
    if args.command == "submit":
        # pure file append: no device ever touched, so no watchdog needed
        return _submit(args)
    if args.command == "stats":
        # pure file read — the read-back toolchain never needs a device
        return _stats(args)
    if args.command == "trace":
        # pure file fusion — capture records in, one Perfetto doc out
        return _trace_merge(args)
    if args.command == "doctor":
        # pure file read-back: the journey reconstruction needs no device
        return _doctor(args)
    if args.command == "top":
        # pure HTTP: scrapes /metrics + /healthz — the operator console
        # runs anywhere the router is reachable, no jax, no watchdog
        return _top(args)
    if args.command == "client":
        # pure HTTP: the gateway owns the devices, the client only needs
        # numpy + urllib — runs anywhere, no watchdog, no jax
        return _client(parser, args)
    if args.command == "fleet":
        # the front tier is stdlib plumbing: only the worker SUBPROCESSES
        # touch jax, so the supervisor/router process needs no watchdog
        return _fleet(args)
    if args.command == "chaos":
        # the drill process is numpy-only (oracles + HTTP); the worker
        # subprocesses own any jax — no watchdog needed here either
        return _chaos_drill(args)
    if args.command == "spill-store":
        # pure stdlib file + HTTP plumbing: no device, no watchdog
        return _spill_store(args)

    from tpu_life.utils.platform import devices_with_watchdog, ensure_platform

    ensure_platform(getattr(args, "platform", None))
    # hang protection (VERDICT r3 item 8): prime the device query under a
    # watchdog so a wedged accelerator plugin degrades into a message + exit
    # instead of blocking the CLI forever.  Once this succeeds, every later
    # in-process jax.devices() hits the cached backend.
    try:
        devices_with_watchdog()
    except TimeoutError as e:
        print(f"tpu_life: {e}", file=sys.stderr)
        return 2
    if args.command == "bench":
        # after the watchdog: _bench queries devices, and a wedged plugin
        # must degrade into the message above, not a hang
        return _bench(args)
    if args.command == "tune":
        return _tune(args)
    if args.command == "serve":
        return _serve(args)
    if args.command == "sweep":
        return _sweep(parser, args)
    if args.command == "gateway":
        return _gateway(args)
    cfg = RunConfig(
        height=args.height if args.height is not None else args.size,
        width=args.width if args.width is not None else args.size,
        steps=args.steps,
        config_file=args.config_file,
        input_file=args.input_file,
        output_file=args.output_file,
        rule=args.rule,
        seed=args.seed,
        temperature=args.temperature,
        bug_compat=args.bug_compat,
        backend=args.backend,
        num_devices=args.num_devices,
        mesh_shape=_parse_mesh_shape(parser, args.mesh_shape),
        block_steps=args.block_steps,
        partition_mode=args.partition_mode,
        local_kernel=args.local_kernel,
        tune_mode=args.tune_mode,
        sync_every=args.sync_every,
        stream_io=args.stream_io,
        pad_lanes=not args.no_pad_lanes,
        bitpack=not args.no_bitpack,
        stencil=args.stencil,
        snapshot_every=args.snapshot_every,
        snapshot_dir=args.snapshot_dir,
        keep_snapshots=args.keep_snapshots,
        resume=args.resume,
        max_restarts=args.max_restarts,
        fault_at=args.fault_at,
        fault_count=args.fault_count,
        restart_wait_s=args.restart_wait,
        profile=args.profile,
        trace_events=args.trace_events,
        metrics=args.metrics,
        metrics_file=args.metrics_file,
        verbose=args.verbose,
    )
    from tpu_life.models.rules import GeometryError
    from tpu_life.runtime.driver import run

    try:
        run(cfg)
    except GeometryError as e:
        # kernel-vs-board geometry (docs/RULES.md): typed exit 2, the
        # CLI twin of the gateway's 400 radius_too_large
        print(f"tpu_life: {e}", file=sys.stderr)
        return 2
    return 0


def _info() -> int:
    # the diagnostic command a user reaches for on a stuck machine must not
    # itself hang on the wedged plugin — same watchdog as the run path
    from tpu_life.utils.platform import devices_with_watchdog, ensure_platform

    ensure_platform()
    try:
        devices_with_watchdog()
    except TimeoutError as e:
        print(f"tpu_life: {e}", file=sys.stderr)
        return 2

    import jax

    from tpu_life.models.rules import RULE_REGISTRY
    from tpu_life.version import __version__

    print(f"tpu-life {__version__}")
    print(f"jax {jax.__version__} backend={jax.default_backend()}")
    for d in jax.devices():
        print(f"  device: {d}")
    from tpu_life.io import native as native_io
    from tpu_life.ops import native_step

    avail = {
        "numpy": "ok",
        "jax": "ok",
        "sharded": f"ok ({len(jax.devices())} devices)",
        "stripes": "ok",
        "mpi": "experimental, thread-simulated only (mpiexec + mpi4py "
        "have never run it; real message passing is covered by the "
        "two-process jax.distributed test instead)",
        "native": "ok" if native_step.available() else "needs `make -C native`",
        "pallas": "ok",
    }
    try:
        from tpu_life.backends import pallas_backend  # noqa: F401
    except ImportError as e:
        avail["pallas"] = f"unavailable ({e})"
    try:
        from mpi4py import MPI  # noqa: F401
    except ImportError:
        avail["mpi"] = (
            "experimental, unavailable here (needs mpi4py; only ever "
            "exercised thread-simulated via an injected fake communicator)"
        )
    print("backends:")
    for name in sorted(avail):
        print(f"  {name}: {avail[name]}")
    print(
        "native io codec:",
        "ok" if native_io.available() else "numpy fallback (make -C native)",
    )
    print("rules:", ", ".join(sorted(RULE_REGISTRY)))
    print(
        "rule axes: B/S + Generations /C + Larger-than-Life R,C,M,S,B specs; "
        "neighborhoods NM (Moore) / NN (von Neumann); topology clamped "
        "(default) / board-sized torus via the ':T' suffix; stochastic "
        "rules ising (needs --temperature) and noisy:<p>/<base> "
        "(docs/STOCHASTIC.md); continuous rules lenia[:<preset>|:R..,m..,s..] "
        "(float32 boards, docs/RULES.md; count path via --stencil)"
    )
    return 0


def _bench(args) -> int:
    """In-process delta-timing throughput measurement, one JSON line.

    The user-facing sibling of the repo's armored `bench.py` capture: same
    delta method (two fused runs of different step counts, differenced to
    cancel dispatch + readback latency), same record shape, but no probe /
    fallback machinery — it measures whatever platform the session has.
    """
    import json

    import numpy as np

    from tpu_life.backends.base import get_backend, measure_throughput
    from tpu_life.models.rules import get_rule

    target = 1e11  # cell-updates/sec/chip north star (BASELINE.json)
    rule = get_rule(args.rule)
    n = args.size
    rng = np.random.default_rng(0)
    board = rng.integers(0, 2, size=(n, n), dtype=np.int8)
    if rule.states > 2:
        board *= rng.integers(1, rule.states, size=(n, n), dtype=np.int8)

    kwargs = {}
    if args.block_steps is not None:
        kwargs["block_steps"] = args.block_steps
    if args.local_kernel is not None:
        # every backend tolerates unknown kwargs; the record below carries
        # what the resolved backend ACTUALLY applied (null = the backend
        # has no local-kernel concept), so `--backend auto` resolving to
        # sharded still honors and truthfully labels the flag
        kwargs["local_kernel"] = args.local_kernel
    from tpu_life.autotune import tuned_record

    backend_name = args.backend
    tuned_source = "flags"
    if backend_name == "tuned":
        # read-path resolution (cache hit or cost model — never measures);
        # knobs already pinned in kwargs by explicit flags win over the
        # cached ones (the shared merge rule, autotune.resolve_backend_kwargs)
        from tpu_life import autotune

        backend_name, _, tuned_source = autotune.resolve_backend_kwargs(
            rule, (n, n), kwargs
        )
    # the rule hint keeps `auto` infallible (e.g. torus rules resolve to a
    # single-device backend), matching the driver's resolution
    backend = get_backend(backend_name, rule=rule, **kwargs)
    per_chip, n_chips = measure_throughput(
        backend, board, rule, args.steps, args.base_steps, args.repeats
    )

    import jax

    print(
        json.dumps(
            {
                "metric": "cell_updates_per_sec_per_chip",
                "value": per_chip,
                "unit": "cells/s/chip",
                "vs_baseline": per_chip / target,
                "rule": args.rule,  # as requested, matching bench.py's record
                "platform": jax.devices()[0].platform,
                "backend": getattr(backend, "name", args.backend),
                "local_kernel": getattr(backend, "local_kernel", None),
                "size": n,
                "steps": args.steps,
                "n_chips": n_chips,
                # reproducibility: the full resolved knob set + where it
                # came from ("flags" | "cache" | "cost_model")
                "tuned": tuned_record(
                    getattr(backend, "name", backend_name), kwargs
                ),
                "tuned_source": tuned_source,
            }
        )
    )
    return 0


def _tune(args) -> int:
    """The offline tuning search: a table of trials to stderr-adjacent
    stdout rows, one JSON summary line last (machine-parseable like
    `bench`), the winner persisted to the autotune cache.

    ``--dry-run`` ranks by the analytic cost model only — candidate
    enumeration and ordering are exercised, no device measurement happens
    and nothing is written: the CI smoke path on CPU.
    """
    import json

    from tpu_life import autotune
    from tpu_life.models.rules import get_rule

    rule = get_rule(args.rule)
    h = args.height if args.height is not None else args.size
    w = args.width if args.width is not None else args.size
    key = autotune.tune_key_for(rule, (h, w))
    backend_set = (
        tuple(s for s in args.backend_set.split(",") if s)
        if args.backend_set
        else None
    )

    unit = "cost" if args.dry_run else "s/step"
    print(f"# tune {key.id()}  trials={args.trials} ({unit})")

    def on_trial(i, total, res):
        if res.ok:
            cells = h * w / res.seconds_per_step
            val = f"{res.seconds_per_step:.3e}  ({cells:.3e} cells/s)"
        else:
            val = f"infeasible: {res.error}"
        print(f"  [{i + 1}/{total}] {res.config.describe():<55s} {val}")

    result = autotune.tune(
        key,
        rule,
        shape=(h, w),
        backend_set=backend_set,
        trials=args.trials,
        steps=args.steps,
        warmup_steps=args.warmup_steps,
        dry_run=args.dry_run,
        cache_file=args.cache_file,
        on_trial=on_trial,
    )
    if args.dry_run:
        for i, res in enumerate(result.results):
            print(
                f"  [{i + 1}/{len(result.results)}] "
                f"{res.config.describe():<55s} cost={res.seconds_per_step:.3f}"
            )
    best = autotune.runner.best_result(result.results)
    print(
        json.dumps(
            {
                "mode": "tune",
                "key": key.id(),
                "best": result.best.to_dict(),
                "source": result.source,
                "candidates": len(result.results),
                "infeasible": sum(1 for r in result.results if not r.ok),
                "seconds_per_step": best.seconds_per_step
                if best is not None and not args.dry_run
                else None,
                "trials": args.trials,
                "cache_file": result.cache_file,
            }
        )
    )
    return 0


def _stats(args) -> int:
    """The read-back half of the telemetry loop (docs/OBSERVABILITY.md):
    ingest a metrics JSONL sink — run chunks, serve rounds, registry
    snapshot records in any mix — and report the aggregates.  With
    --watch the same read-and-summarize pass re-runs every N seconds on
    `top`'s refresh loop (the sinks are append-only, so a re-read is the
    live view); without the flag the single-shot output is unchanged."""
    import json

    from tpu_life.obs import stats as obs_stats

    def summarize_once():
        records = []
        for i, path in enumerate(args.metrics_file):
            for rec in obs_stats.load_records(path):
                # sink provenance: one file = one worker across ALL its
                # restarts (each a fresh run_id) — the devices aggregate
                # needs the worker identity, not the generation's
                rec.setdefault("_sink", i)
                records.append(rec)
        summary = obs_stats.summarize(records)
        if args.json:
            return json.dumps(summary)
        return obs_stats.render(summary)

    if args.watch is None:
        print(summarize_once())
        return 0
    from tpu_life.obs import console

    return console.refresh_loop(summarize_once, args.watch)


def _trace_merge(args) -> int:
    """Fuse a fleet trace-capture directory (docs/OBSERVABILITY.md
    "Distributed tracing") into one Perfetto-loadable Chrome-trace JSON:
    per-worker process tracks, flight events as instant markers, clock
    offsets applied.  Prints one JSON line naming the output and its
    shape (events, incarnations, drops)."""
    import json
    from pathlib import Path

    from tpu_life.obs import journey

    try:
        doc = journey.merge_captures(args.capture_dir)
    except (FileNotFoundError, ValueError) as e:
        print(f"trace merge: {e}", file=sys.stderr)
        return 2
    out = args.output or str(Path(args.capture_dir) / "merged.trace.json")
    Path(out).parent.mkdir(parents=True, exist_ok=True)
    with open(out, "w") as f:
        json.dump(doc, f)
    workers = doc["otherData"]["workers"]
    print(
        json.dumps(
            {
                "mode": "trace-merge",
                "output": out,
                "events": len(doc["traceEvents"]),
                "incarnations": len(workers),
                "dropped": sum(w.get("dropped", 0) for w in workers.values()),
            }
        )
    )
    return 0


def _doctor(args) -> int:
    """The flight-recorder postmortem (docs/OBSERVABILITY.md "Doctor"):
    reconstruct one session's causal journey across workers and check
    its invariants.  Exit 0 when the journey is anomaly-free (findings —
    migrations, kills, injections — are information, not failures);
    exit 1 when an invariant broke (double execution, unbounded
    migration gap, no terminal event, unknown sid); exit 2 on usage or
    unreadable-capture errors."""
    import json

    from tpu_life.obs import journey

    if args.slo and args.scale:
        print("doctor: --slo and --scale are separate postmortems; "
              "pick one", file=sys.stderr)
        return 2
    if args.slo:
        # SLO postmortem: capture-wide, so no --sid needed — every
        # slo.breach instant is joined to its nearest plausible cause
        from tpu_life.obs import slo as obs_slo

        try:
            doc = journey.load_merged(args.capture)
        except (FileNotFoundError, ValueError, json.JSONDecodeError) as e:
            print(f"doctor: {e}", file=sys.stderr)
            return 2
        report = obs_slo.slo_report(doc)
        if args.json:
            print(json.dumps(report))
        else:
            print(obs_slo.render_slo_report(report))
        # breaches are FINDINGS (the postmortem worked), not failures —
        # exit 0 mirrors the journey path where kills are information
        return 0
    if args.scale:
        # scaling postmortem: capture-wide like --slo — the decision
        # sequence is the evidence, so exit 0 whenever the replay worked
        from tpu_life.fleet.autoscaler import render_scale_report, scale_report

        try:
            doc = journey.load_merged(args.capture)
        except (FileNotFoundError, ValueError, json.JSONDecodeError) as e:
            print(f"doctor: {e}", file=sys.stderr)
            return 2
        report = scale_report(doc)
        if args.json:
            print(json.dumps(report))
        else:
            print(render_scale_report(report))
        return 0
    if args.sid is None and args.trace_id is None:
        print("doctor: pass --sid or --trace-id", file=sys.stderr)
        return 2
    try:
        doc = journey.load_merged(args.capture)
    except (FileNotFoundError, ValueError, json.JSONDecodeError) as e:
        print(f"doctor: {e}", file=sys.stderr)
        return 2
    kwargs = {}
    if args.max_gap is not None:
        kwargs["max_gap_s"] = args.max_gap
    report = journey.doctor(
        doc, sid=args.sid, trace_id=args.trace_id, **kwargs
    )
    if args.json:
        print(json.dumps(report))
    else:
        print(journey.render_report(report))
    return 0 if report["ok"] else 1


def _top(args) -> int:
    """The live operator console (docs/OBSERVABILITY.md "top"): scrape
    the router's merged /metrics + /healthz on a refresh loop and render
    per-worker throughput, queue depth, governor bytes vs budget,
    packed/matmul fractions, stream watchers, and SLO burn-rate gauges.
    `--once --json` emits one machine-readable view for scripting."""
    import json
    import time as _time

    from tpu_life.obs import console

    client = console.TopClient(args.url, timeout=max(1.0, args.interval))
    if args.once:
        # two samples one interval apart so the per-second rates are
        # real deltas, not the all-zero first frame
        try:
            client.view()
            _time.sleep(min(args.interval, 2.0))
            view = client.view()
        except Exception as e:
            print(f"top: {e}", file=sys.stderr)
            return 2
        if args.json:
            print(json.dumps(view))
        else:
            print(console.render_view(view, color=sys.stdout.isatty()))
        return 0
    if args.json:
        print("top: --json requires --once", file=sys.stderr)
        return 2
    color = sys.stdout.isatty()

    def paint():
        return console.render_view(client.view(), color=color)

    return console.refresh_loop(paint, args.interval)


def _submit(args) -> int:
    """Append one request line to the serve spool — the client half of the
    file-based front-end (`serve` is the server half).  Geometry falls back
    to the contract config file exactly like `run` does; fully flag-
    specified geometry with no input file queues a seeded random board
    (the `run --size` shorthand, so demos are self-contained)."""
    import json
    from pathlib import Path

    from tpu_life.config import RunConfig

    height = args.height if args.height is not None else args.size
    width = args.width if args.width is not None else args.size
    if (
        height is not None
        and width is not None
        and args.steps is not None
        and not Path(args.input_file).exists()
    ):
        # seeded-random-board shorthand: the request carries no input_file;
        # `serve` (and the gateway) stage random_board(seed) instead.
        # Contract mode (geometry from the config file) keeps requiring a
        # real board file — a typo'd path must fail loudly, not simulate
        # 50%-density noise.
        steps = args.steps
        req = {
            "height": height,
            "width": width,
            "steps": steps,
            "rule": args.rule,
            "seed": args.seed,
        }
        source = f"seeded random board (seed {args.seed})"
    else:
        height, width, steps = RunConfig(
            height=height,
            width=width,
            steps=args.steps,
            config_file=args.config_file,
        ).resolved_geometry()
        req = {
            "input_file": args.input_file,
            "height": height,
            "width": width,
            "steps": steps,
            "rule": args.rule,
            # stochastic rules consume the stream even with a file board;
            # stamping the seed keeps the spool line a full replay record
            "seed": args.seed,
        }
        source = args.input_file
    if args.temperature is not None:
        req["temperature"] = args.temperature
    if args.output_file is not None:
        req["output_file"] = args.output_file
    if args.timeout is not None:
        req["timeout_s"] = args.timeout
    if args.id is not None:
        req["id"] = args.id
    p = Path(args.requests)
    if p.parent != Path("."):
        p.parent.mkdir(parents=True, exist_ok=True)
    with open(p, "a") as f:
        f.write(json.dumps(req) + "\n")
        f.flush()
    print(f"queued {source} ({height}x{width}, {steps} steps) -> {p}")
    return 0


def _serve(args) -> int:
    """The serving loop: spool file in, result boards + one summary JSON
    line out.  Exit 0 when every session completed, 1 when any failed —
    the summary line carries the per-session detail either way."""
    import json
    from pathlib import Path

    from tpu_life.io.codec import read_board, write_board
    from tpu_life.runtime.metrics import configure_logging
    from tpu_life.serve import ServeConfig, SessionState, SimulationService

    configure_logging(args.verbose)
    spool = Path(args.requests)
    if not spool.exists():
        raise FileNotFoundError(
            f"request spool {args.requests!r} not found; queue requests "
            f"with `tpu-life submit` first"
        )
    requests = []
    with open(spool) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            try:
                requests.append(json.loads(line))
            except json.JSONDecodeError as e:
                raise ValueError(
                    f"{args.requests}:{lineno}: bad request line: {e}"
                ) from e

    svc = SimulationService(
        ServeConfig(
            capacity=args.capacity,
            chunk_steps=args.chunk_steps,
            max_queue=args.max_queue,
            backend=args.serve_backend,
            pipeline=not args.sync_pump,
            default_timeout_s=args.timeout,
            metrics=True,
            metrics_file=args.metrics_file,
            profile=args.profile,
            trace_events=args.trace_events,
            prom_file=args.prom_file,
            spill_dir=args.spill_dir,
            spill_every=args.spill_every,
            mc_packed=not args.no_bitpack,
            stencil=args.stencil,
            memory_budget_bytes=args.memory_budget_bytes,
            mesh_devices=args.mesh_devices,
            engine_max_restarts=args.engine_max_restarts,
            settle_deadline_s=args.settle_deadline,
        )
    )
    # admit respecting backpressure: when the bounded queue fills, pump
    # until it drains enough to take the next request — the CLI is a
    # well-behaved client of its own service
    from tpu_life.serve import InsufficientMemory, QueueFull

    from tpu_life import mc
    from tpu_life.models.rules import get_rule

    submitted: list[tuple[str, dict]] = []
    rejected: list[dict] = []
    try:
        for i, req in enumerate(requests):
            if "input_file" in req:
                board = read_board(req["input_file"], req["height"], req["width"])
            else:
                # a seeded request (`submit --size`): no board file exists,
                # the spool line fully describes the workload — staged from
                # the counter-based stream so the seed names the same board
                # on every host (docs/STOCHASTIC.md).  Continuous rules
                # stage the float twin (docs/RULES.md).
                req_rule = get_rule(req.get("rule", "conway"))
                if req_rule.continuous:
                    from tpu_life.models.lenia import (
                        seeded_board as lenia_seeded_board,
                    )

                    board = lenia_seeded_board(
                        req["height"],
                        req["width"],
                        seed=int(req.get("seed", 0)),
                    )
                else:
                    board = mc.seeded_board(
                        req["height"],
                        req["width"],
                        states=req_rule.states,
                        seed=int(req.get("seed", 0)),
                    )
            sid = None
            while True:
                try:
                    sid = svc.submit(
                        board,
                        req.get("rule", "conway"),
                        int(req["steps"]),
                        timeout_s=req.get("timeout_s"),
                        seed=req.get("seed"),
                        temperature=req.get("temperature"),
                    )
                    break
                except QueueFull:
                    svc.pump()
                except InsufficientMemory as e:
                    # the memory governor's typed rejection (docs/
                    # SERVING.md "Resource governance"): requests are
                    # independent — record this one's refusal in the
                    # summary and keep serving the rest
                    rejected.append(
                        {
                            "session": None,
                            "id": req.get("id"),
                            "state": "rejected",
                            "error": f"{type(e).__name__}: {e}",
                        }
                    )
                    break
            if sid is not None:
                submitted.append((sid, req))
        svc.drain()
    finally:
        # a failed serve still flushes its telemetry — trace buffer, prom
        # snapshot, registry snapshot, sink handle; the failed run is the
        # one whose artifacts matter most
        svc.close()

    out_dir = Path(args.output_dir)
    failures = list(rejected)
    written = 0
    for sid, req in submitted:
        view = svc.poll(sid)
        if view.state is SessionState.DONE:
            out = Path(req.get("output_file") or out_dir / f"{sid}.txt")
            out.parent.mkdir(parents=True, exist_ok=True)
            write_board(out, view.result)
            written += 1
        else:
            failures.append(
                {
                    "session": sid,
                    "id": req.get("id"),
                    "state": view.state.value,
                    "error": view.error,
                }
            )
    stats = svc.stats()
    print(
        json.dumps(
            {
                "mode": "serve",
                "run_id": stats["run_id"],
                "backend": args.serve_backend,
                "pump": stats["pump"],
                "device_idle_s": stats["device_idle_seconds"],
                "capacity": args.capacity,
                "chunk_steps": args.chunk_steps,
                "sessions": len(submitted),
                "done": stats["done"],
                "failed": stats["failed"],
                "written": written,
                "rounds": stats["rounds"],
                "elapsed_s": stats["elapsed_s"],
                "sessions_per_sec": stats["sessions_per_sec"],
                "batch_occupancy_mean": stats["batch_occupancy_mean"],
                "queue_wait_p50": stats["queue_wait_p50"],
                "queue_wait_p95": stats["queue_wait_p95"],
                "completion_p50": stats["completion_p50"],
                "rejections": stats["rejections"],
                "failures": failures,
            }
        )
    )
    return 0 if not failures else 1


def _parse_temps(parser, spec: str) -> list[float]:
    """'T1,T2,...' or 'lo:hi:n' -> temperature grid, loudly on malformation."""
    spec = spec.strip()
    try:
        if ":" in spec:
            lo_s, hi_s, n_s = spec.split(":")
            lo, hi, n = float(lo_s), float(hi_s), int(n_s)
            if n < 1:
                raise ValueError
            if n == 1:
                return [lo]
            return [lo + (hi - lo) * i / (n - 1) for i in range(n)]
        temps = [float(t) for t in spec.split(",") if t.strip()]
        if not temps:
            raise ValueError
        return temps
    except ValueError:
        parser.error(
            f"--temps must be 'T1,T2,...' or 'lo:hi:n', got {spec!r}"
        )


def _sweep(parser, args) -> int:
    """The temperature-sweep front (docs/STOCHASTIC.md): N ising sessions
    — same seed, same board, one temperature each — through the
    continuous-batching service, magnetization per temperature out as one
    JSON line.  The MPMD parameter-sweep shape: the whole grid shares ONE
    CompileKey (temperature rides per-slot), which the summary's
    ``compile_counts`` lets scripts assert."""
    import json
    from pathlib import Path

    from tpu_life import mc
    from tpu_life.models.rules import get_rule
    from tpu_life.runtime.metrics import configure_logging
    from tpu_life.serve import (
        InsufficientMemory,
        QueueFull,
        ServeConfig,
        SessionState,
        SimulationService,
    )

    configure_logging(args.verbose)
    height = args.height if args.height is not None else args.size
    width = args.width if args.width is not None else args.size
    if height is None or width is None:
        parser.error("sweep needs --size (or --height/--width)")
    temps = _parse_temps(parser, args.temps)
    rule = get_rule(args.rule)
    try:
        # lattice contract checked BEFORE the board is staged: odd ising
        # dimensions and the PRNG counter-width area cap reject typed
        # here instead of after the staging work (the service re-checks
        # at submit with the same capability)
        mc.validate_board_shape(
            rule,
            (height, width),
            wide_counter=mc.wide_counter_capable(
                rule, args.serve_backend, bitpack=not args.no_bitpack
            ),
        )
        # kernel-vs-board geometry (docs/RULES.md): typed exit 2 here
        # too, before any board is staged
        from tpu_life.models.rules import validate_rule_geometry

        validate_rule_geometry(rule, (height, width))
    except ValueError as e:
        parser.error(str(e))
    board = mc.seeded_board(
        height, width, args.density, states=rule.states, seed=args.seed
    )
    capacity = args.capacity if args.capacity is not None else len(temps)
    svc = SimulationService(
        ServeConfig(
            capacity=capacity,
            chunk_steps=args.chunk_steps,
            max_queue=max(64, len(temps)),
            backend=args.serve_backend,
            pipeline=not args.sync_pump,
            metrics=bool(args.metrics_file),
            metrics_file=args.metrics_file,
            mc_packed=not args.no_bitpack,
            stencil=args.stencil,
            memory_budget_bytes=args.memory_budget_bytes,
            mesh_devices=args.mesh_devices,
            engine_max_restarts=args.engine_max_restarts,
            settle_deadline_s=args.settle_deadline,
        )
    )
    try:
        sids: list[str] = []
        for t in temps:
            while True:
                try:
                    sids.append(
                        svc.submit(
                            board,
                            rule,
                            args.steps,
                            seed=args.seed,
                            temperature=t,
                        )
                    )
                    break
                except QueueFull:
                    svc.pump()
                except InsufficientMemory as e:
                    # the whole grid shares ONE CompileKey: if it cannot
                    # fit the budget, no session of this sweep ever can —
                    # a typed config refusal, before any work runs (the
                    # finally below closes the service)
                    print(f"sweep: {e}", file=sys.stderr)
                    return 2
        svc.drain()
        # snapshot BEFORE close: close() releases idle engines, and the
        # summary's compile_counts (the one-compile sweep invariant CI
        # asserts) lives on them
        stats = svc.stats()
    finally:
        svc.close()

    out_dir = Path(args.output_dir) if args.output_dir else None
    sessions = []
    failures = 0
    for sid, t in zip(sids, temps):
        view = svc.poll(sid)
        entry = {
            "session": sid,
            "temperature": t,
            "state": view.state.value,
            "steps": view.steps_done,
        }
        if view.state is SessionState.DONE:
            entry["magnetization"] = mc.ising.magnetization(view.result)
            if out_dir is not None:
                from tpu_life.io.codec import write_board

                out_dir.mkdir(parents=True, exist_ok=True)
                write_board(out_dir / f"{sid}.txt", view.result)
        else:
            entry["error"] = view.error
            failures += 1
        sessions.append(entry)
    print(
        json.dumps(
            {
                "mode": "sweep",
                "run_id": stats["run_id"],
                "rule": rule.name,
                "seed": args.seed,
                "height": height,
                "width": width,
                "steps": args.steps,
                "backend": args.serve_backend,
                "capacity": capacity,
                "sessions": sessions,
                "done": stats["done"],
                "failed": stats["failed"],
                "rounds": stats["rounds"],
                "elapsed_s": stats["elapsed_s"],
                "compile_counts": stats["compile_counts"],
            }
        )
    )
    return 0 if not failures else 1


def _gateway(args) -> int:
    """The network front door (docs/GATEWAY.md): serve the HTTP API until
    SIGTERM/SIGINT, then drain gracefully — stop admitting, finish
    in-flight sessions, flush telemetry — and exit 0.

    Prints one JSON line at startup (bound URL + run_id, so scripts can
    wait for readiness) and one summary line after the drain.
    """
    import json

    from tpu_life.gateway import Gateway, GatewayConfig
    from tpu_life.gateway.protocol import MAX_BODY
    from tpu_life.runtime.metrics import configure_logging, log
    from tpu_life.serve import ServeConfig, SimulationService

    configure_logging(args.verbose)
    if args.standby and args.register is None:
        print(
            "gateway: --standby needs --register (the standby pool is a "
            "control-plane concept)",
            file=sys.stderr,
        )
        return 2
    qos = None
    if args.qos is not None:
        from tpu_life.serve.qos import QosPolicy

        try:
            qos = QosPolicy.load(args.qos)
        except (OSError, ValueError) as e:
            # typed, before any socket or engine exists
            print(f"gateway: bad --qos: {e}", file=sys.stderr)
            return 2
    try:
        svc = SimulationService(
            ServeConfig(
                capacity=args.capacity,
                chunk_steps=args.chunk_steps,
                max_queue=args.max_queue,
                backend=args.serve_backend,
                pipeline=not args.sync_pump,
                default_timeout_s=args.timeout,
                metrics=True,
                metrics_file=args.metrics_file,
                trace_events=args.trace_events,
                prom_file=args.prom_file,
                spill_dir=args.spill_dir,
                spill_every=args.spill_every,
                spill_url=args.spill_url,
                spill_namespace=args.spill_namespace,
                spill_replicas=args.spill_replicas,
                qos=qos,
                mc_packed=not args.no_bitpack,
                stencil=args.stencil,
                memory_budget_bytes=args.memory_budget_bytes,
                mesh_devices=args.mesh_devices,
                engine_max_restarts=args.engine_max_restarts,
                settle_deadline_s=args.settle_deadline,
                series_every_s=args.series_every,
            )
        )
    except ValueError as e:
        # e.g. --spill-dir with --spill-url: typed, before any socket
        print(f"gateway: {e}", file=sys.stderr)
        return 2
    gw = Gateway(
        svc,
        GatewayConfig(
            host=args.host,
            port=args.port,
            api_rate=args.api_rate,
            api_burst=args.api_burst,
            shed_high_water=args.shed_high_water,
            max_body=args.max_body if args.max_body is not None else MAX_BODY,
            qos=qos,
        ),
    )
    gw.install_signal_handlers()
    gw.start()
    # a fleet supervisor reads the resolved device count/kind from this
    # line to weight routing (docs/FLEET.md placement).  Resolution runs
    # on a background thread; wait a BOUNDED beat for it — on CPU (and
    # any healthy attach) it lands well inside this — but a slow or
    # wedged accelerator must not delay the startup line past the
    # supervisor's startup timeout: the fields are simply omitted and
    # the supervisor picks them up from /readyz once they exist.
    # chaos seam (docs/CHAOS.md): a worker that is slow out of the gate —
    # the startup line (which the fleet supervisor blocks on) is delayed,
    # exercising the startup-timeout / recycle path without a real slow
    # accelerator attach
    from tpu_life import chaos as _chaos

    _delay = _chaos.delay("worker.start_delay")
    if _delay > 0:
        import time as _time

        _time.sleep(_delay)
    startup = {
        "mode": "gateway",
        "url": f"http://{gw.host}:{gw.port}",
        "run_id": svc.run_id,
        "backend": args.serve_backend,
        "capacity": args.capacity,
        "max_queue": args.max_queue,
        "api_rate": args.api_rate,
    }
    info = gw.device_info(wait_s=10.0)
    if info is not None:
        startup["devices"], startup["device_kind"] = info
    print(json.dumps(startup), flush=True)
    registrar = None
    if args.register is not None:
        # wire registration (docs/FLEET.md "Cross-host topology"): the
        # startup line above IS the registration body; the registrar
        # keeps the lease renewed, rebinds the spill namespace to each
        # grant, and on a lease_expired fence drops the local copies of
        # re-homed sessions (finishing them would double-execute) before
        # re-registering for a fresh generation
        from tpu_life.fleet.membership import Registrar

        def _on_grant(grant: dict) -> None:
            sp = grant.get("spill")
            if isinstance(sp, dict) and sp.get("namespace"):
                try:
                    svc.rebind_spill(str(sp["namespace"]))
                except ValueError as e:
                    log.warning("gateway: cannot rebind spill: %s", e)

        registrar = Registrar(
            args.register,
            self_url=startup["url"],
            run_id=svc.run_id,
            device_info=lambda: gw.device_info(wait_s=0.0),
            on_grant=_on_grant,
            on_fenced=lambda reason: svc.cancel_live(reason),
            standby=args.standby,
        )
        registrar.start()
    try:
        gw.wait()
    finally:
        if registrar is not None:
            registrar.stop()
        gw.close()
    stats = svc.stats()
    print(
        json.dumps(
            {
                "mode": "gateway",
                "run_id": stats["run_id"],
                "pump": stats["pump"],
                "device_idle_s": stats["device_idle_seconds"],
                # a pump crash is a failed serve even though the drain
                # machinery shut everything down tidily — exit 1 below
                "pump_error": str(gw.pump_error) if gw.pump_error else None,
                "sessions": stats["sessions"],
                "done": stats["done"],
                "failed": stats["failed"],
                "cancelled": stats["cancelled"],
                "rejections": stats["rejections"],
                "rounds": stats["rounds"],
                "elapsed_s": stats["elapsed_s"],
                "sessions_per_sec": stats["sessions_per_sec"],
                "batch_occupancy_mean": stats["batch_occupancy_mean"],
                "queue_wait_p50": stats["queue_wait_p50"],
                "completion_p50": stats["completion_p50"],
                # wire membership evidence (docs/FLEET.md cross-host):
                # how often this worker registered and how often it was
                # fenced — the drill reads these back from the log
                **(
                    {
                        "registrar": {
                            "registrations": registrar.registrations,
                            "fenced": registrar.fenced_count,
                            "worker": registrar.worker,
                            "generation": registrar.generation,
                        }
                    }
                    if registrar is not None
                    else {}
                ),
            }
        ),
        flush=True,
    )
    return 1 if gw.pump_error else 0


def _fleet(args) -> int:
    """The horizontally scaled front tier (docs/FLEET.md): supervise N
    gateway workers, route sessions across them, and drain the whole
    fleet gracefully on SIGTERM/SIGINT.

    Prints one JSON line at startup (router URL + fleet run_id, so
    scripts can wait for readiness via ``/readyz``) and one summary line
    after the drain.  Exit 0 on a clean drain; 1 if any worker ended with
    its circuit breaker open (a crash-looping worker is a failure even
    when the drain itself was tidy).
    """
    import json

    from tpu_life.fleet import Fleet, FleetConfig, WorkerState
    from tpu_life.fleet.placement import PlacementError, parse_devices_per_worker
    from tpu_life.runtime.metrics import configure_logging

    configure_logging(args.verbose)
    worker_args = [
        "--capacity", str(args.capacity),
        "--chunk-steps", str(args.chunk_steps),
        "--max-queue", str(args.max_queue),
        "--serve-backend", args.serve_backend,
        "--api-rate", str(args.api_rate),
        "--api-burst", str(args.api_burst),
    ]
    if args.sync_pump:
        worker_args += ["--sync-pump"]
    if args.stencil != "auto":
        worker_args += ["--stencil", args.stencil]
    # the per-worker resource governor (docs/SERVING.md): each gateway
    # worker enforces its own budget/restart/watchdog knobs
    if args.memory_budget_bytes is not None:
        worker_args += ["--memory-budget-bytes", str(args.memory_budget_bytes)]
    if args.mesh_devices:
        worker_args += ["--mesh-devices", str(args.mesh_devices)]
    if args.engine_max_restarts != 3:
        worker_args += ["--engine-max-restarts", str(args.engine_max_restarts)]
    if args.settle_deadline is not None:
        worker_args += ["--settle-deadline", str(args.settle_deadline)]
    if args.timeout is not None:
        worker_args += ["--timeout", str(args.timeout)]
    if args.platform is not None:
        worker_args += ["--platform", args.platform]
    if args.verbose:
        worker_args += ["--verbose"]
    # tenant QoS rides to every worker as the policy FILE (the workers
    # parse it themselves; validate here so a typo fails before spawn)
    if args.qos is not None:
        from tpu_life.serve.qos import QosPolicy

        try:
            QosPolicy.load(args.qos)
        except (OSError, ValueError) as e:
            print(f"fleet: bad --qos: {e}", file=sys.stderr)
            return 2
        worker_args += ["--qos", args.qos]
    if args.spill_replicas != 1:
        if args.spill_dir is None:
            print(
                "fleet: --spill-replicas needs --spill-dir (replication "
                "is a local-directory spill feature)",
                file=sys.stderr,
            )
            return 2
        worker_args += ["--spill-replicas", str(args.spill_replicas)]
    if args.spill_dir is not None and args.spill_url is not None:
        print(
            "fleet: --spill-dir and --spill-url are mutually exclusive "
            "(a fleet spills locally OR through the remote store)",
            file=sys.stderr,
        )
        return 2
    try:
        if args.placement == "none" and (
            args.devices_per_worker is not None or args.total_devices is not None
        ):
            raise PlacementError(
                "--devices-per-worker/--total-devices have no effect "
                "without --placement auto — pass it explicitly (refusing "
                "to silently keep the shared spawning env)"
            )
        autoscale = None
        if args.autoscale:
            from tpu_life.fleet.autoscaler import AutoscaleConfig

            autoscale = AutoscaleConfig(
                min_workers=args.scale_min,
                max_workers=args.scale_max,
                depth_high=args.scale_up_depth,
                depth_low=args.scale_down_depth,
                idle_grace_s=args.scale_idle_grace,
                cooldown_up_s=args.scale_cooldown_up,
                cooldown_down_s=args.scale_cooldown_down,
            )
        fleet = Fleet(
            FleetConfig(
                workers=args.workers,
                host=args.host,
                port=args.port,
                worker_args=tuple(worker_args),
                metrics_dir=args.metrics_dir,
                log_dir=args.log_dir,
                spill_dir=args.spill_dir,
                spill_every=args.spill_every,
                spill_url=args.spill_url,
                site=args.site,
                peers=tuple(args.peers or ()),
                lease_ttl_s=args.lease_ttl,
                trace_dir=args.trace_dir,
                series_every_s=args.series_every,
                slo_file=args.slo_file,
                standby=args.standby,
                autoscale=autoscale,
                probe_interval_s=args.probe_interval,
                backoff_base_s=args.restart_backoff,
                # the flag counts RESTARTS; the breaker counts consecutive
                # failures, of which the initial crash is the first — so N
                # permitted restarts means the breaker opens on failure N+1
                breaker_threshold=args.max_restarts + 1,
                placement=args.placement,
                devices_per_worker=parse_devices_per_worker(
                    args.devices_per_worker, args.workers
                ),
                total_devices=args.total_devices,
                placement_platform=args.platform or "cpu",
            )
        )
    except PlacementError as e:
        # a plan that can never come up healthy fails FAST and typed —
        # before any worker process exists, never via the restart budget
        print(
            json.dumps(
                {
                    "mode": "fleet",
                    "error": {"code": "placement_invalid", "message": str(e)},
                }
            ),
            flush=True,
        )
        print(f"fleet: placement error: {e}", file=sys.stderr)
        return 2
    except (ValueError, OSError) as e:
        # e.g. a malformed --site prefix or an unreadable/invalid --slo
        # spec file: typed, before any worker spawns
        print(f"fleet: {e}", file=sys.stderr)
        return 2
    fleet.install_signal_handlers()
    fleet.start()
    print(
        json.dumps(
            {
                "mode": "fleet",
                "url": f"http://{fleet.host}:{fleet.port}",
                "run_id": fleet.run_id,
                "workers": args.workers,
                "backend": args.serve_backend,
                "capacity": args.capacity,
                "max_queue": args.max_queue,
                "log_dir": str(fleet.supervisor.log_dir),
                "placement": args.placement,
                # planned devices per worker (the startup view; workers
                # overwrite with what their jax init actually resolved)
                "devices": {
                    w.name: w.devices for w in fleet.supervisor.workers
                },
            }
        ),
        flush=True,
    )
    try:
        fleet.wait()
    finally:
        fleet.close()
    stats = fleet.stats()
    failed = [
        name
        for name, state in stats["workers"].items()
        if state == WorkerState.FAILED.value
    ]
    print(
        json.dumps(
            {
                "mode": "fleet",
                "run_id": stats["run_id"],
                "workers": stats["workers"],
                "generations": stats["generations"],
                "restarts": stats["restarts"],
                "routed": stats["routed"],
                "retries": stats["retries"],
                "sessions_pinned": stats["sessions_pinned"],
                # per-worker resolved devices + routing weights, and the
                # fleet's aggregate chip count (docs/FLEET.md placement)
                "capacity": stats["capacity"],
                "devices_total": stats["devices_total"],
                # worker-death migrations by outcome (present only with
                # --spill-dir): migrated / corrupt / failed
                **(
                    {"migrations": stats["migrations"]}
                    if "migrations" in stats
                    else {}
                ),
                # autoscaling evidence (present only when configured):
                # deployed/parked counts and how many decisions the
                # control loop took
                **({"scale": stats["scale"]} if "scale" in stats else {}),
                # a breaker-open worker is a real failure even though the
                # drain machinery shut everything down tidily — exit 1
                "failed_workers": failed,
            }
        ),
        flush=True,
    )
    return 1 if failed else 0


def _spill_store(args) -> int:
    """Host the remote spill store until SIGTERM/SIGINT: one JSON line at
    startup (bound URL, so scripts and supervisors can point workers at
    it), one at shutdown."""
    import json
    import signal
    import threading

    from tpu_life.runtime.metrics import configure_logging
    from tpu_life.serve.spill_http import SpillHTTPServer

    configure_logging(args.verbose)
    server = SpillHTTPServer(args.root, host=args.host, port=args.port)
    server.start()
    print(
        json.dumps(
            {"mode": "spill-store", "url": server.url, "root": str(server.root)}
        ),
        flush=True,
    )
    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda signum, frame: stop.set())
    stop.wait()
    server.close()
    print(json.dumps({"mode": "spill-store", "stopped": True}), flush=True)
    return 0


def _chaos_drill(args) -> int:
    """The seeded chaos drill (docs/CHAOS.md): a real fleet under a
    deterministic fault schedule, machine-verified invariants, one JSON
    summary line.  Exit 0 only when every invariant held; on failure the
    summary (and a stderr line) carries the seed + plan digest that
    replay the run verbatim — the CI seed-replay contract.
    """
    import json
    import tempfile

    from tpu_life import chaos
    from tpu_life.chaos.drill import DrillConfig, run_drill
    from tpu_life.runtime.metrics import configure_logging

    configure_logging(args.verbose)
    points = None
    if args.plan is not None:
        try:
            points = json.loads(args.plan)
            if not isinstance(points, dict):
                raise ValueError("plan must be a JSON object of points")
            # validate NOW, typed — before any worker is spawned
            chaos.ChaosPlan(args.seed, points)
        except (ValueError, chaos.ChaosError) as e:
            print(f"chaos: bad --plan: {e}", file=sys.stderr)
            return 2
    if sum((args.governor, args.stream, args.surge)) > 1:
        print(
            "chaos: --governor, --stream and --surge are separate drills; "
            "pick one",
            file=sys.stderr,
        )
        return 2
    if args.cross_host:
        if args.governor or args.stream or args.surge:
            print(
                "chaos: --governor/--stream/--surge and --cross-host are "
                "separate drills; pick one",
                file=sys.stderr,
            )
            return 2
        return _chaos_cross_host(args, points)
    cfg = DrillConfig(
        seed=args.seed,
        workers=args.workers,
        det_sessions=args.sessions,
        ising_sessions=args.ising_sessions,
        size=args.size,
        steps=args.steps,
        kills=args.kills,
        points=points,
        backend=args.backend,
        capacity=args.capacity,
        chunk_steps=args.chunk_steps,
        spill_every=args.spill_every,
        recovery_bound_s=args.recovery_bound,
        wait_timeout_s=args.wait_timeout,
        workdir=args.workdir or tempfile.mkdtemp(prefix="tpu-life-chaos-"),
        summary_file=args.summary_file,
        governor=args.governor,
        settle_deadline_s=args.settle_deadline,
        stream=args.stream,
        lenia_sessions=args.lenia_sessions,
        watchers_per_session=args.watchers,
        surge=args.surge,
        standby=args.surge_standby,
        surge_factor=args.surge_factor,
        qos_p99_bound_s=args.qos_p99_bound,
    )
    if cfg.surge:
        # the surge drill's faults are the scale seams, not SIGKILLs;
        # its session count is trickle + burst, both conway
        cfg.kills = 0
        cfg.ising_sessions = 0
    print(
        json.dumps(
            {
                "mode": "chaos",
                "governor": cfg.governor,
                "stream": cfg.stream,
                "surge": cfg.surge,
                "seed": cfg.seed,
                "workers": cfg.workers,
                "sessions": cfg.det_sessions
                + cfg.ising_sessions
                + (cfg.lenia_sessions if cfg.stream else 0)
                + (cfg.surge_factor * cfg.det_sessions if cfg.surge else 0),
                "kills": cfg.kills,
                "workdir": cfg.workdir,
            }
        ),
        flush=True,
    )
    summary = run_drill(cfg)
    print(json.dumps(summary), flush=True)
    if not summary["ok"]:
        if cfg.governor:
            flag = " --governor"
        elif cfg.stream:
            flag = " --stream"
        elif cfg.surge:
            flag = " --surge"
        else:
            flag = ""
        print(
            f"chaos: INVARIANT FAILURE — replay verbatim with: "
            f"tpu-life chaos{flag} --seed {cfg.seed} "
            f"(plan digest {summary['plan_digest']})",
            file=sys.stderr,
        )
        return 1
    return 0


def _chaos_cross_host(args, points) -> int:
    """The two-control-plane leg of ``tpu-life chaos`` (docs/FLEET.md
    "Cross-host topology"): same contract as the single-plane drill —
    one startup JSON line, one summary line, exit 0 only when every
    invariant held, the seed echoed for verbatim replay on failure."""
    import json
    import tempfile

    from tpu_life.chaos.crosshost import CrossHostConfig, run_cross_host_drill

    if args.kills != 1:
        # validate NOW, typed — before any plane or store is spawned
        print(
            "chaos: the cross-host drill performs exactly one adopter "
            "SIGKILL (--kills must be 1); --kills N is the single-plane "
            "drill's knob",
            file=sys.stderr,
        )
        return 2
    cfg = CrossHostConfig(
        seed=args.seed,
        workers=args.workers,
        det_sessions=args.sessions,
        ising_sessions=args.ising_sessions,
        size=args.size,
        steps=args.steps,
        kills=args.kills,
        points=points,
        backend=args.backend,
        capacity=args.capacity,
        chunk_steps=args.chunk_steps,
        spill_every=args.spill_every,
        lease_ttl_s=args.lease_ttl,
        recovery_bound_s=args.recovery_bound,
        wait_timeout_s=args.wait_timeout,
        workdir=args.workdir or tempfile.mkdtemp(prefix="tpu-life-crosshost-"),
        summary_file=args.summary_file,
    )
    print(
        json.dumps(
            {
                "mode": "chaos",
                "cross_host": True,
                "seed": cfg.seed,
                "workers_b": cfg.workers,
                "sessions": cfg.det_sessions + cfg.ising_sessions,
                "lease_ttl_s": cfg.lease_ttl_s,
                "workdir": cfg.workdir,
            }
        ),
        flush=True,
    )
    summary = run_cross_host_drill(cfg)
    print(json.dumps(summary), flush=True)
    if not summary["ok"]:
        print(
            f"chaos: CROSS-HOST INVARIANT FAILURE — replay verbatim with: "
            f"tpu-life chaos --cross-host --seed {cfg.seed} "
            f"(plan digest {summary['plan_digest']})",
            file=sys.stderr,
        )
        return 1
    return 0


def _client(parser, args) -> int:
    """The CLI face of ``tpu_life.gateway.client`` — one JSON line per
    action (machine-parseable like `bench`/`tune`), boards in contract
    format or RLE."""
    import json
    from pathlib import Path

    from tpu_life.gateway.client import GatewayClient, GatewayError

    client = GatewayClient(
        args.url, api_key=args.api_key, retries=args.retries
    )

    def need_session() -> str:
        if args.session is None:
            parser.error(f"client {args.action} needs --session SID")
        return args.session

    try:
        if args.action == "health":
            print(json.dumps({"health": client.healthz(), "ready": _ready(client)}))
            return 0
        if args.action == "poll":
            print(json.dumps(client.poll(need_session())))
            return 0
        if args.action == "cancel":
            sid = need_session()
            print(json.dumps({"session": sid, "cancelled": client.cancel(sid)}))
            return 0
        if args.action == "result":
            return _client_result(args, client, need_session())
        # submit
        if args.steps is None:
            parser.error("client submit needs --steps")
        kwargs: dict = dict(rule=args.rule, steps=args.steps, timeout_s=args.timeout)
        if args.temperature is not None:
            kwargs["temperature"] = args.temperature
        if args.seed is not None:
            # meaningful for inline boards too: a stochastic rule's
            # trajectory is named by (board, seed, temperature)
            kwargs["seed"] = args.seed
        if args.input_file is not None:
            from tpu_life.config import RunConfig
            from tpu_life.io.codec import read_board

            height, width, _ = RunConfig(
                height=args.height if args.height is not None else args.size,
                width=args.width if args.width is not None else args.size,
                steps=args.steps,
                config_file=args.config_file,
            ).resolved_geometry()
            kwargs["board"] = read_board(args.input_file, height, width)
        else:
            if args.size is None and (args.height is None or args.width is None):
                parser.error(
                    "client submit needs --input-file, or --size (or "
                    "--height/--width) for a server-seeded board"
                )
            kwargs.update(
                size=args.size,
                height=args.height,
                width=args.width,
                seed=args.seed,
                density=args.density,
            )
        sid = client.submit(**kwargs)
        if not args.wait:
            print(json.dumps(client.poll(sid)))
            return 0
        view = client.wait(sid)
        print(json.dumps(view))
        if view["state"] != "done":
            return 1
        if args.output_file is not None:
            from tpu_life.io.codec import write_board

            out = Path(args.output_file)
            out.parent.mkdir(parents=True, exist_ok=True)
            write_board(out, client.result_board(sid))
        return 0
    except GatewayError as e:
        print(
            json.dumps(
                {"error": {"code": e.code, "message": e.message}, "status": e.status}
            )
        )
        return 1


def _ready(client) -> bool:
    from tpu_life.gateway.client import GatewayClient, GatewayError

    # readiness is a yes/no — probe with a zero-retry client so a draining
    # gateway answers False immediately instead of after the retry budget
    probe = GatewayClient(client.base_url, api_key=client.api_key, retries=0)
    try:
        probe.readyz()
        return True
    except GatewayError:
        return False


def _client_result(args, client, sid: str) -> int:
    from pathlib import Path

    from tpu_life.io.codec import write_board

    if args.output_file is not None:
        board = client.result_board(sid)
        out = Path(args.output_file)
        out.parent.mkdir(parents=True, exist_ok=True)
        write_board(out, board)
        h, w = board.shape
        print(f"wrote {out} ({h}x{w})")
        return 0
    payload = client.result(sid, fmt=args.format)
    if args.format == "rle":
        print(payload["rle"], end="")
    else:
        import json

        print(json.dumps(payload))
    return 0


def _pattern(parser, args) -> int:
    """RLE interchange (`tpu_life/io/rle.py`): published patterns drop into
    the reference's contract codec and back out."""
    from pathlib import Path

    import numpy as np

    from tpu_life.io import rle
    from tpu_life.io.codec import read_board, read_config, write_board, write_config
    from tpu_life.models import patterns

    named = {
        n.lower(): getattr(patterns, n)
        for n in dir(patterns)
        if n.isupper() and isinstance(getattr(patterns, n), np.ndarray)
    }
    if args.action == "list":
        for n in sorted(named):
            h, w = named[n].shape
            print(f"{n}  {h}x{w}")
        return 0

    if args.action == "export":
        height, width = args.height, args.width
        if height is None or width is None:
            ch, cw, _ = read_config(args.config_file)
            height = ch if height is None else height
            width = cw if width is None else width
        board = read_board(args.input_file, height, width)
        try:
            from tpu_life.models.rules import get_rule

            states = get_rule(args.rule).states
        except (KeyError, ValueError):
            states = 2  # unknown rule string: dialect follows board content
        text = rle.emit_rle(board, rule=args.rule, states=states)
        if args.rle:
            Path(args.rle).write_text(text)
            print(f"wrote {args.rle} ({height}x{width})")
        else:
            print(text, end="")
        return 0

    # import
    if (args.rle is None) == (args.name is None):
        parser.error("pattern import needs exactly one of --rle / --name")
    if args.rle is not None:
        cells, meta = rle.parse_rle(Path(args.rle).read_text())
        if cells.max(initial=0) > 9:
            parser.error(
                "pattern uses states > 9, which don't fit the contract "
                "codec's digit encoding"
            )
        if meta.get("rule"):
            print(f"pattern rule: {meta['rule']} (pass via `run --rule`)")
    else:
        key = args.name.lower()
        if key not in named:
            parser.error(
                f"unknown pattern {args.name!r}; see `tpu_life pattern list`"
            )
        cells = named[key]
    ph, pw = cells.shape
    height = args.height if args.height is not None else ph
    width = args.width if args.width is not None else pw
    if args.at is not None:
        try:
            top, left = (int(v) for v in args.at.split(","))
        except ValueError:
            parser.error(f"--at must be 'R,C', got {args.at!r}")
    else:
        top, left = (height - ph) // 2, (width - pw) // 2
    if top < 0 or left < 0 or top + ph > height or left + pw > width:
        parser.error(
            f"pattern {ph}x{pw} at ({top},{left}) does not fit a "
            f"{height}x{width} board"
        )
    board = patterns.place(patterns.empty(height, width), cells, top, left)
    write_board(args.input_file, board)
    write_config(args.config_file, height, width, args.steps)
    print(
        f"wrote {args.input_file} ({height}x{width}, pattern at "
        f"{top},{left}) and {args.config_file}"
    )
    return 0


def _gen(args) -> int:
    from tpu_life.io.codec import write_board, write_config
    from tpu_life.models.patterns import random_board

    board = random_board(
        args.height,
        args.width,
        args.density,
        states=args.states,
        seed=args.seed,
    )
    write_board(args.input_file, board)
    write_config(args.config_file, args.height, args.width, args.steps)
    print(f"wrote {args.input_file} ({args.height}x{args.width}) and {args.config_file}")
    return 0


def console_main() -> int:
    """Process entry point: user-facing errors become one tidy stderr line
    + exit 1 instead of a traceback.  ``main`` itself keeps raising so
    library callers (and tests) see the real exceptions."""
    try:
        return main()
    except KeyboardInterrupt:
        print("tpu_life: interrupted", file=sys.stderr)
        return 130
    except (ValueError, RuntimeError, OSError) as e:
        # user-facing errors (bad config/flags, missing files/libraries,
        # unwritable outputs, incomplete distributed specs) — OSError covers
        # FileNotFound/IsADirectory/Permission; unexpected bugs still show
        # their traceback
        print(f"tpu_life: error: {e}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    raise SystemExit(console_main())
