"""Fleet trace fusion and the flight-recorder doctor.

The write half of distributed tracing (docs/OBSERVABILITY.md
"Distributed tracing") leaves a **capture directory** behind: per-worker
scrape files (``<name>.jsonl`` — one JSON scrape record per line, drained
from each worker's ``GET /v1/debug/trace`` by the supervisor's monitor
tick), the control plane's own ``control.jsonl``, and any per-incarnation
``*.trace.json`` files a gracefully-exiting worker wrote.  This module is
the read half:

- :func:`merge_captures` fuses a capture directory into ONE
  Perfetto-loadable Chrome-trace JSON: every worker incarnation becomes
  its own process track (synthetic pid + ``process_name`` metadata),
  span timestamps are re-anchored from each tracer's ``wall_t0`` through
  the scrape's handshake-estimated clock offset onto the collector
  clock, and flight events become ``flight.<kind>`` instant markers —
  so a migrated session's journey reads as one contiguous ``trace_id``
  across two worker tracks (``tpu-life trace merge``).
- :func:`doctor` reconstructs one session's causal timeline from a
  merged capture and machine-checks it: submit → rounds on w0 →
  injection → kill → migration → rounds on w1 → done, with **typed
  findings** (migrations, kills, spills) and **anomalies** (overlapping
  execution intervals on two incarnations — double execution — an
  unbounded migration gap, a journey with no terminal event)
  (``tpu-life doctor``).

Everything here is pure file/JSON work — no jax, no numpy — safe on a
login node against captures copied off a fleet host, like ``obs.stats``.
"""

from __future__ import annotations

import json
import re
from pathlib import Path

from tpu_life.obs import flight

#: Wall-time slack (seconds) tolerated when comparing intervals from two
#: processes: the handshake offset estimate is bounded by half the scrape
#: round-trip, so sub-50 ms "overlaps" are clock noise, not double
#: execution.
CLOCK_SLACK_S = 0.05

#: Default bound on the kill -> resumed-on-survivor gap before the doctor
#: flags it: generous against CPU-reference recovery times (~2 s) while
#: still catching a migration that silently stalled.
DEFAULT_MAX_GAP_S = 60.0

_TRACE_FILE_RE = re.compile(r"(?P<worker>.+?)g(?P<gen>\d+)$")


# ---------------------------------------------------------------------------
# capture loading
# ---------------------------------------------------------------------------
def load_captures(path) -> list[dict]:
    """Read every scrape record under a capture directory.

    ``*.jsonl`` files hold one scrape record per line (the supervisor's
    drains); ``*.trace.json`` files are whole written Tracer files (a
    graceful worker exit's undrained tail), converted into one pseudo
    scrape record each — worker/generation parsed from the file stem
    (``w0g3.trace.json``), offset 0 (same-host write).  A torn FINAL
    jsonl line (a killed writer) is tolerated; torn middle lines raise,
    like ``obs.stats``.
    """
    root = Path(path)
    if not root.is_dir():
        raise FileNotFoundError(f"capture directory {root} does not exist")
    records: list[dict] = []
    for f in sorted(root.glob("*.jsonl")):
        lines = f.read_text().splitlines()
        for i, line in enumerate(lines):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                if i == len(lines) - 1:
                    break  # torn final line: the writer was killed mid-append
                raise ValueError(f"{f}:{i + 1}: corrupt capture line") from None
            if isinstance(rec, dict):
                records.append(rec)
    for f in sorted(root.glob("*.trace.json")):
        try:
            doc = json.loads(f.read_text())
        except json.JSONDecodeError:
            continue  # a file torn by a mid-write kill: the scrapes have the rest
        other = doc.get("otherData") or {}
        if other.get("merged"):
            # a previous merge's own output (the CLI default lands in
            # this directory): not a capture source — re-merging it
            # would mint a phantom incarnation and double the file
            continue
        stem = f.name[: -len(".trace.json")]
        m = _TRACE_FILE_RE.fullmatch(stem)
        records.append(
            {
                "worker": m.group("worker") if m else stem,
                "generation": int(m.group("gen")) if m else 0,
                "pid": other.get("pid"),
                "run_id": other.get("run_id"),
                "wall_t0": other.get("wall_t0"),
                "offset_s": 0.0,
                "dropped": other.get("dropped", 0),
                "events": doc.get("traceEvents") or [],
                "flight": [],
            }
        )
    return records


# ---------------------------------------------------------------------------
# the merge
# ---------------------------------------------------------------------------
def merge_records(records: list[dict]) -> dict:
    """Fuse scrape records into one Perfetto-loadable Chrome-trace doc.

    Each ``(worker, generation)`` incarnation gets a synthetic pid and a
    ``process_name`` metadata event; every timestamp is re-anchored onto
    the collector clock (``wall_t0 - offset_s`` maps a tracer's ts=0 to
    collector epoch) and then rebased so the merged timeline starts at 0.
    """
    # incarnation -> synthetic pid (stable: sorted by first appearance,
    # control first so the routing track leads the view)
    incarnations: dict[tuple, dict] = {}
    for rec in records:
        key = (str(rec.get("worker", "?")), int(rec.get("generation", 0)))
        info = incarnations.setdefault(
            key, {"pid": None, "run_id": rec.get("run_id"), "dropped": 0.0}
        )
        # the tracer's dropped counter is CUMULATIVE and repeated on
        # every scrape record — the incarnation's true loss is the
        # newest (max) value, never the sum across scrapes
        info["dropped"] = max(info["dropped"], float(rec.get("dropped") or 0))
        if info["run_id"] is None:
            info["run_id"] = rec.get("run_id")
    order = sorted(incarnations, key=lambda k: (k[0] != "control", k))
    for i, key in enumerate(order, start=1):
        incarnations[key]["pid"] = i

    out_events: list[dict] = []
    t_min: float | None = None

    def epoch_us(rec: dict, ev_ts_us: float) -> float | None:
        wall_t0 = rec.get("wall_t0")
        if wall_t0 is None:
            return None
        return (float(wall_t0) - float(rec.get("offset_s") or 0.0)) * 1e6 + ev_ts_us

    staged: list[tuple[float, dict]] = []
    for rec in records:
        key = (str(rec.get("worker", "?")), int(rec.get("generation", 0)))
        pid = incarnations[key]["pid"]
        for ev in rec.get("events") or []:
            if not isinstance(ev, dict) or "ts" not in ev:
                continue
            t = epoch_us(rec, float(ev["ts"]))
            if t is None:
                continue  # a span with no wall anchor cannot be placed
            e = dict(ev)
            e["pid"] = pid
            if "dur" in e:
                e["dur"] = float(e["dur"])
            staged.append((t, e))
            t_min = t if t_min is None else min(t_min, t)
        for ev in rec.get("flight") or []:
            if not isinstance(ev, dict) or "t" not in ev:
                continue
            t = (float(ev["t"]) - float(rec.get("offset_s") or 0.0)) * 1e6
            staged.append((t, flight.as_instant(ev, pid=pid, ts=t)))
            t_min = t if t_min is None else min(t_min, t)
    t0 = t_min or 0.0
    for t, e in sorted(staged, key=lambda x: x[0]):
        e["ts"] = t - t0
        out_events.append(e)
    meta_events = []
    workers_meta = {}
    for (worker, gen), info in incarnations.items():
        label = f"{worker} g{gen}" if worker != "control" else "control"
        meta_events.append(
            {
                "name": "process_name",
                "ph": "M",
                "pid": info["pid"],
                "args": {"name": label},
            }
        )
        workers_meta[str(info["pid"])] = {
            "worker": worker,
            "generation": gen,
            "run_id": info["run_id"],
            "dropped": info["dropped"],
        }
    return {
        "traceEvents": meta_events + out_events,
        "displayTimeUnit": "ms",
        "otherData": {
            "merged": True,
            "t0_epoch_s": t0 / 1e6,
            "workers": workers_meta,
        },
    }


def merge_captures(path) -> dict:
    """``load_captures`` + ``merge_records`` for a capture directory."""
    return merge_records(load_captures(path))


# ---------------------------------------------------------------------------
# the doctor
# ---------------------------------------------------------------------------
def _incarnation_of(doc: dict, pid) -> tuple[str, int]:
    meta = (doc.get("otherData") or {}).get("workers") or {}
    info = meta.get(str(pid)) or {}
    return str(info.get("worker", f"pid{pid}")), int(info.get("generation", 0))


def resolve_trace_id(doc: dict, sid: str) -> str | None:
    """Find the trace id a session id belongs to: the router's
    ``flight.route.submit`` pin event for a fleet sid, else any event
    stamped with both this sid and a trace id (a worker-local sid)."""
    fallback = None
    for ev in doc.get("traceEvents", []):
        args = ev.get("args")
        if not isinstance(args, dict):
            continue
        tid = args.get("trace_id")
        if not tid:
            continue
        if ev.get("name") == "flight.route.submit" and args.get("sid") == sid:
            return tid
        if fallback is None and sid in (
            args.get("sid"),
            args.get("worker_sid"),
            ev.get("id"),
        ):
            fallback = tid
    return fallback


def doctor(
    doc: dict,
    *,
    sid: str | None = None,
    trace_id: str | None = None,
    max_gap_s: float = DEFAULT_MAX_GAP_S,
) -> dict:
    """Reconstruct (and machine-check) one session's cross-process
    journey from a merged capture.

    Returns a report dict: ``ok`` (no anomalies), the ordered
    ``journey`` event list, typed ``findings`` (informational:
    migrations, worker exits, spill recovery points), and ``anomalies``
    (invariant violations: ``double_execution`` — two incarnations
    executing the sid at overlapping wall times beyond clock slack,
    ``migration_gap_exceeded``, ``no_terminal``, ``unknown_sid``).
    """
    report: dict = {
        "sid": sid,
        "trace_id": trace_id,
        "journey": [],
        "findings": [],
        "anomalies": [],
        "incarnations": [],
        "outcome": None,
    }
    if trace_id is None:
        if sid is None:
            raise ValueError("doctor needs a --sid or a --trace-id")
        trace_id = resolve_trace_id(doc, sid)
        if trace_id is None:
            report["anomalies"].append(
                {
                    "kind": "unknown_sid",
                    "detail": f"no event in the capture names sid {sid!r}",
                }
            )
            report["ok"] = False
            return report
        report["trace_id"] = trace_id

    events = [
        ev
        for ev in doc.get("traceEvents", [])
        if isinstance(ev.get("args"), dict)
        and ev["args"].get("trace_id") == trace_id
        and "ts" in ev
    ]
    events.sort(key=lambda e: float(e["ts"]))
    incs = []  # insertion-ordered (worker, gen) of the journey
    for ev in events:
        key = _incarnation_of(doc, ev.get("pid"))
        if key not in incs:
            incs.append(key)
    # kill markers of the incarnations this journey touched: they carry
    # no trace_id (the death is about the process), so they join by
    # incarnation — the left edge of a migration gap.  A local worker's
    # death is flight.worker.exit; a wire-registered worker has no
    # process to reap, so its death marker is flight.lease.expired.
    exits: dict[tuple[str, int], float] = {}
    for ev in doc.get("traceEvents", []):
        args = ev.get("args")
        if ev.get("name") not in (
            "flight.worker.exit", "flight.lease.expired"
        ) or not isinstance(args, dict):
            continue
        key = (str(args.get("worker")), int(args.get("generation", 0)))
        if key in incs and "ts" in ev:
            exits[key] = float(ev["ts"])

    def entry(ev, key):
        worker, gen = key
        return {
            "t_s": round(float(ev["ts"]) / 1e6, 6),
            "worker": worker,
            "generation": gen,
            "name": ev.get("name"),
            "ph": ev.get("ph"),
            "args": {
                k: v for k, v in ev["args"].items() if k != "trace_id"
            },
        }

    # per-incarnation execution intervals from the serve.exec async pairs
    intervals: dict[tuple, list[list]] = {}
    for ev in events:
        key = _incarnation_of(doc, ev.get("pid"))
        report["journey"].append(entry(ev, key))
        if ev.get("name") != "serve.exec":
            continue
        spans = intervals.setdefault(key, [])
        ts = float(ev["ts"])
        if ev.get("ph") == "b":
            spans.append([ts, None, None])
        elif ev.get("ph") == "e" and spans:
            for span in reversed(spans):
                if span[1] is None:
                    span[1] = ts
                    span[2] = ev["args"].get("outcome")
                    break
    # close open intervals at the incarnation's exit (SIGKILL: the end
    # event died with the worker) or its last observed journey event
    flat: list[tuple[float, float, tuple, str | None, bool]] = []
    for key, spans in intervals.items():
        last_seen = max(
            (float(e["ts"]) for e in events
             if _incarnation_of(doc, e.get("pid")) == key),
            default=0.0,
        )
        for begin, end, outcome in spans:
            open_ended = end is None
            if open_ended:
                end = exits.get(key, last_seen)
                end = max(end, begin)
            flat.append((begin, end, key, outcome, open_ended))
    flat.sort()
    report["incarnations"] = [
        {"worker": k[0], "generation": k[1]} for k in incs
    ]

    # -- invariants ---------------------------------------------------------
    slack_us = CLOCK_SLACK_S * 1e6
    for i in range(len(flat)):
        for j in range(i + 1, len(flat)):
            b1, e1, k1, _, _ = flat[i]
            b2, e2, k2, _, _ = flat[j]
            if k1 == k2:
                continue  # same process: salvage re-begins nest legally
            overlap = min(e1, e2) - max(b1, b2)
            if overlap > slack_us:
                report["anomalies"].append(
                    {
                        "kind": "double_execution",
                        "detail": (
                            f"{k1[0]} g{k1[1]} and {k2[0]} g{k2[1]} both "
                            f"executed this session for "
                            f"{overlap / 1e6:.3f}s of wall time"
                        ),
                        "overlap_s": overlap / 1e6,
                    }
                )
    # migration findings + gap bound: consecutive intervals on DIFFERENT
    # incarnations
    for a, b in zip(flat, flat[1:]):
        if a[2] == b[2]:
            continue
        gap_s = max(0.0, (b[0] - a[1]) / 1e6)
        finding = {
            "kind": "migration",
            "from": f"{a[2][0]} g{a[2][1]}",
            "to": f"{b[2][0]} g{b[2][1]}",
            "gap_s": round(gap_s, 3),
        }
        report["findings"].append(finding)
        if gap_s > max_gap_s:
            report["anomalies"].append(
                {
                    "kind": "migration_gap_exceeded",
                    "detail": (
                        f"{gap_s:.1f}s between the last event on "
                        f"{a[2][0]} g{a[2][1]} and resumption on "
                        f"{b[2][0]} g{b[2][1]} (bound {max_gap_s}s)"
                    ),
                    "gap_s": round(gap_s, 3),
                }
            )
    for key, ts in sorted(exits.items(), key=lambda kv: kv[1]):
        report["findings"].append(
            {
                "kind": "worker_exit",
                "worker": key[0],
                "generation": key[1],
                "t_s": round(ts / 1e6, 6),
            }
        )
    spills = [e for e in events if e.get("name") == "serve.session.spill"]
    if spills:
        report["findings"].append(
            {
                "kind": "spill",
                "count": len(spills),
                "last_step": spills[-1]["args"].get("step"),
            }
        )
    injections = [
        e for e in events if e.get("name") == "chaos.injection"
    ]
    for e in injections:
        report["findings"].append(
            {
                "kind": "injection",
                "point": e["args"].get("point"),
                "decision": e["args"].get("decision"),
                "t_s": round(float(e["ts"]) / 1e6, 6),
            }
        )
    # terminal outcome: the last exec end's outcome, or a flight.terminal
    outcome = None
    for ev in reversed(events):
        if ev.get("name") == "flight.terminal":
            outcome = ev["args"].get("outcome")
            break
        if ev.get("name") == "serve.exec" and ev.get("ph") == "e":
            outcome = ev["args"].get("outcome")
            break
    report["outcome"] = outcome
    if not events:
        report["anomalies"].append(
            {
                "kind": "unknown_sid",
                "detail": f"no events carry trace_id {trace_id!r}",
            }
        )
    elif outcome is None:
        report["anomalies"].append(
            {
                "kind": "no_terminal",
                "detail": "the journey never reached a terminal event "
                "(still in flight at capture time, or the terminal "
                "events were lost)",
            }
        )
    report["ok"] = not report["anomalies"]
    return report


def load_merged(path) -> dict:
    """A doctor input: a merged (or single-tracer) trace file, or a
    capture directory (merged in memory)."""
    p = Path(path)
    if p.is_dir():
        return merge_captures(p)
    doc = json.loads(p.read_text())
    if not isinstance(doc, dict) or "traceEvents" not in doc:
        raise ValueError(f"{p} is not a Chrome-trace JSON document")
    if not (doc.get("otherData") or {}).get("merged"):
        # a single written tracer file: wrap it as a one-process capture
        # so the doctor's incarnation logic has a workers table
        other = doc.get("otherData") or {}
        pids = {
            ev.get("pid")
            for ev in doc.get("traceEvents", [])
            if isinstance(ev, dict) and "pid" in ev
        }
        doc.setdefault("otherData", other)["workers"] = {
            str(pid): {
                "worker": "local",
                "generation": 0,
                "run_id": other.get("run_id"),
                "dropped": other.get("dropped", 0),
            }
            for pid in pids
        }
    return doc


def render_report(report: dict) -> str:
    """The human doctor output: the journey as one line per event plus
    the findings/anomalies verdict."""
    lines = []
    lines.append(
        f"journey of sid={report.get('sid')} trace_id={report.get('trace_id')}"
    )
    for e in report["journey"]:
        args = e.get("args") or {}
        detail = " ".join(
            f"{k}={v}" for k, v in args.items() if v is not None
        )
        ph = e.get("ph")
        tag = {"b": "begin", "e": "end"}.get(ph, "")
        lines.append(
            f"  {e['t_s']:>10.3f}s  {e['worker']:>8} g{e['generation']}  "
            f"{e['name']} {tag} {detail}".rstrip()
        )
    for f in report["findings"]:
        lines.append(f"finding: {json.dumps(f, sort_keys=True)}")
    for a in report["anomalies"]:
        lines.append(f"ANOMALY: {json.dumps(a, sort_keys=True)}")
    lines.append(
        f"verdict: {'OK' if report.get('ok') else 'ANOMALOUS'} "
        f"(outcome={report.get('outcome')}, "
        f"{len(report['findings'])} finding(s), "
        f"{len(report['anomalies'])} anomaly(ies))"
    )
    return "\n".join(lines)
