"""The operator console behind ``tpu-life top`` (docs/OBSERVABILITY.md
"top").

``top`` is a read-only client of surfaces the fleet already serves:
``GET /metrics`` (the router's merged Prometheus exposition, every
worker's samples tagged ``worker="<name>"``) and ``GET /healthz`` (whose
``slo`` section carries the supervisor's live burn gauges).  Each
refresh takes one scrape, diffs it against the previous one, and renders
per-worker throughput, queue depth, governor bytes vs budget,
packed/matmul fractions, stream watchers, and the SLO burn table with
breach highlighting.  ``--once --json`` emits the same view as one JSON
document — the scripting contract ROADMAP item 3's autoscaler will
consume (two samples one interval apart, so the rates are real).

Pointing ``top`` at a single ``serve`` gateway works too: its samples
carry no ``worker`` label and land on one ``local`` row.

Counter deltas here are client-side: a negative delta means the far end
restarted between scrapes (a new incarnation's counters start at zero),
so the new cumulative value IS the delta — the same new-series rule the
sampled rings apply per (worker, generation).

``tpu-life stats --watch`` borrows only :func:`refresh_loop` — the
single-shot stats output stays byte-identical when the flag is absent.

Stdlib only, no jax/numpy: a login-node terminal is the target.
"""

from __future__ import annotations

import json
import re
import sys
import time
import urllib.request

#: Default refresh cadence (seconds) — one scrape per paint.
DEFAULT_INTERVAL_S = 2.0

_ANSI_CLEAR = "\x1b[2J\x1b[H"
_ANSI_RED = "\x1b[31;1m"
_ANSI_DIM = "\x1b[2m"
_ANSI_RESET = "\x1b[0m"

_LABEL_RE = re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')


def _unescape(v: str) -> str:
    return v.replace(r"\"", '"').replace(r"\n", "\n").replace(r"\\", "\\")


def parse_labels(labelpart: str) -> dict:
    """``k="v",...`` (exposition label syntax, escapes honoured) → dict."""
    return {m.group(1): _unescape(m.group(2)) for m in _LABEL_RE.finditer(labelpart)}


def parse_prom_text(text: str) -> dict:
    """One Prometheus text exposition → a structured snapshot.

    Returns ``{"t", "types": {family: kind}, "scalars": [(name, labels,
    value)], "hists": {key: {...}}}`` where histograms are reassembled
    from their ``_bucket``/``_sum``/``_count`` sample lines back into
    the cumulative-vector shape ``obs.timeseries`` uses (``le`` finite
    bounds, ``buckets`` cumulative with the +Inf slot last), keyed by
    ``name{labels-minus-le}``.  Unparseable lines are skipped — a
    console must keep painting through a half-written exposition."""
    types: dict[str, str] = {}
    scalars: list[tuple[str, dict, float]] = []
    hists: dict[str, dict] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("# TYPE "):
            parts = line.split(" ", 3)
            if len(parts) > 3:
                types[parts[2]] = parts[3]
            continue
        if line.startswith("#"):
            continue
        head, _, value = line.rpartition(" ")
        if not head:
            continue
        try:
            val = float(value)
        except ValueError:
            continue
        brace = head.find("{")
        if brace >= 0 and head.endswith("}"):
            name, labels = head[:brace], parse_labels(head[brace + 1 : -1])
        else:
            name, labels = head, {}
        base = None
        for suffix in ("_bucket", "_sum", "_count"):
            stem = name[: -len(suffix)] if name.endswith(suffix) else None
            if stem is not None and types.get(stem) == "histogram":
                base = (stem, suffix)
                break
        if base is None:
            scalars.append((name, labels, val))
            continue
        stem, suffix = base
        le = labels.pop("le", None)
        key = _key(stem, labels)
        h = hists.setdefault(
            key,
            {"name": stem, "labels": labels, "le": [], "buckets": [],
             "count": 0, "sum": 0.0, "_inf": 0.0},
        )
        if suffix == "_bucket":
            if le == "+Inf":
                h["_inf"] = val
            elif le is not None:
                h["le"].append(float(le))
                h["buckets"].append(val)
        elif suffix == "_sum":
            h["sum"] = val
        else:
            h["count"] = int(val)
    for h in hists.values():
        h["buckets"].append(h.pop("_inf"))
    return {"t": time.time(), "types": types, "scalars": scalars, "hists": hists}


def _key(name: str, labels: dict) -> str:
    if not labels:
        return name
    inner = ",".join(f"{k}={v}" for k, v in sorted(labels.items()))
    return name + "{" + inner + "}"


# -- the view -------------------------------------------------------------
def _split_worker(labels: dict) -> tuple[str, dict]:
    rest = dict(labels)
    return rest.pop("worker", "local"), rest


def _delta(prev: float | None, cur: float) -> float:
    """Client-side counter delta; a reset (negative) reads as a fresh
    series — the new cumulative IS the windowed increment."""
    if prev is None or cur < prev:
        return cur
    return cur - prev


def build_view(prev: dict | None, cur: dict, healthz: dict | None = None) -> dict:
    """Two parsed scrapes (``prev`` may be None on the first paint) plus
    the router's healthz → the per-worker console rows and fleet totals.
    Pure data out: the ``--json`` document and the renderer's input."""
    dt = max(1e-9, cur["t"] - prev["t"]) if prev else None
    prev_scalars: dict[str, float] = {}
    if prev:
        for name, labels, val in prev["scalars"]:
            prev_scalars[_key(name, labels)] = val

    workers: dict[str, dict] = {}

    def row(worker: str) -> dict:
        return workers.setdefault(
            worker,
            {"steps_s": None, "rounds_s": None, "sessions_s": None,
             "queue": None, "occupancy": None, "watchers": None,
             "est_bytes": None, "budget_bytes": None,
             "steps": 0.0, "packed_steps": 0.0, "matmul_keys": None,
             "mesh": None, "frames_s": None, "gaps_s": None},
        )

    def rated(key: str, cur_val: float) -> float | None:
        if dt is None:
            return None
        return _delta(prev_scalars.get(key), cur_val) / dt

    # the tenants pane (docs/SERVING.md "Tenant QoS"): live sessions and
    # typed sheds per tenant label, summed across workers
    tenants: dict[str, dict] = {}

    def tenant_row(t: str) -> dict:
        return tenants.setdefault(
            t, {"sessions": None, "sheds_s": None, "sheds": 0.0}
        )

    for name, labels, val in cur["scalars"]:
        worker, rest = _split_worker(labels)
        kind = cur["types"].get(name)
        key = _key(name, labels)
        r = row(worker)
        if name == "serve_steps_total":
            r["steps"] += val
            rate = rated(key, val)
            if rate is not None:
                r["steps_s"] = (r["steps_s"] or 0.0) + rate
        elif name == "serve_packed_steps_total":
            r["packed_steps"] += val
        elif name == "serve_rounds_total":
            rate = rated(key, val)
            if rate is not None:
                r["rounds_s"] = (r["rounds_s"] or 0.0) + rate
        elif name == "serve_sessions_finished_total":
            rate = rated(key, val)
            if rate is not None:
                r["sessions_s"] = (r["sessions_s"] or 0.0) + rate
        elif name == "serve_queue_depth":
            r["queue"] = val
        elif name == "serve_batch_occupancy":
            r["occupancy"] = val
        elif name == "stream_watchers":
            r["watchers"] = (r["watchers"] or 0.0) + val
        elif name == "stream_frames_total":
            rate = rated(key, val)
            if rate is not None:
                r["frames_s"] = (r["frames_s"] or 0.0) + rate
        elif name == "stream_frame_gaps_total":
            rate = rated(key, val)
            if rate is not None:
                r["gaps_s"] = (r["gaps_s"] or 0.0) + rate
        elif name == "serve_estimated_bytes":
            r["est_bytes"] = (r["est_bytes"] or 0.0) + val
        elif name == "serve_memory_budget_bytes":
            r["budget_bytes"] = val
        elif name == "serve_matmul_keys":
            r["matmul_keys"] = val
        elif name == "serve_mesh_sessions":
            r["mesh"] = (r["mesh"] or 0.0) + val
        elif name == "serve_tenant_sessions":
            tr = tenant_row(rest.get("tenant", "<none>"))
            tr["sessions"] = (tr["sessions"] or 0.0) + val
        elif name == "tenant_shed_total":
            tr = tenant_row(rest.get("tenant", "<none>"))
            tr["sheds"] += val
            rate = rated(key, val)
            if rate is not None:
                tr["sheds_s"] = (tr["sheds_s"] or 0.0) + rate
        elif kind == "counter" and name.endswith("_total"):
            pass  # unrowed counters still merge into fleet totals below

    for r in workers.values():
        r["packed_frac"] = (r["packed_steps"] / r["steps"]) if r["steps"] else None
        del r["steps"], r["packed_steps"]

    def total(field):
        vals = [r[field] for r in workers.values() if r[field] is not None]
        return sum(vals) if vals else None

    view = {
        "t": cur["t"],
        "interval_s": dt,
        "workers": {k: workers[k] for k in sorted(workers)},
        "fleet": {
            "steps_s": total("steps_s"),
            "sessions_s": total("sessions_s"),
            "queue": total("queue"),
            "watchers": total("watchers"),
            "mesh": total("mesh"),
            "frames_s": total("frames_s"),
            "gaps_s": total("gaps_s"),
        },
        "slo": (healthz or {}).get("slo") or {},
        "states": (healthz or {}).get("workers") or {},
        "tenants": {k: tenants[k] for k in sorted(tenants)},
    }
    return view


# -- rendering ------------------------------------------------------------
def _fmt_num(v, unit: str = "") -> str:
    if v is None:
        return "-"
    if unit == "b":  # bytes, scaled
        for suf in ("B", "KiB", "MiB", "GiB"):
            if abs(v) < 1024 or suf == "GiB":
                return f"{v:.1f}{suf}" if suf != "B" else f"{int(v)}B"
            v /= 1024
    if abs(v) >= 1000:
        return f"{v:,.0f}"
    if isinstance(v, float) and not float(v).is_integer():
        return f"{v:.2f}"
    return str(int(v))


def render_view(view: dict, *, color: bool = True) -> str:
    red = _ANSI_RED if color else ""
    dim = _ANSI_DIM if color else ""
    rst = _ANSI_RESET if color else ""
    lines = []
    stamp = time.strftime("%H:%M:%S", time.localtime(view["t"]))
    iv = view.get("interval_s")
    lines.append(
        f"tpu-life top  {stamp}"
        + (f"  (rates over {iv:.1f}s)" if iv else f"  {dim}(first sample — rates next paint){rst}")
    )
    states = view.get("states") or {}
    if states:
        lines.append(
            "workers: "
            + "  ".join(f"{w}={s}" for w, s in sorted(states.items()))
        )
    cols = (
        ("worker", 8), ("steps/s", 10), ("sess/s", 7), ("queue", 6),
        ("occ", 5), ("watch", 6), ("frames/s", 9), ("gaps/s", 7),
        ("packed", 7), ("mm", 4), ("mesh", 5), ("mem", 14),
    )
    lines.append(" ".join(f"{h:>{w}}" for h, w in cols))
    rows = dict(view["workers"])
    fleet = view["fleet"]
    for worker, r in rows.items():
        mem = "-"
        if r["est_bytes"] is not None:
            mem = _fmt_num(r["est_bytes"], "b")
            if r["budget_bytes"]:
                mem += f"/{_fmt_num(r['budget_bytes'], 'b')}"
        packed = "-" if r["packed_frac"] is None else f"{r['packed_frac'] * 100:.0f}%"
        vals = (
            worker, _fmt_num(r["steps_s"]), _fmt_num(r["sessions_s"]),
            _fmt_num(r["queue"]), _fmt_num(r["occupancy"]),
            _fmt_num(r["watchers"]), _fmt_num(r["frames_s"]),
            _fmt_num(r["gaps_s"]), packed, _fmt_num(r["matmul_keys"]),
            _fmt_num(r["mesh"]), mem,
        )
        lines.append(" ".join(f"{str(v):>{w}}" for v, (_, w) in zip(vals, cols)))
    if len(rows) > 1:
        vals = (
            "TOTAL", _fmt_num(fleet["steps_s"]), _fmt_num(fleet["sessions_s"]),
            _fmt_num(fleet["queue"]), "-", _fmt_num(fleet["watchers"]),
            _fmt_num(fleet["frames_s"]), _fmt_num(fleet["gaps_s"]), "-", "-",
            _fmt_num(fleet["mesh"]), "-",
        )
        lines.append(" ".join(f"{str(v):>{w}}" for v, (_, w) in zip(vals, cols)))
    tenants = view.get("tenants") or {}
    if tenants:
        lines.append("")
        lines.append(f"{'tenant':>16} {'sessions':>9} {'sheds/s':>8} {'sheds':>8}")
        for t in sorted(tenants):
            tr = tenants[t]
            lines.append(
                f"{t:>16} {_fmt_num(tr.get('sessions')):>9} "
                f"{_fmt_num(tr.get('sheds_s')):>8} {_fmt_num(tr.get('sheds')):>8}"
            )
    slo = view.get("slo") or {}
    if slo:
        lines.append("")
        lines.append(f"{'slo':>16} {'kind':>9} {'objective':>10} "
                     f"{'burn 5m':>8} {'burn 1h':>8} {'observed':>10}")
        for name in sorted(slo):
            st = slo[name]
            burn_f = st.get("burn_fast")
            burn_s = st.get("burn_slow")
            obs = st.get("observed")
            line = (
                f"{name:>16} {st.get('kind', '?'):>9} "
                f"{_fmt_num(st.get('objective')):>10} "
                f"{_fmt_num(burn_f):>8} {_fmt_num(burn_s):>8} "
                f"{_fmt_num(obs):>10}"
            )
            if st.get("breaching"):
                line = f"{red}{line}  BREACH{rst}"
            lines.append(line)
    return "\n".join(lines)


# -- the client + loop ----------------------------------------------------
class TopClient:
    """Scrapes one base URL (fleet router or single gateway) and keeps
    the previous parse so every :meth:`view` has real rates."""

    def __init__(self, url: str, timeout: float = 3.0):
        self.url = url.rstrip("/")
        self.timeout = timeout
        self._prev: dict | None = None

    def _get(self, path: str) -> bytes:
        with urllib.request.urlopen(self.url + path, timeout=self.timeout) as resp:
            return resp.read()

    def sample(self) -> dict:
        return parse_prom_text(self._get("/metrics").decode("utf-8", "replace"))

    def healthz(self) -> dict | None:
        try:
            doc = json.loads(self._get("/healthz"))
            return doc if isinstance(doc, dict) else None
        except Exception:
            return None  # a bare gateway has no /healthz — rows still paint

    def view(self) -> dict:
        cur = self.sample()
        v = build_view(self._prev, cur, self.healthz())
        self._prev = cur
        return v


def refresh_loop(
    paint,
    interval_s: float = DEFAULT_INTERVAL_S,
    *,
    once: bool = False,
    out=None,
    clear: bool = True,
    max_iterations: int | None = None,
) -> int:
    """The shared console loop (``top`` and ``stats --watch``): call
    ``paint()`` for a string, clear-and-draw, sleep, repeat until ^C.
    ``once`` paints a single frame with no clear (pipeline-friendly);
    ``max_iterations`` bounds the loop for tests.  Returns an exit code;
    a scrape error paints as a message, not a crash — a console must
    survive its fleet restarting."""
    out = sys.stdout if out is None else out
    n = 0
    while True:
        try:
            frame = paint()
        except KeyboardInterrupt:
            return 0
        except Exception as e:
            frame = f"[unreachable: {e}]"
        if clear and not once:
            out.write(_ANSI_CLEAR)
        out.write(frame + "\n")
        out.flush()
        n += 1
        if once or (max_iterations is not None and n >= max_iterations):
            return 0
        try:
            time.sleep(interval_s)
        except KeyboardInterrupt:
            return 0
