"""tpu_life.obs: unified telemetry — spans, metrics, and read-back.

The reference's observability was one ``MPI_Wtime`` bracket; the repo had
grown three disconnected signals (``MetricsRecorder`` JSONL, a whole-run
``jax.profiler`` wrapper, bare log lines) with no shared identity.  This
package ties them together around one generated ``run_id``:

- :mod:`tpu_life.obs.trace` — Chrome trace-event spans (Perfetto-loadable,
  ``--trace-events FILE``) bracketing every host phase: driver
  config-resolve / compile / staging / chunks / snapshots / recovery,
  serve rounds (admit / step-chunk / retire / per-session queue wait),
  autotune trials.  Disabled tracing is a shared ``nullcontext`` — zero
  per-step Python cost, asserted via the :func:`span_count` probe.
- :mod:`tpu_life.obs.registry` — ``Counter`` / ``Gauge`` / ``Histogram``
  families with bounded-cardinality labels, exported both as records in
  the metrics JSONL sink and as a Prometheus text snapshot
  (``serve --prom-file``).
- :mod:`tpu_life.obs.stats` — the read-back toolchain behind
  ``tpu-life stats``: one JSONL file in, throughput aggregates and
  histogram quantiles out (``--json`` for machines).
- :mod:`tpu_life.obs.timeseries` — bounded rings of periodic registry
  snapshots with pure windowed queries (``rate``,
  ``quantile_over_window``), scraped fleet-wide through
  ``GET /v1/debug/series?cursor=`` into a per-(worker, generation)
  store; disabled sampling is one ``is None`` check, asserted via the
  :func:`~tpu_life.obs.timeseries.sample_count` probe.
- :mod:`tpu_life.obs.slo` — declarative SLO specs (JSON/TOML or
  built-in defaults) evaluated with multi-window burn rates on the
  supervisor tick; a breach is a typed ``slo.breach`` flight event that
  ``tpu-life doctor --slo`` joins to its cause.
- :mod:`tpu_life.obs.console` — the ``tpu-life top`` operator console:
  a Prometheus-exposition parser, client-side counter deltas, and the
  refresh loop ``stats --watch`` shares.

Correlation model: the driver / serve service / bench each generate one
``run_id`` per invocation and stamp it into every trace file, every JSONL
record and every BENCH record they emit, so the artifacts of one run join
on one key.  ``TELEMETRY_SCHEMA`` versions the shared vocabulary.

This module imports neither jax nor numpy — the CLI's jax-free paths
(``stats``, ``submit``) and ``bench.py``'s signal emitters can use it
before (or without) any device touch.
"""

from tpu_life.obs.registry import (
    DEFAULT_BUCKETS,
    Counter,
    Family,
    Gauge,
    Histogram,
    MetricsRegistry,
)
from tpu_life.obs.trace import (
    DEFAULT_MAX_EVENTS,
    TELEMETRY_SCHEMA,
    Tracer,
    activate,
    active_tracer,
    ensure_parent,
    async_begin,
    async_end,
    complete,
    instant,
    new_run_id,
    new_trace_id,
    now,
    reset_span_count,
    span,
    span_count,
    start_tracing,
    stop_tracing,
    tracing,
    valid_trace_id,
)
from tpu_life.obs import console, flight, slo, stats, timeseries

__all__ = [
    "TELEMETRY_SCHEMA",
    "DEFAULT_BUCKETS",
    "DEFAULT_MAX_EVENTS",
    "Counter",
    "Family",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "Tracer",
    "activate",
    "active_tracer",
    "console",
    "slo",
    "timeseries",
    "async_begin",
    "ensure_parent",
    "async_end",
    "complete",
    "flight",
    "instant",
    "new_run_id",
    "new_trace_id",
    "now",
    "reset_span_count",
    "span",
    "span_count",
    "start_tracing",
    "stop_tracing",
    "stats",
    "tracing",
    "valid_trace_id",
]
