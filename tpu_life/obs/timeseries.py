"""Time-series retention over the metrics registry (docs/OBSERVABILITY.md
"Time series").

Every signal the stack emits today is a point-in-time snapshot: the
registry answers "what is p99 *now*", never "what was p99 over the last
five minutes" — the question an SLO burn-rate alert (obs/slo.py), the
``tpu-life top`` console, and ROADMAP item 3's autoscaler all ask.  This
module closes the gap with a per-process bounded ring of periodic
registry snapshots and *pure* windowed queries over them:

- **Counters are delta-encoded** per snapshot (the cumulative value is
  kept privately by the sampler): the windowed rate is just the sum of
  the in-window deltas over the window.  Counters are monotone within a
  process, so deltas are never negative; a worker respawn starts a NEW
  ring (fresh ``seq``), and the supervisor's :class:`SeriesStore` keys
  retention by (worker, generation) — a counter reset reads as a new
  series, never a negative rate.
- **Histogram bucket vectors stay cumulative**: the distribution
  observed inside a window is the element-wise difference of two
  snapshots' vectors, so :func:`quantile_over_window` is a two-sample
  subtraction plus the registry's interpolation rule — a pure function
  of two snapshots, replayable from any capture of them.

The ring is scraped (non-destructively) through the worker verb
``GET /v1/debug/series?cursor=N``: the scraper passes the next sequence
number it wants, gets every retained snapshot at or past it plus
``next_cursor``, and ``dropped`` counts the snapshots that were evicted
before the cursor could catch up — same bounded, drop-counted,
survivor-safe discipline as the PR 14 trace ring, except a cursor read
is repeatable (two scrapers, or a replay, see the same snapshots).

Cost discipline mirrors the tracer: a service with sampling disabled
holds no ring at all — the pump's retire tail does one ``is None``
check and nothing else — and the :func:`sample_count` probe counts real
snapshot builds so the disabled-overhead regression test can pin the
zero.

This module imports neither jax nor numpy (the obs package contract):
``tpu-life top`` and the capture read-back run login-node clean.
"""

from __future__ import annotations

import json
import threading
import time
from collections import OrderedDict, deque
from pathlib import Path

#: Versions the snapshot/wire vocabulary (bump on shape changes).
SERIES_SCHEMA = 1

#: Default per-process snapshot retention.  At the default 1 s sampling
#: cadence this holds ~8.5 minutes of history — comfortably past the
#: 5 m fast SLO window; the slow window lives in the supervisor store.
DEFAULT_MAX_SNAPSHOTS = 512

#: Default per-(worker, generation) retention in a supervisor-side
#: store: one hour of 1 Hz snapshots, the slow-window horizon.
DEFAULT_STORE_SNAPSHOTS = 3600

#: Bound on distinct (worker, generation) series a store retains; the
#: oldest series is evicted first (a months-running control plane with a
#: flapping worker must not grow without bound).
DEFAULT_STORE_SERIES = 256


# -- the disabled-cost probe (the obs.span_count discipline) --------------
_PROBE = {"samples": 0}


def sample_count() -> int:
    """Real snapshot builds since the last reset — the disabled-overhead
    regression test asserts this stays at zero when sampling is off."""
    return _PROBE["samples"]


def reset_sample_count() -> None:
    _PROBE["samples"] = 0


# -- snapshot construction ------------------------------------------------
def series_key(name: str, labels: dict) -> str:
    """The flat key one label series gets in a snapshot:
    ``name`` bare, or ``name{k=v,...}`` in label-name order — small,
    stable, and joinable with the Prometheus exposition's series."""
    if not labels:
        return name
    inner = ",".join(f"{k}={v}" for k, v in labels.items())
    return name + "{" + inner + "}"


def snapshot_registry(registry, last_counters: dict | None = None, *, t=None) -> dict:
    """One snapshot of a :class:`~tpu_life.obs.registry.MetricsRegistry`.

    ``last_counters`` is the sampler's private cumulative view from the
    previous snapshot; counters land in the snapshot as deltas against
    it (and the dict is updated in place).  Histogram vectors are
    emitted *cumulative* (counts per bucket from process start) next to
    their bucket bounds, so two snapshots subtract into a windowed
    distribution.  Pure data out: JSON-ready, no instrument references.
    """
    from tpu_life.obs.registry import Counter, Gauge, Histogram

    snap = {
        "t": time.time() if t is None else float(t),
        "c": {},
        "g": {},
        "h": {},
    }
    for fam in registry.families():
        for labels, inst in fam.series():
            key = series_key(fam.name, labels)
            if isinstance(inst, Counter):
                cum = float(inst.value)
                prev = 0.0
                if last_counters is not None:
                    prev = last_counters.get(key, 0.0)
                    last_counters[key] = cum
                snap["c"][key] = cum - prev
            elif isinstance(inst, Gauge):
                snap["g"][key] = float(inst.value)
            elif isinstance(inst, Histogram):
                cum_counts = []
                acc = 0
                for c in inst.counts:
                    acc += c
                    cum_counts.append(acc)
                snap["h"][key] = {
                    "count": inst.count,
                    "sum": inst.sum,
                    "le": list(inst.buckets),
                    # cumulative counts, one per finite bound plus +Inf
                    "buckets": cum_counts,
                }
    return snap


class SeriesRing:
    """The per-process bounded snapshot ring behind ``/v1/debug/series``.

    Appends assign monotone sequence numbers; past ``max_snapshots`` the
    oldest snapshot is evicted (flight-recorder semantics) and the loss
    is visible to any cursor that had not read it yet.  Reads are
    cursor-based and non-destructive — the scrape discipline is
    *incremental* like the trace ring's drain, but repeatable, so a
    second scraper (or a replay of the first) never races the first.
    """

    def __init__(self, max_snapshots: int = DEFAULT_MAX_SNAPSHOTS):
        if max_snapshots < 1:
            raise ValueError(f"max_snapshots must be >= 1, got {max_snapshots}")
        self.max_snapshots = max_snapshots
        self._snaps: deque = deque()
        self._next_seq = 0
        self._last_counters: dict[str, float] = {}
        self._lock = threading.Lock()

    def sample(self, registry, *, t=None) -> dict:
        """Snapshot ``registry`` and append it to the ring."""
        snap = snapshot_registry(registry, self._last_counters, t=t)
        with self._lock:
            snap["seq"] = self._next_seq
            self._next_seq += 1
            self._snaps.append(snap)
            if len(self._snaps) > self.max_snapshots:
                self._snaps.popleft()
        _PROBE["samples"] += 1
        return snap

    def read(self, cursor: int = 0) -> dict:
        """Snapshots with ``seq >= cursor``, plus the scrape bookkeeping:
        ``next_cursor`` (pass it back next time) and ``dropped`` — how
        many snapshots past the cursor were evicted before this read
        (0 when the scraper is keeping up)."""
        if cursor < 0:
            raise ValueError(f"cursor must be >= 0, got {cursor}")
        with self._lock:
            oldest = self._snaps[0]["seq"] if self._snaps else self._next_seq
            dropped = max(0, min(oldest, self._next_seq) - cursor)
            out = [s for s in self._snaps if s["seq"] >= cursor]
            return {
                "schema": SERIES_SCHEMA,
                "snapshots": out,
                "next_cursor": self._next_seq,
                "dropped": dropped,
            }

    def snapshots(self) -> list[dict]:
        with self._lock:
            return list(self._snaps)

    def __len__(self) -> int:
        with self._lock:
            return len(self._snaps)


# -- pure windowed queries ------------------------------------------------
def window_snapshots(snapshots: list[dict], window_s: float, now: float | None = None) -> list[dict]:
    """The snapshots inside ``[now - window_s, now]`` (time-ordered in =
    time-ordered out).  ``now`` defaults to the newest snapshot's stamp,
    so a replay over a capture needs no live clock."""
    if not snapshots:
        return []
    if now is None:
        now = max(s["t"] for s in snapshots)
    lo = now - window_s
    return [s for s in snapshots if lo <= s["t"] <= now]


def rate(
    snapshots: list[dict],
    key: str,
    window_s: float,
    now: float | None = None,
) -> float | None:
    """Windowed counter rate: the sum of in-window deltas over the
    window.  ``None`` when the window holds no snapshot carrying the
    key (no data is not a zero rate).  Deltas are non-negative by
    construction — a reset is a different (worker, generation) series,
    never a negative contribution here."""
    win = window_snapshots(snapshots, window_s, now)
    hits = [s["c"][key] for s in win if key in s.get("c", {})]
    if not hits:
        return None
    return sum(hits) / window_s if window_s > 0 else None


def hist_window(older: dict | None, newer: dict, key: str) -> dict | None:
    """The distribution observed between two snapshots: element-wise
    difference of their cumulative bucket vectors.

    ``older=None`` (or an older snapshot without the key) reads as
    "since series start" — the newer vector alone.  A negative
    difference means the two snapshots straddle a counter reset (two
    generations mixed into one series by a caller): the window falls
    back to the newer snapshot alone — the new series — instead of ever
    producing negative mass.  Returns ``None`` when the newer snapshot
    does not carry the key."""
    h1 = newer.get("h", {}).get(key)
    if h1 is None:
        return None
    h0 = older.get("h", {}).get(key) if older is not None else None
    if h0 is None or h0.get("le") != h1.get("le"):
        return {"le": list(h1["le"]), "buckets": list(h1["buckets"]),
                "count": h1["count"], "sum": h1["sum"]}
    diff = [b1 - b0 for b0, b1 in zip(h0["buckets"], h1["buckets"])]
    if any(d < 0 for d in diff) or h1["count"] < h0["count"]:
        # counter reset inside the pair: the newer snapshot IS the new
        # series — read it alone, never report negative mass
        return {"le": list(h1["le"]), "buckets": list(h1["buckets"]),
                "count": h1["count"], "sum": h1["sum"]}
    return {
        "le": list(h1["le"]),
        "buckets": diff,
        "count": h1["count"] - h0["count"],
        "sum": h1["sum"] - h0["sum"],
    }


def quantile_from_cumulative(le: list, buckets: list, q: float) -> float | None:
    """The registry's interpolation rule over a cumulative bucket vector
    (``le`` = finite upper bounds; ``buckets`` has one extra +Inf slot).

    Without per-window min/max there is nothing to clamp against, so the
    estimate interpolates inside the target bucket; a rank landing in
    the +Inf tail returns the highest finite bound — the documented
    honest *lower* bound for the tail (there is no finite upper one)."""
    if not 0.0 <= q <= 1.0:
        raise ValueError(f"quantile must be in [0, 1], got {q}")
    total = buckets[-1] if buckets else 0
    if not total:
        return None
    rank = q * total
    lo = 0.0
    for i, bound in enumerate(le):
        cum = buckets[i]
        if cum >= rank:
            prev = buckets[i - 1] if i else 0
            in_bucket = cum - prev
            if not in_bucket:
                return bound
            return lo + (bound - lo) * (rank - prev) / in_bucket
        lo = bound
    return le[-1] if le else None


def quantile_over_window(
    older: dict | None, newer: dict, key: str, q: float
) -> float | None:
    """Windowed quantile as a pure function of two snapshots: subtract
    the cumulative vectors (:func:`hist_window`), interpolate the rank.
    ``None`` on an empty window (no observations between the samples)."""
    win = hist_window(older, newer, key)
    if win is None or not win["count"]:
        return None
    return quantile_from_cumulative(win["le"], win["buckets"], q)


def merge_hist_windows(windows: list[dict]) -> dict | None:
    """Sum windowed distributions across series (a fleet's workers):
    element-wise bucket addition.  Series with mismatched bounds are
    skipped (never silently misbinned); ``None`` when nothing merges."""
    windows = [w for w in windows if w is not None]
    if not windows:
        return None
    le = windows[0]["le"]
    merged = None
    for w in windows:
        if w["le"] != le:
            continue
        if merged is None:
            merged = {"le": list(le), "buckets": list(w["buckets"]),
                      "count": w["count"], "sum": w["sum"]}
        else:
            merged["buckets"] = [
                a + b for a, b in zip(merged["buckets"], w["buckets"])
            ]
            merged["count"] += w["count"]
            merged["sum"] += w["sum"]
    return merged


# -- the supervisor-side store --------------------------------------------
class SeriesStore:
    """Fleet-wide snapshot retention keyed by (worker, generation).

    Each scrape of a worker's ring lands here (and, with ``--trace-dir``,
    in the ``<name>.series.jsonl`` capture file).  Keying by generation
    is what makes counter continuity hold across a respawn: the dead
    incarnation's deltas stay under its own key, the successor starts a
    fresh series, and a windowed query sums *deltas* across series —
    no subtraction ever crosses a generation boundary."""

    def __init__(
        self,
        max_snapshots: int = DEFAULT_STORE_SNAPSHOTS,
        max_series: int = DEFAULT_STORE_SERIES,
    ):
        self.max_snapshots = max_snapshots
        self.max_series = max_series
        self._series: OrderedDict[tuple[str, int], deque] = OrderedDict()
        #: scrape-reported eviction losses per (worker, generation) —
        #: snapshots the ring dropped before the scraper caught up
        self.dropped: dict[tuple[str, int], int] = {}
        self._lock = threading.Lock()

    def extend(
        self, worker: str, generation: int, snapshots: list[dict], dropped: int = 0
    ) -> None:
        key = (worker, int(generation))
        with self._lock:
            dq = self._series.get(key)
            if dq is None:
                dq = self._series[key] = deque(maxlen=self.max_snapshots)
                while len(self._series) > self.max_series:
                    old, _ = self._series.popitem(last=False)
                    self.dropped.pop(old, None)
            seen = dq[-1]["seq"] if dq and "seq" in dq[-1] else -1
            for s in snapshots:
                # a re-scraped overlap (repeatable cursor reads) folds
                # away on seq: only genuinely new snapshots append
                if s.get("seq", seen + 1) > seen:
                    dq.append(s)
                    seen = s.get("seq", seen + 1)
            if dropped:
                self.dropped[key] = self.dropped.get(key, 0) + int(dropped)

    def series_keys(self) -> list[tuple[str, int]]:
        with self._lock:
            return list(self._series)

    def get(self, worker: str, generation: int) -> list[dict]:
        with self._lock:
            return list(self._series.get((worker, int(generation)), ()))

    def all_series(self, worker: str | None = None) -> dict[tuple[str, int], list[dict]]:
        with self._lock:
            return {
                k: list(v)
                for k, v in self._series.items()
                if worker is None or k[0] == worker
            }

    # -- fleet-wide windowed queries (pure over the retained snapshots) --
    def fleet_rate(
        self, key: str, window_s: float, now: float | None = None
    ) -> tuple[float, dict[str, float]] | None:
        """Summed windowed rate across every retained series, plus the
        per-worker contributions (the breach's "top contributing label").
        ``None`` when no series carries the key in the window."""
        per_worker: dict[str, float] = {}
        any_hit = False
        for (worker, _gen), snaps in self.all_series().items():
            r = rate(snaps, key, window_s, now)
            if r is None:
                continue
            any_hit = True
            per_worker[worker] = per_worker.get(worker, 0.0) + r
        if not any_hit:
            return None
        return sum(per_worker.values()), per_worker

    def fleet_gauge(
        self, key: str, max_age_s: float | None = None, now: float | None = None
    ) -> tuple[float, dict[str, float]] | None:
        """Summed *latest* gauge value across the fleet, plus per-worker
        contributions — the instantaneous-load read the autoscaler keys
        on (queue depth, estimated bytes).  Only each worker's newest
        generation counts (a dead incarnation's final gauge must not
        double-count against its successor), and with ``max_age_s`` set,
        series whose newest snapshot is older than that are skipped —
        a wedged worker's stale gauge is not demand.  ``None`` when no
        live series carries the key."""
        newest_gen: dict[str, int] = {}
        for worker, gen in self.series_keys():
            if gen >= newest_gen.get(worker, gen):
                newest_gen[worker] = gen
        per_worker: dict[str, float] = {}
        any_hit = False
        t_ref = now
        if t_ref is None and max_age_s is not None:
            stamps = [
                snaps[-1]["t"]
                for snaps in self.all_series().values()
                if snaps
            ]
            t_ref = max(stamps) if stamps else None
        for (worker, gen), snaps in self.all_series().items():
            if gen != newest_gen.get(worker) or not snaps:
                continue
            last = snaps[-1]
            if (
                max_age_s is not None
                and t_ref is not None
                and last["t"] < t_ref - max_age_s
            ):
                continue
            v = last.get("g", {}).get(key)
            if v is None:
                continue
            any_hit = True
            per_worker[worker] = per_worker.get(worker, 0.0) + float(v)
        if not any_hit:
            return None
        return sum(per_worker.values()), per_worker

    def fleet_quantile(
        self, key: str, q: float, window_s: float, now: float | None = None
    ) -> tuple[float, dict[str, int]] | None:
        """Fleet-wide windowed quantile: per series, subtract the newest
        in-window snapshot from the one just before the window (series
        start when none), merge the distributions, interpolate.  Also
        returns per-worker in-window observation counts (the top
        contributor).  ``None`` on an empty fleet window."""
        windows = []
        counts: dict[str, int] = {}
        for (worker, _gen), snaps in self.all_series().items():
            if not snaps:
                continue
            t_now = now if now is not None else max(s["t"] for s in snaps)
            lo = t_now - window_s
            inside = [s for s in snaps if lo <= s["t"] <= t_now]
            if not inside:
                continue
            before = [s for s in snaps if s["t"] < lo]
            older = before[-1] if before else None
            win = hist_window(older, inside[-1], key)
            if win is None or not win["count"]:
                continue
            windows.append(win)
            counts[worker] = counts.get(worker, 0) + win["count"]
        merged = merge_hist_windows(windows)
        if merged is None or not merged["count"]:
            return None
        return quantile_from_cumulative(merged["le"], merged["buckets"], q), counts


# -- capture read-back ----------------------------------------------------
def load_series_capture(path: str) -> SeriesStore:
    """Rebuild a :class:`SeriesStore` from a fleet's ``*.series.jsonl``
    capture files (a directory, or one file) — the replay path behind
    the acceptance drill: every windowed query over the store is a pure
    function of these scraped snapshots.  A torn final line (killed
    collector) is tolerated, the stats-loader rule."""
    p = Path(path)
    files = sorted(p.glob("*.series.jsonl")) if p.is_dir() else [p]
    if p.is_dir() and not files:
        raise FileNotFoundError(f"no *.series.jsonl capture files under {path}")
    store = SeriesStore()
    for f in files:
        lines = f.read_text().splitlines()
        for lineno, line in enumerate(lines, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                if lineno == len(lines):
                    break  # torn tail: a killed writer, not a bad capture
                raise ValueError(f"{f}:{lineno}: bad series record: {e}") from e
            store.extend(
                str(rec.get("worker", "?")),
                int(rec.get("generation", 0)),
                rec.get("snapshots") or [],
                dropped=int(rec.get("dropped", 0)),
            )
    return store
