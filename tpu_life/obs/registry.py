"""A labeled metrics registry: Counter / Gauge / Histogram families.

The serving layer needs latency *distributions* (queue wait, completion)
and the driver needs compile counts and chunk-duration spread — plain
per-chunk JSONL lines can't answer "what is p95 queue wait".  This is the
minimal production shape: metric *families* keyed by name, label *series*
under each family, and two exporters — records in the existing metrics
JSONL vocabulary (so one sink file carries both the per-chunk stream and
the end-of-run aggregates) and a Prometheus text-exposition snapshot.

Cardinality is bounded by construction: each family accepts at most
``max_series`` distinct label combinations; the first combination past the
cap is collapsed into a single ``__overflow__`` series (with one warning),
so a misbehaving label value — a raw session id, an unbucketed shape —
can degrade a metric's resolution but never grow memory without bound.
Label values must come from small closed sets by convention: backend
names, rule names, CompileKey buckets (``rule:HxW:backend``).

Histograms are fixed-bucket (Prometheus style): observation cost is one
bisect, memory is ``len(buckets)+1`` ints, and quantiles are estimated by
linear interpolation inside the bucket containing the target rank,
clamped to the observed min/max (exact at the extremes, documented
approximation in between — the standard trade for bounded memory).
"""

from __future__ import annotations

import logging
from bisect import bisect_left

log = logging.getLogger("tpu_life")

#: Default histogram buckets (seconds): Prometheus' latency defaults plus a
#: 1 ms floor bucket — serve chunk rounds on CPU tests land well under 5 ms.
DEFAULT_BUCKETS = (
    0.001,
    0.0025,
    0.005,
    0.01,
    0.025,
    0.05,
    0.1,
    0.25,
    0.5,
    1.0,
    2.5,
    5.0,
    10.0,
)

#: Default per-family series cap (distinct label combinations).
MAX_SERIES = 64

OVERFLOW = "__overflow__"


class Counter:
    """Monotonically increasing count."""

    kind = "counter"

    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(f"counter increments must be >= 0, got {n}")
        self.value += n

    def state(self) -> dict:
        return {"value": self.value}


class Gauge:
    """A value that goes up and down (queue depth, occupancy)."""

    kind = "gauge"

    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0

    def set(self, v: float) -> None:
        self.value = float(v)

    def inc(self, n: float = 1.0) -> None:
        self.value += n

    def dec(self, n: float = 1.0) -> None:
        self.value -= n

    def state(self) -> dict:
        return {"value": self.value}


class Histogram:
    """Fixed-bucket distribution with quantile estimation.

    ``buckets`` are inclusive upper bounds (ascending); one implicit
    ``+Inf`` bucket catches the tail.  ``quantile(q)`` walks the
    cumulative counts to the bucket holding rank ``q * count`` and
    interpolates linearly inside it; results are clamped to the observed
    ``[min, max]``, so ``quantile(0.0) == min`` and ``quantile(1.0) == max``
    exactly.  Empty histograms return ``None``.
    """

    kind = "histogram"

    __slots__ = ("buckets", "counts", "count", "sum", "min", "max")

    def __init__(self, buckets: tuple = DEFAULT_BUCKETS):
        b = tuple(float(x) for x in buckets)
        if not b or any(b[i] >= b[i + 1] for i in range(len(b) - 1)):
            raise ValueError(f"buckets must be ascending and non-empty, got {buckets}")
        self.buckets = b
        self.counts = [0] * (len(b) + 1)  # last = +Inf
        self.count = 0
        self.sum = 0.0
        self.min: float | None = None
        self.max: float | None = None

    def observe(self, v: float) -> None:
        v = float(v)
        self.counts[bisect_left(self.buckets, v)] += 1
        self.count += 1
        self.sum += v
        self.min = v if self.min is None else min(self.min, v)
        self.max = v if self.max is None else max(self.max, v)

    def quantile(self, q: float) -> float | None:
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile must be in [0, 1], got {q}")
        if self.count == 0:
            return None
        assert self.min is not None and self.max is not None
        rank = q * self.count
        cum = 0
        lo = 0.0
        for i, c in enumerate(self.counts):
            if c == 0:
                lo = self.buckets[i] if i < len(self.buckets) else lo
                continue
            if cum + c >= rank:
                if i >= len(self.buckets):
                    # +Inf bucket: no finite upper bound — the observed max
                    # is the only honest estimate for the tail
                    return self.max
                hi = self.buckets[i]
                est = lo + (hi - lo) * (rank - cum) / c
                return min(max(est, self.min), self.max)
            cum += c
            lo = self.buckets[i] if i < len(self.buckets) else lo
        return self.max

    def state(self) -> dict:
        rec = {
            "count": self.count,
            "sum": self.sum,
            "min": self.min,
            "max": self.max,
            # per-bucket (non-cumulative) counts keyed by upper bound; the
            # stats toolchain can re-derive quantiles from these
            "buckets": {
                **{repr(b): c for b, c in zip(self.buckets, self.counts)},
                "+Inf": self.counts[-1],
            },
        }
        for name, q in (("p50", 0.5), ("p95", 0.95), ("p99", 0.99)):
            rec[name] = self.quantile(q)
        return rec


class Family:
    """One named metric family: label series of a single instrument kind."""

    def __init__(
        self,
        name: str,
        cls,
        help: str = "",
        labelnames: tuple = (),
        max_series: int = MAX_SERIES,
        **instrument_kwargs,
    ):
        self.name = name
        self.cls = cls
        self.help = help
        self.labelnames = tuple(labelnames)
        self.max_series = max_series
        self._kwargs = instrument_kwargs
        self._series: dict[tuple, object] = {}
        self._warned_overflow = False

    def labels(self, **labelvalues):
        """The instrument for one label combination (created on first use;
        past the cardinality cap, the shared ``__overflow__`` series)."""
        if set(labelvalues) != set(self.labelnames):
            raise ValueError(
                f"metric {self.name!r} takes labels {self.labelnames}, "
                f"got {tuple(labelvalues)}"
            )
        key = tuple(str(labelvalues[n]) for n in self.labelnames)
        inst = self._series.get(key)
        if inst is None:
            if len(self._series) >= self.max_series and key != self._overflow_key():
                if not self._warned_overflow:
                    self._warned_overflow = True
                    log.warning(
                        "metric %s exceeded its %d-series label cardinality "
                        "cap; further label combinations collapse into %s",
                        self.name,
                        self.max_series,
                        OVERFLOW,
                    )
                key = self._overflow_key()
                inst = self._series.get(key)
                if inst is not None:
                    return inst
            inst = self._series[key] = self.cls(**self._kwargs)
        return inst

    def _overflow_key(self) -> tuple:
        return tuple(OVERFLOW for _ in self.labelnames)

    # unlabeled convenience: a family declared with no labelnames behaves
    # like its single instrument
    def _default(self):
        return self.labels()

    def inc(self, n: float = 1.0) -> None:
        self._default().inc(n)

    def set(self, v: float) -> None:
        self._default().set(v)

    def observe(self, v: float) -> None:
        self._default().observe(v)

    def quantile(self, q: float):
        return self._default().quantile(q)

    @property
    def value(self):
        return self._default().value

    def series(self) -> list[tuple[dict, object]]:
        """(labels dict, instrument) per series, insertion-ordered."""
        return [
            (dict(zip(self.labelnames, key)), inst)
            for key, inst in self._series.items()
        ]


class MetricsRegistry:
    """Registered metric families plus the two exporters.

    Registration is idempotent: asking for an existing name with the same
    kind and labelnames returns the existing family (so layers can declare
    their instruments independently); a kind or label mismatch raises.
    """

    def __init__(self):
        self._families: dict[str, Family] = {}

    def _register(self, name, cls, help, labels, max_series, **kwargs) -> Family:
        fam = self._families.get(name)
        if fam is not None:
            if fam.cls is not cls or fam.labelnames != tuple(labels):
                raise ValueError(
                    f"metric {name!r} already registered as {fam.cls.kind} "
                    f"with labels {fam.labelnames}"
                )
            return fam
        fam = self._families[name] = Family(
            name, cls, help=help, labelnames=tuple(labels),
            max_series=max_series, **kwargs,
        )
        return fam

    def counter(
        self, name: str, help: str = "", labels: tuple = (),
        max_series: int = MAX_SERIES,
    ) -> Family:
        return self._register(name, Counter, help, labels, max_series)

    def gauge(
        self, name: str, help: str = "", labels: tuple = (),
        max_series: int = MAX_SERIES,
    ) -> Family:
        return self._register(name, Gauge, help, labels, max_series)

    def histogram(
        self, name: str, help: str = "", labels: tuple = (),
        buckets: tuple = DEFAULT_BUCKETS, max_series: int = MAX_SERIES,
    ) -> Family:
        return self._register(
            name, Histogram, help, labels, max_series, buckets=buckets
        )

    def families(self) -> list[Family]:
        return list(self._families.values())

    # -- exporters --------------------------------------------------------
    def snapshot(self, run_id: str | None = None) -> list[dict]:
        """One record per series in the metrics-JSONL vocabulary
        (``kind: "metric"``) — appended to the same sink file as the
        per-chunk stream, read back by ``tpu-life stats``."""
        out = []
        for fam in self._families.values():
            for labels, inst in fam.series():
                rec = {
                    "kind": "metric",
                    "metric": fam.name,
                    "type": inst.kind,
                    "labels": labels,
                    **inst.state(),
                }
                if run_id is not None:
                    rec["run_id"] = run_id
                out.append(rec)
        return out

    def prom_text(self) -> str:
        """Prometheus text exposition (one snapshot, not a live endpoint —
        write it to ``--prom-file`` for node-exporter-style file scraping)."""
        lines: list[str] = []
        for fam in self._families.values():
            series = fam.series()
            if not series:
                continue
            if fam.help:
                lines.append(f"# HELP {fam.name} {fam.help}")
            lines.append(f"# TYPE {fam.name} {fam.cls.kind}")
            for labels, inst in series:
                if isinstance(inst, Histogram):
                    cum = 0
                    for b, c in zip(inst.buckets, inst.counts):
                        cum += c
                        lines.append(
                            f"{fam.name}_bucket"
                            f"{_prom_labels({**labels, 'le': _fmt(b)})} {cum}"
                        )
                    lines.append(
                        f"{fam.name}_bucket"
                        f"{_prom_labels({**labels, 'le': '+Inf'})} {inst.count}"
                    )
                    lines.append(
                        f"{fam.name}_sum{_prom_labels(labels)} {_fmt(inst.sum)}"
                    )
                    lines.append(
                        f"{fam.name}_count{_prom_labels(labels)} {inst.count}"
                    )
                else:
                    lines.append(
                        f"{fam.name}{_prom_labels(labels)} {_fmt(inst.value)}"
                    )
        return "\n".join(lines) + ("\n" if lines else "")


def _fmt(v: float) -> str:
    # integral values print without the trailing .0 (matches prom tooling)
    return str(int(v)) if float(v).is_integer() else repr(float(v))


def _escape(v: str) -> str:
    return v.replace("\\", r"\\").replace('"', r"\"").replace("\n", r"\n")


def _prom_labels(labels: dict) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape(str(v))}"' for k, v in labels.items())
    return "{" + inner + "}"
