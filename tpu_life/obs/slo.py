"""Declarative SLOs with multi-window burn-rate alerting
(docs/OBSERVABILITY.md "SLOs and burn rates").

The time-series store (obs/timeseries.py) answers "what was p99 over the
last five minutes"; this module decides whether that answer is a page.
SLOs are **declarative specs** — a metric, an objective, and two
evaluation windows — loaded from a JSON/TOML file (``fleet --slo FILE``)
or the built-in defaults, and evaluated on the supervisor's monitor tick
with the SRE multi-window burn-rate rule: alert only when BOTH the fast
window (default 5 m — catches a cliff quickly) and the slow window
(default 1 h — suppresses blips the budget can absorb) burn the error
budget at or past the threshold.  A **breach** emits a typed
``slo.breach`` flight-recorder event and a trace instant carrying the
window, observed vs objective, the burn rate, and the top contributing
worker — so ``tpu-life doctor --slo CAPTURE`` can join a breach to its
cause (a kill, an OOM ladder walk, a watcher shed storm) the same way
the doctor joins migrations today.

Three spec kinds cover the stack's failure surface:

- ``quantile``: a latency bound — windowed p\\ *q* of a histogram family
  vs an objective in seconds (burn = observed / objective);
- ``ratio``: an error-budget fraction — a "bad" counter's windowed rate
  over a "total" counter's, vs an objective fraction;
- ``recovery``: a liveness bound — wall seconds from a worker's death to
  its replacement probing READY, fed by the supervisor's exit/ready
  hooks rather than the store (the victim can't report its own wake).

Spec files: JSON always works; TOML works on Python ≥ 3.11 (stdlib
``tomllib``) and falls back to a minimal flat-table subset parser on
older interpreters — no third-party dependency either way.

Pure stdlib, no jax/numpy (the obs package contract).
"""

from __future__ import annotations

import json
import time
from dataclasses import dataclass, field
from pathlib import Path

from tpu_life.obs import flight, trace
from tpu_life.obs.timeseries import SeriesStore

#: SRE fast window: long enough for a real rate, short enough to page
#: before the budget is gone.
DEFAULT_FAST_WINDOW_S = 300.0

#: SRE slow window: the budget-absorption horizon.
DEFAULT_SLOW_WINDOW_S = 3600.0

#: Burn >= this in BOTH windows -> breach.  1.0 means "consuming budget
#: exactly at the objective" — the conservative default for a reference
#: stack; production alerting typically sets 2–14.
DEFAULT_BURN_THRESHOLD = 1.0

#: Seconds a breaching SLO stays quiet after firing (a breach is a
#: state, the event marks its edge; refiring every tick would flood the
#: flight ring that postmortems depend on).
REFIRE_SUPPRESS_S = 30.0

VALID_KINDS = ("quantile", "ratio", "recovery")


@dataclass(frozen=True)
class SloSpec:
    """One declarative objective.  ``metric``/``bad``/``total`` name
    series keys in the sampled snapshots (``obs.timeseries.series_key``
    form: bare family name, or ``name{label=value}``)."""

    name: str
    kind: str
    objective: float
    metric: str = ""          # quantile: histogram key; unused for ratio
    bad: str = ""             # ratio: numerator counter key
    total: str = ""           # ratio: denominator counter key
    q: float = 0.99           # quantile: which quantile
    fast_window_s: float = DEFAULT_FAST_WINDOW_S
    slow_window_s: float = DEFAULT_SLOW_WINDOW_S
    burn_threshold: float = DEFAULT_BURN_THRESHOLD

    def __post_init__(self):
        if self.kind not in VALID_KINDS:
            raise ValueError(
                f"slo {self.name!r}: kind must be one of {VALID_KINDS}, "
                f"got {self.kind!r}"
            )
        if self.objective <= 0:
            raise ValueError(
                f"slo {self.name!r}: objective must be > 0, got {self.objective}"
            )
        if self.kind == "quantile" and not self.metric:
            raise ValueError(f"slo {self.name!r}: quantile kind needs a metric")
        if self.kind == "ratio" and not (self.bad and self.total):
            raise ValueError(f"slo {self.name!r}: ratio kind needs bad and total")
        if not 0.0 <= self.q <= 1.0:
            raise ValueError(f"slo {self.name!r}: q must be in [0, 1], got {self.q}")
        if self.fast_window_s <= 0 or self.slow_window_s < self.fast_window_s:
            raise ValueError(
                f"slo {self.name!r}: need 0 < fast_window_s <= slow_window_s"
            )


def default_specs() -> list[SloSpec]:
    """The built-in objectives — one per tier of the serving story."""
    return [
        SloSpec(
            name="admission-p99",
            kind="quantile",
            metric="serve_queue_wait_seconds",
            q=0.99,
            objective=1.0,
        ),
        SloSpec(
            name="session-success",
            kind="ratio",
            bad='serve_sessions_finished_total{state=failed}',
            total="serve_sessions_finished_total",
            objective=0.01,
        ),
        SloSpec(
            name="frame-gap",
            kind="ratio",
            bad="stream_frame_gaps_total",
            total="stream_frames_total",
            objective=0.01,
        ),
        SloSpec(
            name="recovery-time",
            kind="recovery",
            objective=30.0,
        ),
    ]


# -- spec loading ---------------------------------------------------------
_NUM_FIELDS = ("objective", "q", "fast_window_s", "slow_window_s", "burn_threshold")
_STR_FIELDS = ("name", "kind", "metric", "bad", "total")


def _spec_from_dict(d: dict, where: str) -> SloSpec:
    unknown = set(d) - set(_NUM_FIELDS) - set(_STR_FIELDS)
    if unknown:
        raise ValueError(f"{where}: unknown slo field(s) {sorted(unknown)}")
    kw = {}
    for k in _STR_FIELDS:
        if k in d:
            kw[k] = str(d[k])
    for k in _NUM_FIELDS:
        if k in d:
            try:
                kw[k] = float(d[k])
            except (TypeError, ValueError):
                raise ValueError(f"{where}: field {k!r} must be a number") from None
    if "name" not in kw or "kind" not in kw or "objective" not in kw:
        raise ValueError(f"{where}: an slo needs name, kind, and objective")
    return SloSpec(**kw)


def _parse_toml_subset(text: str, where: str) -> dict:
    """The spec grammar's TOML subset, for interpreters without
    ``tomllib`` (< 3.11): ``[[slo]]`` array-of-tables whose entries are
    flat ``key = value`` scalars (strings, numbers, booleans).  Anything
    richer (nested tables, arrays, multi-line strings) raises with a
    pointer at the line — use JSON there."""
    slos: list[dict] = []
    current: dict | None = None
    for lineno, raw in enumerate(text.splitlines(), 1):
        line = raw.split("#", 1)[0].strip() if not raw.strip().startswith("#") else ""
        if not line:
            continue
        if line == "[[slo]]":
            current = {}
            slos.append(current)
            continue
        if line.startswith("["):
            raise ValueError(
                f"{where}:{lineno}: only [[slo]] tables are supported by the "
                f"built-in TOML subset reader (Python < 3.11); use JSON for "
                f"richer specs"
            )
        if "=" not in line or current is None:
            raise ValueError(f"{where}:{lineno}: expected key = value inside [[slo]]")
        key, _, val = line.partition("=")
        key, val = key.strip(), val.strip()
        if val.startswith('"') and val.endswith('"') and len(val) >= 2:
            current[key] = val[1:-1]
        elif val.startswith("'") and val.endswith("'") and len(val) >= 2:
            current[key] = val[1:-1]
        elif val in ("true", "false"):
            current[key] = val == "true"
        else:
            try:
                current[key] = float(val) if "." in val or "e" in val.lower() else int(val)
            except ValueError:
                raise ValueError(
                    f"{where}:{lineno}: unsupported value {val!r} (subset "
                    f"reader takes strings, numbers, booleans)"
                ) from None
    return {"slo": slos}


def load_specs(path: str) -> list[SloSpec]:
    """Load SLO specs from a ``.json`` or ``.toml`` file.

    JSON shape: ``{"slo": [{...}, ...]}`` (or a bare list).  TOML shape:
    one ``[[slo]]`` table per objective.  TOML parses with stdlib
    ``tomllib`` when available (Python ≥ 3.11), else the flat-subset
    reader — same grammar, no new dependency."""
    p = Path(path)
    text = p.read_text()
    where = str(p)
    if p.suffix.lower() == ".toml":
        try:
            import tomllib  # Python >= 3.11

            data = tomllib.loads(text)
        except ModuleNotFoundError:
            data = _parse_toml_subset(text, where)
        except Exception as e:  # tomllib.TOMLDecodeError
            raise ValueError(f"{where}: bad TOML: {e}") from e
    else:
        try:
            data = json.loads(text)
        except json.JSONDecodeError as e:
            raise ValueError(f"{where}: bad JSON: {e}") from e
    if isinstance(data, list):
        raw = data
    elif isinstance(data, dict):
        raw = data.get("slo")
        if raw is None:
            raise ValueError(f'{where}: expected {{"slo": [...]}} or a bare list')
    else:
        raise ValueError(f"{where}: expected a list or table of slo specs")
    specs = [
        _spec_from_dict(d, f"{where} slo[{i}]") for i, d in enumerate(raw)
    ]
    names = [s.name for s in specs]
    dupes = {n for n in names if names.count(n) > 1}
    if dupes:
        raise ValueError(f"{where}: duplicate slo name(s) {sorted(dupes)}")
    if not specs:
        raise ValueError(f"{where}: no slo specs defined")
    return specs


# -- the engine -----------------------------------------------------------
@dataclass
class _RecoveryState:
    exit_t: float
    generation: int
    breached: bool = False  # already fired for this outage


class SloEngine:
    """Evaluates specs against a :class:`SeriesStore` on every call to
    :meth:`evaluate` (the supervisor's monitor tick).  Windows clamp to
    the data actually retained — a fleet ten seconds old is judged on
    ten seconds, not absolved by an empty hour.  Not thread-safe on its
    own: the supervisor calls it from the tick thread only."""

    def __init__(
        self,
        specs: list[SloSpec],
        store: SeriesStore,
        *,
        clock=time.time,
    ):
        self.specs = list(specs)
        self.store = store
        self.clock = clock
        self._last_fire: dict[str, float] = {}
        self._outages: dict[str, _RecoveryState] = {}
        self._status: dict[str, dict] = {
            s.name: {"kind": s.kind, "objective": s.objective, "burn_fast": None,
                     "burn_slow": None, "observed": None, "breaching": False}
            for s in self.specs
        }
        self.breaches_fired = 0

    # -- recovery hooks (the supervisor's exit/ready path) ---------------
    def note_worker_exit(self, worker: str, generation: int, t: float | None = None) -> None:
        """A worker incarnation died un-drained; the recovery clock for
        its name starts now (an already-open outage keeps its original
        edge — a crash-looping respawn does not reset the clock)."""
        t = self.clock() if t is None else t
        if worker not in self._outages:
            self._outages[worker] = _RecoveryState(exit_t=t, generation=int(generation))

    def note_worker_ready(self, worker: str, generation: int, t: float | None = None) -> None:
        """A worker probed READY; if its name had an open outage, the
        recovery time is judged against every ``recovery`` spec."""
        state = self._outages.pop(worker, None)
        if state is None:
            return
        t = self.clock() if t is None else t
        took = max(0.0, t - state.exit_t)
        for spec in self.specs:
            if spec.kind != "recovery":
                continue
            st = self._status[spec.name]
            st["observed"] = took
            burn = took / spec.objective
            st["burn_fast"] = st["burn_slow"] = burn
            if took > spec.objective and not state.breached:
                self._fire(
                    spec, observed=took, burn=burn, window_s=took,
                    worker=worker, detail=f"recovered after {took:.3f}s",
                )
            st["breaching"] = took > spec.objective

    # -- evaluation -------------------------------------------------------
    def evaluate(self, now: float | None = None) -> list[dict]:
        """One burn-rate pass over every spec; returns the breaches
        fired THIS pass (already recorded to the flight ring)."""
        now = self.clock() if now is None else now
        fired = []
        for spec in self.specs:
            if spec.kind == "quantile":
                ev = self._eval_quantile(spec, now)
            elif spec.kind == "ratio":
                ev = self._eval_ratio(spec, now)
            else:
                ev = self._eval_recovery_open(spec, now)
            if ev is not None:
                fired.append(ev)
        return fired

    def _eval_quantile(self, spec: SloSpec, now: float) -> dict | None:
        fast = self.store.fleet_quantile(spec.metric, spec.q, spec.fast_window_s, now)
        slow = self.store.fleet_quantile(spec.metric, spec.q, spec.slow_window_s, now)
        st = self._status[spec.name]
        if fast is None or slow is None:
            st.update(burn_fast=None, burn_slow=None, observed=None, breaching=False)
            return None
        obs_fast, contrib = fast
        obs_slow, _ = slow
        burn_fast = obs_fast / spec.objective
        burn_slow = obs_slow / spec.objective
        st.update(burn_fast=burn_fast, burn_slow=burn_slow, observed=obs_fast)
        return self._judge(spec, burn_fast, burn_slow, obs_fast, contrib, now)

    def _eval_ratio(self, spec: SloSpec, now: float) -> dict | None:
        st = self._status[spec.name]

        def ratio_in(window_s):
            total = self.store.fleet_rate(spec.total, window_s, now)
            if total is None or total[0] <= 0:
                return None, None
            bad = self.store.fleet_rate(spec.bad, window_s, now)
            bad_rate, contrib = (0.0, {}) if bad is None else bad
            return bad_rate / total[0], contrib

        r_fast, contrib = ratio_in(spec.fast_window_s)
        r_slow, _ = ratio_in(spec.slow_window_s)
        if r_fast is None or r_slow is None:
            st.update(burn_fast=None, burn_slow=None, observed=None, breaching=False)
            return None
        burn_fast = r_fast / spec.objective
        burn_slow = r_slow / spec.objective
        st.update(burn_fast=burn_fast, burn_slow=burn_slow, observed=r_fast)
        return self._judge(spec, burn_fast, burn_slow, r_fast, contrib, now)

    def _eval_recovery_open(self, spec: SloSpec, now: float) -> dict | None:
        """An outage still open past the objective is a breach already —
        waiting for the ready edge would let a worker that never comes
        back never page."""
        st = self._status[spec.name]
        worst = None
        for worker, state in self._outages.items():
            down_for = now - state.exit_t
            if worst is None or down_for > worst[1]:
                worst = (worker, down_for, state)
        if worst is None:
            st["breaching"] = False
            return None
        worker, down_for, state = worst
        st["observed"] = down_for
        burn = down_for / spec.objective
        st["burn_fast"] = st["burn_slow"] = burn
        st["breaching"] = down_for > spec.objective
        if down_for > spec.objective and not state.breached:
            state.breached = True
            return self._fire(
                spec, observed=down_for, burn=burn, window_s=down_for,
                worker=worker, detail=f"down {down_for:.3f}s and counting",
            )
        return None

    def _judge(
        self, spec: SloSpec, burn_fast: float, burn_slow: float,
        observed: float, contrib: dict, now: float,
    ) -> dict | None:
        breaching = (
            burn_fast >= spec.burn_threshold and burn_slow >= spec.burn_threshold
        )
        self._status[spec.name]["breaching"] = breaching
        if not breaching:
            return None
        last = self._last_fire.get(spec.name)
        if last is not None and now - last < REFIRE_SUPPRESS_S:
            return None
        top = max(contrib, key=contrib.get) if contrib else None
        return self._fire(
            spec, observed=observed, burn=burn_fast,
            window_s=spec.fast_window_s, worker=top, now=now,
        )

    def _fire(
        self, spec: SloSpec, *, observed: float, burn: float, window_s: float,
        worker: str | None, detail: str | None = None, now: float | None = None,
    ) -> dict:
        now = self.clock() if now is None else now
        self._last_fire[spec.name] = now
        self.breaches_fired += 1
        ev = {
            "slo": spec.name,
            "slo_kind": spec.kind,
            "window_s": round(window_s, 3),
            "observed": round(observed, 6),
            "objective": spec.objective,
            "burn": round(burn, 3),
            "worker": worker,
        }
        if detail:
            ev["detail"] = detail
        flight.record("slo.breach", **ev)
        trace.instant("slo.breach", **ev)
        return ev

    def status(self) -> dict:
        """The burn gauges ``/healthz`` and ``tpu-life top`` show:
        per-slo kind, objective, fast/slow burn, observed, breaching."""
        return {name: dict(st) for name, st in self._status.items()}


# -- the doctor join ------------------------------------------------------
#: How far (seconds) before a breach the doctor looks for its cause.
CAUSE_HORIZON_S = 120.0

#: Event names that count as a plausible breach cause, best first.
_CAUSE_NAMES = (
    "flight.worker.exit",
    "flight.lease.expired",
    "flight.chaos.injection",
    "chaos.injection",
    "flight.engine.recovery",
    "flight.watcher.shed",
    "flight.oom.backoff",
)


def slo_report(doc: dict, *, horizon_s: float = CAUSE_HORIZON_S) -> dict:
    """Join every ``slo.breach`` instant in a merged capture to its
    plausible cause: the nearest preceding control-plane event (a kill,
    a lease expiry, a chaos injection, an engine recovery, a shed
    storm) within ``horizon_s`` — ``tpu-life doctor --slo CAPTURE``.

    Returns ``{"breaches": [...], "ok": bool}`` where each breach is a
    typed finding carrying the spec's numbers, the named worker, and a
    ``cause`` sub-record (or ``None`` when nothing in the horizon
    explains it)."""
    events = [
        ev for ev in doc.get("traceEvents", [])
        if isinstance(ev, dict) and "ts" in ev and isinstance(ev.get("args"), dict)
    ]
    events.sort(key=lambda e: float(e["ts"]))
    causes = [e for e in events if e.get("name") in _CAUSE_NAMES]
    breaches = []
    for ev in events:
        if ev.get("name") != "flight.slo.breach":
            continue
        args = ev["args"]
        ts = float(ev["ts"])
        cause = None
        for c in reversed(causes):
            c_ts = float(c["ts"])
            if c_ts > ts:
                continue
            if ts - c_ts > horizon_s * 1e6:
                break
            # prefer a cause naming the same worker when the breach
            # names one; otherwise the nearest cause wins
            c_args = c.get("args") or {}
            if args.get("worker") and c_args.get("worker") not in (
                None, args.get("worker")
            ):
                if cause is not None:
                    continue
            cause = {
                "kind": c.get("name"),
                "t_s": round(c_ts / 1e6, 6),
                "gap_s": round((ts - c_ts) / 1e6, 3),
                "args": {k: v for k, v in c_args.items() if k != "trace_id"},
            }
            if c_args.get("worker") == args.get("worker"):
                break  # exact worker match: stop looking
        breaches.append(
            {
                "kind": "slo_breach",
                "slo": args.get("slo"),
                "slo_kind": args.get("slo_kind"),
                "t_s": round(ts / 1e6, 6),
                "observed": args.get("observed"),
                "objective": args.get("objective"),
                "burn": args.get("burn"),
                "window_s": args.get("window_s"),
                "worker": args.get("worker"),
                "cause": cause,
            }
        )
    return {"breaches": breaches, "ok": not breaches}


def render_slo_report(report: dict) -> str:
    lines = []
    for b in report["breaches"]:
        head = (
            f"BREACH {b['slo']} ({b['slo_kind']}) at {b['t_s']:.3f}s: "
            f"observed {b['observed']} vs objective {b['objective']} "
            f"(burn {b['burn']}x over {b['window_s']}s"
        )
        head += f", worker {b['worker']})" if b.get("worker") else ")"
        lines.append(head)
        cause = b.get("cause")
        if cause:
            detail = " ".join(f"{k}={v}" for k, v in (cause["args"] or {}).items())
            lines.append(
                f"  cause: {cause['kind']} {cause['gap_s']}s earlier {detail}".rstrip()
            )
        else:
            lines.append("  cause: none found in the horizon")
    lines.append(
        f"verdict: {'OK' if report['ok'] else 'BREACHED'} "
        f"({len(report['breaches'])} breach(es))"
    )
    return "\n".join(lines)
