"""Run-correlated trace spans in Chrome trace-event JSON.

The reference's entire tracing story is one ``MPI_Wtime`` bracket
(Parallel_Life_MPI.cpp:199,233); ``--profile`` grew that into a whole-run
``jax.profiler`` trace, but the *host-side phase structure* — config
resolution, compilation, staging, each host-sync chunk, snapshot writes,
recovery rewinds, serve scheduling rounds, autotune trials — stayed
invisible.  This module makes it a first-class artifact: a
:class:`Tracer` collects Chrome trace events (the format Perfetto and
``chrome://tracing`` load directly) and writes them as one JSON object
``{"traceEvents": [...], "otherData": {"run_id": ...}}``.

Design rules:

- **Disabled tracing is free.**  The module-level :func:`span` returns a
  shared ``nullcontext`` when no tracer is active — no event dict, no
  timestamp read, no probe increment.  The fused device loop never sees a
  per-step Python callback either way; spans bracket *host* phases only.
- **Run identity.**  Every tracer carries a ``run_id`` (also stamped into
  metrics JSONL records and BENCH records), so the trace file, the
  metrics sink and the bench artifact from one invocation join on one key.
- **Probe counter.**  ``span_count()`` counts real span entries the way
  ``autotune.trial_count()`` counts device measurements — the
  disabled-telemetry overhead tests assert it stays at zero.

Event vocabulary (all timestamps in microseconds since tracer start):

- ``ph: "B"/"E"`` — nested duration spans (:meth:`Tracer.span`); strictly
  stack-disciplined per thread, so the pairs always nest.
- ``ph: "X"``     — complete events with an explicit duration
  (:meth:`Tracer.complete`) — the per-chunk records, emitted after the
  fact from the driver's chunk callback.
- ``ph: "b"/"e"`` — async (non-nested) spans keyed by ``id``
  (:meth:`Tracer.async_begin` / :meth:`Tracer.async_end`) — per-session
  queue-wait intervals in the serve layer, which overlap freely.
- ``ph: "i"``     — instant markers (:meth:`Tracer.instant`).
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid
from contextlib import contextmanager, nullcontext
from pathlib import Path

#: Version of the telemetry record vocabulary (trace event args, metrics
#: JSONL fields, BENCH stamp).  Bump when a consumer-visible field changes
#: meaning, so perf-trajectory tooling can join records across PRs safely.
TELEMETRY_SCHEMA = 1


def new_run_id() -> str:
    """A fresh correlation id: 12 hex chars, unique per invocation."""
    return uuid.uuid4().hex[:12]


def ensure_parent(path) -> None:
    """Create a file's parent directories (the shared exporter prelude)."""
    Path(path).parent.mkdir(parents=True, exist_ok=True)


# the span probe, mirroring autotune.runner._MEASURED: a mutable holder so
# tests hold a live view through the module, not a stale int import
_PROBE = {"spans": 0}


def span_count() -> int:
    """Spans actually entered by an active tracer in this process — the
    disabled-telemetry overhead probe (zero when tracing never enabled)."""
    return _PROBE["spans"]


def reset_span_count() -> None:
    _PROBE["spans"] = 0


class Tracer:
    """Collects Chrome trace events in memory; :meth:`write` emits the file.

    In-memory buffering keeps the hot path to one dict append; the driver
    and the serve service call :meth:`write` from a ``finally`` so a failed
    run still leaves its partial trace on disk.
    """

    def __init__(self, path: str, run_id: str | None = None):
        self.path = str(path)
        self.run_id = run_id or new_run_id()
        self._t0 = time.perf_counter()
        self._pid = os.getpid()
        self._events: list[dict] = []

    # -- clocks -----------------------------------------------------------
    def now(self) -> float:
        """Seconds since tracer start (the clock every event lives on)."""
        return time.perf_counter() - self._t0

    def _ts(self) -> float:
        return (time.perf_counter() - self._t0) * 1e6

    # -- event emitters ---------------------------------------------------
    @contextmanager
    def span(self, name: str, **attrs):
        """A nested B/E duration span around the enclosed block."""
        _PROBE["spans"] += 1
        tid = threading.get_ident()
        self._events.append(
            {
                "name": name,
                "ph": "B",
                "ts": self._ts(),
                "pid": self._pid,
                "tid": tid,
                "args": attrs,
            }
        )
        try:
            yield self
        finally:
            self._events.append(
                {
                    "name": name,
                    "ph": "E",
                    "ts": self._ts(),
                    "pid": self._pid,
                    "tid": tid,
                }
            )

    def complete(self, name: str, start_s: float, end_s: float, **attrs) -> None:
        """A complete (ph ``X``) event for an interval measured after the
        fact — ``start_s``/``end_s`` are on this tracer's :meth:`now` clock."""
        self._events.append(
            {
                "name": name,
                "ph": "X",
                "ts": start_s * 1e6,
                "dur": max(0.0, end_s - start_s) * 1e6,
                "pid": self._pid,
                "tid": threading.get_ident(),
                "args": attrs,
            }
        )

    def instant(self, name: str, **attrs) -> None:
        self._events.append(
            {
                "name": name,
                "ph": "i",
                "s": "p",  # process-scoped marker
                "ts": self._ts(),
                "pid": self._pid,
                "tid": threading.get_ident(),
                "args": attrs,
            }
        )

    def async_begin(self, name: str, aid: str, **attrs) -> None:
        """Open an async interval (``ph: "b"``) keyed by ``aid`` — for
        overlapping non-nested intervals like per-session queue waits."""
        self._events.append(
            {
                "name": name,
                "cat": name,
                "ph": "b",
                "id": aid,
                "ts": self._ts(),
                "pid": self._pid,
                "tid": threading.get_ident(),
                "args": attrs,
            }
        )

    def async_end(self, name: str, aid: str, **attrs) -> None:
        self._events.append(
            {
                "name": name,
                "cat": name,
                "ph": "e",
                "id": aid,
                "ts": self._ts(),
                "pid": self._pid,
                "tid": threading.get_ident(),
                "args": attrs,
            }
        )

    # -- output -----------------------------------------------------------
    def write(self) -> str:
        """Write the Chrome-trace JSON object; returns the path written."""
        ensure_parent(self.path)
        doc = {
            "traceEvents": self._events,
            "displayTimeUnit": "ms",
            "otherData": {
                "run_id": self.run_id,
                "telemetry_schema": TELEMETRY_SCHEMA,
            },
        }
        with open(self.path, "w") as f:
            json.dump(doc, f)
        return self.path


# -- the module-level switchboard ------------------------------------------
# one active tracer per process (the driver and the serve service each own
# one invocation); disabled == None == every entry point below is a no-op

_NULL = nullcontext()
_ACTIVE: Tracer | None = None


def active_tracer() -> Tracer | None:
    return _ACTIVE


def start_tracing(path: str, run_id: str | None = None) -> Tracer:
    """Activate a tracer writing to ``path``; returns it (pass back to
    :func:`stop_tracing`).  Starting over an already-active tracer replaces
    it — the previous owner's ``stop_tracing(tracer)`` still writes its
    file, it just stops receiving new events."""
    global _ACTIVE
    _ACTIVE = Tracer(path, run_id)
    return _ACTIVE


@contextmanager
def activate(tracer: Tracer | None):
    """Make ``tracer`` the active one for the enclosed block, restoring the
    previous tracer after — how a long-lived owner (the serve service)
    routes the emissions of everything it calls into ITS file without
    claiming the process-global slot between rounds.  ``tracer=None``
    leaves the ambient tracer untouched (a no-op scope)."""
    global _ACTIVE
    if tracer is None:
        yield None
        return
    prev = _ACTIVE
    _ACTIVE = tracer
    try:
        yield tracer
    finally:
        _ACTIVE = prev


def stop_tracing(tracer: Tracer | None = None) -> str | None:
    """Write and deactivate (``tracer=None`` stops whichever is active).
    Returns the path written, or None when there was nothing to stop."""
    global _ACTIVE
    t = tracer if tracer is not None else _ACTIVE
    if t is None:
        return None
    if _ACTIVE is t:
        _ACTIVE = None
    return t.write()


def span(name: str, **attrs):
    """A span on the active tracer, or a free shared ``nullcontext``."""
    t = _ACTIVE
    if t is None:
        return _NULL
    return t.span(name, **attrs)


def complete(name: str, start_s: float, end_s: float, **attrs) -> None:
    t = _ACTIVE
    if t is not None:
        t.complete(name, start_s, end_s, **attrs)


def instant(name: str, **attrs) -> None:
    t = _ACTIVE
    if t is not None:
        t.instant(name, **attrs)


def async_begin(name: str, aid: str, **attrs) -> None:
    t = _ACTIVE
    if t is not None:
        t.async_begin(name, aid, **attrs)


def async_end(name: str, aid: str, **attrs) -> None:
    t = _ACTIVE
    if t is not None:
        t.async_end(name, aid, **attrs)


def now() -> float:
    """The active tracer's clock (seconds), or 0.0 when tracing is off —
    callers that measure intervals for :func:`complete` events can call it
    unconditionally."""
    t = _ACTIVE
    return t.now() if t is not None else 0.0
