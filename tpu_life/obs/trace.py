"""Run-correlated trace spans in Chrome trace-event JSON.

The reference's entire tracing story is one ``MPI_Wtime`` bracket
(Parallel_Life_MPI.cpp:199,233); ``--profile`` grew that into a whole-run
``jax.profiler`` trace, but the *host-side phase structure* — config
resolution, compilation, staging, each host-sync chunk, snapshot writes,
recovery rewinds, serve scheduling rounds, autotune trials — stayed
invisible.  This module makes it a first-class artifact: a
:class:`Tracer` collects Chrome trace events (the format Perfetto and
``chrome://tracing`` load directly) and writes them as one JSON object
``{"traceEvents": [...], "otherData": {"run_id": ...}}``.

Design rules:

- **Disabled tracing is free.**  The module-level :func:`span` returns a
  shared ``nullcontext`` when no tracer is active — no event dict, no
  timestamp read, no probe increment.  The fused device loop never sees a
  per-step Python callback either way; spans bracket *host* phases only.
- **Run identity.**  Every tracer carries a ``run_id`` (also stamped into
  metrics JSONL records and BENCH records), so the trace file, the
  metrics sink and the bench artifact from one invocation join on one key.
- **Probe counter.**  ``span_count()`` counts real span entries the way
  ``autotune.trial_count()`` counts device measurements — the
  disabled-telemetry overhead tests assert it stays at zero.

Event vocabulary (all timestamps in microseconds since tracer start):

- ``ph: "B"/"E"`` — nested duration spans (:meth:`Tracer.span`); strictly
  stack-disciplined per thread, so the pairs always nest.
- ``ph: "X"``     — complete events with an explicit duration
  (:meth:`Tracer.complete`) — the per-chunk records, emitted after the
  fact from the driver's chunk callback.
- ``ph: "b"/"e"`` — async (non-nested) spans keyed by ``id``
  (:meth:`Tracer.async_begin` / :meth:`Tracer.async_end`) — per-session
  queue-wait intervals in the serve layer, which overlap freely.
- ``ph: "i"``     — instant markers (:meth:`Tracer.instant`).

Distributed tracing (docs/OBSERVABILITY.md "Distributed tracing"): a
**trace id** names one session's whole journey across processes — the
fleet router mints one per submitted session (honoring a client-supplied
``X-Trace-Id``), workers stamp it onto the session, the spill manifest
persists it, and a migrated session CONTINUES the same trace on its
survivor.  The buffer is a bounded ring (:data:`DEFAULT_MAX_EVENTS`;
drops counted in ``Tracer.dropped`` / ``trace_spans_dropped_total``) so
a long-running serve process never grows without bound, and
:meth:`Tracer.drain` hands the buffered events to a fleet scraper
(``GET /v1/debug/trace``) for cross-process merging
(``tpu-life trace merge``).
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
import uuid
from collections import deque
from contextlib import contextmanager, nullcontext
from pathlib import Path

#: Version of the telemetry record vocabulary (trace event args, metrics
#: JSONL fields, BENCH stamp).  Bump when a consumer-visible field changes
#: meaning, so perf-trajectory tooling can join records across PRs safely.
TELEMETRY_SCHEMA = 1


#: Span-ring capacity (events) — a long-running serve process must not
#: grow its trace buffer without bound between scrapes.  At roughly 200
#: bytes per event dict this caps the buffer near ~13 MB; past it the
#: OLDEST events are evicted (flight-recorder semantics: the most recent
#: window survives) and ``Tracer.dropped`` counts the loss, exported as
#: the ``trace_spans_dropped_total`` metric by the serve tier.
DEFAULT_MAX_EVENTS = 65536

#: The wire shape of a trace id: bounded, filesystem- and header-safe.
#: Anything else on ``X-Trace-Id`` / ``trace_id`` is a typed 400 — a
#: hostile header must not mint unbounded junk into every span.
TRACE_ID_RE = re.compile(r"[A-Za-z0-9][A-Za-z0-9._:-]{0,63}")


def new_run_id() -> str:
    """A fresh correlation id: 12 hex chars, unique per invocation."""
    return uuid.uuid4().hex[:12]


def new_trace_id() -> str:
    """A fresh distributed-trace id: 16 hex chars, minted once per
    submitted session (by the fleet router, or the gateway when it fronts
    clients directly) and carried through every hop the session takes."""
    return uuid.uuid4().hex[:16]


def valid_trace_id(s) -> bool:
    """True when ``s`` is a legal client-supplied trace id."""
    return isinstance(s, str) and TRACE_ID_RE.fullmatch(s) is not None


def ensure_parent(path) -> None:
    """Create a file's parent directories (the shared exporter prelude)."""
    Path(path).parent.mkdir(parents=True, exist_ok=True)


# the span probe, mirroring autotune.runner._MEASURED: a mutable holder so
# tests hold a live view through the module, not a stale int import
_PROBE = {"spans": 0}


def span_count() -> int:
    """Spans actually entered by an active tracer in this process — the
    disabled-telemetry overhead probe (zero when tracing never enabled)."""
    return _PROBE["spans"]


def reset_span_count() -> None:
    _PROBE["spans"] = 0


class Tracer:
    """Collects Chrome trace events in a bounded ring; :meth:`write`
    emits the file, :meth:`drain` hands the buffer to a fleet scraper.

    In-memory buffering keeps the hot path to one deque append; the
    driver and the serve service call :meth:`write` from a ``finally`` so
    a failed run still leaves its partial trace on disk.  The ring is
    bounded (``max_events``): a months-running serve process evicts its
    OLDEST events rather than growing without bound, and ``dropped``
    counts the evictions (a B whose E was evicted — or vice versa — is
    an unmatched pair the Perfetto loader tolerates).
    """

    def __init__(
        self,
        path: str,
        run_id: str | None = None,
        *,
        max_events: int = DEFAULT_MAX_EVENTS,
    ):
        self.path = str(path)
        self.run_id = run_id or new_run_id()
        self._t0 = time.perf_counter()
        #: wall clock at tracer start — the cross-process anchor: an
        #: event's epoch time is ``wall_t0 + ts/1e6``, which is how the
        #: fleet merge aligns per-worker rings on one timeline
        self.wall_t0 = time.time()
        self._pid = os.getpid()
        if max_events < 1:
            raise ValueError(f"max_events must be >= 1, got {max_events}")
        self.max_events = int(max_events)
        self._events: deque = deque()
        # emitters (pump/verb threads) and drain (the HTTP scrape
        # handler) run on different threads: the ring is locked so a
        # span racing a scrape lands on exactly one side of the drain,
        # never on an abandoned buffer.  Events are host-phase-level —
        # one uncontended acquire each is noise (the flight ring pays
        # the same).
        self._buf_lock = threading.Lock()
        self.dropped = 0

    # -- clocks -----------------------------------------------------------
    def now(self) -> float:
        """Seconds since tracer start (the clock every event lives on)."""
        return time.perf_counter() - self._t0

    def _ts(self) -> float:
        return (time.perf_counter() - self._t0) * 1e6

    def _emit(self, ev: dict) -> None:
        with self._buf_lock:
            self._events.append(ev)
            # ring semantics: evict oldest past the cap (one popleft per
            # append once saturated — O(1), no reallocation)
            while len(self._events) > self.max_events:
                self._events.popleft()
                self.dropped += 1

    def drain(self) -> list[dict]:
        """Atomically take (and clear) the buffered events — the fleet
        scrape path (``GET /v1/debug/trace``): each scrape is an
        increment, and a graceful :meth:`write` afterwards emits only
        what was never drained.  Locked against emitters, so a span
        racing a scrape lands on exactly one side of the drain."""
        with self._buf_lock:
            taken, self._events = self._events, deque()
        return list(taken)

    # -- event emitters ---------------------------------------------------
    @contextmanager
    def span(self, name: str, **attrs):
        """A nested B/E duration span around the enclosed block."""
        _PROBE["spans"] += 1
        tid = threading.get_ident()
        self._emit(
            {
                "name": name,
                "ph": "B",
                "ts": self._ts(),
                "pid": self._pid,
                "tid": tid,
                "args": attrs,
            }
        )
        try:
            yield self
        finally:
            self._emit(
                {
                    "name": name,
                    "ph": "E",
                    "ts": self._ts(),
                    "pid": self._pid,
                    "tid": tid,
                }
            )

    def complete(self, name: str, start_s: float, end_s: float, **attrs) -> None:
        """A complete (ph ``X``) event for an interval measured after the
        fact — ``start_s``/``end_s`` are on this tracer's :meth:`now` clock."""
        self._emit(
            {
                "name": name,
                "ph": "X",
                "ts": start_s * 1e6,
                "dur": max(0.0, end_s - start_s) * 1e6,
                "pid": self._pid,
                "tid": threading.get_ident(),
                "args": attrs,
            }
        )

    def instant(self, name: str, **attrs) -> None:
        self._emit(
            {
                "name": name,
                "ph": "i",
                "s": "p",  # process-scoped marker
                "ts": self._ts(),
                "pid": self._pid,
                "tid": threading.get_ident(),
                "args": attrs,
            }
        )

    def async_begin(self, name: str, aid: str, **attrs) -> None:
        """Open an async interval (``ph: "b"``) keyed by ``aid`` — for
        overlapping non-nested intervals like per-session queue waits."""
        self._emit(
            {
                "name": name,
                "cat": name,
                "ph": "b",
                "id": aid,
                "ts": self._ts(),
                "pid": self._pid,
                "tid": threading.get_ident(),
                "args": attrs,
            }
        )

    def async_end(self, name: str, aid: str, **attrs) -> None:
        self._emit(
            {
                "name": name,
                "cat": name,
                "ph": "e",
                "id": aid,
                "ts": self._ts(),
                "pid": self._pid,
                "tid": threading.get_ident(),
                "args": attrs,
            }
        )

    # -- output -----------------------------------------------------------
    def write(self) -> str:
        """Write the Chrome-trace JSON object; returns the path written."""
        ensure_parent(self.path)
        with self._buf_lock:
            # snapshot under the ring lock: a handler-thread emit (or a
            # racing scrape) during the copy would otherwise mutate the
            # deque mid-iteration and abort the write
            events = list(self._events)
            dropped = self.dropped
        doc = {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {
                "run_id": self.run_id,
                "telemetry_schema": TELEMETRY_SCHEMA,
                # the cross-process anchors (docs/OBSERVABILITY.md
                # "Distributed tracing"): the epoch second ts=0 maps to,
                # and how many ring evictions this buffer suffered —
                # additive fields, so schema-1 consumers are unaffected
                "wall_t0": self.wall_t0,
                "pid": self._pid,
                "dropped": dropped,
            },
        }
        with open(self.path, "w") as f:
            json.dump(doc, f)
        return self.path


# -- the module-level switchboard ------------------------------------------
# one active tracer per process (the driver and the serve service each own
# one invocation); disabled == None == every entry point below is a no-op

_NULL = nullcontext()
_ACTIVE: Tracer | None = None


def active_tracer() -> Tracer | None:
    return _ACTIVE


def start_tracing(path: str, run_id: str | None = None) -> Tracer:
    """Activate a tracer writing to ``path``; returns it (pass back to
    :func:`stop_tracing`).  Starting over an already-active tracer replaces
    it — the previous owner's ``stop_tracing(tracer)`` still writes its
    file, it just stops receiving new events."""
    global _ACTIVE
    _ACTIVE = Tracer(path, run_id)
    return _ACTIVE


@contextmanager
def activate(tracer: Tracer | None):
    """Make ``tracer`` the active one for the enclosed block, restoring the
    previous tracer after — how a long-lived owner (the serve service)
    routes the emissions of everything it calls into ITS file without
    claiming the process-global slot between rounds.  ``tracer=None``
    leaves the ambient tracer untouched (a no-op scope)."""
    global _ACTIVE
    if tracer is None:
        yield None
        return
    prev = _ACTIVE
    _ACTIVE = tracer
    try:
        yield tracer
    finally:
        _ACTIVE = prev


def stop_tracing(tracer: Tracer | None = None) -> str | None:
    """Write and deactivate (``tracer=None`` stops whichever is active).
    Returns the path written, or None when there was nothing to stop."""
    global _ACTIVE
    t = tracer if tracer is not None else _ACTIVE
    if t is None:
        return None
    if _ACTIVE is t:
        _ACTIVE = None
    return t.write()


def tracing() -> bool:
    """True while a tracer is active — the ONE global check callers use
    before building costly span attributes (per-slot sid/trace lists):
    the disarmed path stays a single ``None`` test, nothing allocated."""
    return _ACTIVE is not None


def span(name: str, **attrs):
    """A span on the active tracer, or a free shared ``nullcontext``."""
    t = _ACTIVE
    if t is None:
        return _NULL
    return t.span(name, **attrs)


def complete(name: str, start_s: float, end_s: float, **attrs) -> None:
    t = _ACTIVE
    if t is not None:
        t.complete(name, start_s, end_s, **attrs)


def instant(name: str, **attrs) -> None:
    t = _ACTIVE
    if t is not None:
        t.instant(name, **attrs)


def async_begin(name: str, aid: str, **attrs) -> None:
    t = _ACTIVE
    if t is not None:
        t.async_begin(name, aid, **attrs)


def async_end(name: str, aid: str, **attrs) -> None:
    t = _ACTIVE
    if t is not None:
        t.async_end(name, aid, **attrs)


def now() -> float:
    """The active tracer's clock (seconds), or 0.0 when tracing is off —
    callers that measure intervals for :func:`complete` events can call it
    unconditionally."""
    t = _ACTIVE
    return t.now() if t is not None else 0.0
