"""The flight recorder: a bounded ring of structured control-plane events.

Trace spans answer "where did the time go"; the flight recorder answers
"what happened to this session" — the small set of *decisions* a
postmortem needs (admissions, rejections, terminal outcomes, engine
recoveries, wedge verdicts, worker exits, leases, fences, migration
phases, chaos injections), kept in one process-wide bounded ring that is
always on.  Events are rare (per lifecycle transition, never per step or
per round), so recording is unconditionally cheap: one dict append under
a lock, oldest evicted past :data:`DEFAULT_MAX_EVENTS`.

Read-back paths:

- **servable live**: the gateway's ``GET /v1/debug/trace`` drain verb
  carries the flight ring next to the span ring, so a fleet supervisor's
  scrape (and ``tpu-life trace merge``) folds both into one timeline;
- **dumped on drain/wedge/crash**: a written trace file embeds the
  remaining flight events as ``flight.<kind>`` instant markers (the
  serve tier's close path), and a pump crash records its own event
  before the shutdown so the last capture names the cause.  A SIGKILL
  leaves whatever the last scrape already collected — which is why the
  supervisor scrapes continuously, like the PR 11 chaos-counter scrape.

Every event is ``{"t": <epoch seconds>, "kind": <str>, ...attrs}``;
events about a session carry ``sid`` (and ``trace_id`` when the session
has one) so ``tpu-life doctor`` can join them into a journey.
"""

from __future__ import annotations

import threading
import time
from collections import deque

#: Flight-ring capacity.  Control-plane events are rare; 4096 covers
#: hours of a busy fleet while bounding a months-running process.
DEFAULT_MAX_EVENTS = 4096


class FlightRecorder:
    """One bounded event ring; the module-global :data:`RECORDER` is the
    process-wide instance every tier records into."""

    def __init__(self, max_events: int = DEFAULT_MAX_EVENTS):
        if max_events < 1:
            raise ValueError(f"max_events must be >= 1, got {max_events}")
        self.max_events = int(max_events)
        self._lock = threading.Lock()
        self._events: deque = deque(maxlen=self.max_events)
        self._dropped = 0
        self._recorded = 0

    def record(self, kind: str, **attrs) -> None:
        ev = {"t": time.time(), "kind": kind, **attrs}
        with self._lock:
            if len(self._events) == self.max_events:
                self._dropped += 1
            self._events.append(ev)
            self._recorded += 1

    def drain(self) -> list[dict]:
        """Take (and clear) the ring — the scrape path: each drain is an
        increment, so repeated scrapes never duplicate events."""
        with self._lock:
            taken = list(self._events)
            self._events.clear()
        return taken

    def snapshot(self) -> list[dict]:
        """A non-destructive copy (the written-file dump path)."""
        with self._lock:
            return list(self._events)

    @property
    def dropped(self) -> int:
        return self._dropped

    @property
    def recorded(self) -> int:
        """Total events ever recorded in this process — a probe the way
        ``chaos.injection_count`` is one."""
        return self._recorded

    def reset(self) -> None:
        """Clear events and counters (tests)."""
        with self._lock:
            self._events.clear()
            self._dropped = 0
            self._recorded = 0


#: The process-wide recorder (one per process, like the chaos counters).
RECORDER = FlightRecorder()


def as_instant(ev: dict, *, pid: int, ts: float, tid: int = 0) -> dict:
    """One flight event rendered as a Chrome-trace ``flight.<kind>``
    instant marker — the ONE conversion both read-back paths use (the
    serve close-time dump and the capture merge differ only in how they
    anchor ``ts`` on their timeline, never in the event shape)."""
    attrs = {k: v for k, v in ev.items() if k not in ("t", "kind")}
    return {
        "name": f"flight.{ev.get('kind', 'event')}",
        "ph": "i",
        "s": "p",
        "pid": pid,
        "tid": tid,
        "ts": ts,
        "args": attrs,
    }


def record(kind: str, **attrs) -> None:
    """Record one structured event on the process-wide ring."""
    RECORDER.record(kind, **attrs)


def drain() -> list[dict]:
    return RECORDER.drain()


def snapshot() -> list[dict]:
    return RECORDER.snapshot()


def reset() -> None:
    RECORDER.reset()
