"""The read-back toolchain: metrics JSONL in, summary out.

The sink files the runtime writes (``run --metrics-file``, ``serve
--metrics-file``) were append-only artifacts nothing in the repo could
read back; ``tpu-life stats`` closes the loop.  It ingests one JSONL file
— any mix of per-chunk run records (``step`` / ``steps_per_sec``),
per-round serve records (``kind: "serve"``) and end-of-run registry
snapshots (``kind: "metric"``) — and reports the aggregates a human (or
``--json``, a machine) asks first: step and cell throughput, histogram
quantiles (p50/p95/p99), batch occupancy, admission rejection rate.

Quantiles prefer the precomputed ``p50/p95/p99`` fields a snapshot record
carries; a record without them (hand-written, older schema) falls back to
re-deriving from its bucket counts with the same interpolation rule as
:meth:`tpu_life.obs.registry.Histogram.quantile`.
"""

from __future__ import annotations

import json
from pathlib import Path


def load_records(path: str) -> list[dict]:
    """Parse a metrics JSONL file (blank lines and ``#`` comments skipped);
    a malformed line raises with its line number — a truncated tail line
    from a killed run is the one exception, tolerated with a warning field
    left to the caller (it is the expected artifact of a mid-write kill)."""
    records: list[dict] = []
    lines = Path(path).read_text().splitlines()
    for lineno, line in enumerate(lines, 1):
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        try:
            records.append(json.loads(line))
        except json.JSONDecodeError as e:
            if lineno == len(lines):
                break  # torn final line: a killed writer, not a bad file
            raise ValueError(f"{path}:{lineno}: bad metrics line: {e}") from e
    return records


def _quantile_from_buckets(rec: dict, q: float) -> float | None:
    """Re-derive a quantile from a snapshot record's bucket counts —
    the fallback when the precomputed field is absent."""
    count = rec.get("count", 0)
    if not count:
        return None
    finite = sorted(
        (float(b), c) for b, c in rec.get("buckets", {}).items() if b != "+Inf"
    )
    rank = q * count
    cum = 0
    lo = 0.0
    lo_clamp = rec.get("min", 0.0) or 0.0
    hi_clamp = rec.get("max")
    for hi, c in finite:
        if c:
            if cum + c >= rank:
                est = lo + (hi - lo) * (rank - cum) / c
                est = max(est, lo_clamp)
                return min(est, hi_clamp) if hi_clamp is not None else est
            cum += c
        lo = hi
    return hi_clamp


def hist_quantiles(rec: dict) -> dict:
    """{"p50", "p95", "p99"} of a histogram snapshot record."""
    out = {}
    for name, q in (("p50", 0.5), ("p95", 0.95), ("p99", 0.99)):
        v = rec.get(name)
        out[name] = v if v is not None else _quantile_from_buckets(rec, q)
    return out


def _labels_id(labels: dict) -> str:
    return ",".join(f"{k}={v}" for k, v in sorted(labels.items()))


def _labels_from_id(labels_id: str) -> dict:
    """Invert :func:`_labels_id` — the tenant families carry two labels,
    so the single-label ``partition`` trick the older families use is
    not enough."""
    out: dict = {}
    for part in labels_id.split(","):
        if "=" in part:
            k, _, v = part.partition("=")
            out[k] = v
    return out


def _group_by_run(records: list[dict]) -> dict:
    """Records bucketed by ``run_id`` (insertion-ordered; None = unstamped)."""
    groups: dict = {}
    for r in records:
        groups.setdefault(r.get("run_id"), []).append(r)
    return groups


def _run_summary(chunks: list[dict]) -> dict:
    last = chunks[-1]
    rates = [r["steps_per_sec"] for r in chunks if r.get("steps_per_sec")]
    cell_rates = [
        r["cell_updates_per_sec"]
        for r in chunks
        if r.get("cell_updates_per_sec")
    ]
    return {
        "chunks": len(chunks),
        "final_step": last["step"],
        "elapsed_s": last.get("elapsed_s"),
        # the per-chunk steps_per_sec is cumulative (done / elapsed), so
        # the final record IS the whole-run average; max is the best
        # window the run ever sustained
        "steps_per_sec": last.get("steps_per_sec"),
        "steps_per_sec_max": max(rates) if rates else 0.0,
        "cell_updates_per_sec": last.get("cell_updates_per_sec"),
        "cell_updates_per_sec_max": max(cell_rates) if cell_rates else 0.0,
        "live_cells_final": last.get("live_cells"),
    }


def _serve_summary(rounds: list[dict]) -> dict:
    last = rounds[-1]
    occ = [r.get("batch_occupancy", 0.0) for r in rounds]
    out = {
        "rounds": len(rounds),
        "elapsed_s": last.get("elapsed_s"),
        "sessions_done": last.get("sessions_done"),
        "sessions_per_sec": last.get("sessions_per_sec"),
        "steps_advanced": sum(r.get("steps_advanced", 0) for r in rounds),
        "admitted": sum(r.get("admitted", 0) for r in rounds),
        "completed": sum(r.get("completed", 0) for r in rounds),
        "failed": sum(r.get("failed", 0) for r in rounds),
        "batch_occupancy_mean": sum(occ) / len(occ),
        "queue_depth_max": max(r.get("queue_depth", 0) for r in rounds),
    }
    # the pipelined-pump stamps (ISSUE 7) — only when the sink carries
    # them, so summaries of pre-pipeline sinks are byte-stable
    if "pump" in last:
        out["pump"] = last["pump"]
    if any("device_idle_s" in r for r in rounds):
        idle = last.get("device_idle_s") or 0.0
        elapsed = last.get("elapsed_s") or 0.0
        out["device_idle_seconds"] = idle
        out["device_idle_fraction"] = idle / elapsed if elapsed > 0 else 0.0
    if any("pipeline_depth" in r for r in rounds):
        out["pipeline_depth_max"] = max(
            r.get("pipeline_depth", 0) for r in rounds
        )
    # the durability stamps (ISSUE 8) — present only when the run spilled
    if any("snapshot_s" in r for r in rounds):
        # snapshot_s is cumulative like device_idle_s: the last record is
        # the run total; spilled_sessions is a gauge, so max is the peak
        # number of sessions resumable at once
        out["snapshot_seconds"] = last.get("snapshot_s") or 0.0
        out["spilled_sessions_max"] = max(
            r.get("spilled_sessions", 0) for r in rounds
        )
    # storage-path attribution (ISSUE 12): the slice of stepped work run
    # by bitplane-packed stochastic engines — only when the sink carries
    # the stamp, so pre-packed sinks summarize byte-stable
    if any("steps_advanced_packed" in r for r in rounds):
        packed = sum(r.get("steps_advanced_packed", 0) for r in rounds)
        out["steps_advanced_packed"] = packed
        total = out["steps_advanced"]
        out["packed_steps_fraction"] = packed / total if total else 0.0
    # the governor stamp (ISSUE 13): chunk faults masked by in-place
    # engine recovery — only when the sink carries it (newer runtimes)
    if any("engine_recoveries" in r for r in rounds):
        out["engine_recoveries"] = sum(
            r.get("engine_recoveries", 0) for r in rounds
        )
    # the stencil stamp (ISSUE 15): live matmul-path engines (a gauge —
    # the last record is the run's final view) and each CompileKey's
    # resolved counting path, union'd across the run's rounds — only
    # when the sink carries them, so older sinks summarize byte-stable
    if any("matmul_keys" in r for r in rounds):
        out["matmul_keys"] = last.get("matmul_keys", 0)
        stencil_keys: dict = {}
        for r in rounds:
            stencil_keys.update(r.get("stencil_keys") or {})
        out["stencil_keys"] = stencil_keys
    # the mega-board stamp (ISSUE 19): live mesh-placed sessions (a
    # gauge — the last record is the run's final view, max the peak
    # concurrent count) — only when the sink carries it, so mesh-less
    # sinks summarize byte-stable
    if any("mesh_sessions" in r for r in rounds):
        out["mesh_sessions"] = last.get("mesh_sessions", 0)
        out["mesh_sessions_max"] = max(
            r.get("mesh_sessions", 0) for r in rounds
        )
    # the live-session stamps (ISSUE 16): frames/gaps are cumulative
    # counters (max = the final reading, robust to a tail round that
    # dropped the gated stamp), watchers is a gauge (max = the peak) —
    # only when the sink carries them, so unstreamed runs stay byte-stable
    if any("stream_frames_total" in r for r in rounds):
        out["stream_frames_total"] = max(
            r.get("stream_frames_total", 0) for r in rounds
        )
        out["stream_frame_gaps_total"] = max(
            r.get("stream_frame_gaps_total", 0) for r in rounds
        )
        out["stream_watchers"] = max(
            r.get("stream_watchers", 0) for r in rounds
        )
    return out


def _merge_serve(per_run: dict) -> dict:
    """Combine per-run serve summaries into one fleet-level view: counts
    and rates sum (the workers ran concurrently), elapsed is the longest
    worker's wall clock, occupancy is the round-weighted mean."""
    summaries = list(per_run.values())
    total_rounds = sum(s["rounds"] for s in summaries)
    merged = {
        "rounds": total_rounds,
        "elapsed_s": max((s.get("elapsed_s") or 0.0) for s in summaries),
        "sessions_done": sum(s.get("sessions_done") or 0 for s in summaries),
        "sessions_per_sec": sum(
            s.get("sessions_per_sec") or 0.0 for s in summaries
        ),
        "steps_advanced": sum(s["steps_advanced"] for s in summaries),
        "admitted": sum(s["admitted"] for s in summaries),
        "completed": sum(s["completed"] for s in summaries),
        "failed": sum(s["failed"] for s in summaries),
        "batch_occupancy_mean": (
            sum(s["batch_occupancy_mean"] * s["rounds"] for s in summaries)
            / total_rounds
            if total_rounds
            else 0.0
        ),
        "queue_depth_max": max(s["queue_depth_max"] for s in summaries),
        "runs_merged": len(summaries),
    }
    # device-idle merges like the counts: seconds sum across workers, the
    # fraction renormalizes over their combined wall time (workers ran
    # concurrently, so per-worker fractions are what each chip wasted)
    idles = [
        s["device_idle_seconds"] for s in summaries
        if "device_idle_seconds" in s
    ]
    if idles:
        merged["device_idle_seconds"] = sum(idles)
        total_elapsed = sum(
            s.get("elapsed_s") or 0.0
            for s in summaries
            if "device_idle_seconds" in s
        )
        merged["device_idle_fraction"] = (
            sum(idles) / total_elapsed if total_elapsed > 0 else 0.0
        )
    depths = [
        s["pipeline_depth_max"] for s in summaries
        if "pipeline_depth_max" in s
    ]
    if depths:
        merged["pipeline_depth_max"] = max(depths)
    # durability merges like the idle metrics: spill seconds sum across
    # workers, the peak resumable-session gauge maxes
    snaps = [s["snapshot_seconds"] for s in summaries if "snapshot_seconds" in s]
    if snaps:
        merged["snapshot_seconds"] = sum(snaps)
        merged["spilled_sessions_max"] = max(
            s.get("spilled_sessions_max", 0) for s in summaries
        )
    # masked chunk faults sum like the counts they are
    recoveries = [
        s["engine_recoveries"] for s in summaries
        if "engine_recoveries" in s
    ]
    if recoveries:
        merged["engine_recoveries"] = sum(recoveries)
    # packed attribution sums like the step counts it slices
    packed = [
        s["steps_advanced_packed"] for s in summaries
        if "steps_advanced_packed" in s
    ]
    if packed:
        merged["steps_advanced_packed"] = sum(packed)
        merged["packed_steps_fraction"] = (
            sum(packed) / merged["steps_advanced"]
            if merged["steps_advanced"]
            else 0.0
        )
    # the stencil stamp merges like the fleet's live-engine view:
    # matmul-key gauges sum across concurrent workers, the per-key path
    # maps union (workers of one fleet resolve each key identically)
    matmul = [s["matmul_keys"] for s in summaries if "matmul_keys" in s]
    if matmul:
        merged["matmul_keys"] = sum(matmul)
        stencil_keys: dict = {}
        for s in summaries:
            stencil_keys.update(s.get("stencil_keys") or {})
        merged["stencil_keys"] = stencil_keys
    # mesh-session gauges sum like the fleet's other live-engine views
    # (concurrent workers each held that many mega-boards at once)
    mesh = [s["mesh_sessions"] for s in summaries if "mesh_sessions" in s]
    if mesh:
        merged["mesh_sessions"] = sum(mesh)
        merged["mesh_sessions_max"] = sum(
            s.get("mesh_sessions_max", 0) for s in summaries
        )
    # streaming merges like the counts: frames and gaps sum across the
    # fleet's workers, watcher peaks sum too (concurrent workers each
    # held that many watchers at once)
    frames = [
        s["stream_frames_total"] for s in summaries
        if "stream_frames_total" in s
    ]
    if frames:
        merged["stream_frames_total"] = sum(frames)
        merged["stream_frame_gaps_total"] = sum(
            s.get("stream_frame_gaps_total", 0) for s in summaries
        )
        merged["stream_watchers"] = sum(
            s.get("stream_watchers", 0) for s in summaries
        )
    return merged


def summarize(records: list[dict]) -> dict:
    """The summary dict behind both output modes of ``tpu-life stats``.

    Records from a single run keep the classic shape.  Records carrying
    *multiple* run_ids — a fleet's per-worker sinks read back together —
    are grouped by run_id: the ``serve`` section becomes the fleet-level
    merge (counts sum, occupancy is round-weighted) and ``runs`` carries
    each worker's own summary keyed by its run_id.
    """
    chunks = [r for r in records if "step" in r and "kind" not in r]
    rounds = [r for r in records if r.get("kind") == "serve"]
    metrics = [r for r in records if r.get("kind") == "metric"]

    summary: dict = {
        "records": len(records),
        "run_ids": sorted({r["run_id"] for r in records if r.get("run_id")}),
    }

    if chunks:
        groups = _group_by_run(chunks)
        if len(groups) == 1:
            summary["run"] = _run_summary(chunks)
        else:
            for rid, g in groups.items():
                summary.setdefault("runs", {}).setdefault(rid or "<none>", {})[
                    "run"
                ] = _run_summary(g)

    if rounds:
        groups = _group_by_run(rounds)
        per_run = {rid or "<none>": _serve_summary(g) for rid, g in groups.items()}
        if len(per_run) == 1:
            summary["serve"] = next(iter(per_run.values()))
        else:
            summary["serve"] = _merge_serve(per_run)
            for rid, s in per_run.items():
                summary.setdefault("runs", {}).setdefault(rid, {})["serve"] = s

    if metrics:
        summary["metrics"] = []
        counters = {}
        devices_by_worker: dict = {}
        budget_by_worker: dict = {}
        for rec in metrics:
            if rec["metric"] == "serve_memory_budget_bytes" and rec.get("value"):
                # same keying rule as serve_devices below: per sink (a
                # worker's file spans its restarts — per-run_id summing
                # would double-count dead generations), last snapshot wins
                budget_by_worker[
                    rec.get("_sink", rec.get("run_id"))
                ] = rec["value"]
            if rec["metric"] == "serve_devices" and rec.get("value"):
                # keyed by SINK when the loader stamped one (a fleet
                # worker's file spans its restarts, each generation a
                # fresh run_id — summing per run_id would double-count
                # the dead generations' chips), else by run_id; either
                # way the LAST snapshot per key wins (the live one)
                devices_by_worker[
                    rec.get("_sink", rec.get("run_id"))
                ] = rec["value"]
            entry = {
                "metric": rec["metric"],
                "type": rec["type"],
                "labels": rec.get("labels", {}),
            }
            if len(summary["run_ids"]) > 1 and rec.get("run_id"):
                # merged sinks: the same metric arrives once per worker —
                # keep them distinguishable in the report
                entry["run_id"] = rec["run_id"]
            if rec["type"] == "histogram":
                entry.update(
                    count=rec.get("count"),
                    sum=rec.get("sum"),
                    min=rec.get("min"),
                    max=rec.get("max"),
                    **hist_quantiles(rec),
                )
            else:
                entry["value"] = rec.get("value")
                # keyed per run_id too: two workers' identical counters
                # must SUM below, not overwrite each other
                counters[
                    (
                        rec["metric"],
                        _labels_id(rec.get("labels", {})),
                        rec.get("run_id"),
                    )
                ] = rec.get("value") or 0.0
            summary["metrics"].append(entry)
        # admission rejection rate: rejected / offered, when both counters
        # are present in the snapshot
        rejected = sum(
            v for (name, _, _), v in counters.items()
            if name == "serve_admission_rejections_total"
        )
        submitted = sum(
            v for (name, _, _), v in counters.items()
            if name == "serve_sessions_submitted_total"
        )
        if submitted or rejected:
            summary.setdefault("serve", {})["rejection_rate"] = (
                rejected / (submitted + rejected) if (submitted + rejected) else 0.0
            )
        # the governor families (ISSUE 13): in-place recoveries by ladder
        # outcome and typed admission rejections by reason — summed across
        # workers (fleet sinks), keyed by their one label
        for family, out_key in (
            ("serve_engine_recoveries_total", "engine_recoveries_by_outcome"),
            ("serve_admission_rejected_total", "admission_rejected_by_reason"),
            # the fan-out tier's typed sheds (ISSUE 16), by reason
            ("watcher_shed_total", "watcher_shed_by_reason"),
        ):
            by_label: dict = {}
            for (name, labels_id, _), v in counters.items():
                if name != family or not v:
                    continue
                label = labels_id.partition("=")[2] or "<none>"
                by_label[label] = by_label.get(label, 0.0) + v
            if by_label:
                summary.setdefault("serve", {})[out_key] = by_label
        # the tenant families (ISSUE 20): per-tenant live sessions (a
        # gauge — concurrent workers each held that many, so the fleet
        # sums) and typed sheds keyed (tenant, reason), summed across
        # workers; absent families leave older sinks byte-stable
        tenant_sessions: dict = {}
        tenant_sheds: dict = {}
        for (name, labels_id, _), v in counters.items():
            if not v:
                continue
            labels = _labels_from_id(labels_id)
            if name == "serve_tenant_sessions":
                t = labels.get("tenant", "<none>")
                tenant_sessions[t] = tenant_sessions.get(t, 0.0) + v
            elif name == "tenant_shed_total":
                key = (labels.get("tenant", "<none>"),
                       labels.get("reason", "<none>"))
                tenant_sheds[key] = tenant_sheds.get(key, 0.0) + v
        if tenant_sessions or tenant_sheds:
            tenants: dict = {}
            for t, v in tenant_sessions.items():
                tenants.setdefault(t, {})["sessions"] = v
            for (t, reason), v in tenant_sheds.items():
                tenants.setdefault(t, {}).setdefault("sheds", {})[reason] = v
            summary.setdefault("serve", {})["tenants"] = {
                t: tenants[t] for t in sorted(tenants)
            }
        if budget_by_worker:
            # fleet budget = sum of the workers' budgets (each governs
            # its own engines); a single sink reports its own value
            summary.setdefault("serve", {})["memory_budget_bytes"] = int(
                sum(budget_by_worker.values())
            )
        if devices_by_worker:
            # the fleet's aggregate device count: each worker snapshot
            # carries its own resolved serve_devices gauge, and the
            # workers ran concurrently, so the fleet owns the sum.
            # CAVEAT: the sum assumes DISJOINT device slices (placement
            # auto); sinks carry no placement record, so shared-env
            # workers (placement none) co-claiming one device set are
            # counted once each — the router's /healthz devices_total is
            # the authoritative number in that mode (docs/FLEET.md)
            summary.setdefault("serve", {})["devices_total"] = int(
                sum(devices_by_worker.values())
            )

    return summary


def _fmt(v) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        if v == 0:
            return "0"
        if abs(v) >= 1e5 or 0 < abs(v) < 1e-3:
            return f"{v:.3e}"
        return f"{v:.4g}"
    return str(v)


def render(summary: dict) -> str:
    """The human table (``--json`` bypasses this)."""
    lines: list[str] = []
    rid = summary.get("run_ids") or []
    lines.append(
        f"metrics summary — {summary['records']} records, "
        f"run_id {', '.join(rid) if rid else '<none>'}"
    )
    run = summary.get("run")
    if run:
        lines.append("run:")
        lines.append(
            f"  chunks={run['chunks']}  final_step={run['final_step']}  "
            f"elapsed_s={_fmt(run['elapsed_s'])}"
        )
        lines.append(
            f"  steps/s={_fmt(run['steps_per_sec'])} "
            f"(max {_fmt(run['steps_per_sec_max'])})  "
            f"cells/s={_fmt(run['cell_updates_per_sec'])} "
            f"(max {_fmt(run['cell_updates_per_sec_max'])})"
        )
    serve = summary.get("serve")
    if serve:
        lines.append("serve:")
        if "rounds" in serve:
            lines.append(
                f"  rounds={serve['rounds']}  done={_fmt(serve.get('sessions_done'))}  "
                f"sessions/s={_fmt(serve.get('sessions_per_sec'))}  "
                f"occupancy={_fmt(serve.get('batch_occupancy_mean'))}  "
                f"queue_depth_max={_fmt(serve.get('queue_depth_max'))}"
            )
        if "device_idle_seconds" in serve:
            pump = serve.get("pump")
            lines.append(
                f"  device_idle_s={_fmt(serve['device_idle_seconds'])}  "
                f"idle_fraction={_fmt(serve.get('device_idle_fraction'))}  "
                f"pipeline_depth_max={_fmt(serve.get('pipeline_depth_max'))}"
                + (f"  pump={pump}" if pump else "")
            )
        if "snapshot_seconds" in serve:
            lines.append(
                f"  snapshot_s={_fmt(serve['snapshot_seconds'])}  "
                f"spilled_sessions_max={_fmt(serve.get('spilled_sessions_max'))}"
            )
        if "matmul_keys" in serve:
            paths = serve.get("stencil_keys") or {}
            lines.append(
                f"  matmul_keys={_fmt(serve['matmul_keys'])}  "
                + " ".join(
                    f"{k}:{v}" for k, v in sorted(paths.items())
                )
            )
        if "mesh_sessions" in serve:
            lines.append(
                f"  mesh_sessions={_fmt(serve['mesh_sessions'])} "
                f"(max {_fmt(serve.get('mesh_sessions_max'))})"
            )
        if "steps_advanced_packed" in serve:
            lines.append(
                f"  packed_steps={_fmt(serve['steps_advanced_packed'])}  "
                f"packed_fraction={_fmt(serve.get('packed_steps_fraction'))}"
            )
        if "rejection_rate" in serve:
            lines.append(f"  rejection_rate={_fmt(serve['rejection_rate'])}")
        if "engine_recoveries" in serve or "engine_recoveries_by_outcome" in serve:
            by = serve.get("engine_recoveries_by_outcome") or {}
            detail = " ".join(f"{k}={_fmt(v)}" for k, v in sorted(by.items()))
            lines.append(
                f"  engine_recoveries={_fmt(serve.get('engine_recoveries', sum(by.values())))}"
                + (f"  ({detail})" if detail else "")
            )
        if "admission_rejected_by_reason" in serve:
            detail = " ".join(
                f"{k}={_fmt(v)}"
                for k, v in sorted(serve["admission_rejected_by_reason"].items())
            )
            lines.append(f"  admission_rejected: {detail}")
        if "stream_frames_total" in serve:
            lines.append(
                f"  stream_frames={_fmt(serve['stream_frames_total'])}  "
                f"frame_gaps={_fmt(serve.get('stream_frame_gaps_total'))}  "
                f"stream_watchers={_fmt(serve.get('stream_watchers'))}"
            )
        if "watcher_shed_by_reason" in serve:
            detail = " ".join(
                f"{k}={_fmt(v)}"
                for k, v in sorted(serve["watcher_shed_by_reason"].items())
            )
            lines.append(f"  watcher_shed: {detail}")
        if "tenants" in serve:
            for t, info in serve["tenants"].items():
                sheds = info.get("sheds") or {}
                detail = " ".join(
                    f"{k}={_fmt(v)}" for k, v in sorted(sheds.items())
                )
                lines.append(
                    f"  tenant {t}: sessions={_fmt(info.get('sessions', 0))}"
                    + (f"  shed: {detail}" if detail else "")
                )
        if "memory_budget_bytes" in serve:
            lines.append(
                f"  memory_budget_bytes={_fmt(serve['memory_budget_bytes'])}"
            )
        if "devices_total" in serve:
            lines.append(f"  devices_total={_fmt(serve['devices_total'])}")
    runs = summary.get("runs")
    if runs:
        lines.append("per run:")
        for rid, r in runs.items():
            s = r.get("serve")
            if s:
                lines.append(
                    f"  {rid}  rounds={s['rounds']}  "
                    f"done={_fmt(s.get('sessions_done'))}  "
                    f"sessions/s={_fmt(s.get('sessions_per_sec'))}  "
                    f"occupancy={_fmt(s.get('batch_occupancy_mean'))}"
                )
            rn = r.get("run")
            if rn:
                lines.append(
                    f"  {rid}  chunks={rn['chunks']}  "
                    f"final_step={rn['final_step']}  "
                    f"steps/s={_fmt(rn.get('steps_per_sec'))}"
                )
    mets = summary.get("metrics")
    if mets:
        lines.append("metrics:")
        name_w = max(len(m["metric"]) for m in mets)
        for m in mets:
            label = _labels_id(m["labels"])
            if m.get("run_id"):
                label = f"run_id={m['run_id']}" + (f",{label}" if label else "")
            tag = f"{m['metric']:<{name_w}}" + (f"  [{label}]" if label else "")
            if m["type"] == "histogram":
                lines.append(
                    f"  {tag}  count={_fmt(m['count'])}  p50={_fmt(m['p50'])}  "
                    f"p95={_fmt(m['p95'])}  p99={_fmt(m['p99'])}"
                )
            else:
                lines.append(f"  {tag}  {m['type']}={_fmt(m['value'])}")
    return "\n".join(lines)
