"""Row-sharded multi-device backend — the framework's distributed core.

The board lives as one global array stripe-sharded over a 1-D mesh
(``NamedSharding(P('rows', None))``) — or block-sharded over a 2-D
rows × cols mesh (``mesh_shape=(r, c)``), which goes beyond the reference's
stripe decomposition and keeps halo traffic proportional to the shard
perimeter; halos move over ICI via ``ppermute``
(``tpu_life.parallel.halo``).  Life-like rules run bit-sliced (uint32
bitboard, 32 cells/lane — ``tpu_life.ops.bitlife``), which also shrinks
each halo exchange 32x.  Two partitioning modes:

- ``shard_map``: explicit SPMD — hand-written halo exchange with deep-halo
  blocking (``block_steps``), the analogue of the reference's explicit
  ``MPI_Sendrecv`` design (Parallel_Life_MPI.cpp:104-145) done the XLA way.
- ``gspmd``: the same masked step simply jitted with sharding constraints;
  XLA's SPMD partitioner derives the halo exchange from the shifted-slice
  data flow.  Kept as a cross-check and a benchmark rival for shard_map.

Construction of the global array goes through
``jax.make_array_from_callback`` so each host only ever touches its own
stripes — the analogue of every rank reading its own byte range
(Parallel_Life_MPI.cpp:85), and the thing that keeps 65536^2 feasible.
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax

from tpu_life.backends.base import ChunkCallback, register_backend, run_with_runner
from tpu_life.models.rules import Rule
from tpu_life.ops import bitlife
from tpu_life.ops.conv import resolve_stencil, validate_stencil
from tpu_life.ops.stencil import make_masked_step
from tpu_life.parallel.halo import make_sharded_run
from tpu_life.parallel.mesh import (
    COL_AXIS,
    ROW_AXIS,
    board_sharding,
    make_mesh,
    make_mesh_2d,
)
from tpu_life.utils.padding import LANE, SUBLANE, ceil_to


@register_backend("sharded")
class ShardedBackend:
    name = "sharded"

    def __init__(
        self,
        *,
        num_devices: int | None = None,
        block_steps: int | None = None,
        partition_mode: str = "shard_map",
        pad_lanes: bool = True,
        bitpack: bool = True,
        mesh=None,
        mesh_shape: tuple[int, int] | None = None,
        local_kernel: str = "auto",
        pallas_block_rows: int = 256,
        pallas_block_cols: int = 512,
        pallas_interpret: bool | None = None,
        stencil: str = "roll",
        **_,
    ):
        # the per-shard counting path (docs/RULES.md): "roll" shift-adds
        # or "matmul" banded matmuls, threaded into the halo scaffold's
        # local substep — the PR 15 known limit (CompileKey.stencil
        # stopped at the single-device executors) discharged.  "auto"
        # resolves per rule at prepare time (_stencil), same as the
        # single-chip backends.
        self.stencil = validate_stencil(stencil)
        if mesh_shape is not None and num_devices is not None:
            r, c = mesh_shape
            if r * c != num_devices:
                raise ValueError(
                    f"mesh_shape {mesh_shape} ({r * c} devices) contradicts "
                    f"num_devices={num_devices}"
                )
        if mesh is not None and mesh_shape is not None:
            raise ValueError("pass either mesh or mesh_shape, not both")
        if mesh is not None:
            self.mesh = mesh
        elif mesh_shape is not None and mesh_shape[1] > 1:
            self.mesh = make_mesh_2d(tuple(mesh_shape))
        elif mesh_shape is not None:
            self.mesh = make_mesh(mesh_shape[0])
        else:
            self.mesh = make_mesh(num_devices)
        self.n = self.mesh.shape[ROW_AXIS]
        self.n_cols = self.mesh.shape.get(COL_AXIS, 1)
        # None = per-kernel default (1 for the XLA scan; deep-halo 8/16 for
        # the Pallas local kernel, mirroring PallasBackend)
        self._block_steps_arg = block_steps
        self.block_steps = max(1, block_steps or 1)
        if partition_mode not in ("shard_map", "gspmd"):
            raise ValueError(f"unknown partition_mode {partition_mode!r}")
        self.partition_mode = partition_mode
        self.pad_lanes = pad_lanes
        self.bitpack = bitpack
        if local_kernel not in ("auto", "xla", "pallas"):
            raise ValueError(f"unknown local_kernel {local_kernel!r}")
        self.local_kernel = local_kernel
        self.pallas_block_rows = max(8, pallas_block_rows - pallas_block_rows % 8)
        self.pallas_block_cols = ceil_to(max(LANE, pallas_block_cols), LANE)
        self.pallas_interpret = pallas_interpret

    def _cell_dtype(self, rule: Rule):
        """Element type of the unpacked board: float32 on the continuous
        tier (a silent int8 cast would quantize a Lenia world to junk —
        models.lenia.require_float_path), int8 everywhere else."""
        return np.float32 if getattr(rule, "continuous", False) else np.int8

    def _device_put_stream(
        self,
        load_block,
        h: int,
        w: int,
        h_pad: int,
        w_phys: int,
        use_bits: bool,
        cell_dtype=np.int8,
    ):
        """Build the sharded device array from a rectangular block loader.

        ``load_block(r0, r1, c0, c1) -> int8[(r1-r0), (c1-c0)]`` supplies the
        requested sub-rectangle of the logical board (columns in cells); each
        device's block is materialized independently and asks for exactly its
        own cells, so on a 2-D mesh a column shard never re-reads the rest of
        its rows, and on a multi-host job every process only loads its own
        shards' bytes — the analogue of per-rank ``MPI_File_read_at`` offsets
        (Parallel_Life_MPI.cpp:85), and what keeps 65536^2 feasible.
        """
        sharding = board_sharding(self.mesh)
        dtype = np.uint32 if use_bits else cell_dtype

        def cb(index):
            rows, cols = index
            r0 = rows.start or 0
            r1 = rows.stop if rows.stop is not None else h_pad
            c0 = cols.start or 0
            c1 = cols.stop if cols.stop is not None else w_phys
            block = np.zeros((r1 - r0, c1 - c0), dtype=dtype)
            n = min(r1, h) - r0
            # storage units (packed words / cells) -> logical cell columns;
            # packed shard boundaries sit on word boundaries, so cell0 is
            # word-aligned and the segment packs independently
            cell0 = c0 * bitlife.WORD if use_bits else c0
            cell1 = min(c1 * bitlife.WORD if use_bits else c1, w)
            if n > 0 and cell1 > cell0:
                seg = load_block(r0, r0 + n, cell0, cell1)
                src = bitlife.pack_np(seg) if use_bits else seg
                block[:n, : src.shape[1]] = src
            return block

        return jax.make_array_from_callback((h_pad, w_phys), sharding, cb)

    def _stencil(self, rule: Rule) -> str:
        """The rule's resolved counting path (conv.resolve_stencil):
        explicit modes win, ``auto`` follows the crossover model — except
        under an explicit Pallas pin, where auto keeps roll (the Pallas
        kernels do their own counting; only an explicit matmul request
        contradicts the pin, in _resolve_local_kernel)."""
        if self.stencil == "auto" and self.local_kernel == "pallas":
            return "roll"
        return resolve_stencil(rule, self.stencil)

    def _use_bits(self, rule: Rule) -> bool:
        if getattr(rule, "continuous", False) or self._stencil(rule) == "matmul":
            # float boards have no bitplane form, and the matmul counting
            # path operates on the cell layout — both pin the unpacked
            # board
            return False
        if rule.boundary == "torus":
            # mirrors _prepare_torus (which rejects local_kernel='pallas'
            # before this matters): life-like torus rules run packed too,
            # and the streamed reader/writer must agree on the layout
            return self.bitpack and bitlife.supports_torus(rule)
        if self.local_kernel == "pallas" and self.n_cols > 1:
            # the packed stripe kernel is 1-D only: explicit pallas on a
            # 2-D mesh runs the int8 kernel on the unpacked layout
            return False
        # on a 2-D mesh, word-aligned shard boundaries keep the bitboard
        # splittable along columns too (ceil(pad/32)-word halos).  The
        # bit-sliced diamond (supports_diamond) rides the same layout.
        return self.bitpack and (
            bitlife.supports(rule) or bitlife.supports_diamond(rule)
        )

    def prepare(self, board: np.ndarray, rule: Rule):
        h, w = board.shape
        board = np.asarray(board, self._cell_dtype(rule))
        return self._prepare_impl(
            lambda r0, r1, c0, c1: board[r0:r1, c0:c1], h, w, rule
        )

    def prepare_from_file(self, path, height: int, width: int, rule: Rule):
        """Runner whose board loads straight from a contract-format board
        file, block by block inside the shard callbacks — the full board
        is never materialized on one host."""
        from tpu_life.io.sharded import read_block

        def load_block(r0: int, r1: int, c0: int, c1: int) -> np.ndarray:
            seg = read_block(path, r0, r1 - r0, c0, c1 - c0, width)
            mx = int(seg.max(initial=0))
            if mx >= rule.states:
                raise ValueError(
                    f"board rows [{r0}, {r1}) contain state {mx} but rule "
                    f"{rule.name!r} has only {rule.states} states"
                )
            return seg

        return self._prepare_impl(load_block, height, width, rule)

    def prepare_from_blocks(self, load_block, height: int, width: int, rule: Rule):
        """Runner whose board loads from an arbitrary rectangular block
        loader (``load_block(r0, r1, c0, c1) -> cells``), block by block
        inside the shard callbacks — the re-gather entry of the serve
        mesh tier (arXiv 2112.01075's redistribution shape): a spilled
        tile set re-enters a mesh of ANY shape, each destination shard
        pulling exactly its own cell rectangle, so the full board is
        never materialized on one host."""
        return self._prepare_impl(load_block, height, width, rule)

    def iter_runner_tiles(self, runner, height: int, width: int, rule: Rule):
        """Yield ``(r0, c0, cells)`` — one logical-cell tile per
        addressable shard of the runner's board (deduplicated, padding
        stripped, bitboards unpacked).  Each host only ever touches its
        own shards' bytes; the serve mesh tier's shard-wise spill and the
        sharded board writer are both this walk."""
        use_bits = self._use_bits(rule)
        x = runner.x
        jax.block_until_ready(x)
        seen: set[tuple[int, int]] = set()
        for shard in x.addressable_shards:
            rows, cols = shard.index
            r0 = rows.start or 0
            c0 = cols.start or 0
            # storage units -> logical cell columns (word-aligned when packed)
            cell0 = c0 * bitlife.WORD if use_bits else c0
            if (r0, cell0) in seen or r0 >= height or cell0 >= width:
                continue
            seen.add((r0, cell0))
            r1 = rows.stop if rows.stop is not None else x.shape[0]
            c1 = cols.stop if cols.stop is not None else x.shape[1]
            n = min(r1, height) - r0
            cell1 = min(c1 * bitlife.WORD if use_bits else c1, width)
            data = np.asarray(shard.data)
            seg = (
                bitlife.unpack_np(data[:n], cell1 - cell0)
                if use_bits
                else data[:n, : cell1 - cell0]
            )
            yield r0, cell0, seg

    def write_runner_to_file(self, runner, path, height: int, width: int, rule: Rule):
        """Write the runner's board per addressable shard at contract byte
        offsets (halo-free, any order) — the ``MPI_File_write_at_all``
        analogue (Parallel_Life_MPI.cpp:175).  On a 2-D mesh each column
        shard writes its row *segments* at ``row * (width+1) + col_offset``
        — the reference's offset scheme (:172-175) generalized to blocks."""
        from tpu_life.io.sharded import write_block

        for r0, cell0, seg in self.iter_runner_tiles(runner, height, width, rule):
            write_block(
                path, r0, cell0, seg, total_rows=height, total_cols=width
            )

    # stripe-scratch budget for the Pallas local kernel (cf.
    # PallasBackend.MAX_PACKED_TILE_BYTES): ext_r x wp uint32 must leave
    # Mosaic's ~16 MB scoped VMEM room for the adder tree's temporaries
    MAX_PALLAS_TILE_BYTES = 2 << 20

    def _pallas_interp(self) -> bool:
        if self.pallas_interpret is not None:
            return self.pallas_interpret
        return self.mesh.devices.flat[0].platform != "tpu"

    def _resolve_local_kernel(self, use_bits: bool, rule: Rule) -> str | None:
        """Which Pallas kernel the per-shard stepper should be, or None for
        the XLA scan (VERDICT round 1 item 1: multi-chip runs keep
        single-chip throughput).  ``'packed'`` = the bit-sliced stripe kernel
        (life-like rules, 1-D row meshes); ``'int8'`` = the 2-D-tiled
        deep-halo kernel (Larger-than-Life / Generations / unpacked boards —
        VERDICT r3 item 3), on 1-D and 2-D meshes alike.  Both need
        shard_map (gspmd derives its own exchange).
        """
        if self._stencil(rule) == "matmul":
            # the banded-matmul counting path is an XLA construction; an
            # explicit Pallas pin contradicts an explicit matmul request
            # (auto never reaches here under the pin — _stencil keeps it
            # on roll)
            if self.local_kernel == "pallas":
                raise ValueError(
                    "stencil='matmul' runs the XLA banded-matmul step; "
                    "it cannot be combined with local_kernel='pallas'"
                )
            return None
        if self.local_kernel == "xla":
            return None
        if self.local_kernel == "pallas":
            if self.partition_mode != "shard_map":
                raise ValueError(
                    "local_kernel='pallas' needs partition_mode='shard_map'"
                )
        # auto: compiled Pallas on TPU; elsewhere interpret mode would be
        # Python-speed, so keep the XLA scan
        elif self.partition_mode != "shard_map" or self._pallas_interp():
            return None
        if use_bits:
            # packed stripes are full-width: on a 2-D mesh `auto` keeps the
            # packed XLA scan (8x less HBM) over unpacked int8 Pallas.
            # Covers the bit-sliced diamond too — the stripe kernel runs
            # von Neumann r<=2 rules via roll shift-by-k planes.
            return "packed" if self.n_cols == 1 else None
        return "int8"

    def _fit_block_rows(self, row_bytes: int, fr: int, sh: int) -> int:
        """Largest sublane-aligned divisor of shard height ``sh`` whose ext
        stripe (``block_rows + 2*fr`` rows of ``row_bytes`` each) fits the
        VMEM budget, or 0 when none does.  Shared by both tiling searches
        so their feasibility decisions cannot drift apart.
        """
        ext_budget = (
            self.MAX_PALLAS_TILE_BYTES // row_bytes // SUBLANE * SUBLANE
        )
        max_br = min(self.pallas_block_rows, ext_budget - 2 * fr, sh)
        return next(
            (d for d in range(max_br - max_br % SUBLANE, 0, -SUBLANE) if sh % d == 0),
            0,
        )

    def _pallas_tiling(
        self, h: int, wp: int, rule: Rule, cells: int
    ) -> tuple[int, int, int, int] | None:
        """(block_rows, block_steps, fr, shard_h) for the sharded Pallas
        stripe kernel, or None when no stripe fits the VMEM budget (then the
        XLA scan takes over).  ``fr`` (the ppermute payload / kernel halo) is
        sublane-aligned; ``block_rows`` divides ``shard_h`` exactly so the
        kernel grid tiles each shard with no remainder stripe.
        """
        sh = ceil_to(-(-h // self.n), SUBLANE)
        if self._block_steps_arg is None:
            # mirror PallasBackend: deep blocks pay off once HBM-bound
            want = 16 if cells >= 8192 * 8192 else 8
        else:
            want = max(1, self._block_steps_arg)
        from tpu_life.backends.pallas_backend import sharded_pallas_halo_rows

        for k in range(want, 0, -1):
            fr = sharded_pallas_halo_rows(rule, k)
            if fr > sh:
                continue
            br = self._fit_block_rows(wp * 4, fr, sh)
            # br >= fr keeps interior tiles inside the chunk for the
            # kernel's stitched (top, chunk, bot) DMA windows (implied for
            # the single-tile br == sh case, since fr <= sh here)
            if br >= max(SUBLANE, fr):
                return br, k, fr, sh
        return None

    def _pallas_int8_tiling(
        self, h: int, w: int, rule: Rule
    ) -> tuple[int, int, int, int, int] | None:
        """(block_rows, block_cols, block_steps, shard_h, shard_w) for the
        sharded int8 2-D-tiled kernel, or None when no tile fits the VMEM
        budget (then the XLA scan takes over).  Shards are halo-free in the
        layout — the epoch loop concatenates halos per block — so the only
        layout constraints are tile divisibility and lane alignment.
        """
        from tpu_life.backends.pallas_backend import sharded_pallas_int8_frame
        from tpu_life.parallel.halo import halo_depth

        if rule.neighborhood != "moore":
            # the int8 kernel's separable box sum is Moore-only; returning
            # None routes von Neumann rules to the XLA local kernel
            return None
        sh = ceil_to(-(-h // self.n), SUBLANE)
        # tile width: lane multiple <= the configured cap whose shard-width
        # rounding wastes the fewest padded columns (every padded column is
        # computed then masked dead each substep — at w_per=750 a blind 512
        # tile would inflate the shard 36%, a 384 tile only 2.4%); ties go
        # to the larger tile (fewer grid programs)
        w_per = -(-w // self.n_cols)
        cap = min(self.pallas_block_cols, ceil_to(w_per, LANE))
        bc = max(
            range(LANE, cap + 1, LANE),
            key=lambda b: (-(ceil_to(w_per, b) - w_per), b),
        )
        sw = ceil_to(w_per, bc)
        if self._block_steps_arg is None:
            want = 8  # mirror PallasBackend's int8 default (k=8 peak on v5e)
        else:
            want = max(1, self._block_steps_arg)
        for k in range(want, 0, -1):
            fr, fc = sharded_pallas_int8_frame(rule, k)
            if fr > sh or (self.n_cols > 1 and halo_depth(rule, k) > sw):
                continue
            # budget the tile's int32 working set (cf. MAX_PALLAS_TILE_BYTES)
            br = self._fit_block_rows((bc + 2 * fc) * 4, fr, sh)
            if br >= SUBLANE:
                return br, bc, k, sh, sw
        return None

    def _blocked_runner(
        self, x, block_steps: int, make_run, to_np, count_live, gspmd_run=None
    ):
        """DeviceRunner over a per-``block_steps`` cache of compiled sharded
        runs: ``advance(n)`` = full blocks at ``block_steps`` + one
        remainder block.  The single scaffold behind both the clamped and
        torus prepare paths, so the blocking logic cannot drift."""
        runs: dict[int, object] = {}

        def get_run(bs: int):
            if bs not in runs:
                runs[bs] = make_run(bs)
            return runs[bs]

        def advance(x, n_steps: int):
            if gspmd_run is not None:
                return gspmd_run(x, steps=n_steps)
            num_blocks, rem = divmod(n_steps, block_steps)
            if num_blocks:
                x = get_run(block_steps)(x, num_blocks)
            if rem:
                x = get_run(rem)(x, 1)
            return x

        from tpu_life.backends.jax_backend import DeviceRunner

        return DeviceRunner(x, advance, to_np, count_live=count_live)

    def _prepare_torus_2d(self, load_rows, h: int, w: int, rule: Rule, use_bits):
        """Torus over a 2-D mesh: closed ppermute rings along BOTH axes
        (`make_sharded_run_torus_2d`) — the wrap is pure halo exchange, no
        in-shard wrap logic.  Packed bitboard only, and the geometry must
        divide exactly: rows by the row mesh, packed words by the column
        mesh, width by the word size."""
        from tpu_life.parallel.halo import make_sharded_run_torus_2d

        if self.local_kernel == "pallas":
            raise ValueError(
                "the Pallas torus stripe kernel is 1-D only; the 2-D-mesh "
                "torus runs the XLA step (local_kernel='xla'/'auto')"
            )
        if use_bits:
            wp = bitlife.packed_width(w)
            if w % bitlife.WORD != 0 or wp % self.n_cols != 0:
                raise ValueError(
                    f"2-D-mesh torus needs the width ({w}) divisible by "
                    f"{bitlife.WORD} and its {wp} packed words divisible by "
                    f"the column mesh ({self.n_cols}): any padding would sit "
                    f"inside the glued seam.  Use a 1-D (rows) mesh for "
                    f"this board."
                )
            w_store, col_unit = wp, bitlife.WORD
            to_np = lambda x: bitlife.unpack_np(np.asarray(x), w)
            count = bitlife.live_count_packed
        else:
            # multistate / wide-radius / continuous torus rules: the same
            # closed-ring construction on the cell board — the seam
            # constraint is plain cell divisibility
            if w % self.n_cols != 0:
                raise ValueError(
                    f"2-D-mesh torus needs the width ({w}) divisible by the "
                    f"column mesh ({self.n_cols}): padding would sit inside "
                    f"the glued seam.  Use a 1-D (rows) mesh for this board."
                )
            w_store, col_unit = w, 1
            to_np = lambda x: np.asarray(x)
            # float boards have no exact "live" count; the runner's host
            # fallback covers the metric
            count = (
                None
                if getattr(rule, "continuous", False)
                else bitlife.live_count_cells
            )
        shard_h = h // self.n
        block_steps = max(
            1,
            min(
                self.block_steps,
                shard_h // max(1, rule.radius),
                # the column halo must stay within one shard's storage
                (w_store // self.n_cols) * col_unit // max(1, rule.radius),
            ),
        )
        x = self._device_put_stream(
            load_rows, h, w, h, w_store, use_bits,
            cell_dtype=self._cell_dtype(rule),
        )
        return self._blocked_runner(
            x,
            block_steps,
            lambda bs: make_sharded_run_torus_2d(
                rule,
                self.mesh,
                (h, w),
                block_steps=bs,
                packed=use_bits,
                stencil=self._stencil(rule),
            ),
            to_np,
            count,
        )

    def _prepare_torus(self, load_rows, h: int, w: int, rule: Rule):
        """Torus sharding: periodic ppermute ring + column-wrap substeps
        (`make_sharded_run_torus`).  The board must be EXACT in rows —
        padding rows would sit inside the glued seam — hence the
        constraints; violations raise with the precise reason instead of
        silently clamping.  Life-like rules run on the packed bitboard
        (seam carries wrap at the logical width; VERDICT r4 item 3);
        other rule families fall back to the int8 wrap-cols scan."""
        if self.partition_mode != "shard_map":
            raise ValueError(
                "torus boundary needs partition_mode='shard_map'"
            )
        if h % self.n != 0:
            raise ValueError(
                f"torus boundary needs the board height ({h}) divisible by "
                f"the mesh size ({self.n}) so no padding rows sit inside "
                f"the glued seam"
            )
        from tpu_life.parallel.halo import make_sharded_run_torus

        use_bits = self._use_bits(rule)
        shard_h = h // self.n

        if getattr(rule, "continuous", False) or self._stencil(rule) == "matmul":
            # the wrap-cols substep of the 1-D torus scan is an int
            # roll-path construction; continuous and matmul-stencil rules
            # instead take the closed-ring 2-D scaffold (exact along both
            # axes; n_cols == 1 self-wraps the column seam), where the
            # local substep is the plain clamped-twin step of whichever
            # counting path the key resolved
            return self._prepare_torus_2d(load_rows, h, w, rule, use_bits)

        if self.n_cols > 1:
            # 2-D mesh torus: every seam is an interior seam of the closed
            # rings (make_sharded_run_torus_2d) — packed bitboard for
            # life-like rules, int8 for multistate/wide-radius — with exact
            # divisibility in BOTH dims (words when packed, cells for
            # int8): any padding would sit inside the glued seam
            return self._prepare_torus_2d(load_rows, h, w, rule, use_bits)

        # the Pallas stripe kernel has a torus variant (seam carries wrap
        # at the logical width, closed ppermute ring): take it whenever
        # the packed layout fits its tiling with NO padded rows (padding
        # rows would sit inside the glued seam; lane-padding words are
        # fine — the kernel's wrap addresses the last LOGICAL word).
        pallas_ok = False
        tiling = None
        w_phys = 0
        if self.local_kernel == "pallas" and not use_bits:
            # an explicit pallas pin must never silently run the int8 scan
            raise ValueError(
                "local_kernel='pallas' on a torus needs the packed "
                "bitboard (life-like rule + bitpack); use "
                "local_kernel='xla'"
            )
        if use_bits:
            want_pallas = self.local_kernel == "pallas" or (
                self.local_kernel in (None, "auto")
                and self.partition_mode == "shard_map"
                and not self._pallas_interp()
            )
            if want_pallas:
                rows_exact = shard_h % SUBLANE == 0
                w_phys = ceil_to(bitlife.packed_width(w), LANE)
                if rows_exact:
                    tiling = self._pallas_tiling(h, w_phys, rule, cells=h * w)
                pallas_ok = tiling is not None and tiling[3] == shard_h
                if not pallas_ok and self.local_kernel == "pallas":
                    raise ValueError(
                        "the Pallas torus stripe kernel needs sublane-exact "
                        f"shards (board height {h} over {self.n} devices "
                        f"gives {shard_h}-row shards; need a multiple of "
                        f"{SUBLANE}) and a VMEM-feasible tiling; use "
                        "local_kernel='xla'"
                    )

        if pallas_ok:
            from tpu_life.backends.pallas_backend import make_sharded_pallas_run

            block_rows, block_steps, _, _ = tiling
            interp = self._pallas_interp()
            x = self._device_put_stream(load_rows, h, w, h, w_phys, use_bits=True)
            wp = bitlife.packed_width(w)
            return self._blocked_runner(
                x,
                block_steps,
                lambda bs: make_sharded_pallas_run(
                    rule,
                    self.mesh,
                    (h, w),
                    block_steps=bs,
                    block_rows=block_rows,
                    interpret=interp,
                    torus=True,
                ),
                lambda x: bitlife.unpack_np(np.asarray(x)[:, :wp], w),
                bitlife.live_count_packed,
            )

        block_steps = max(
            1, min(self.block_steps, shard_h // max(1, rule.radius))
        )
        if use_bits:
            wp = bitlife.packed_width(w)
            x = self._device_put_stream(load_rows, h, w, h, wp, use_bits=True)
            to_np = lambda x: bitlife.unpack_np(np.asarray(x), w)
            count = bitlife.live_count_packed
        else:
            x = self._device_put_stream(load_rows, h, w, h, w, use_bits=False)
            to_np = lambda x: np.asarray(x)
            count = bitlife.live_count_cells
        return self._blocked_runner(
            x,
            block_steps,
            lambda bs: make_sharded_run_torus(
                rule, self.mesh, (h, w), block_steps=bs, packed=use_bits
            ),
            to_np,
            count,
        )

    def _prepare_impl(self, load_rows, h: int, w: int, rule: Rule):
        if rule.boundary == "torus":
            return self._prepare_torus(load_rows, h, w, rule)
        if getattr(rule, "continuous", False):
            # the clamped sharded layout pads rows/lanes and re-masks the
            # padding dead each substep — an int8 construction
            # (ops.stencil.make_masked_step refuses float boards); the
            # torus path above runs continuous rules exactly
            raise ValueError(
                f"continuous rule {rule.name!r} on the sharded backend "
                f"needs the torus boundary (exact shapes, no padding "
                f"mask); the clamped float layout has no masked step"
            )
        logical = (h, w)
        use_bits = self._use_bits(rule)
        kernel_mode = self._resolve_local_kernel(use_bits, rule)

        pallas_tiling = None  # packed stripe kernel (life-like rules)
        int8_tiling = None  # int8 2-D-tiled kernel (LtL / Generations)

        if use_bits:
            # the Pallas stripe kernel DMAs full-width rows, so the packed
            # width must be lane-aligned (Mosaic rejects slices whose minor
            # dim isn't a multiple of 128 — hit on the reference's 500-wide
            # board, 16 words); mirror PallasBackend._prepare_packed.  The
            # extra zero words are re-masked dead every substep.
            unit = LANE if kernel_mode == "packed" else 1
            w_phys = ceil_to(bitlife.packed_width(w), self.n_cols * unit)
            to_np = lambda x: bitlife.unpack_np(
                np.asarray(x)[:h, : bitlife.packed_width(w)], w
            )
            if kernel_mode == "packed":
                pallas_tiling = self._pallas_tiling(h, w_phys, rule, cells=h * w)
                if pallas_tiling is None and self.local_kernel == "pallas":
                    raise ValueError(
                        "no Pallas stripe tiling fits the VMEM budget for this "
                        "board/mesh; use local_kernel='xla'"
                    )
        else:
            if kernel_mode == "int8":
                int8_tiling = self._pallas_int8_tiling(h, w, rule)
                if int8_tiling is None and self.local_kernel == "pallas":
                    if rule.neighborhood != "moore":
                        raise ValueError(
                            "the Pallas int8 kernel counts Moore boxes "
                            "only; von Neumann rules need local_kernel='xla'"
                        )
                    raise ValueError(
                        "no Pallas int8 tiling fits the VMEM budget for this "
                        "board/mesh; use local_kernel='xla'"
                    )
            if int8_tiling is not None:
                # halo-free layout: the epoch loop concatenates halo rows /
                # columns per block, zeros at the board edges
                w_phys = self.n_cols * int8_tiling[4]
                to_np = lambda x: np.asarray(x)[:h, :w]
            else:
                unit = LANE if self.pad_lanes else 1
                w_phys = ceil_to(w, self.n_cols * unit)
                to_np = lambda x: np.asarray(x)[:h, :w]

        if pallas_tiling is not None:
            pallas_block_rows, block_steps, _, shard_h = pallas_tiling
            h_pad = self.n * shard_h
        elif int8_tiling is not None:
            i8_br, i8_bc, block_steps, shard_h, _ = int8_tiling
            h_pad = self.n * shard_h
        else:
            # shard height must divide evenly; keep sublane (8) alignment per shard
            h_pad = ceil_to(h, self.n * 8)
            shard_h = h_pad // self.n
            block_steps = max(1, min(self.block_steps, shard_h // rule.radius))
            if self.n_cols > 1:
                shard_w = w_phys // self.n_cols
                # column-shard width bounds the halo: cells for int8, whole
                # words (32 cells each) for the packed bitboard
                cells_per_shard = shard_w * (bitlife.WORD if use_bits else 1)
                block_steps = max(1, min(block_steps, cells_per_shard // rule.radius))
        x = self._device_put_stream(load_rows, h, w, h_pad, w_phys, use_bits)

        if pallas_tiling is not None:
            from tpu_life.backends.pallas_backend import make_sharded_pallas_run

            interp = self._pallas_interp()
            make_run = lambda bs: make_sharded_pallas_run(
                rule,
                self.mesh,
                logical,
                block_steps=bs,
                block_rows=pallas_block_rows,
                interpret=interp,
            )
        elif int8_tiling is not None:
            from tpu_life.backends.pallas_backend import make_sharded_pallas_int8_run

            interp = self._pallas_interp()
            make_run = lambda bs: make_sharded_pallas_int8_run(
                rule,
                self.mesh,
                logical,
                block_steps=bs,
                block_rows=i8_br,
                block_cols=i8_bc,
                interpret=interp,
            )
        else:
            make_run = lambda bs: make_sharded_run(
                rule,
                self.mesh,
                logical,
                block_steps=bs,
                packed=use_bits,
                stencil=self._stencil(rule),
            )

        gspmd_run = (
            self._gspmd_run(rule, logical, use_bits)
            if self.partition_mode == "gspmd"
            else None
        )

        # live-cell metric as a sharded on-device reduction: each device
        # popcounts its own shard, XLA inserts the psum, two scalars reach
        # the host (SURVEY.md §5).  Padding rows/words are pinned dead by the
        # masked step (and zeroed at load), so the whole physical array is
        # countable without slicing — slicing a sharded axis would reshard.
        count_live = (
            bitlife.live_count_packed if use_bits else bitlife.live_count_cells
        )
        return self._blocked_runner(
            x, block_steps, make_run, to_np, count_live, gspmd_run
        )

    def run(
        self,
        board: np.ndarray,
        rule: Rule,
        steps: int,
        *,
        chunk_steps: int = 0,
        callback: ChunkCallback | None = None,
    ) -> np.ndarray:
        return run_with_runner(
            self, board, rule, steps, chunk_steps=chunk_steps, callback=callback
        )

    def _gspmd_run(self, rule: Rule, logical_shape, use_bits: bool):
        sharding = board_sharding(self.mesh)
        masked = (
            bitlife.make_masked_packed_step(rule, logical_shape)
            if use_bits
            else make_masked_step(rule, logical_shape, self._stencil(rule))
        )

        @partial(
            jax.jit,
            static_argnames="steps",
            donate_argnums=0,
            out_shardings=sharding,
        )
        def run(board, *, steps: int):
            out, _ = jax.lax.scan(
                lambda b, _: (masked(b), None), board, None, length=steps
            )
            return out

        return run
