"""Native C++ threaded CPU backend.

The reference's compute path is native C++ (Parallel_Life_MPI.cpp:16-54);
this backend is the framework's native CPU lineage of it — the pthread
stripe-parallel LUT stencil in native/life.cpp — sitting beside the NumPy
truth executor and the JAX device backends, bit-identical to both on every
(board, rule, steps).  Builds the library on first use when a compiler is
present; refuses cleanly otherwise.
"""

from __future__ import annotations

import numpy as np

from tpu_life.backends.base import ChunkCallback, chunk_sizes, register_backend
from tpu_life.models.rules import Rule
from tpu_life.ops import native_step


@register_backend("native")
class NativeBackend:
    name = "native"

    def __init__(self, *, threads: int | None = None, **_):
        if not native_step.available() and not native_step.build():
            import os

            if os.environ.get("TPU_LIFE_NATIVE", "1") == "0":
                raise RuntimeError(
                    "native backend unavailable: disabled by TPU_LIFE_NATIVE=0"
                )
            raise RuntimeError(
                "native backend unavailable: libtpulife_step.so not built "
                "and no working compiler (make -C native)"
            )
        self.threads = threads

    def run(
        self,
        board: np.ndarray,
        rule: Rule,
        steps: int,
        *,
        chunk_steps: int = 0,
        callback: ChunkCallback | None = None,
    ) -> np.ndarray:
        # fresh array even for steps=0 — every backend returns a board the
        # caller may mutate without aliasing the input
        board = np.array(board, dtype=np.int8)
        done = 0
        for n in chunk_sizes(steps, chunk_steps):
            board = native_step.run_native(board, rule, n, threads=self.threads)
            done += n
            if callback is not None:
                b = board
                callback(done, lambda b=b: b)
        return board
