"""Pallas TPU stencil backend: k CA steps per HBM pass.

The XLA stencil (``tpu_life.ops.stencil``) is one HBM read + one HBM write
per cell per step — XLA cannot multi-step a stencil inside one fusion
because each step's halo depends on the previous step's neighbors.  This
backend breaks that wall the TPU way: a Pallas kernel grids over 2-D tiles,
DMAs each tile *plus a deep halo* (``block_steps * radius`` cells per side)
from HBM into VMEM, advances ``block_steps`` whole CA steps on the VPU, and
writes the tile back — HBM traffic drops ~``block_steps``-fold.  It is the
single-chip twin of the sharded backend's deep-halo communication blocking
(``tpu_life.parallel.halo``): the same compute/communication trade, over
VMEM<->HBM instead of ICI.

Layout trick: the board is stored in HBM *with the halo frame baked in* — a
zero border of ``halo`` cells on all four sides.  Every tile then DMAs one
static-size, always-in-bounds window (no edge special-casing in the kernel),
and the zero frame *is* the reference's clamped dead boundary
(Parallel_Life_MPI.cpp:21-27).  The frame is re-zeroed by four cheap strip
updates between kernel calls, since each call writes a fresh output buffer.

This is the wide-radius path SURVEY.md §7.6 calls for: at Larger-than-Life
radius 5 the separable box sum does 22 shifted adds per cell per step, so
keeping the working set in VMEM across steps matters far more than for
Conway.  The rule application reuses the same branch-free compare/select
chains as the XLA stencil (one rule engine, three executors — cf.
``Rule.transition_table``).  The reference's analogue of all of this is the
nested per-cell loop at Parallel_Life_MPI.cpp:16-54.

On non-TPU platforms the kernel runs in Pallas interpret mode (exact same
code path, Python-speed) — that is how CI exercises it without a chip.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpu_life.backends.base import (
    ChunkCallback,
    Runner,
    register_backend,
    run_with_runner,
)
from tpu_life.backends.jax_backend import DeviceRunner
from tpu_life.models.rules import Rule
from tpu_life.ops.stencil import apply_rule, multi_step
from tpu_life.utils.padding import LANE, SUBLANE, ceil_to, pad_board


def _vmem_counts(x: jax.Array, rule: Rule) -> jax.Array:
    """int32 live-neighbor box counts on a VMEM-resident tile.

    Separable (2r+1)-box sum; vertical shifts are sublane concats, horizontal
    shifts are lane rotations (``pltpu.roll``).  Roll wraparound and concat
    zero-fill only corrupt the outer ``radius * step`` fringe of the tile's
    halo, which is discarded — interior cells only ever see true neighbors
    because the halo is ``block_steps * radius`` deep.
    """
    r = rule.radius
    a = (x == 1).astype(jnp.int32)
    zeros = jnp.zeros_like(a)
    # vertical box sum: acc[i] = sum_{|d|<=r} a[i+d]
    acc = a
    for d in range(1, r + 1):
        up = jnp.concatenate([a[d:], zeros[:d]], axis=0)  # a[i+d]
        down = jnp.concatenate([zeros[:d], a[:-d]], axis=0)  # a[i-d]
        acc = acc + up + down
    # horizontal box sum over acc
    w = x.shape[1]
    tot = acc
    for d in range(1, r + 1):
        tot = tot + pltpu.roll(acc, d, axis=1) + pltpu.roll(acc, w - d, axis=1)
    if not rule.include_center:
        tot = tot - a
    return tot


def make_pallas_multi_step(
    rule: Rule,
    padded_shape: tuple[int, int],
    logical: tuple[int, int],
    frame: tuple[int, int],
    *,
    block_rows: int,
    block_cols: int,
    block_steps: int,
    interpret: bool = False,
) -> Callable[[jax.Array], jax.Array]:
    """``block_steps`` CA steps as one pallas_call over 2-D tiles.

    ``padded_shape`` = interior tiles + a ``frame = (fr, fc)`` zero border;
    interior rows/cols are tiled exactly by ``block_rows x block_cols``.
    The output's frame is left unwritten — callers must re-zero it before
    the next call (see ``_zero_frame``).
    """
    hp, wp = padded_shape
    fr, fc = frame
    lh, lw = logical
    nb_r = (hp - 2 * fr) // block_rows
    nb_c = (wp - 2 * fc) // block_cols
    # each tile DMAs the full frame depth (fr >= halo, fc >= halo) so every
    # window offset is a tile-size multiple — sublane/lane-aligned for free
    ext_r = block_rows + 2 * fr
    ext_c = block_cols + 2 * fc

    def kernel(x_hbm, out_hbm, scratch, in_sem, out_sem):
        i = pl.program_id(0)
        j = pl.program_id(1)
        r0 = i * block_rows  # padded-array row of scratch row 0
        c0 = j * block_cols
        cp = pltpu.make_async_copy(
            x_hbm.at[pl.ds(r0, ext_r), pl.ds(c0, ext_c)], scratch, in_sem
        )
        cp.start()
        cp.wait()

        # validity on the *logical* board: the zero frame and any padding
        # must stay dead after every substep
        row_ids = lax.broadcasted_iota(jnp.int32, (ext_r, ext_c), 0) + (r0 - fr)
        col_ids = lax.broadcasted_iota(jnp.int32, (ext_r, ext_c), 1) + (c0 - fc)
        valid = (row_ids >= 0) & (row_ids < lh) & (col_ids >= 0) & (col_ids < lw)

        def body(_, x):
            counts = _vmem_counts(x, rule)
            return jnp.where(valid, apply_rule(x, counts, rule), jnp.int8(0))

        scratch[:] = lax.fori_loop(0, block_steps, body, scratch[:])

        wr = pltpu.make_async_copy(
            scratch.at[pl.ds(fr, block_rows), pl.ds(fc, block_cols)],
            out_hbm.at[
                pl.ds(i * block_rows + fr, block_rows),
                pl.ds(j * block_cols + fc, block_cols),
            ],
            out_sem,
        )
        wr.start()
        wr.wait()

    grid_step = pl.pallas_call(
        kernel,
        grid=(nb_r, nb_c),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        out_shape=jax.ShapeDtypeStruct((hp, wp), jnp.int8),
        scratch_shapes=[
            pltpu.VMEM((ext_r, ext_c), jnp.int8),
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA(()),
        ],
        interpret=interpret,
    )

    def step_then_zero_frame(x: jax.Array) -> jax.Array:
        y = grid_step(x)
        return _zero_frame(y, fr, fc)

    return step_then_zero_frame


def _zero_frame(y: jax.Array, fr: int, fc: int) -> jax.Array:
    """Re-zero the halo frame (the kernel writes interior tiles only)."""
    hp, wp = y.shape
    z8 = jnp.int8(0)
    y = lax.dynamic_update_slice(y, jnp.full((fr, wp), z8), (0, 0))
    y = lax.dynamic_update_slice(y, jnp.full((fr, wp), z8), (hp - fr, 0))
    y = lax.dynamic_update_slice(y, jnp.full((hp, fc), z8), (0, 0))
    y = lax.dynamic_update_slice(y, jnp.full((hp, fc), z8), (0, wp - fc))
    return y


@register_backend("pallas")
class PallasBackend:
    """Single-device Pallas deep-halo 2-D-tiled stencil backend.

    ``block_rows x block_cols`` is the VMEM tile (the working set is the
    tile plus a ``block_steps * radius`` halo, in int8 plus a few int32
    temporaries — sized to fit VMEM comfortably at the defaults);
    ``block_steps`` is how many CA steps each HBM pass advances.
    ``interpret=None`` picks compiled on TPU, interpret elsewhere.
    """

    name = "pallas"

    def __init__(
        self,
        *,
        device=None,
        block_rows: int = 256,
        block_cols: int = 512,
        block_steps: int = 8,
        interpret: bool | None = None,
        **_,
    ):
        self.device = device if device is not None else jax.devices()[0]
        self.block_rows = ceil_to(block_rows, SUBLANE)
        self.block_cols = ceil_to(block_cols, LANE)
        self.block_steps = max(1, block_steps)
        if interpret is None:
            interpret = self.device.platform != "tpu"
        self.interpret = interpret

    def prepare(self, board: np.ndarray, rule: Rule) -> Runner:
        h, w = board.shape
        logical = (h, w)
        # clamp so the halo stays a minor fraction of the tile: deeper than
        # this and the redundant fringe compute outweighs the HBM savings
        block_steps = max(
            1, min(self.block_steps, min(self.block_rows, self.block_cols) // (4 * rule.radius))
        )
        halo = rule.radius * block_steps
        if h < self.block_rows or w < self.block_cols:
            # small board: the fused XLA scan is already VMEM-resident there
            wp = ceil_to(w, LANE)
            x = jax.device_put(pad_board(board, h, wp), self.device)
            advance = lambda x, n: multi_step(x, rule=rule, steps=n, logical_shape=logical)
            return DeviceRunner(x, advance, lambda x: np.asarray(x)[:h, :w])

        # zero frame: `halo` deep, aligned so DMA window offsets stay on
        # sublane/lane boundaries (fr - halo multiple of 8, fc - halo of 128)
        fr = ceil_to(halo, SUBLANE)
        fc = ceil_to(halo, LANE)
        hp = fr + ceil_to(h, self.block_rows) + fr
        wp = fc + ceil_to(w, self.block_cols) + fc
        host = np.zeros((hp, wp), dtype=np.int8)
        host[fr : fr + h, fc : fc + w] = board
        x = jax.device_put(host, self.device)
        padded_shape = (hp, wp)
        frame = (fr, fc)

        steppers: dict[int, Callable] = {}

        def get_stepper(k: int):
            if k not in steppers:
                steppers[k] = make_pallas_multi_step(
                    rule,
                    padded_shape,
                    logical,
                    frame,
                    block_rows=self.block_rows,
                    block_cols=self.block_cols,
                    block_steps=k,
                    interpret=self.interpret,
                )
            return steppers[k]

        @partial(jax.jit, static_argnames=("blocks", "k"), donate_argnums=0)
        def run_blocks(x, *, blocks: int, k: int):
            step_k = get_stepper(k)
            out, _ = lax.scan(lambda b, _: (step_k(b), None), x, None, length=blocks)
            return out

        def advance(x, steps: int):
            blocks, rem = divmod(steps, block_steps)
            if blocks:
                x = run_blocks(x, blocks=blocks, k=block_steps)
            if rem:
                x = run_blocks(x, blocks=1, k=rem)
            return x

        return DeviceRunner(
            x, advance, lambda x: np.asarray(x)[fr : fr + h, fc : fc + w]
        )

    def run(
        self,
        board: np.ndarray,
        rule: Rule,
        steps: int,
        *,
        chunk_steps: int = 0,
        callback: ChunkCallback | None = None,
    ) -> np.ndarray:
        return run_with_runner(
            self, board, rule, steps, chunk_steps=chunk_steps, callback=callback
        )
