"""Pallas TPU stencil backend: k CA steps per HBM pass.

The XLA stencil (``tpu_life.ops.stencil``) is one HBM read + one HBM write
per cell per step — XLA cannot multi-step a stencil inside one fusion
because each step's halo depends on the previous step's neighbors.  This
backend breaks that wall the TPU way: a Pallas kernel grids over 2-D tiles,
DMAs each tile *plus a deep halo* (``block_steps * radius`` cells per side)
from HBM into VMEM, advances ``block_steps`` whole CA steps on the VPU, and
writes the tile back — HBM traffic drops ~``block_steps``-fold.  It is the
single-chip twin of the sharded backend's deep-halo communication blocking
(``tpu_life.parallel.halo``): the same compute/communication trade, over
VMEM<->HBM instead of ICI.

Layout trick: the board is stored in HBM *with the halo frame baked in* — a
zero border of ``halo`` cells on all four sides.  Every tile then DMAs one
static-size, always-in-bounds window (no edge special-casing in the kernel),
and the zero frame *is* the reference's clamped dead boundary
(Parallel_Life_MPI.cpp:21-27).  The frame is re-zeroed by four cheap strip
updates between kernel calls, since each call writes a fresh output buffer.

This is the wide-radius path SURVEY.md §7.6 calls for: at Larger-than-Life
radius 5 the separable box sum does 22 shifted adds per cell per step, so
keeping the working set in VMEM across steps matters far more than for
Conway.  The rule application reuses the same branch-free compare/select
chains as the XLA stencil (one rule engine, three executors — cf.
``Rule.transition_table``).  The reference's analogue of all of this is the
nested per-cell loop at Parallel_Life_MPI.cpp:16-54.

On non-TPU platforms the kernel runs in Pallas interpret mode (exact same
code path, Python-speed) — that is how CI exercises it without a chip.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpu_life.backends.base import (
    ChunkCallback,
    Runner,
    register_backend,
    run_with_runner,
)
from tpu_life.backends.jax_backend import DeviceRunner, packed_device_runner
from tpu_life.models.rules import Rule
from tpu_life.ops import bitlife
from tpu_life.ops.stencil import apply_rule, multi_step
from tpu_life.utils.padding import LANE, SUBLANE, ceil_to, pad_board


def _vmem_counts(x: jax.Array, rule: Rule) -> jax.Array:
    """int32 live-neighbor box counts on a VMEM-resident tile.

    Separable (2r+1)-box sum; vertical shifts are sublane concats, horizontal
    shifts are lane rotations (``pltpu.roll``).  Roll wraparound and concat
    zero-fill only corrupt the outer ``radius * step`` fringe of the tile's
    halo, which is discarded — interior cells only ever see true neighbors
    because the halo is ``block_steps * radius`` deep.
    """
    r = rule.radius
    a = (x == 1).astype(jnp.int32)
    zeros = jnp.zeros_like(a)
    # vertical box sum: acc[i] = sum_{|d|<=r} a[i+d]
    acc = a
    for d in range(1, r + 1):
        up = jnp.concatenate([a[d:], zeros[:d]], axis=0)  # a[i+d]
        down = jnp.concatenate([zeros[:d], a[:-d]], axis=0)  # a[i-d]
        acc = acc + up + down
    # horizontal box sum over acc
    w = x.shape[1]
    tot = acc
    for d in range(1, r + 1):
        tot = tot + pltpu.roll(acc, d, axis=1) + pltpu.roll(acc, w - d, axis=1)
    if not rule.include_center:
        tot = tot - a
    return tot


def _int8_substeps(scratch, valid: jax.Array, rule: Rule, block_steps: int) -> None:
    """Advance a VMEM-resident int8 tile ``block_steps`` substeps in place.

    The whole substep loop runs in int32: state is int8 only at the HBM
    boundary (Mosaic rejects selects mixing int8/int32 mask layouts).
    ``valid`` pins out-of-board cells dead after every substep.  Shared by
    the single-device 2-D-tiled kernel and its sharded twin.
    """

    def body(_, x):
        counts = _vmem_counts(x, rule)
        return jnp.where(valid, apply_rule(x, counts, rule), 0)

    xi = lax.fori_loop(0, block_steps, body, scratch[:].astype(jnp.int32))
    scratch[:] = xi.astype(jnp.int8)


def make_pallas_multi_step(
    rule: Rule,
    padded_shape: tuple[int, int],
    logical: tuple[int, int],
    frame: tuple[int, int],
    *,
    block_rows: int,
    block_cols: int,
    block_steps: int,
    interpret: bool = False,
) -> Callable[[jax.Array], jax.Array]:
    """``block_steps`` CA steps as one pallas_call over 2-D tiles.

    ``padded_shape`` = interior tiles + a ``frame = (fr, fc)`` zero border;
    interior rows/cols are tiled exactly by ``block_rows x block_cols``.
    The output's frame is left unwritten — callers must re-zero it before
    the next call (see ``_zero_frame``).
    """
    hp, wp = padded_shape
    fr, fc = frame
    lh, lw = logical
    nb_r = (hp - 2 * fr) // block_rows
    nb_c = (wp - 2 * fc) // block_cols
    # each tile DMAs the full frame depth (fr >= halo, fc >= halo) so every
    # window offset is a tile-size multiple — sublane/lane-aligned for free
    ext_r = block_rows + 2 * fr
    ext_c = block_cols + 2 * fc

    def kernel(x_hbm, out_hbm, scratch, in_sem, out_sem):
        i = pl.program_id(0)
        j = pl.program_id(1)
        r0 = i * block_rows  # padded-array row of scratch row 0
        c0 = j * block_cols
        cp = pltpu.make_async_copy(
            x_hbm.at[pl.ds(r0, ext_r), pl.ds(c0, ext_c)], scratch, in_sem
        )
        cp.start()
        cp.wait()

        # validity on the *logical* board: the zero frame and any padding
        # must stay dead after every substep
        row_ids = lax.broadcasted_iota(jnp.int32, (ext_r, ext_c), 0) + (r0 - fr)
        col_ids = lax.broadcasted_iota(jnp.int32, (ext_r, ext_c), 1) + (c0 - fc)
        valid = (row_ids >= 0) & (row_ids < lh) & (col_ids >= 0) & (col_ids < lw)

        _int8_substeps(scratch, valid, rule, block_steps)

        wr = pltpu.make_async_copy(
            scratch.at[pl.ds(fr, block_rows), pl.ds(fc, block_cols)],
            out_hbm.at[
                pl.ds(i * block_rows + fr, block_rows),
                pl.ds(j * block_cols + fc, block_cols),
            ],
            out_sem,
        )
        wr.start()
        wr.wait()

    grid_step = pl.pallas_call(
        kernel,
        grid=(nb_r, nb_c),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        out_shape=jax.ShapeDtypeStruct((hp, wp), jnp.int8),
        scratch_shapes=[
            pltpu.VMEM((ext_r, ext_c), jnp.int8),
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA(()),
        ],
        interpret=interpret,
    )

    def step_then_zero_frame(x: jax.Array) -> jax.Array:
        y = grid_step(x)
        return _zero_frame(y, fr, fc)

    return step_then_zero_frame


def _zero_frame(y: jax.Array, fr: int, fc: int) -> jax.Array:
    """Re-zero the halo frame (the kernel writes interior tiles only)."""
    hp, wp = y.shape
    z = jnp.asarray(0, y.dtype)
    if fr:
        y = lax.dynamic_update_slice(y, jnp.full((fr, wp), z), (0, 0))
        y = lax.dynamic_update_slice(y, jnp.full((fr, wp), z), (hp - fr, 0))
    if fc:
        y = lax.dynamic_update_slice(y, jnp.full((hp, fc), z), (0, 0))
        y = lax.dynamic_update_slice(y, jnp.full((hp, fc), z), (0, wp - fc))
    return y


def _packed_tile_advance(
    rule: Rule,
    tile_shape: tuple[int, int],
    logical: tuple[int, int],
    block_steps: int,
    *,
    torus: bool = False,
) -> Callable[[jax.Array, jax.Array | int], jax.Array]:
    """``advance(tile, row0) -> tile`` after ``block_steps`` masked bit-sliced
    substeps, for use *inside* a Pallas kernel on a VMEM-resident tile.

    ``row0`` is the global (logical-board) row index of tile row 0 — static
    in the single-device kernel, a scalar-prefetch value in the sharded one.

    Horizontal neighbor planes use ``pltpu.roll`` word shifts with the
    wrapped carry masked at the board's first/last lane — exactly the
    reference's clamped dead boundary (Parallel_Life_MPI.cpp:21-27) with no
    dead columns needed.  Vertical shifts clamp at tile edges
    (``bitlife._vshift``): wrong only on the halo fringe, which callers
    discard.  Cells beyond the logical board (lane padding, the last partial
    word, halo rows past the edges) are re-masked dead every substep.

    ``torus=True`` swaps the seam semantics (the VMEM twin of
    ``bitlife.make_torus_hshifts``): the lane-0 carry comes from the last
    LOGICAL word — bit ``rem-1`` re-aligned to bit 31 when the width is
    not word-aligned — and the last logical word's top valid bit receives
    column 0; ``pltpu.roll``'s physical wraparound alone would wrap at the
    lane-PADDED width, through the dead padding words.  Row wrap arrives
    as halo rows from the closed ppermute ring, so the row mask drops out
    (every tile row is real board content) while the column mask stays.
    """
    ext_r, wp = tile_shape
    lh, lw = logical
    full_words, rem_bits = divmod(lw, bitlife.WORD)
    partial = np.uint32((1 << rem_bits) - 1)
    u0 = np.uint32(0)
    ones32 = np.uint32(0xFFFFFFFF)
    # lane index of the last LOGICAL word and its top valid bit
    last_idx = full_words if rem_bits else full_words - 1
    top_bit = (rem_bits or bitlife.WORD) - 1

    def advance(tile: jax.Array, row0) -> jax.Array:
        lane = lax.broadcasted_iota(jnp.int32, (ext_r, wp), 1)
        rows = lax.broadcasted_iota(jnp.int32, (ext_r, wp), 0) + row0
        first_lane = lane == 0
        last_lane = lane == wp - 1
        last_logical = lane == last_idx

        if torus:

            def hshift_left(x):  # L[c] = x[(c-1) mod lw]: seam wraps
                # roll(x, wp - last_idx) puts the last logical word at
                # lane 0; << re-aligns its top valid bit to bit 31
                wrap = pltpu.roll(x, wp - last_idx, axis=1) << (31 - top_bit)
                carry = jnp.where(
                    first_lane, wrap, pltpu.roll(x, 1, axis=1)
                )
                return (x << 1) | (carry >> 31)

            def hshift_right(x):  # R[c] = x[(c+1) mod lw]
                carry = jnp.where(
                    last_logical, u0, pltpu.roll(x, wp - 1, axis=1)
                )
                base = (x >> 1) | (carry << 31)
                # roll(x, last_idx) puts word 0 at lane last_idx; its bit 0
                # becomes the top valid bit of the last logical word
                wrap0 = pltpu.roll(x, last_idx, axis=1)
                wrapped = (x >> 1) | ((wrap0 & 1) << top_bit)
                return jnp.where(last_logical, wrapped, base)

        else:

            def hshift_left(x):  # L[c] = x[c-1]; no left word at lane 0
                carry = jnp.where(first_lane, u0, pltpu.roll(x, 1, axis=1))
                return (x << 1) | (carry >> 31)

            def hshift_right(x):  # R[c] = x[c+1]; no right word at the last lane
                carry = jnp.where(last_lane, u0, pltpu.roll(x, wp - 1, axis=1))
                return (x >> 1) | (carry << 31)

        if rule.neighborhood == "von_neumann" and not torus:
            # the bit-sliced diamond in VMEM: shift-by-k lane rolls (the
            # adjacent-word carry is the same roll(x, 1) for any k <= 32),
            # board-edge carries clamped like the Moore shifts above
            # (torus diamonds are excluded upstream: supports_torus is
            # Moore-only, supports_diamond clamped-only)
            def hshift_left_by(x, k):
                carry = jnp.where(first_lane, u0, pltpu.roll(x, 1, axis=1))
                return (x << k) | (carry >> (32 - k))

            def hshift_right_by(x, k):
                carry = jnp.where(last_lane, u0, pltpu.roll(x, wp - 1, axis=1))
                return (x >> k) | (carry << (32 - k))

            step = bitlife.make_packed_diamond_step(
                rule, hshift_left_by, hshift_right_by, bitlife._vshift_by
            )
        else:
            step = bitlife.make_packed_step(
                rule,
                bitlife.make_total_planes(
                    hshift_left, hshift_right, bitlife._vshift
                ),
            )
        # iota/where restatement of the in-board word mask that
        # bitlife.make_masked_packed_step builds from word offsets: a captured
        # constant array is rejected by pallas_call, so the mask is rebuilt
        # from lane ids (keep in sync with col_mask's partial-word semantics)
        colmask = jnp.where(
            lane < full_words, ones32, jnp.where(lane == full_words, partial, u0)
        )
        if torus:
            mask = colmask  # halo rows are wrapped board content: all valid
        else:
            mask = jnp.where((rows >= 0) & (rows < lh), colmask, u0)

        def body(_, x):
            return step(x) & mask

        return lax.fori_loop(0, block_steps, body, tile)

    return advance


def make_pallas_packed_multi_step(
    rule: Rule,
    padded_shape: tuple[int, int],
    logical: tuple[int, int],
    fr: int,
    *,
    block_rows: int,
    block_steps: int,
    interpret: bool = False,
) -> Callable[[jax.Array], jax.Array]:
    """``block_steps`` bit-sliced CA steps as one pallas_call over row stripes.

    The fast path for life-like rules at scale: the board is a uint32
    bitboard (``tpu_life.ops.bitlife`` — 32 cells/lane, 8x less HBM traffic
    than int8), tiled as **full-width row stripes** so the only halo is
    vertical (``fr >= block_steps`` rows).  Each stripe is DMA'd into VMEM
    once, advanced ``block_steps`` whole steps with the carry-save adder
    tree (``_packed_tile_advance``), and written back — compute per HBM byte
    goes up ``block_steps``-x on top of bit-slicing's 8x.
    """
    hp, wp = padded_shape
    nb_r = (hp - 2 * fr) // block_rows
    ext_r = block_rows + 2 * fr
    advance = _packed_tile_advance(rule, (ext_r, wp), logical, block_steps)

    def kernel(x_hbm, out_hbm, scratch, in_sem, out_sem):
        i = pl.program_id(0)
        r0 = i * block_rows  # padded-array row of scratch row 0
        cp = pltpu.make_async_copy(
            x_hbm.at[pl.ds(r0, ext_r), :], scratch, in_sem
        )
        cp.start()
        cp.wait()

        scratch[:] = advance(scratch[:], r0 - fr)

        wr = pltpu.make_async_copy(
            scratch.at[pl.ds(fr, block_rows), :],
            out_hbm.at[pl.ds(r0 + fr, block_rows), :],
            out_sem,
        )
        wr.start()
        wr.wait()

    grid_step = pl.pallas_call(
        kernel,
        grid=(nb_r,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        out_shape=jax.ShapeDtypeStruct((hp, wp), jnp.uint32),
        scratch_shapes=[
            pltpu.VMEM((ext_r, wp), jnp.uint32),
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA(()),
        ],
        interpret=interpret,
    )

    def step_then_zero_frame(x: jax.Array) -> jax.Array:
        return _zero_frame(grid_step(x), fr, 0)

    return step_then_zero_frame


def make_pallas_sharded_stripe_block(
    rule: Rule,
    ext_shape: tuple[int, int],
    logical: tuple[int, int],
    fr: int,
    *,
    block_rows: int,
    block_steps: int,
    interpret: bool = False,
    torus: bool = False,
) -> Callable[..., jax.Array]:
    """The per-shard twin of :func:`make_pallas_packed_multi_step`.

    ``block(top, chunk, bot, row0) -> chunk``: one deep-halo block
    (``block_steps`` bit-sliced CA steps) on a shard's packed chunk plus
    its ``fr``-row halos, gridding over row stripes.  The halos arrive as
    SEPARATE arrays (the ppermute outputs) rather than pre-concatenated:
    edge tiles stitch their VMEM window from two inputs inside the kernel
    DMA, so the whole-chunk HBM copy a ``jnp.concatenate`` would cost per
    block never happens — on a 16384² shard that copy was ~10% of the
    composed path's step time.  Requires ``block_rows >= fr`` so interior
    tiles stay within the chunk (enforced by the tiling search).  ``row0``
    (global row of virtual ext row 0, i.e. of ``top[0]``) is a traced
    scalar delivered via prefetch so the validity mask can pin out-of-board
    rows dead at any mesh position.
    """
    ext_rows, wp = ext_shape
    out_rows = ext_rows - 2 * fr
    nb_r = out_rows // block_rows
    ext_r = block_rows + 2 * fr
    if nb_r > 1 and block_rows < fr:
        raise ValueError(
            f"block_rows {block_rows} < halo depth {fr}: edge-tile DMA "
            "stitching needs block_rows >= fr"
        )
    advance = _packed_tile_advance(
        rule, (ext_r, wp), logical, block_steps, torus=torus
    )

    def kernel(row0_ref, top_hbm, x_hbm, bot_hbm, out_hbm, scratch, in_sems, out_sem):
        i = pl.program_id(0)
        r0 = i * block_rows  # virtual ext row of scratch row 0

        def dma_all(*pairs):
            # segments target disjoint scratch rows: start every copy
            # before waiting so the stitch overlaps instead of serializing
            cps = [
                pltpu.make_async_copy(src, dst, in_sems.at[j])
                for j, (src, dst) in enumerate(pairs)
            ]
            for cp in cps:
                cp.start()
            for cp in cps:
                cp.wait()

        # virtual ext rows: [0, fr) = top, [fr, fr+out_rows) = chunk,
        # [fr+out_rows, ...) = bot; stitch this tile's window per segment
        if nb_r == 1:
            dma_all(
                (top_hbm.at[:, :], scratch.at[pl.ds(0, fr), :]),
                (x_hbm.at[:, :], scratch.at[pl.ds(fr, out_rows), :]),
                (bot_hbm.at[:, :], scratch.at[pl.ds(fr + out_rows, fr), :]),
            )
        else:

            @pl.when(i == 0)
            def _():
                dma_all(
                    (top_hbm.at[:, :], scratch.at[pl.ds(0, fr), :]),
                    (
                        x_hbm.at[pl.ds(0, block_rows + fr), :],
                        scratch.at[pl.ds(fr, block_rows + fr), :],
                    ),
                )

            @pl.when((i > 0) & (i < nb_r - 1))
            def _():
                # i*block_rows - fr is a multiple of 8 (both terms are),
                # but Mosaic's divisibility prover can't see through the
                # subtraction — assert it
                start = pl.multiple_of(r0 - fr, SUBLANE)
                dma_all((x_hbm.at[pl.ds(start, ext_r), :], scratch.at[:, :]))

            @pl.when(i == nb_r - 1)
            def _():
                dma_all(
                    (
                        x_hbm.at[pl.ds(out_rows - block_rows - fr, block_rows + fr), :],
                        scratch.at[pl.ds(0, block_rows + fr), :],
                    ),
                    (
                        bot_hbm.at[:, :],
                        scratch.at[pl.ds(block_rows + fr, fr), :],
                    ),
                )

        scratch[:] = advance(scratch[:], row0_ref[0] + r0)

        wr = pltpu.make_async_copy(
            scratch.at[pl.ds(fr, block_rows), :],
            out_hbm.at[pl.ds(r0, block_rows), :],
            out_sem,
        )
        wr.start()
        wr.wait()

    stepper = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(nb_r,),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 3,
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            scratch_shapes=[
                pltpu.VMEM((ext_r, wp), jnp.uint32),
                pltpu.SemaphoreType.DMA((3,)),  # one per stitch segment
                pltpu.SemaphoreType.DMA(()),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((out_rows, wp), jnp.uint32),
        interpret=interpret,
    )

    def block(
        top: jax.Array, chunk: jax.Array, bot: jax.Array, row0: jax.Array
    ) -> jax.Array:
        return stepper(jnp.atleast_1d(row0).astype(jnp.int32), top, chunk, bot)

    return block


def _sharded_epoch_loop(
    mesh,
    row_axis: str,
    fr: int,
    make_block,
    *,
    col_axis: str | None = None,
    fc: int = 0,
    halo_cols: int = 0,
    periodic: bool = False,
) -> Callable[[jax.Array, int], jax.Array]:
    """Shared scaffold for the sharded Pallas runs: ``ppermute`` row halos
    — non-periodic by default (skipped entirely on one-shard axes, where
    both neighbors are off the mesh end — VERDICT r3 item 2), a closed
    ring with ``periodic=True`` (the packed torus; fc == 0 convention
    only) — a ``lax.scan`` over deep-halo blocks, and the jit + shard_map
    wrapper.

    Two kernel conventions, switched on ``fc``:

    - ``fc == 0`` (packed stripes): ``make_block(hl, wl) ->
      block(top, chunk, bot, row0)`` — the halos stay separate arrays and
      the kernel stitches its DMA windows, so no whole-chunk copy happens
      per block.
    - ``fc > 0`` (int8 2-D tiles): ``make_block(hl, wl) ->
      block(ext, row0, col0)`` — the loop materializes the row+column
      extended chunk (the column phase needs it).  ``(row0, col0)`` are the
      global board coordinates of ext cell (0, 0).

    Columns: with ``fc > 0`` the chunk is column-extended too.  On a 2-D
    mesh (``col_axis`` sized > 1) only the ``halo_cols`` edge columns that
    the stencil actually needs ride the column ``ppermute`` — they are
    exchanged *after* (and including) the row extension, so corner cells
    arrive transitively, exactly like the two-phase XLA exchange
    (tpu_life.parallel.halo) — and are padded with dead zeros out to the
    lane-aligned ``fc`` the kernel DMA windows require.  On a 1-D mesh the
    whole column extension is the zero frame (the clamped board edge).
    """
    from jax.sharding import PartitionSpec as P

    # no jax.experimental fallback here: the call below passes check_vma,
    # which the pre-0.6 experimental shard_map (check_rep) would reject —
    # a fallback import could never actually run (ADVICE r2)
    from jax import shard_map

    n_r = mesh.shape[row_axis]
    split_cols = col_axis is not None and mesh.shape.get(col_axis, 1) > 1
    n_c = mesh.shape[col_axis] if split_cols else 1
    if periodic:
        # the closed ring: the wrap pair the clamped exchange omits
        # (tpu_life.parallel.halo.make_sharded_run_torus's ppermute shape)
        fwd_r = [(i, (i + 1) % n_r) for i in range(n_r)]
        bwd_r = [((i + 1) % n_r, i) for i in range(n_r)]
    else:
        fwd_r = [(i, i + 1) for i in range(n_r - 1)]
        bwd_r = [(i + 1, i) for i in range(n_r - 1)]
    fwd_c = [(i, i + 1) for i in range(n_c - 1)]
    bwd_c = [(i + 1, i) for i in range(n_c - 1)]

    def local_run(chunk: jax.Array, num_blocks: int) -> jax.Array:
        hl, wl = chunk.shape
        if fr > hl or (split_cols and halo_cols > wl):
            raise ValueError(
                f"halo depth {(fr, halo_cols)} exceeds shard shape "
                f"{(hl, wl)}; lower block_steps or use a smaller mesh"
            )
        kern = make_block(hl, wl)
        ri = lax.axis_index(row_axis)
        row0 = ri * hl - fr  # global row of ext row 0
        if split_cols:
            col0 = lax.axis_index(col_axis) * wl - fc
        else:
            col0 = -fc

        zero_rows = jnp.zeros((fr, wl), chunk.dtype)
        er = hl + 2 * fr

        def block(c: jax.Array) -> jax.Array:
            if n_r == 1:
                if periodic:
                    # one shard: its own edges ARE the wrap neighbors
                    top = c[hl - fr :, :]
                    bot = c[:fr, :]
                else:
                    top = bot = zero_rows
            else:
                # clamped: ppermute zero-fills at the mesh ends = the dead
                # boundary; periodic: the ring is closed, every shard has
                # both neighbors
                top = lax.ppermute(c[hl - fr :, :], row_axis, fwd_r)
                bot = lax.ppermute(c[:fr, :], row_axis, bwd_r)
            if not fc:
                # split-halo convention: the kernel stitches its own DMA
                # windows from (top, chunk, bot) — no whole-chunk copy
                return kern(top, c, bot, row0)
            ext = jnp.concatenate([top, c, bot], axis=0)
            if split_cols:
                # exchange only the stencil-needed edge columns of the
                # row-extended chunk; pad to the aligned fc with zeros
                left = lax.ppermute(ext[:, wl - halo_cols :], col_axis, fwd_c)
                right = lax.ppermute(ext[:, :halo_cols], col_axis, bwd_c)
                pad = jnp.zeros((er, fc - halo_cols), chunk.dtype)
                ext = jnp.concatenate([pad, left, ext, right, pad], axis=1)
            else:
                zpad = jnp.zeros((er, fc), chunk.dtype)
                ext = jnp.concatenate([zpad, ext, zpad], axis=1)
            return kern(ext, row0, col0)

        out, _ = lax.scan(
            lambda c, _: (block(c), None), chunk, None, length=num_blocks
        )
        return out

    spec = P(row_axis, col_axis if split_cols else None)

    @partial(jax.jit, static_argnames="num_blocks", donate_argnums=0)
    def run(board: jax.Array, num_blocks: int) -> jax.Array:
        # check_vma=False: varying-mesh-axes tracking still cannot check this
        # path.  Revisited 2026-07 (VERDICT r3 weak #6): pallas_call's
        # out_shape now *accepts* a vma annotation, but the checker then
        # aborts one level up — dynamic_slice "requires varying manual axes
        # to match, got [{'rows'}, {}, {}]" — and JAX's own error text says
        # to file an issue and pass check_vma=False as the workaround.
        # Re-verified on jax 0.9.0, 2026-07-30 (round 5): unannotated
        # out_shape still demands vma, annotated still dies in the
        # dynamic_slice checker; status unchanged.  The
        # specs still partition the board; only the extra static consistency
        # check is off, and the glider-across-seam + cross-executor
        # bit-identity tests cover the same invariant dynamically.
        return shard_map(
            partial(local_run, num_blocks=num_blocks),
            mesh=mesh,
            in_specs=spec,
            out_specs=spec,
            check_vma=False,
        )(board)

    return run


def sharded_pallas_halo_rows(rule: Rule, block_steps: int) -> int:
    """ppermute payload / kernel halo depth for the sharded stripe kernel:
    sublane-aligned so every DMA window offset stays aligned.  The single
    source of truth for both the tiling feasibility check
    (``ShardedBackend._pallas_tiling``) and the kernel construction below.
    """
    from tpu_life.parallel.halo import halo_depth

    return ceil_to(halo_depth(rule, block_steps), SUBLANE)


def make_sharded_pallas_run(
    rule: Rule,
    mesh,
    logical_shape: tuple[int, int],
    *,
    block_steps: int = 1,
    block_rows: int = 256,
    row_axis: str | None = None,
    interpret: bool = False,
    torus: bool = False,
) -> Callable[[jax.Array, int], jax.Array]:
    """``run(board, num_blocks)``: the sharded epoch loop with the Pallas
    stripe kernel as the local stepper — single-chip kernel throughput on a
    multi-chip mesh.

    The composition VERDICT.md round 1 called for: halos move over ICI via
    non-periodic ``ppermute`` exactly as in ``tpu_life.parallel.halo``
    (the reference's ``MPI_Sendrecv`` ring, Parallel_Life_MPI.cpp:104-145),
    while each shard's ``block_steps`` substeps run in the deep-halo VMEM
    kernel instead of the XLA scan.  1-D row meshes + packed bitboards only
    (the headline configuration); the XLA path remains for 2-D meshes and
    non-life-like rules.

    The ppermute payload is ``fr = ceil8(radius * block_steps)`` rows —
    sublane-aligned so every kernel DMA window stays aligned; the few extra
    halo rows are real neighbor rows and simply widen the valid fringe.
    """
    from tpu_life.parallel.mesh import ROW_AXIS

    if row_axis is None:
        row_axis = ROW_AXIS
    fr = sharded_pallas_halo_rows(rule, block_steps)

    def make_block(hl: int, wp: int):
        if hl % block_rows:
            raise ValueError(
                f"shard height {hl} not a multiple of block_rows {block_rows}"
            )
        # split-halo convention (fc == 0): block(top, chunk, bot, row0)
        return make_pallas_sharded_stripe_block(
            rule,
            (hl + 2 * fr, wp),
            tuple(logical_shape),
            fr,
            block_rows=block_rows,
            block_steps=block_steps,
            interpret=interpret,
            torus=torus,
        )

    return _sharded_epoch_loop(mesh, row_axis, fr, make_block, periodic=torus)


def sharded_pallas_int8_frame(rule: Rule, block_steps: int) -> tuple[int, int]:
    """(fr, fc) halo extension depths for the sharded int8 kernel: rows
    sublane-aligned, columns lane-aligned (both concatenated onto the shard
    per block by the epoch loop — neighbor data up to the stencil's reach,
    dead zeros beyond; the shard layout itself is halo-free).  Single source
    of truth for ``ShardedBackend._pallas_int8_tiling`` and the kernel
    construction below."""
    from tpu_life.parallel.halo import halo_depth

    d = halo_depth(rule, block_steps)
    return ceil_to(d, SUBLANE), ceil_to(d, LANE)


def make_pallas_sharded_int8_block(
    rule: Rule,
    ext_shape: tuple[int, int],
    logical: tuple[int, int],
    frame: tuple[int, int],
    *,
    block_rows: int,
    block_cols: int,
    block_steps: int,
    interpret: bool = False,
) -> Callable[[jax.Array, jax.Array, jax.Array], jax.Array]:
    """The per-shard twin of :func:`make_pallas_multi_step` — wide-radius /
    multistate rules on a mesh-sharded board (SURVEY.md §7.6's deep-halo
    design composed with the mesh; reference analogue: the ghost-row scheme
    of Parallel_Life_MPI.cpp:104-145 at radius > 1, generalized to 2-D
    block decompositions).

    ``block(ext_chunk, row0, col0) -> chunk``: ``block_steps`` int8 CA
    steps on a shard's halo-extended chunk, gridding over 2-D tiles.  Both
    halos (``fr`` rows, ``fc`` cols) arrive concatenated onto the chunk by
    the epoch loop — neighbor data on interior edges, zeros at the mesh
    ends — and are dropped from the output, which therefore tiles exactly
    (no unwritten frame to re-zero).  ``row0``/``col0`` (the global board
    coordinates of ext cell (0, 0)) are scalar-prefetched so the in-kernel
    validity mask can pin out-of-board cells dead on every mesh position.
    """
    ext_rows, ext_cols = ext_shape
    fr, fc = frame
    lh, lw = logical
    out_rows = ext_rows - 2 * fr
    out_cols = ext_cols - 2 * fc
    nb_r = out_rows // block_rows
    nb_c = out_cols // block_cols
    ext_r = block_rows + 2 * fr
    ext_c = block_cols + 2 * fc

    def kernel(origin_ref, x_hbm, out_hbm, scratch, in_sem, out_sem):
        i = pl.program_id(0)
        j = pl.program_id(1)
        r0 = i * block_rows  # ext-chunk row of scratch row 0
        c0 = j * block_cols  # ext-chunk col of scratch col 0
        cp = pltpu.make_async_copy(
            x_hbm.at[pl.ds(r0, ext_r), pl.ds(c0, ext_c)], scratch, in_sem
        )
        cp.start()
        cp.wait()

        # validity on the logical board: the scalar-prefetched origin is
        # the global coordinate of ext cell (0, 0) for this shard
        row_ids = lax.broadcasted_iota(jnp.int32, (ext_r, ext_c), 0) + (
            origin_ref[0] + r0
        )
        col_ids = lax.broadcasted_iota(jnp.int32, (ext_r, ext_c), 1) + (
            origin_ref[1] + c0
        )
        valid = (row_ids >= 0) & (row_ids < lh) & (col_ids >= 0) & (col_ids < lw)

        _int8_substeps(scratch, valid, rule, block_steps)

        wr = pltpu.make_async_copy(
            scratch.at[pl.ds(fr, block_rows), pl.ds(fc, block_cols)],
            out_hbm.at[pl.ds(r0, block_rows), pl.ds(c0, block_cols)],
            out_sem,
        )
        wr.start()
        wr.wait()

    stepper = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(nb_r, nb_c),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            scratch_shapes=[
                pltpu.VMEM((ext_r, ext_c), jnp.int8),
                pltpu.SemaphoreType.DMA(()),
                pltpu.SemaphoreType.DMA(()),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((out_rows, out_cols), jnp.int8),
        interpret=interpret,
    )

    def block(ext: jax.Array, row0: jax.Array, col0: jax.Array) -> jax.Array:
        origin = jnp.stack(
            [jnp.asarray(row0, jnp.int32), jnp.asarray(col0, jnp.int32)]
        )
        return stepper(origin, ext)

    return block


def make_sharded_pallas_int8_run(
    rule: Rule,
    mesh,
    logical_shape: tuple[int, int],
    *,
    block_steps: int = 1,
    block_rows: int = 256,
    block_cols: int = 512,
    row_axis: str | None = None,
    col_axis: str | None = None,
    interpret: bool = False,
) -> Callable[[jax.Array, int], jax.Array]:
    """``run(board, num_blocks)``: the sharded epoch loop with the int8
    deep-halo kernel as the local stepper — Larger-than-Life / Generations
    rules at single-chip kernel throughput on a multi-chip mesh (VERDICT r3
    item 3; BASELINE.md row 6's weak-scaling config).

    Works on 1-D row meshes and 2-D rows × cols block meshes alike: the
    epoch loop extends each shard with ``fr`` halo rows and ``fc`` halo
    columns per side (ppermute on sharded axes, zeros at mesh ends / on a
    1-D mesh), and the kernel tiles the extended chunk in 2-D.  Only the
    ``radius * block_steps`` columns the stencil needs ride the column
    exchange; the rest of the lane-aligned ``fc`` is dead padding.
    """
    from tpu_life.parallel.halo import halo_depth
    from tpu_life.parallel.mesh import COL_AXIS, ROW_AXIS

    if row_axis is None:
        row_axis = ROW_AXIS
    if col_axis is None:
        col_axis = COL_AXIS
    fr, fc = sharded_pallas_int8_frame(rule, block_steps)

    def make_block(hl: int, wl: int):
        if hl % block_rows or wl % block_cols:
            raise ValueError(
                f"shard {(hl, wl)} not tiled by blocks "
                f"{(block_rows, block_cols)}"
            )
        return make_pallas_sharded_int8_block(
            rule,
            (hl + 2 * fr, wl + 2 * fc),
            tuple(logical_shape),
            (fr, fc),
            block_rows=block_rows,
            block_cols=block_cols,
            block_steps=block_steps,
            interpret=interpret,
        )

    return _sharded_epoch_loop(
        mesh,
        row_axis,
        fr,
        make_block,
        col_axis=col_axis,
        fc=fc,
        halo_cols=halo_depth(rule, block_steps),
    )


@register_backend("pallas")
class PallasBackend:
    """Single-device Pallas deep-halo 2-D-tiled stencil backend.

    ``block_rows x block_cols`` is the VMEM tile (the working set is the
    tile plus a ``block_steps * radius`` halo, in int8 plus a few int32
    temporaries — sized to fit VMEM comfortably at the defaults);
    ``block_steps`` is how many CA steps each HBM pass advances.
    ``interpret=None`` picks compiled on TPU, interpret elsewhere.
    """

    name = "pallas"

    def __init__(
        self,
        *,
        device=None,
        block_rows: int = 256,
        block_cols: int = 512,
        block_steps: int | None = None,
        bitpack: bool = True,
        interpret: bool | None = None,
        **_,
    ):
        self.device = device if device is not None else jax.devices()[0]
        self.block_rows = ceil_to(block_rows, SUBLANE)
        self.block_cols = ceil_to(block_cols, LANE)
        # measured on v5e: int8 peaks at k=8; packed at k=16 for HBM-bound
        # boards (2.2e12 cells/s at 16384^2) but k=8 when the board is small
        # enough that the halo fringe recompute dominates (4096^2: 1.5e12 at
        # k=8 vs 1.1e12 at k=16) — see experiments/pallas_bench.py
        self._block_steps_arg = block_steps
        self.block_steps = max(1, 8 if block_steps is None else block_steps)
        self.bitpack = bitpack
        if interpret is None:
            interpret = self.device.platform != "tpu"
        self.interpret = interpret

    @staticmethod
    def _make_runner(
        x,
        make_stepper: Callable[[int], Callable],
        block_steps: int,
        to_np,
        count_live=None,
    ):
        """Shared scaffolding over a ``make_stepper(k)`` factory: per-k stepper
        cache, jitted donate-in-place scan over blocks, remainder split."""
        steppers: dict[int, Callable] = {}

        def get_stepper(k: int):
            if k not in steppers:
                steppers[k] = make_stepper(k)
            return steppers[k]

        @partial(jax.jit, static_argnames=("blocks", "k"), donate_argnums=0)
        def run_blocks(x, *, blocks: int, k: int):
            step_k = get_stepper(k)
            out, _ = lax.scan(lambda b, _: (step_k(b), None), x, None, length=blocks)
            return out

        def advance(x, steps: int):
            blocks, rem = divmod(steps, block_steps)
            if blocks:
                x = run_blocks(x, blocks=blocks, k=block_steps)
            if rem:
                x = run_blocks(x, blocks=1, k=rem)
            return x

        return DeviceRunner(x, advance, to_np, count_live=count_live)

    # stripe-scratch budget: ext_r x wp uint32 must leave Mosaic's ~16 MB
    # scoped VMEM room for the adder tree's temporaries
    MAX_PACKED_TILE_BYTES = 2 << 20

    def _packed_tiling(
        self, h: int, w: int, radius: int = 1
    ) -> tuple[int, int, int] | None:
        """(block_rows, block_steps, fr) for the packed stripe kernel, or
        None when no full-width stripe fits the VMEM budget (very wide
        boards fall back to the column-tiled int8 kernel).  ``radius``
        scales the halo (the bit-sliced diamond runs r=2 stripes too)."""
        wp = ceil_to(bitlife.packed_width(w), LANE)
        ext_budget = self.MAX_PACKED_TILE_BYTES // (wp * 4) // SUBLANE * SUBLANE
        if self._block_steps_arg is None:
            want = 16 if h * w >= 8192 * 8192 else 8
        else:
            want = max(1, self._block_steps_arg)
        for k in range(want, 0, -1):
            fr = ceil_to(radius * k, SUBLANE)
            block_rows = min(self.block_rows, ext_budget - 2 * fr)
            if (
                block_rows >= SUBLANE
                and radius * k <= block_rows // 4
                and h >= block_rows
            ):
                return block_rows, k, fr
        return None

    def _prepare_packed(
        self, board: np.ndarray, rule: Rule, tiling: tuple[int, int, int]
    ) -> Runner:
        """Bit-sliced stripe-tiled path (life-like rules)."""
        h, w = board.shape
        block_rows, block_steps, fr = tiling
        hp = fr + ceil_to(h, block_rows) + fr
        packed = bitlife.pack_np(np.asarray(board, np.int8))
        wp = ceil_to(packed.shape[1], LANE)
        host = np.zeros((hp, wp), dtype=np.uint32)
        host[fr : fr + h, : packed.shape[1]] = packed
        x = jax.device_put(host, self.device)

        def make_stepper(k: int):
            return make_pallas_packed_multi_step(
                rule,
                (hp, wp),
                (h, w),
                fr,
                block_rows=block_rows,
                block_steps=k,
                interpret=self.interpret,
            )

        return self._make_runner(
            x,
            make_stepper,
            block_steps,
            lambda x: bitlife.unpack_np(np.asarray(x)[fr : fr + h], w),
            # the frame rows are re-masked dead every step, but count only
            # the logical rows anyway so the invariant isn't load-bearing
            count_live=jax.jit(lambda x: bitlife.live_count_packed(x[fr : fr + h])),
        )

    def _xla_scan_runner(
        self, board: np.ndarray, rule: Rule, logical: tuple[int, int]
    ) -> Runner:
        """Fused-XLA-scan DeviceRunner — the single fallback for every case
        no Pallas kernel covers (small boards, non-Moore neighborhoods,
        torus topology)."""
        h, w = logical
        if self.bitpack and bitlife.supports(rule):
            return packed_device_runner(board, rule, self.device)
        if self.bitpack and bitlife.supports_diamond(rule):
            # 2-state NN rules keep the bit-sliced diamond here too —
            # `auto` resolves single-chip TPU runs to this backend, so a
            # missing dispatch would silently re-open the int8 fallback
            # the diamond executor replaced
            return packed_device_runner(
                board,
                rule,
                self.device,
                advance=lambda x, n: bitlife.multi_step_packed_diamond(
                    x, rule=rule, steps=n, logical_shape=logical
                ),
            )
        if self.bitpack and bitlife.supports_torus(rule):
            return packed_device_runner(
                board,
                rule,
                self.device,
                advance=lambda x, n: bitlife.multi_step_packed_torus(
                    x, rule=rule, steps=n, width=w
                ),
            )
        # torus boards stay unpadded (the rolls wrap at the logical edges)
        wp = ceil_to(w, LANE) if rule.boundary == "clamped" else w
        x = jax.device_put(pad_board(board, h, wp), self.device)
        advance = lambda x, n: multi_step(
            x, rule=rule, steps=n, logical_shape=logical
        )
        return DeviceRunner(
            x,
            advance,
            lambda x: np.asarray(x)[:h, :w],
            count_live=bitlife.live_count_cells,
        )

    def prepare(self, board: np.ndarray, rule: Rule) -> Runner:
        h, w = board.shape
        logical = (h, w)
        if self.bitpack and bitlife.supports_diamond(rule):
            # 2-state clamped von Neumann: the stripe kernel runs the
            # bit-sliced diamond in VMEM (roll shift-by-k planes under the
            # same CSA reduction); small boards fall back to the fused XLA
            # packed diamond scan inside _xla_scan_runner
            tiling = self._packed_tiling(h, w, radius=rule.radius)
            if tiling is not None:
                return self._prepare_packed(board, rule, tiling)
            return self._xla_scan_runner(board, rule, logical)
        if rule.neighborhood != "moore" or rule.boundary != "clamped":
            # the remaining Pallas kernels count clamped Moore boxes;
            # other diamonds and torus wraparound run on the fused XLA
            # scan (whose stencil supports them) or its packed variants
            return self._xla_scan_runner(board, rule, logical)
        if self.bitpack and bitlife.supports(rule):
            tiling = self._packed_tiling(h, w)
            if tiling is not None:
                return self._prepare_packed(board, rule, tiling)
        # clamp so the halo stays a minor fraction of the tile: deeper than
        # this and the redundant fringe compute outweighs the HBM savings
        block_steps = max(
            1, min(self.block_steps, min(self.block_rows, self.block_cols) // (4 * rule.radius))
        )
        halo = rule.radius * block_steps
        if h < self.block_rows or w < self.block_cols:
            # small board: the fused XLA scan is already VMEM-resident there;
            # _xla_scan_runner keeps the bit-sliced fast path when the rule
            # allows it
            return self._xla_scan_runner(board, rule, logical)

        # zero frame: `halo` deep, aligned so DMA window offsets stay on
        # sublane/lane boundaries (fr - halo multiple of 8, fc - halo of 128)
        fr = ceil_to(halo, SUBLANE)
        fc = ceil_to(halo, LANE)
        hp = fr + ceil_to(h, self.block_rows) + fr
        wp = fc + ceil_to(w, self.block_cols) + fc
        host = np.zeros((hp, wp), dtype=np.int8)
        host[fr : fr + h, fc : fc + w] = board
        x = jax.device_put(host, self.device)
        padded_shape = (hp, wp)
        frame = (fr, fc)

        def make_stepper(k: int):
            return make_pallas_multi_step(
                rule,
                padded_shape,
                logical,
                frame,
                block_rows=self.block_rows,
                block_cols=self.block_cols,
                block_steps=k,
                interpret=self.interpret,
            )

        return self._make_runner(
            x,
            make_stepper,
            block_steps,
            lambda x: np.asarray(x)[fr : fr + h, fc : fc + w],
            count_live=jax.jit(
                lambda x: bitlife.live_count_cells(x[fr : fr + h, fc : fc + w])
            ),
        )

    def run(
        self,
        board: np.ndarray,
        rule: Rule,
        steps: int,
        *,
        chunk_steps: int = 0,
        callback: ChunkCallback | None = None,
    ) -> np.ndarray:
        return run_with_runner(
            self, board, rule, steps, chunk_steps=chunk_steps, callback=callback
        )
