from tpu_life.backends.base import Backend, get_backend, BACKENDS

__all__ = ["Backend", "get_backend", "BACKENDS"]
