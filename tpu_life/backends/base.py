"""The Backend interface — the seam the reference never factored.

The reference fuses decomposition, exchange, kernel and driver into one
``main`` (Parallel_Life_MPI.cpp:190-240).  Here a backend is one object with
one method: advance a board ``steps`` steps.  All backends are bit-identical
on the same (board, rule, steps) — that invariant *is* the test strategy
(SURVEY.md §4) — and differ only in where and how the work runs:

- ``numpy``   pure-NumPy truth executor, single process
- ``jax``     single-device XLA (TPU when present), fused scan epoch loop
- ``sharded`` row-sharded over a device mesh, ppermute halos
- ``stripes`` CPU stripe-decomposition simulator mirroring the reference's
              rank structure (explicit halos, MPI-lineage shape)
- ``pallas``  single-device Pallas TPU stencil kernel
"""

from __future__ import annotations

from typing import Callable, Protocol

import numpy as np

from tpu_life.models.rules import Rule

# callback(step_index, get_board) where get_board() lazily materializes the
# current board as np.int8 — laziness keeps device->host transfers out of the
# hot loop unless a subscriber (snapshots, metrics, verbose dump) asks.
ChunkCallback = Callable[[int, Callable[[], np.ndarray]], None]


class Runner(Protocol):
    """Device-resident run handle: state stays on device between advances.

    This is the seam the benchmark times — ``advance`` queues work with no
    host round-trip; ``sync`` forces completion (a 1-element readback, which
    defeats async completion reporting on tunneled devices); ``fetch``
    materializes the board on host.
    """

    def advance(self, steps: int) -> None: ...

    def sync(self) -> None: ...

    def fetch(self) -> np.ndarray: ...

    def snapshot(self) -> Callable[[], np.ndarray]:
        """A ``get_board`` thunk bound to the *current* state (not late-bound
        to whatever the runner holds when the thunk finally runs)."""
        ...

    def live_count(self) -> int:
        """Exact count of live (state 1) cells — on device runners a sharded
        on-device reduction, never a board gather (SURVEY.md §5)."""
        ...


class HostRunner:
    """Fallback Runner for host backends (numpy / stripes): state is a
    host array and ``advance`` just calls ``backend.run`` on it."""

    def __init__(self, backend: "Backend", board: np.ndarray, rule: Rule):
        self.backend = backend
        self.board = np.asarray(board, np.int8)
        self.rule = rule

    def advance(self, steps: int) -> None:
        self.board = self.backend.run(self.board, self.rule, steps)

    def sync(self) -> None:
        pass

    def fetch(self) -> np.ndarray:
        return self.board

    def snapshot(self) -> Callable[[], np.ndarray]:
        return lambda board=self.board: board

    def live_count(self) -> int:
        return int(np.count_nonzero(self.board == 1))


class Backend(Protocol):
    name: str

    def run(
        self,
        board: np.ndarray,
        rule: Rule,
        steps: int,
        *,
        chunk_steps: int = 0,
        callback: ChunkCallback | None = None,
    ) -> np.ndarray: ...

def make_runner(
    backend: "Backend",
    board: np.ndarray,
    rule: Rule,
    *,
    seed: int = 0,
    temperature: float | None = None,
    start_step: int = 0,
    packed: bool | None = None,
) -> Runner:
    """Stage ``board`` on the backend's devices and return a Runner.

    Backends with device-resident state implement ``prepare``; host
    backends fall back to ``HostRunner``.  Stochastic rules
    (``tpu_life.mc``) dispatch to the MC runners, which also consume the
    counter-based PRNG state: ``seed`` names the stream, ``start_step``
    is the absolute resume point (so checkpoint/resume re-enters the
    stream exactly), ``temperature`` is the ising scalar.  Backends
    without the key schedule are a typed rejection.  ``packed`` pins the
    stochastic bitplane path on or off (None = the backend's own
    ``bitpack`` default); deterministic rules ignore it (their packing
    is a backend-construction knob).
    """
    if getattr(rule, "stochastic", False):
        from tpu_life.mc.engine import mc_runner_for

        return mc_runner_for(
            backend,
            board,
            rule,
            seed=seed,
            temperature=temperature,
            start_step=start_step,
            packed=packed,
        )
    if getattr(rule, "continuous", False):
        # the continuous tier (models/lenia.py): float32 boards need a
        # float executor — typed rejection elsewhere, never an int8 cast
        from tpu_life.models.lenia import lenia_runner_for

        return lenia_runner_for(backend, board, rule)
    prep = getattr(backend, "prepare", None)
    if prep is not None:
        return prep(board, rule)
    return HostRunner(backend, board, rule)


def drive_runner(
    r: Runner,
    steps: int,
    *,
    chunk_steps: int = 0,
    callback: ChunkCallback | None = None,
) -> None:
    """The shared chunked epoch loop over a Runner (no final fetch).

    Each chunk's ``get_board`` thunk is bound to that chunk's state
    (``Runner.snapshot``), so subscribers may defer materialization.
    """
    done = 0
    for n in chunk_sizes(steps, chunk_steps):
        r.advance(n)
        done += n
        if callback is not None:
            callback(done, r.snapshot())
    r.sync()


def run_with_runner(
    backend: "Backend",
    board: np.ndarray,
    rule: Rule,
    steps: int,
    *,
    chunk_steps: int = 0,
    callback: ChunkCallback | None = None,
) -> np.ndarray:
    """Chunked ``run`` over a fresh Runner, returning the final board."""
    r = make_runner(backend, board, rule)
    drive_runner(r, steps, chunk_steps=chunk_steps, callback=callback)
    return r.fetch()


def measure_throughput(
    backend: "Backend",
    board: np.ndarray,
    rule: Rule,
    steps: int,
    base_steps: int,
    repeats: int = 3,
) -> tuple[float, int]:
    """(cells/s/chip, n_chips) of a backend via delta timing.

    The single measurement core shared by ``bench.py`` and the CLI's
    ``bench`` subcommand so their numbers cannot drift: stage the board,
    difference two fused runs (`delta_seconds_per_step`), and divide by
    the device count the backend actually spans (a mesh backend may use
    fewer devices than ``jax.devices()`` reports).
    """
    from tpu_life.utils.timing import delta_seconds_per_step

    runner = make_runner(backend, board, rule)
    per_step = delta_seconds_per_step(runner, steps, base_steps, repeats=repeats)
    mesh = getattr(backend, "mesh", None)
    n_chips = int(mesh.devices.size) if mesh is not None else 1
    h, w = board.shape
    return h * w / per_step / n_chips, n_chips


def measure_parity_interleaved(
    composed: "Backend",
    single: "Backend",
    board: np.ndarray,
    rule: Rule,
    steps: int,
    base_steps: int,
    repeats: int = 6,
) -> dict:
    """THE parity methodology (VERDICT r4 item 2), shared by ``bench.py``
    and ``experiments/r5_capture.py`` so their verdicts cannot drift:
    back-to-back (composed, single) delta pairs cancel chip-window wobble;
    the reported ratio is the median per-pair composed-per-chip over
    single-chip throughput.  Returns the ``parity_*`` record fields
    (``parity_ratio`` None when every pair was timer noise).
    """
    import statistics

    from tpu_life.utils.timing import paired_delta_seconds_per_step

    r_comp = make_runner(composed, board, rule)
    r_single = make_runner(single, board, rule)
    pairs = paired_delta_seconds_per_step(
        r_comp, r_single, steps, base_steps, repeats=repeats
    )
    if not pairs:
        return {"parity_ratio": None, "parity_ok": False}
    mesh = getattr(composed, "mesh", None)
    n_chips = int(mesh.devices.size) if mesh is not None else 1
    ratios = [d_single / (d_comp * n_chips) for d_comp, d_single in pairs]
    comp_deltas = [d for d, _ in pairs]
    h, w = board.shape
    ratio = statistics.median(ratios)
    return {
        "parity_single_chip": h * w / min(d for _, d in pairs),
        "parity_ratio": ratio,
        "parity_pairs": len(pairs),
        "parity_window_spread": max(comp_deltas) / min(comp_deltas),
        "parity_ok": ratio >= 0.8,
        "parity_in_band": 0.95 <= ratio <= 1.05,
    }


BACKENDS: dict[str, Callable[..., Backend]] = {}


def register_backend(name: str):
    def deco(factory):
        BACKENDS[name] = factory
        return factory

    return deco


def get_backend(name: str, *, rule: Rule | None = None, **kwargs) -> Backend:
    """Instantiate a backend by name; ``auto`` prefers accelerated paths.

    ``rule`` is an optional hint for ``auto``: on MULTI-device hosts torus
    rules resolve to a single-device backend, because the sharded torus
    path carries constraints (1-D mesh, height divisible by the mesh)
    that ``auto`` cannot guarantee — auto must never raise.  Pass
    ``--backend sharded`` explicitly to opt into the mesh torus.  On ONE
    device every constraint holds trivially (h % 1 == 0, the mesh is
    1-D), so single-device torus runs DO take the sharded backend — on
    TPU that is the Pallas torus stripe kernel, the fastest torus path.
    """
    # import for registration side effects
    from tpu_life.backends import numpy_backend, jax_backend, sharded_backend  # noqa: F401

    if name == "auto":
        if rule is not None and getattr(rule, "stochastic", False):
            # stochastic rules run on the executors that implement the
            # counter-based key schedule; the single-device XLA path is
            # the accelerated one (numpy stays the explicit ground truth)
            name = "jax"
        elif rule is not None and getattr(rule, "continuous", False):
            # continuous rules run on the float executors only — on a
            # TPU host auto must not wander to pallas/sharded (no float
            # path there) and raise; jax is the accelerated float path
            name = "jax"
        else:
            import jax

            devices = jax.devices()
            torus = rule is not None and rule.boundary == "torus"
            if len(devices) > 1 and not torus:
                name = "sharded"
            elif (
                torus
                and len(devices) == 1
                and devices[0].platform == "tpu"
                and kwargs.get("partition_mode") in (None, "shard_map")
                and kwargs.get("local_kernel") != "pallas"
            ):
                # n=1 mesh: the MESH torus constraints are vacuous and the
                # sharded backend carries the Pallas torus kernel (tiling
                # permitting; it degrades to the packed XLA torus scan
                # itself).  User-pinned kwargs that can make _prepare_torus
                # raise (gspmd, an explicit pallas pin on an infeasible
                # board) keep the old single-device routing instead — auto
                # must never raise.
                name = "sharded"
            elif devices[0].platform == "tpu":
                # the Pallas deep-halo kernels are the fastest single-chip
                # path (and fall back to the fused XLA scan on small
                # boards); keep "auto" infallible if pallas cannot import
                try:
                    from tpu_life.backends import pallas_backend  # noqa: F401

                    name = "pallas"
                except ImportError:
                    name = "jax"
            else:
                name = "jax"
    if name not in BACKENDS:
        try:
            if name == "pallas":
                from tpu_life.backends import pallas_backend  # noqa: F401
            elif name in ("stripes", "mpi"):
                from tpu_life.backends import stripes_backend  # noqa: F401
            elif name == "native":
                from tpu_life.backends import native_backend  # noqa: F401
        except ImportError as e:
            raise ValueError(f"backend {name!r} is unavailable: {e}") from e
    if name not in BACKENDS:
        raise ValueError(f"unknown backend {name!r}; available: {sorted(BACKENDS)}")
    try:
        return BACKENDS[name](**kwargs)
    except ImportError as e:
        raise ValueError(f"backend {name!r} is unavailable: {e}") from e


def chunk_sizes(steps: int, chunk_steps: int) -> list[int]:
    """Split ``steps`` into host-sync chunks (0 or >= steps -> one chunk)."""
    if steps <= 0:
        return []
    if chunk_steps <= 0 or chunk_steps >= steps:
        return [steps]
    out = [chunk_steps] * (steps // chunk_steps)
    if steps % chunk_steps:
        out.append(steps % chunk_steps)
    return out
