"""Pure-NumPy backend: the single-process ground truth."""

from __future__ import annotations

import numpy as np

from tpu_life.backends.base import ChunkCallback, chunk_sizes, register_backend
from tpu_life.models.rules import Rule
from tpu_life.ops.reference import step_np


@register_backend("numpy")
class NumpyBackend:
    name = "numpy"

    def __init__(self, **_):
        pass

    def run(
        self,
        board: np.ndarray,
        rule: Rule,
        steps: int,
        *,
        chunk_steps: int = 0,
        callback: ChunkCallback | None = None,
    ) -> np.ndarray:
        board = np.asarray(board, dtype=np.int8)
        done = 0
        for n in chunk_sizes(steps, chunk_steps):
            for _ in range(n):
                board = step_np(board, rule)
            done += n
            if callback is not None:
                b = board
                callback(done, lambda b=b: b)
        return board
