"""Pure-NumPy backend: the single-process ground truth."""

from __future__ import annotations

import numpy as np

from tpu_life.backends.base import ChunkCallback, chunk_sizes, register_backend
from tpu_life.models.rules import Rule
from tpu_life.ops.reference import step_np


@register_backend("numpy")
class NumpyBackend:
    name = "numpy"

    def __init__(self, *, stencil: str = "auto", **_):
        # the counting-path knob (--stencil, docs/RULES.md): "auto"
        # keeps this executor on the roll path — it is the oracle the
        # matmul path is compared against; explicit "matmul" runs the
        # banded-matmul counts here too (the parity tests' host leg)
        from tpu_life.ops.conv import validate_stencil

        self.stencil = validate_stencil(stencil)

    def run(
        self,
        board: np.ndarray,
        rule: Rule,
        steps: int,
        *,
        chunk_steps: int = 0,
        callback: ChunkCallback | None = None,
    ) -> np.ndarray:
        from tpu_life.ops.conv import resolve_stencil

        stencil = resolve_stencil(rule, self.stencil, "numpy")
        if getattr(rule, "continuous", False):
            from tpu_life.models import lenia

            board = lenia.validate_board(board, rule)
            fn = lenia.make_lenia_step(np, rule, board.shape, stencil)
        elif stencil == "matmul":
            from tpu_life.ops.conv import make_counts_matmul

            board = np.asarray(board, dtype=np.int8)
            counts_fn = make_counts_matmul(np, rule, board.shape)
            table = rule.transition_table
            fn = lambda b: table[b.astype(np.int64), counts_fn(b)]
        else:
            board = np.asarray(board, dtype=np.int8)
            fn = lambda b: step_np(b, rule)
        done = 0
        for n in chunk_sizes(steps, chunk_steps):
            for _ in range(n):
                board = fn(board)
            done += n
            if callback is not None:
                b = board
                callback(done, lambda b=b: b)
        return board
