"""Stripe-decomposition CPU backend — the reference's rank structure, kept honest.

Mirrors the MPI program's shape (SURVEY.md §3): R "ranks", each owning a
block-row stripe plus one halo row per interior edge; per epoch every rank
updates its extended stripe locally and then exchanges edge rows with its
neighbors (the corrected form of Parallel_Life_MPI.cpp:104-145 — the
received halo is actually *stored*, unlike the reference's discarded-copy
bug at :111/:127).  Exists for three reasons:

- a structural cross-check that the decomposition/halo logic is
  shard-count-invariant on plain NumPy, independent of XLA;
- the ``--backend mpi`` path: with ``mpi4py`` installed the same stripe
  update runs one-rank-per-process over real MPI (send/recv of edge rows);
- a teaching artifact: diffing this file against the sharded backend shows
  exactly what ``shard_map`` + ``ppermute`` replace.

Unlike the reference, the remainder rows are balanced across stripes
(``stripe_bounds``) rather than dumped on the last rank
(Parallel_Life_MPI.cpp:76-78).
"""

from __future__ import annotations

import numpy as np

from tpu_life.backends.base import ChunkCallback, chunk_sizes, register_backend
from tpu_life.io.sharded import stripe_bounds
from tpu_life.models.rules import Rule
from tpu_life.ops.reference import step_np


def _exchange_halos(stripes: list[np.ndarray], r: int) -> list[np.ndarray]:
    """Return stripes extended with up-to-r halo rows from their neighbors."""
    out = []
    for i, s in enumerate(stripes):
        top = stripes[i - 1][-r:] if i > 0 else np.zeros((0, s.shape[1]), s.dtype)
        bot = stripes[i + 1][:r] if i < len(stripes) - 1 else np.zeros((0, s.shape[1]), s.dtype)
        out.append(np.vstack([top, s, bot]))
    return out


def _update_stripe(ext: np.ndarray, rule: Rule, n_top: int, n_bot: int) -> np.ndarray:
    """One CA step on an extended stripe; returns the interior rows.

    Interior edges see true neighbor rows (the halos); global edges see the
    clamped dead boundary exactly like the unsharded step.
    """
    nxt = step_np(ext, rule)
    stop = nxt.shape[0] - n_bot if n_bot else nxt.shape[0]
    return nxt[n_top:stop]


@register_backend("stripes")
class StripesBackend:
    name = "stripes"

    def __init__(self, *, num_devices: int | None = None, **_):
        self.num_ranks = num_devices or 4

    def run(
        self,
        board: np.ndarray,
        rule: Rule,
        steps: int,
        *,
        chunk_steps: int = 0,
        callback: ChunkCallback | None = None,
    ) -> np.ndarray:
        board = np.asarray(board, np.int8)
        if rule.boundary == "torus":
            raise ValueError(
                "torus boundary is not supported on the stripes backend; "
                "use --backend numpy/jax"
            )
        h, _ = board.shape
        ranks = min(self.num_ranks, max(1, h // max(1, rule.radius)))
        bounds = stripe_bounds(h, ranks)
        stripes = [board[a:b].copy() for a, b in bounds]
        r = rule.radius
        done = 0
        for n in chunk_sizes(steps, chunk_steps):
            for _ in range(n):
                exts = _exchange_halos(stripes, r)
                stripes = [
                    _update_stripe(
                        ext,
                        rule,
                        n_top=r if i > 0 else 0,
                        n_bot=r if i < ranks - 1 else 0,
                    )
                    for i, ext in enumerate(exts)
                ]
            done += n
            if callback is not None:
                out = np.vstack(stripes)
                callback(done, lambda out=out: out)
        return np.vstack(stripes)


@register_backend("mpi")
class MpiBackend:
    """Real-MPI variant: one stripe per rank via mpi4py (EXPERIMENTAL).

    The driver process is rank 0; this backend only functions under
    ``mpiexec`` with mpi4py installed — otherwise it raises with guidance.
    mpi4py is not installable in the CI image, so the per-rank logic is
    exercised by ``tests/test_stripes.py`` through an injected in-process
    fake communicator (``comm=``) that implements the same ``Sendrecv`` /
    ``gather`` / ``allgather`` surface over threads; a real ``mpiexec -n``
    run has never executed in CI — hence the experimental label in the CLI.
    Halo traffic uses 1 byte/cell (the reference inflated halos 4x by
    sending MPI_INT, Parallel_Life_MPI.cpp:114-115; SURVEY.md §2.4).
    """

    name = "mpi"

    def __init__(self, *, comm=None, **_):
        if comm is None:
            try:
                from mpi4py import MPI
            except ImportError as e:
                raise ImportError(
                    "backend 'mpi' needs mpi4py (not installed in this image); "
                    "use --backend stripes for the single-process structural "
                    "equivalent"
                ) from e
            comm = MPI.COMM_WORLD
        self.comm = comm

    def run(
        self,
        board: np.ndarray,
        rule: Rule,
        steps: int,
        *,
        chunk_steps: int = 0,
        callback: ChunkCallback | None = None,
    ) -> np.ndarray:
        comm = self.comm
        rank, size = comm.Get_rank(), comm.Get_size()
        board = np.asarray(board, np.int8)
        if rule.boundary == "torus":
            raise ValueError(
                "torus boundary is not supported on the mpi backend; "
                "use --backend numpy/jax"
            )
        h, w = board.shape
        bounds = stripe_bounds(h, size)
        a, b = bounds[rank]
        stripe = np.ascontiguousarray(board[a:b])
        r = rule.radius
        done = 0
        for n in chunk_sizes(steps, chunk_steps):
            for _ in range(n):
                step_i = done
                top = np.zeros((r, w), np.int8)
                bot = np.zeros((r, w), np.int8)
                # paired exchanges; Sendrecv is deadlock-free by construction
                if rank > 0:
                    comm.Sendrecv(
                        np.ascontiguousarray(stripe[:r]), dest=rank - 1,
                        sendtag=step_i, recvbuf=top, source=rank - 1,
                        recvtag=step_i,
                    )
                if rank < size - 1:
                    comm.Sendrecv(
                        np.ascontiguousarray(stripe[-r:]), dest=rank + 1,
                        sendtag=step_i, recvbuf=bot, source=rank + 1,
                        recvtag=step_i,
                    )
                # zero halos at the global edges *are* the clamped boundary,
                # so updating the extended stripe and trimming r rows per
                # side is exact for every rank
                ext = np.vstack([top, stripe, bot]) if size > 1 else stripe
                nxt = step_np(ext, rule)
                stripe = nxt[r:-r] if size > 1 else nxt
                done += 1
            if callback is not None:
                # per-chunk side effects (snapshots, metrics) are rank-0
                # single-writer — gather to root only, instead of every rank
                # reconstructing the whole board (O(size) traffic, not
                # O(size^2); VERDICT r3 item 9)
                parts = comm.gather(stripe, root=0)
                if rank == 0:
                    full = np.vstack(parts)
                    callback(done, lambda full=full: full)
        # the Backend.run contract returns the board on every caller
        gathered = comm.allgather(stripe)
        return np.vstack(gathered)
