"""Stripe-decomposition CPU backend — the reference's rank structure, kept honest.

Mirrors the MPI program's shape (SURVEY.md §3): R "ranks", each owning a
block-row stripe plus one halo row per interior edge; per epoch every rank
updates its extended stripe locally and then exchanges edge rows with its
neighbors (the corrected form of Parallel_Life_MPI.cpp:104-145 — the
received halo is actually *stored*, unlike the reference's discarded-copy
bug at :111/:127).  Exists for three reasons:

- a structural cross-check that the decomposition/halo logic is
  shard-count-invariant on plain NumPy, independent of XLA;
- the ``--backend mpi`` path: with ``mpi4py`` installed the same stripe
  update runs one-rank-per-process over real MPI (send/recv of edge rows);
- a teaching artifact: diffing this file against the sharded backend shows
  exactly what ``shard_map`` + ``ppermute`` replace.

Unlike the reference, the remainder rows are balanced across stripes
(``stripe_bounds``) rather than dumped on the last rank
(Parallel_Life_MPI.cpp:76-78).
"""

from __future__ import annotations

import numpy as np

from tpu_life.backends.base import ChunkCallback, chunk_sizes, register_backend
from tpu_life.io.sharded import stripe_bounds
from tpu_life.models.rules import Rule
from tpu_life.ops.reference import step_np, step_np_wrap_cols


def _exchange_halos(
    stripes: list[np.ndarray], r: int, torus: bool
) -> list[np.ndarray]:
    """Return stripes extended with up-to-r halo rows from their neighbors.

    Clamped: the first/last stripes get no top/bottom halo (the dead
    boundary).  Torus: every stripe gets both halos — the ring closes via
    the (i±1) mod n neighbors (the MPI_Cart periods=1 the reference's
    rank±1 topology never takes)."""
    n = len(stripes)
    out = []
    for i, s in enumerate(stripes):
        if torus:
            top = stripes[(i - 1) % n][-r:]
            bot = stripes[(i + 1) % n][:r]
        else:
            top = stripes[i - 1][-r:] if i > 0 else np.zeros((0, s.shape[1]), s.dtype)
            bot = stripes[i + 1][:r] if i < n - 1 else np.zeros((0, s.shape[1]), s.dtype)
        out.append(np.vstack([top, s, bot]))
    return out


def _update_stripe(ext: np.ndarray, rule: Rule, n_top: int, n_bot: int) -> np.ndarray:
    """One CA step on an extended stripe; returns the interior rows.

    Interior edges see true neighbor rows (the halos); global edges see
    the clamped dead boundary — or, for torus rules, wrap halos on the row
    axis and in-place column wrap (``step_np_wrap_cols``).
    """
    step = step_np_wrap_cols if rule.boundary == "torus" else step_np
    nxt = step(ext, rule)
    stop = nxt.shape[0] - n_bot if n_bot else nxt.shape[0]
    return nxt[n_top:stop]


@register_backend("stripes")
class StripesBackend:
    name = "stripes"

    def __init__(self, *, num_devices: int | None = None, **_):
        self.num_ranks = num_devices or 4

    def run(
        self,
        board: np.ndarray,
        rule: Rule,
        steps: int,
        *,
        chunk_steps: int = 0,
        callback: ChunkCallback | None = None,
    ) -> np.ndarray:
        board = np.asarray(board, np.int8)
        h, _ = board.shape
        ranks = min(self.num_ranks, max(1, h // max(1, rule.radius)))
        bounds = stripe_bounds(h, ranks)
        stripes = [board[a:b].copy() for a, b in bounds]
        r = rule.radius
        torus = rule.boundary == "torus"
        done = 0
        for n in chunk_sizes(steps, chunk_steps):
            for _ in range(n):
                exts = _exchange_halos(stripes, r, torus)
                stripes = [
                    _update_stripe(
                        ext,
                        rule,
                        n_top=r if (torus or i > 0) else 0,
                        n_bot=r if (torus or i < ranks - 1) else 0,
                    )
                    for i, ext in enumerate(exts)
                ]
            done += n
            if callback is not None:
                out = np.vstack(stripes)
                callback(done, lambda out=out: out)
        return np.vstack(stripes)


@register_backend("mpi")
class MpiBackend:
    """Real-MPI variant: one stripe per rank via mpi4py (EXPERIMENTAL).

    The driver process is rank 0; this backend only functions under
    ``mpiexec`` with mpi4py installed — otherwise it raises with guidance.
    THREAD-SIMULATED ONLY (the honest label, VERDICT r4 item 8): this
    image ships ``libmpi.so`` but no launcher, headers, or mpi4py, and
    installs are off-limits, so a real ``mpiexec -n`` run has never
    executed anywhere — the per-rank logic is exercised by
    ``tests/test_stripes.py`` through an injected in-process fake
    communicator (``comm=``) implementing the same ``Sendrecv`` /
    ``gather`` / ``allgather`` surface over threads.  Real cross-process
    message passing (process isolation, real buffer semantics) is covered
    by the two-OS-process ``jax.distributed`` + Gloo run in
    ``tests/test_distributed.py`` — the path that matters on TPU.
    Halo traffic uses 1 byte/cell (the reference inflated halos 4x by
    sending MPI_INT, Parallel_Life_MPI.cpp:114-115; SURVEY.md §2.4).
    """

    name = "mpi"

    def __init__(self, *, comm=None, **_):
        if comm is None:
            try:
                from mpi4py import MPI
            except ImportError as e:
                raise ImportError(
                    "backend 'mpi' needs mpi4py (not installed in this image); "
                    "use --backend stripes for the single-process structural "
                    "equivalent"
                ) from e
            comm = MPI.COMM_WORLD
        self.comm = comm

    def run(
        self,
        board: np.ndarray,
        rule: Rule,
        steps: int,
        *,
        chunk_steps: int = 0,
        callback: ChunkCallback | None = None,
    ) -> np.ndarray:
        comm = self.comm
        rank, size = comm.Get_rank(), comm.Get_size()
        board = np.asarray(board, np.int8)
        torus = rule.boundary == "torus"
        h, w = board.shape
        bounds = stripe_bounds(h, size)
        if min(b - a for a, b in bounds) < rule.radius:
            # a stripe shorter than the radius makes the single-hop halo
            # exchange insufficient (true neighbors live two ranks away) —
            # refuse rather than silently diverge.  StripesBackend clamps
            # its rank count for the same reason; a fixed MPI world cannot.
            raise ValueError(
                f"board height {h} over {size} ranks gives a stripe "
                f"shorter than the rule radius {rule.radius}; use fewer "
                f"ranks"
            )
        a, b = bounds[rank]
        stripe = np.ascontiguousarray(board[a:b])
        r = rule.radius
        done = 0
        for n in chunk_sizes(steps, chunk_steps):
            for _ in range(n):
                top = np.zeros((r, w), np.int8)
                bot = np.zeros((r, w), np.int8)
                # paired exchanges; Sendrecv is deadlock-free by construction.
                # Torus closes the ring with (rank±1) mod size neighbors —
                # MPI_Cart periods=1, the option the reference's rank±1
                # topology never takes (Parallel_Life_MPI.cpp:105-123)
                if torus and size == 1:
                    top, bot = stripe[-r:].copy(), stripe[:r].copy()
                elif torus:
                    # two cyclic SHIFTS (the MPI_Cart_shift pattern): each
                    # call pairs its send with the recv satisfied by the
                    # SAME call on the neighbor, so the ring cannot deadlock
                    # — pairing send-up with recv-from-up instead would
                    # leave every rank waiting on a message its peer only
                    # posts in the next phase.  Constant phase tags (0/1)
                    # keep size == 2 (both phases talk to the same peer)
                    # unambiguous; MPI's in-order matching per (source,
                    # tag) handles successive steps, and per-step tags
                    # would overflow MPI_TAG_UB on long runs.
                    up, down = (rank - 1) % size, (rank + 1) % size
                    tag_up, tag_down = 0, 1
                    # shift up: my top rows become up's bottom halo; my
                    # bottom halo arrives from down (its top rows)
                    comm.Sendrecv(
                        np.ascontiguousarray(stripe[:r]),
                        dest=up, sendtag=tag_up,
                        recvbuf=bot, source=down, recvtag=tag_up,
                    )
                    # shift down: my bottom rows become down's top halo; my
                    # top halo arrives from up (its bottom rows)
                    comm.Sendrecv(
                        np.ascontiguousarray(stripe[-r:]),
                        dest=down, sendtag=tag_down,
                        recvbuf=top, source=up, recvtag=tag_down,
                    )
                else:
                    if rank > 0:
                        comm.Sendrecv(
                            np.ascontiguousarray(stripe[:r]), dest=rank - 1,
                            sendtag=0, recvbuf=top, source=rank - 1,
                            recvtag=0,
                        )
                    if rank < size - 1:
                        comm.Sendrecv(
                            np.ascontiguousarray(stripe[-r:]), dest=rank + 1,
                            sendtag=0, recvbuf=bot, source=rank + 1,
                            recvtag=0,
                        )
                if torus:
                    # every stripe carries both halos; column seam wraps in
                    # the substep, the fringe rows are trimmed
                    ext = np.vstack([top, stripe, bot])
                    stripe = step_np_wrap_cols(ext, rule)[r:-r]
                else:
                    # zero halos at the global edges *are* the clamped
                    # boundary, so updating the extended stripe and trimming
                    # r rows per side is exact for every rank
                    ext = np.vstack([top, stripe, bot]) if size > 1 else stripe
                    nxt = step_np(ext, rule)
                    stripe = nxt[r:-r] if size > 1 else nxt
                done += 1
            if callback is not None:
                # per-chunk side effects (snapshots, metrics) are rank-0
                # single-writer — gather to root only, instead of every rank
                # reconstructing the whole board (O(size) traffic, not
                # O(size^2); VERDICT r3 item 9)
                parts = comm.gather(stripe, root=0)
                if rank == 0:
                    full = np.vstack(parts)
                    callback(done, lambda full=full: full)
        # the Backend.run contract returns the board on every caller
        gathered = comm.allgather(stripe)
        return np.vstack(gathered)
