"""Single-device XLA backend: the minimum end-to-end TPU slice.

The reference's per-epoch {update; exchange; barrier} host loop
(Parallel_Life_MPI.cpp:215-221) becomes one ``lax.scan`` under one ``jit``
with donated buffers — the double-buffer ``swap`` at :53 is expressed as
argument donation, so even 65536^2 boards hold one HBM copy.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from tpu_life.backends.base import ChunkCallback, chunk_sizes, register_backend
from tpu_life.models.rules import Rule
from tpu_life.ops.stencil import multi_step
from tpu_life.utils.padding import LANE, ceil_to, pad_board


@register_backend("jax")
class JaxBackend:
    name = "jax"

    def __init__(self, *, device=None, pad_lanes: bool = True, **_):
        self.device = device if device is not None else jax.devices()[0]
        self.pad_lanes = pad_lanes

    def run(
        self,
        board: np.ndarray,
        rule: Rule,
        steps: int,
        *,
        chunk_steps: int = 0,
        callback: ChunkCallback | None = None,
    ) -> np.ndarray:
        h, w = board.shape
        w_pad = ceil_to(w, LANE) if self.pad_lanes else w
        x = jax.device_put(pad_board(board, h, w_pad), self.device)
        logical = (h, w)
        done = 0
        for n in chunk_sizes(steps, chunk_steps):
            x = multi_step(x, rule=rule, steps=n, logical_shape=logical)
            done += n
            if callback is not None:
                callback(done, lambda x=x: np.asarray(x)[:h, :w])
        x.block_until_ready()
        return np.asarray(x)[:h, :w]
