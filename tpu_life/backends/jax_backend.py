"""Single-device XLA backend: the minimum end-to-end TPU slice.

The reference's per-epoch {update; exchange; barrier} host loop
(Parallel_Life_MPI.cpp:215-221) becomes one ``lax.scan`` under one ``jit``
with donated buffers — the double-buffer ``swap`` at :53 is expressed as
argument donation, so even 65536^2 boards hold one HBM copy.

For life-like (2-state, radius-1) rules the board runs **bit-sliced**:
32 cells per uint32 lane with full-adder bitplane counting
(``tpu_life.ops.bitlife``) — ~8x less HBM traffic and far fewer VPU ops
than the general int8 stencil, which remains the path for Generations /
Larger-than-Life rules.
"""

from __future__ import annotations

import numpy as np

import jax

from tpu_life.backends.base import (
    ChunkCallback,
    Runner,
    register_backend,
    run_with_runner,
)
from tpu_life.models.rules import Rule
from tpu_life.ops import bitlife
from tpu_life.ops.stencil import multi_step
from tpu_life.utils.padding import LANE, ceil_to, pad_board


class DeviceRunner:
    """Runner over a device-resident board: ``advance`` dispatches fused
    scans with no host round-trip; ``sync`` forces completion via a
    1-element readback (``block_until_ready`` alone can return before the
    device finishes on async tunneled platforms)."""

    def __init__(self, x: jax.Array, advance, to_np, count_live=None):
        self.x = x
        self._advance = advance
        self._to_np = to_np
        self._count_live = count_live

    def advance(self, steps: int) -> None:
        if steps > 0:
            self.x = self._advance(self.x, steps)

    def sync(self) -> None:
        jax.block_until_ready(self.x)
        np.asarray(self.x[:1, :1])

    def fetch(self) -> np.ndarray:
        return self._to_np(self.x)

    def live_count(self) -> int:
        """Exact live-cell (state 1) count, reduced *on device* — on a
        sharded board each device reduces its own shard and XLA inserts the
        cross-device psum, so only two scalars reach the host (SURVEY.md §5
        "live-cell count via sharded reduction").  Falls back to a host
        count only for runners without a device reduction."""
        if self._count_live is not None:
            return bitlife.combine_live_count(self._count_live(self.x))
        return int(np.count_nonzero(self.fetch() == 1))

    def snapshot(self):
        """Thunk bound to the current device array.  Valid until the next
        ``advance`` donates that buffer — i.e. materialize within the
        chunk callback, matching the driver's synchronous use."""
        return lambda x=self.x: self._to_np(x)


def packed_device_runner(
    board: np.ndarray, rule: Rule, device, advance=None
) -> DeviceRunner:
    """DeviceRunner over the bit-sliced board representation: 32 cells per
    uint32 lane, fused packed scan.  Shared by the ``jax`` backend (Moore,
    diamond, and torus advance variants) and the ``pallas`` backend's
    small-board fallback; ``advance`` defaults to the clamped Moore scan."""
    h, w = board.shape
    x = jax.device_put(bitlife.pack_np(np.asarray(board, np.int8)), device)
    if advance is None:
        advance = lambda x, n: bitlife.multi_step_packed(
            x, rule=rule, steps=n, logical_shape=(h, w)
        )
    return DeviceRunner(
        x,
        advance,
        lambda x: bitlife.unpack_np(np.asarray(x), w),
        count_live=bitlife.live_count_packed,
    )


@register_backend("jax")
class JaxBackend:
    name = "jax"

    def __init__(
        self,
        *,
        device=None,
        pad_lanes: bool = True,
        bitpack: bool = True,
        stencil: str = "auto",
        **_,
    ):
        from tpu_life.ops.conv import validate_stencil

        self.device = device if device is not None else jax.devices()[0]
        self.pad_lanes = pad_lanes
        self.bitpack = bitpack
        # the counting-path knob (--stencil, docs/RULES.md): roll
        # shift-adds vs banded matmuls; "auto" follows the crossover
        # model (matmul at large radii and on weighted kernels).  The
        # bit-sliced fast paths below are untouched — they are already
        # the radius-1 winner the crossover model keeps on roll.
        self.stencil = validate_stencil(stencil)

    def prepare(self, board: np.ndarray, rule: Rule) -> Runner:
        from tpu_life.ops.conv import resolve_stencil

        h, w = board.shape
        logical = (h, w)
        if getattr(rule, "continuous", False):
            # the continuous tier: float32 boards, weighted-kernel
            # correlation (matmul under auto — its whole point)
            from tpu_life.models.lenia import LeniaDeviceRunner

            return LeniaDeviceRunner(
                board,
                rule,
                stencil=resolve_stencil(rule, self.stencil, "jax"),
                device=self.device,
            )
        stencil = resolve_stencil(rule, self.stencil, "jax")
        # an explicit (or crossover-resolved) matmul pin outranks the
        # bit-sliced fast paths: the user asked to run — and measure —
        # the banded-matmul counting executor
        bitpack = self.bitpack and stencil != "matmul"
        if bitpack and bitlife.supports(rule):
            return packed_device_runner(board, rule, self.device)
        if bitpack and bitlife.supports_diamond(rule):
            # 2-state von Neumann rules run bit-sliced too: the diamond as
            # stacked shifted row boxes under one CSA reduction
            return packed_device_runner(
                board,
                rule,
                self.device,
                advance=lambda x, n: bitlife.multi_step_packed_diamond(
                    x, rule=rule, steps=n, logical_shape=logical
                ),
            )
        if bitpack and bitlife.supports_torus(rule):
            # torus life-like rules run packed too: roll-based row wrap,
            # seam carries at the logical width (bitlife.make_torus_hshifts)
            return packed_device_runner(
                board,
                rule,
                self.device,
                advance=lambda x, n: bitlife.multi_step_packed_torus(
                    x, rule=rule, steps=n, width=w
                ),
            )
        # torus boards must stay at exact shape: padding would sit between
        # the logical edges the torus glues together (lane alignment is a
        # perf preference; correctness wins).  The matmul stencil's band
        # operators are already lane-shaped dense matrices, so it skips
        # the lane padding too — padding would only grow the operands.
        pad = (
            self.pad_lanes
            and rule.boundary == "clamped"
            and stencil != "matmul"
        )
        w_pad = ceil_to(w, LANE) if pad else w
        x = jax.device_put(pad_board(board, h, w_pad), self.device)
        advance = lambda x, n: multi_step(
            x, rule=rule, steps=n, logical_shape=logical, stencil=stencil
        )
        return DeviceRunner(
            x,
            advance,
            lambda x: np.asarray(x)[:h, :w],
            count_live=bitlife.live_count_cells,
        )

    def run(
        self,
        board: np.ndarray,
        rule: Rule,
        steps: int,
        *,
        chunk_steps: int = 0,
        callback: ChunkCallback | None = None,
    ) -> np.ndarray:
        return run_with_runner(
            self, board, rule, steps, chunk_steps=chunk_steps, callback=callback
        )
