"""Single-device XLA backend: the minimum end-to-end TPU slice.

The reference's per-epoch {update; exchange; barrier} host loop
(Parallel_Life_MPI.cpp:215-221) becomes one ``lax.scan`` under one ``jit``
with donated buffers — the double-buffer ``swap`` at :53 is expressed as
argument donation, so even 65536^2 boards hold one HBM copy.

For life-like (2-state, radius-1) rules the board runs **bit-sliced**:
32 cells per uint32 lane with full-adder bitplane counting
(``tpu_life.ops.bitlife``) — ~8x less HBM traffic and far fewer VPU ops
than the general int8 stencil, which remains the path for Generations /
Larger-than-Life rules.
"""

from __future__ import annotations

import numpy as np

import jax

from tpu_life.backends.base import ChunkCallback, chunk_sizes, register_backend
from tpu_life.models.rules import Rule
from tpu_life.ops import bitlife
from tpu_life.ops.stencil import multi_step
from tpu_life.utils.padding import LANE, ceil_to, pad_board


@register_backend("jax")
class JaxBackend:
    name = "jax"

    def __init__(self, *, device=None, pad_lanes: bool = True, bitpack: bool = True, **_):
        self.device = device if device is not None else jax.devices()[0]
        self.pad_lanes = pad_lanes
        self.bitpack = bitpack

    def run(
        self,
        board: np.ndarray,
        rule: Rule,
        steps: int,
        *,
        chunk_steps: int = 0,
        callback: ChunkCallback | None = None,
    ) -> np.ndarray:
        h, w = board.shape
        logical = (h, w)
        use_bits = self.bitpack and bitlife.supports(rule)
        if use_bits:
            x = jax.device_put(bitlife.pack_np(np.asarray(board, np.int8)), self.device)
            advance = lambda x, n: bitlife.multi_step_packed(
                x, rule=rule, steps=n, logical_shape=logical
            )
            to_np = lambda x: bitlife.unpack_np(np.asarray(x), w)
        else:
            w_pad = ceil_to(w, LANE) if self.pad_lanes else w
            x = jax.device_put(pad_board(board, h, w_pad), self.device)
            advance = lambda x, n: multi_step(
                x, rule=rule, steps=n, logical_shape=logical
            )
            to_np = lambda x: np.asarray(x)[:h, :w]

        done = 0
        for n in chunk_sizes(steps, chunk_steps):
            x = advance(x, n)
            done += n
            if callback is not None:
                callback(done, lambda x=x: to_np(x))
        x.block_until_ready()
        return to_np(x)
