__version__ = "0.3.0"
