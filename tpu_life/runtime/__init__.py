from tpu_life.runtime.driver import run, RunResult

__all__ = ["run", "RunResult"]
