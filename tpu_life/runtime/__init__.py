__all__ = ["run", "RunResult"]


def __getattr__(name):
    # lazy (PEP 562): driver's import chain reaches jax via parallel.mesh,
    # and jax-free consumers (the serve scheduler importing only the
    # recovery submodule, `tpu_life submit`/`gen`) must not pay for it
    if name in __all__:
        from tpu_life.runtime import driver

        return getattr(driver, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
