"""Failure detection + elastic recovery.

The reference has neither: any rank failure kills the whole MPI job, and the
per-epoch ``MPI_Barrier`` is its only synchronization primitive
(Parallel_Life_MPI.cpp:220; SURVEY.md §5 "failure detection" row).  On TPU
the realistic failure modes are preemption and device/runtime loss, which
surface as ``RuntimeError`` (XlaRuntimeError) from a blocked step.  The
driver treats those as *recoverable*: it rebuilds the backend, resumes from
the newest snapshot (or the original input when none exists yet), and
re-runs the lost steps — up to ``--max-restarts`` times.  This closes the
loop SURVEY.md §5 left open: snapshots were already restartable by hand via
``--resume``; now the driver detects the failure and restarts itself.

``--fault-at N`` is the matching fault-injection drill: a proxy Runner
raises a simulated device loss the first time the fused loop would cross
absolute step N, exercising exactly the recovery path a real failure takes
(and doubling as the recovery test fixture, ``tests/test_recovery.py``).

What recovery can NOT do in-process: a chip grant that *hangs* (rather than
raises) never returns control — that mode is handled one level up by the
CLI's watchdogged device probe (``tpu_life/utils/platform.py``), which
refuses to start the run instead.  And recovery is *process-local*: in a
multi-process job, one process rewinding while its peers sit in a posted
collective would deadlock or diverge, so the driver disables it when
``jax.process_count() > 1`` — there the recovery unit is the whole job
(relaunch with ``--resume``, which every process resolves identically).
"""

from __future__ import annotations

from typing import Callable

import numpy as np


class InjectedFault(RuntimeError):
    """Simulated device loss, raised by the ``--fault-at`` drill."""


#: Exception types the driver may recover from by rebuilding + resuming.
#: Device/runtime loss (XlaRuntimeError) subclasses RuntimeError; config
#: and user errors (ValueError, FileNotFoundError, KeyError) never match,
#: so a typo cannot silently burn restart attempts.
RECOVERABLE: tuple[type[BaseException], ...] = (RuntimeError,)


#: Message markers that identify a device out-of-memory among the
#: RECOVERABLE family.  XLA surfaces OOM as an XlaRuntimeError whose
#: message leads with the RESOURCE_EXHAUSTED status (TPU and GPU alike);
#: the chaos ``engine.oom`` drill injects the same marker so the
#: classifier exercised in tests is the one production runs.
_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "out of memory", "Out of memory")


def is_oom(e: BaseException) -> bool:
    """True when a RECOVERABLE error is a device out-of-memory — the one
    failure shape with its own recovery ladder (halve the chunk, then
    demote to the host engine) instead of a plain rebuild-and-replay."""
    msg = str(e)
    return any(m in msg for m in _OOM_MARKERS)


def unwrap(runner):
    """The backend's own Runner behind a possible ``FaultingRunner`` proxy —
    for backend APIs that take their runner back (``write_runner_to_file``)."""
    return runner._inner if isinstance(runner, FaultingRunner) else runner


class FaultingRunner:
    """Runner proxy that raises ``InjectedFault`` in ``advance`` — where a
    real device failure would surface — when the run *crosses* absolute
    step ``fault_at`` (a run resumed at or past ``fault_at`` has already
    crossed it and is left alone).

    ``fired`` is a list shared across restarts (one entry per firing), so
    the drill kills the run ``fault_count`` times per ``driver.run`` call:
    after recovery rewinds to a snapshot before ``fault_at``, the re-wrapped
    runner fires again until the budget is spent — which is how the
    multi-failure / budget-exhaustion paths get exercised.
    """

    def __init__(
        self,
        inner,
        start_step: int,
        fault_at: int,
        fired: list[bool],
        fault_count: int = 1,
    ):
        self._inner = inner
        self._done = start_step
        self._fault_at = fault_at
        self._fired = fired
        self._fault_count = fault_count

    def advance(self, steps: int) -> None:
        if (
            len(self._fired) < self._fault_count
            and self._done < self._fault_at <= self._done + steps
        ):
            self._fired.append(True)
            raise InjectedFault(
                f"injected device failure crossing step {self._fault_at} "
                f"({len(self._fired)}/{self._fault_count})"
            )
        self._inner.advance(steps)
        self._done += steps

    def sync(self) -> None:
        self._inner.sync()

    def fetch(self) -> np.ndarray:
        return self._inner.fetch()

    def snapshot(self) -> Callable[[], np.ndarray]:
        return self._inner.snapshot()

    def live_count(self) -> int:
        return self._inner.live_count()
