"""The driver: what the reference's ``main`` (Parallel_Life_MPI.cpp:190-240)
becomes once the layers are factored.

Sequence (mirrors §3.1 of SURVEY.md, with the barriers dissolved):
read config -> load board (or resume) -> pick backend -> fused epoch
loop with optional snapshot/metric chunking -> write output -> report
``Total time = <s>`` from the lead process.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from pathlib import Path

import numpy as np

from tpu_life.backends.base import drive_runner, get_backend, make_runner
from tpu_life.config import RunConfig
from tpu_life.io.codec import read_board, write_board
from tpu_life.models.rules import get_rule
from tpu_life.parallel.mesh import init_distributed
from tpu_life.runtime import checkpoint as ckpt
from tpu_life.runtime.metrics import MetricsRecorder, configure_logging, dump_board, log
from tpu_life.runtime.profiling import maybe_profile
from tpu_life.utils.timing import Timer


# auto-streaming threshold: boards at or above this many cells skip host
# materialization when the backend can load/store per-shard (256 Mcells)
_STREAM_AUTO_CELLS = 1 << 28


@dataclass
class RunResult:
    board: np.ndarray | None  # None on streamed runs (never materialized)
    steps_run: int
    elapsed_s: float
    backend: str
    rule: str
    metrics: list[dict] = field(default_factory=list)


def _is_lead_process() -> bool:
    """True on the process that owns single-writer side effects (whole-board
    output, the ``Total time`` report) — the analogue of the reference's
    rank-0 gating (Parallel_Life_MPI.cpp:234-236).  Per-shard streamed writes
    are NOT gated on this: like ``MPI_File_write_at_all``
    (Parallel_Life_MPI.cpp:175), every process writes the byte ranges of the
    shards it addresses."""
    import jax

    return jax.process_index() == 0


def run(cfg: RunConfig) -> RunResult:
    configure_logging(cfg.verbose)
    # Join a multi-host job if the environment describes one — the MPI_Init
    # analogue (Parallel_Life_MPI.cpp:195-197).  Must precede any device
    # query, hence before backend construction below.
    init_distributed()
    height, width, steps = cfg.resolved_geometry()
    rule = get_rule(cfg.effective_rule())

    timer = Timer()  # spans I/O too, like the reference's Wtime bracket

    backend_name = cfg.backend
    if cfg.mesh_shape is not None:
        # a mesh shape only means something to the sharded backend — don't
        # let `auto` resolve elsewhere and silently ignore it
        if backend_name == "auto":
            backend_name = "sharded"
        elif backend_name != "sharded":
            raise ValueError(
                f"--mesh-shape requires the sharded backend, got {backend_name!r}"
            )
    backend_kwargs = dict(
        num_devices=cfg.num_devices,
        mesh_shape=cfg.mesh_shape,
        partition_mode=cfg.partition_mode,
        pad_lanes=cfg.pad_lanes,
        bitpack=cfg.bitpack,
        local_kernel=cfg.local_kernel,
    )
    if cfg.block_steps is not None:
        backend_kwargs["block_steps"] = cfg.block_steps
    backend = get_backend(backend_name, **backend_kwargs)

    # Board source: a contract-format file (+ completed steps when resuming).
    # Streamed per-shard straight onto the mesh when supported — the 65536^2
    # path where the board never materializes whole on one host.
    start_step = 0
    input_path = cfg.input_file
    if cfg.resume:
        input_path, start_step, height, width = ckpt.resolve_resume(
            cfg.resume, height, width
        )
        log.info("resuming from %s at step %d", input_path, start_step)

    can_stream = hasattr(backend, "prepare_from_file")
    stream = (
        cfg.stream_io
        if cfg.stream_io is not None
        # auto-stream only when the result goes to a file — a library caller
        # with no output_file needs RunResult.board, which streaming skips
        else can_stream
        and bool(cfg.output_file)
        and height * width >= _STREAM_AUTO_CELLS
    )
    if stream and not can_stream:
        raise ValueError(
            "--stream-io needs the sharded backend "
            f"(got backend {backend_name!r})"
        )
    if (
        stream
        and not cfg.output_file
        and cfg.snapshot_every <= 0
        and not cfg.metrics
    ):
        # a streamed run's board is never materialized, so with no output
        # file, no snapshots and no metrics the run would compute into the
        # void — reject instead of silently returning RunResult(board=None).
        # (metrics-only streamed runs are fine: live counts flow through the
        # gather-free on-device reduction into RunResult.metrics)
        raise ValueError(
            "stream_io=True produces no host board; pass output_file, "
            "snapshot_every or metrics, or use stream_io=False to get "
            "RunResult.board"
        )

    board = None
    if stream:
        runner = backend.prepare_from_file(input_path, height, width, rule)
    else:
        board = read_board(input_path, height, width)
        max_state = int(board.max(initial=0))
        if max_state >= rule.states:
            raise ValueError(
                f"board contains state {max_state} but rule {rule.name!r} has "
                f"only {rule.states} states (0..{rule.states - 1})"
            )
        runner = make_runner(backend, board, rule)

    remaining = max(0, steps - start_step)
    recorder = MetricsRecorder(
        height * width, cfg.metrics or cfg.verbose, start_step=start_step
    )

    chunk = cfg.sync_every
    if cfg.snapshot_every > 0:
        chunk = (
            cfg.snapshot_every
            if chunk <= 0
            else min(chunk, cfg.snapshot_every)
        )

    last_snap = 0  # crossing detection: snapshot at the first sync point
    # at-or-past each snapshot_every multiple, so sync_every and
    # snapshot_every need not divide each other

    def on_chunk(done_local: int, get_board) -> None:
        nonlocal last_snap
        done = start_step + done_local
        if recorder.enabled:
            # live count via the runner's on-device sharded reduction — two
            # scalars cross to the host, never the board (SURVEY.md §5), so
            # --metrics composes with --stream-io at any board size
            recorder.record_chunk(done, timer.elapsed, runner.live_count())
        # a board gather happens only for the --verbose small-board dump
        board_np = get_board() if cfg.verbose else None
        if (
            cfg.snapshot_every > 0
            and done_local // cfg.snapshot_every > last_snap // cfg.snapshot_every
        ):
            last_snap = done_local
            if stream:
                # per-shard snapshot write: the board stays sharded
                Path(cfg.snapshot_dir).mkdir(parents=True, exist_ok=True)
                p = ckpt.snapshot_path(cfg.snapshot_dir, done)
                backend.write_runner_to_file(runner, p, height, width, rule)
                ckpt.write_sidecar(p, done, rule.name, height, width)
            else:
                p = ckpt.save_snapshot(
                    cfg.snapshot_dir,
                    done,
                    board_np if board_np is not None else get_board(),
                    rule=rule.name,
                )
            log.info("snapshot step=%d -> %s", done, p)
        if cfg.verbose and board_np is not None:
            log.debug("board at step %d:\n%s", done, dump_board(board_np))

    callback = (
        on_chunk
        if (cfg.snapshot_every > 0 or cfg.metrics or cfg.verbose)
        else None
    )

    with maybe_profile(cfg.profile):
        drive_runner(runner, remaining, chunk_steps=chunk, callback=callback)
    if not stream:
        board = runner.fetch()

    lead = _is_lead_process()
    if cfg.output_file:
        Path(cfg.output_file).parent.mkdir(parents=True, exist_ok=True)
        if stream:
            # per-shard collective write: every process writes the byte
            # ranges of the shards it addresses (MPI_File_write_at_all,
            # Parallel_Life_MPI.cpp:175) — never gated on the lead
            backend.write_runner_to_file(
                runner, cfg.output_file, height, width, rule
            )
        elif lead:
            # whole-board write: single writer, like rank 0 owning the
            # host-materialized result
            write_board(cfg.output_file, board)

    elapsed = timer.elapsed
    if lead:
        # Contract parity: the reference's lead-rank report
        # (Parallel_Life_MPI.cpp:234-236).
        print(f"Total time = {elapsed}")
    return RunResult(
        board=board,
        steps_run=remaining,
        elapsed_s=elapsed,
        backend=getattr(backend, "name", cfg.backend),
        rule=rule.name,
        metrics=recorder.records,
    )
