"""The driver: what the reference's ``main`` (Parallel_Life_MPI.cpp:190-240)
becomes once the layers are factored.

Sequence (mirrors §3.1 of SURVEY.md, with the barriers dissolved):
read config -> load board (or resume) -> pick backend -> fused epoch
loop with optional snapshot/metric chunking -> write output -> report
``Total time = <s>`` from the lead process.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from pathlib import Path

import numpy as np

from tpu_life.backends.base import get_backend
from tpu_life.config import RunConfig
from tpu_life.io.codec import read_board, write_board
from tpu_life.models.rules import get_rule
from tpu_life.runtime import checkpoint as ckpt
from tpu_life.runtime.metrics import MetricsRecorder, configure_logging, dump_board, log
from tpu_life.runtime.profiling import maybe_profile
from tpu_life.utils.timing import Timer


@dataclass
class RunResult:
    board: np.ndarray
    steps_run: int
    elapsed_s: float
    backend: str
    rule: str
    metrics: list[dict] = field(default_factory=list)


def run(cfg: RunConfig) -> RunResult:
    configure_logging(cfg.verbose)
    height, width, steps = cfg.resolved_geometry()
    rule = get_rule(cfg.effective_rule())

    timer = Timer()  # spans I/O too, like the reference's Wtime bracket

    start_step = 0
    if cfg.resume:
        board, start_step = ckpt.load_resume(cfg.resume, height, width)
        log.info("resumed from %s at step %d", cfg.resume, start_step)
    else:
        board = read_board(cfg.input_file, height, width)
    if board.shape != (height, width):
        raise ValueError(
            f"board shape {board.shape} != configured ({height}, {width})"
        )
    max_state = int(board.max(initial=0))
    if max_state >= rule.states:
        raise ValueError(
            f"board contains state {max_state} but rule {rule.name!r} has "
            f"only {rule.states} states (0..{rule.states - 1})"
        )

    backend_name = cfg.backend
    if cfg.mesh_shape is not None:
        # a mesh shape only means something to the sharded backend — don't
        # let `auto` resolve elsewhere and silently ignore it
        if backend_name == "auto":
            backend_name = "sharded"
        elif backend_name != "sharded":
            raise ValueError(
                f"--mesh-shape requires the sharded backend, got {backend_name!r}"
            )
    backend_kwargs = dict(
        num_devices=cfg.num_devices,
        mesh_shape=cfg.mesh_shape,
        partition_mode=cfg.partition_mode,
        pad_lanes=cfg.pad_lanes,
        bitpack=cfg.bitpack,
    )
    if cfg.block_steps is not None:
        backend_kwargs["block_steps"] = cfg.block_steps
    backend = get_backend(backend_name, **backend_kwargs)

    remaining = max(0, steps - start_step)
    recorder = MetricsRecorder(
        height * width, cfg.metrics or cfg.verbose, start_step=start_step
    )

    chunk = cfg.sync_every
    if cfg.snapshot_every > 0:
        chunk = (
            cfg.snapshot_every
            if chunk <= 0
            else min(chunk, cfg.snapshot_every)
        )

    last_snap = 0  # crossing detection: snapshot at the first sync point
    # at-or-past each snapshot_every multiple, so sync_every and
    # snapshot_every need not divide each other

    def on_chunk(done_local: int, get_board) -> None:
        nonlocal last_snap
        done = start_step + done_local
        board_np = get_board()  # one device->host transfer per chunk
        recorder.record_chunk(done, timer.elapsed, board_np)
        if (
            cfg.snapshot_every > 0
            and done_local // cfg.snapshot_every > last_snap // cfg.snapshot_every
        ):
            last_snap = done_local
            p = ckpt.save_snapshot(
                cfg.snapshot_dir, done, board_np, rule=rule.name
            )
            log.info("snapshot step=%d -> %s", done, p)
        if cfg.verbose:
            log.debug("board at step %d:\n%s", done, dump_board(board_np))

    callback = (
        on_chunk
        if (cfg.snapshot_every > 0 or cfg.metrics or cfg.verbose)
        else None
    )

    with maybe_profile(cfg.profile):
        board = backend.run(
            board,
            rule,
            remaining,
            chunk_steps=chunk,
            callback=callback,
        )

    if cfg.output_file:
        Path(cfg.output_file).parent.mkdir(parents=True, exist_ok=True)
        write_board(cfg.output_file, board)

    elapsed = timer.elapsed
    # Contract parity: the reference's lead-rank report
    # (Parallel_Life_MPI.cpp:234-236).
    print(f"Total time = {elapsed}")
    return RunResult(
        board=board,
        steps_run=remaining,
        elapsed_s=elapsed,
        backend=getattr(backend, "name", cfg.backend),
        rule=rule.name,
        metrics=recorder.records,
    )
