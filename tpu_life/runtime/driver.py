"""The driver: what the reference's ``main`` (Parallel_Life_MPI.cpp:190-240)
becomes once the layers are factored.

Sequence (mirrors §3.1 of SURVEY.md, with the barriers dissolved):
read config -> load board (or resume) -> pick backend -> fused epoch
loop with optional snapshot/metric chunking -> write output -> report
``Total time = <s>`` from the lead process.

Telemetry (docs/OBSERVABILITY.md): every invocation generates one
``run_id`` stamped into the metrics JSONL records and the
``--trace-events`` Chrome trace, whose spans bracket each host phase —
config-resolve, backend-build (compilation), stage (initial transfer),
each host-sync chunk, snapshot writes, recovery rewinds, the final
gather/output.  With tracing and metrics both off the chunk callback is
None and the fused loop runs with zero per-step Python cost, exactly as
before.
"""

from __future__ import annotations

import time
from contextlib import nullcontext
from dataclasses import dataclass, field
from pathlib import Path

import numpy as np

from tpu_life import obs
from tpu_life.backends.base import drive_runner, get_backend, make_runner
from tpu_life.config import RunConfig
from tpu_life.io.codec import read_board, write_board
from tpu_life.models.rules import get_rule
from tpu_life.parallel.mesh import init_distributed
from tpu_life.runtime import checkpoint as ckpt
from tpu_life.runtime import recovery
from tpu_life.runtime.metrics import MetricsRecorder, configure_logging, dump_board, log
from tpu_life.runtime.profiling import maybe_profile
from tpu_life.utils.timing import Timer


# auto-streaming threshold: boards at or above this many cells skip host
# materialization when the backend can load/store per-shard (256 Mcells)
_STREAM_AUTO_CELLS = 1 << 28


@dataclass
class RunResult:
    board: np.ndarray | None  # None on streamed runs (never materialized)
    steps_run: int
    elapsed_s: float
    backend: str
    rule: str
    metrics: list[dict] = field(default_factory=list)
    restarts: int = 0  # recoveries taken by the elastic-recovery loop
    run_id: str = ""  # correlation id shared by metrics/trace artifacts
    # the counter-based PRNG seed (tpu_life.mc) — stamped for stochastic
    # rules and for seeded-random-board staging, so the telemetry record
    # is a full replay recipe; None when the run consumed no seed
    seed: int | None = None
    temperature: float | None = None  # ising per-run scalar (None elsewhere)


def _single_process() -> bool:
    import jax

    return jax.process_count() == 1


def _is_lead_process() -> bool:
    """True on the process that owns single-writer side effects (whole-board
    output, the ``Total time`` report) — the analogue of the reference's
    rank-0 gating (Parallel_Life_MPI.cpp:234-236).  Per-shard streamed writes
    are NOT gated on this: like ``MPI_File_write_at_all``
    (Parallel_Life_MPI.cpp:175), every process writes the byte ranges of the
    shards it addresses."""
    import jax

    return jax.process_index() == 0


def run(cfg: RunConfig) -> RunResult:
    configure_logging(cfg.verbose)
    # Join a multi-host job if the environment describes one — the MPI_Init
    # analogue (Parallel_Life_MPI.cpp:195-197).  Must precede any device
    # query, hence before backend construction below.
    init_distributed()
    run_id = obs.new_run_id()
    # the trace file is a single-writer side effect, lead-only like the
    # metrics sink; obs.span/complete degrade to no-ops on peers
    tracer = (
        obs.start_tracing(cfg.trace_events, run_id=run_id)
        if cfg.trace_events and _is_lead_process()
        else None
    )
    try:
        with obs.span("run", run_id=run_id, backend=cfg.backend, rule=cfg.rule):
            return _run(cfg, run_id)
    finally:
        if tracer is not None:
            obs.stop_tracing(tracer)
            log.info("trace events -> %s (run_id=%s)", tracer.path, run_id)


def _run(cfg: RunConfig, run_id: str) -> RunResult:
    with obs.span("config-resolve"):
        height, width, steps = cfg.resolved_geometry()
        rule = get_rule(cfg.effective_rule())
        # stochastic-tier gating (tpu_life.mc) happens before any backend
        # resolution: a stochastic rule on an executor without the
        # counter-based key schedule — including "tuned", whose resolver
        # could pick one — is a typed rejection, and the (rule,
        # temperature) pairing is validated once for every front
        from tpu_life import mc

        mc.ensure_backend_supported(rule, cfg.backend)
        mc.validate_params(rule, cfg.temperature)
        # board area vs PRNG counter width: the packed path (jax default,
        # --bitpack) carries the wide two-word cell index, so over-2^32-
        # cell lattices route there; the roll path rejects them typed
        mc.validate_board_shape(
            rule,
            (height, width),
            wide_counter=mc.wide_counter_capable(
                rule, cfg.backend, bitpack=cfg.bitpack
            ),
        )
        # kernel-vs-board geometry (docs/RULES.md): a Larger-than-Life or
        # continuous kernel wider than the board is the typed
        # GeometryError — the CLI exits 2, never a downstream shape error
        from tpu_life.models.rules import validate_rule_geometry

        validate_rule_geometry(rule, (height, width))

    timer = Timer()  # spans I/O too, like the reference's Wtime bracket

    backend_name = cfg.backend
    tuned = None  # TunedConfig once "tuned" resolves, else None
    if backend_name == "tuned":
        # autotune resolution: cache hit -> tuned knobs; miss -> analytic
        # cost model, or (tune_mode="measure") the measured search, which
        # persists its winner so the next run is a cache hit.  Resolution
        # happens BEFORE the mesh-shape check so a tuned pick of the
        # sharded backend composes with an explicit --mesh-shape.
        from tpu_life import autotune

        with obs.span("autotune-resolve"):
            key = autotune.tune_key_for(rule, (height, width))
            tuned, source = autotune.resolve(
                key, mode=cfg.tune_mode, shape=(height, width)
            )
            if source != "cache" and cfg.tune_mode == "measure":
                result = autotune.tune(key, rule, shape=(height, width))
                tuned, source = result.best, "measured"
        log.info(
            "autotune: %s -> %s (%s)", key.id(), tuned.describe(), source
        )
        backend_name = tuned.backend
    elif cfg.tune_mode not in ("off", "cache", "measure"):
        raise ValueError(
            f"tune_mode must be off|cache|measure, got {cfg.tune_mode!r}"
        )
    if cfg.mesh_shape is not None:
        # a mesh shape only means something to the sharded backend — don't
        # let `auto` resolve elsewhere and silently ignore it
        if backend_name == "auto":
            backend_name = "sharded"
        elif backend_name != "sharded":
            raise ValueError(
                f"--mesh-shape requires the sharded backend, got {backend_name!r}"
            )
    backend_kwargs = dict(
        num_devices=cfg.num_devices,
        mesh_shape=cfg.mesh_shape,
        partition_mode=cfg.partition_mode,
        pad_lanes=cfg.pad_lanes,
        bitpack=cfg.bitpack,
        local_kernel=cfg.local_kernel,
        stencil=cfg.stencil,
    )
    if cfg.block_steps is not None:
        backend_kwargs["block_steps"] = cfg.block_steps
    if tuned is not None:
        # tuned knobs fill in wherever the user left the default; an
        # explicit flag (--block-steps, --local-kernel, --no-bitpack,
        # --stencil) always wins over the cache — tuning informs, never
        # overrides
        if cfg.block_steps is None and tuned.block_steps is not None:
            backend_kwargs["block_steps"] = tuned.block_steps
        if cfg.local_kernel == "auto":
            backend_kwargs["local_kernel"] = tuned.local_kernel
        backend_kwargs["bitpack"] = cfg.bitpack and tuned.bitpack
        if cfg.stencil == "auto" and tuned.stencil != "auto":
            # the measured stencil axis (docs/AUTOTUNE.md): under
            # --stencil auto the cache's verdict beats the analytic
            # crossover model — auto is measured, not guessed
            backend_kwargs["stencil"] = tuned.stencil
    registry = obs.MetricsRegistry()
    builds = registry.counter(
        "run_backend_builds_total",
        "backend (re)builds — each one is a compilation event",
        labels=("backend",),
    )
    with obs.span("backend-build", backend=backend_name):
        backend = get_backend(backend_name, rule=rule, **backend_kwargs)
    resolved_backend = getattr(backend, "name", backend_name)
    builds.labels(backend=resolved_backend).inc()

    # Board source: a contract-format file (+ completed steps when resuming).
    # Streamed per-shard straight onto the mesh when supported — the 65536^2
    # path where the board never materializes whole on one host.
    start_step = 0
    input_path = cfg.input_file
    if cfg.resume:
        input_path, start_step, height, width = ckpt.resolve_resume(
            cfg.resume, height, width
        )
        log.info("resuming from %s at step %d", input_path, start_step)
    elif (
        cfg.height is not None
        and cfg.width is not None
        and cfg.steps is not None
        and not Path(input_path).exists()
    ):
        # fully flag-specified geometry with no input file: an exploratory
        # run (`run --size 512 --steps 64`) — stage a seeded random board
        # instead of failing, like `gen` piped into `run`.  Contract mode
        # (geometry from the config file) keeps failing loudly on a missing
        # data file.
        log.info(
            "input file %r absent; using a seeded random board (%dx%d, "
            "density 0.5, seed %d)",
            input_path,
            height,
            width,
            cfg.seed,
        )
        input_path = None

    can_stream = hasattr(backend, "prepare_from_file")
    stream = (
        cfg.stream_io
        if cfg.stream_io is not None
        # auto-stream only when the result goes to a file — a library caller
        # with no output_file needs RunResult.board, which streaming skips
        else can_stream
        and bool(cfg.output_file)
        and height * width >= _STREAM_AUTO_CELLS
    )
    if stream and not can_stream:
        raise ValueError(
            "--stream-io needs the sharded backend "
            f"(got backend {backend_name!r})"
        )
    if stream and input_path is None:
        raise ValueError(
            "stream_io needs an input file to stream from; "
            f"{cfg.input_file!r} does not exist"
        )
    if (
        stream
        and not cfg.output_file
        and cfg.snapshot_every <= 0
        and not cfg.metrics
        and not cfg.metrics_file
    ):
        # a streamed run's board is never materialized, so with no output
        # file, no snapshots and no metrics the run would compute into the
        # void — reject instead of silently returning RunResult(board=None).
        # (metrics-only streamed runs are fine: live counts flow through the
        # gather-free on-device reduction into RunResult.metrics)
        raise ValueError(
            "stream_io=True produces no host board; pass output_file, "
            "snapshot_every or metrics, or use stream_io=False to get "
            "RunResult.board"
        )

    origin = (input_path, start_step)  # restart target when no snapshot exists
    fault_fired: list[bool] = []

    def build_runner(source, start):
        """(runner, host_board|None) staged from a contract-format file
        (``source=None``: the seeded random board of an exploratory run).

        Called once up front and again after each elastic-recovery restart
        (with the rebuilt ``backend`` binding from the enclosing scope)."""
        with obs.span("stage", resume_step=start):
            if stream:
                r = backend.prepare_from_file(source, height, width, rule)
                b = None
            else:
                if source is None:
                    # counter-based staging (tpu_life.mc.prng): the board
                    # a seed names is identical on every host/backend, so
                    # the stamped seed fully replays the run.  The
                    # continuous tier stages its float twin.
                    if rule.continuous:
                        from tpu_life.models.lenia import (
                            seeded_board as lenia_seeded_board,
                        )

                        b = lenia_seeded_board(height, width, seed=cfg.seed)
                    else:
                        b = mc.seeded_board(
                            height, width, states=rule.states, seed=cfg.seed
                        )
                else:
                    b = read_board(source, height, width)
                    if rule.continuous:
                        from tpu_life.models.lenia import validate_board

                        b = validate_board(b, rule)
                    else:
                        max_state = int(b.max(initial=0))
                        if max_state >= rule.states:
                            raise ValueError(
                                f"board contains state {max_state} but rule "
                                f"{rule.name!r} has only {rule.states} states "
                                f"(0..{rule.states - 1})"
                            )
                r = make_runner(
                    backend,
                    b,
                    rule,
                    seed=cfg.seed,
                    temperature=cfg.temperature,
                    start_step=start,
                )
            if cfg.fault_at > 0:
                r = recovery.FaultingRunner(
                    r, start, cfg.fault_at, fault_fired, cfg.fault_count
                )
        return r, b

    remaining = max(0, steps - start_step)
    recorder = MetricsRecorder(
        height * width,
        # enabled must be UNIFORM across processes: record_chunk calls the
        # runner's collective live-count reduction, and a lead-only
        # recorder would leave peers out of the psum and hang the job
        cfg.metrics or cfg.verbose or bool(cfg.metrics_file),
        start_step=start_step,
        # the JSONL sink itself is a single-writer side effect: lead-only.
        # It is a raw append log — recovery rewinds may repeat steps there
        # (RunResult.metrics is the deduplicated record)
        sink=cfg.metrics_file if _is_lead_process() else None,
        run_id=run_id,
        registry=registry,
        labels={"backend": resolved_backend, "rule": rule.name},
    )

    chunk = cfg.sync_every
    if chunk <= 0 and tuned is not None and tuned.sync_every > 0:
        chunk = tuned.sync_every
    if cfg.snapshot_every > 0:
        chunk = (
            cfg.snapshot_every
            if chunk <= 0
            else min(chunk, cfg.snapshot_every)
        )

    # crossing detection: snapshot at the first sync point at-or-past each
    # snapshot_every multiple, so sync_every and snapshot_every need not
    # divide each other.  `last_snap` lives in ABSOLUTE step space and
    # restarts rewind it to the resume step, so the cadence stays anchored
    # to global snapshot_every multiples across --resume and elastic
    # recovery instead of drifting a full interval per restart (ADVICE r4).
    # Mutable holder because the elastic-recovery loop rewinds it;
    # `written` records the absolute steps of snapshots THIS run wrote —
    # the only snapshots recovery will trust as restart sources.
    state = {
        "start": start_step,
        "last_snap": start_step,
        "written": [],
        "chunk_t0": 0.0,  # trace clock at the last chunk boundary
    }
    # retention pruning is a single-writer side effect (racing unlinks in a
    # multi-process job would trip each other); gate it on the lead
    lead_snapshots = _is_lead_process()

    def on_chunk(done_local: int, get_board) -> None:
        done = state["start"] + done_local
        # the chunk's trace record is a complete (ph "X") event spanning
        # since the previous boundary — emitted after the fact because the
        # chunked loop owns the advance, not this callback
        t_end = obs.now()
        obs.complete("chunk", state["chunk_t0"], t_end, step=done)
        state["chunk_t0"] = t_end
        if recorder.enabled:
            # live count via the runner's on-device sharded reduction — two
            # scalars cross to the host, never the board (SURVEY.md §5), so
            # --metrics composes with --stream-io at any board size
            recorder.record_chunk(done, timer.elapsed, runner.live_count())
        # a board gather happens only for the --verbose small-board dump
        board_np = get_board() if cfg.verbose else None
        if (
            cfg.snapshot_every > 0
            and done // cfg.snapshot_every
            > state["last_snap"] // cfg.snapshot_every
        ):
            state["last_snap"] = done
            with obs.span("snapshot-write", step=done):
                if stream:
                    # per-shard snapshot write: the board stays sharded.
                    # Single-process: publish atomically (ckpt.atomic_publish).
                    # Multi-process: every process pwrites its shards into ONE
                    # file, so a rename dance cannot work — the collective
                    # write goes direct, and resolve_resume compensates by
                    # skipping truncated snapshots (ckpt.snapshot_intact).
                    Path(cfg.snapshot_dir).mkdir(parents=True, exist_ok=True)
                    p = ckpt.snapshot_path(cfg.snapshot_dir, done)
                    if _single_process():
                        with ckpt.atomic_publish(p) as tmp:
                            backend.write_runner_to_file(
                                recovery.unwrap(runner), tmp, height, width, rule
                            )
                    else:
                        backend.write_runner_to_file(
                            recovery.unwrap(runner), p, height, width, rule
                        )
                    if lead_snapshots:
                        # the sidecar content is identical on every process;
                        # N racing writers of one path would only add torn-
                        # file risk, so it is a single-writer side effect
                        ckpt.write_sidecar(p, done, rule.name, height, width)
                else:
                    p = ckpt.save_snapshot(
                        cfg.snapshot_dir,
                        done,
                        board_np if board_np is not None else get_board(),
                        rule=rule.name,
                    )
                state["written"].append(done)
                log.info("snapshot step=%d -> %s", done, p)
                if cfg.keep_snapshots > 0 and lead_snapshots:
                    # retention manages only THIS run's snapshots, and the
                    # kept list replaces state["written"] so elastic recovery
                    # never targets a pruned file
                    state["written"] = ckpt.prune_snapshots(
                        cfg.snapshot_dir, cfg.keep_snapshots, state["written"]
                    )
        if cfg.verbose and board_np is not None:
            log.debug("board at step %d:\n%s", done, dump_board(board_np))

    callback = (
        on_chunk
        if (
            cfg.snapshot_every > 0
            or cfg.metrics
            or cfg.metrics_file
            or cfg.verbose
            # chunk trace events need the boundary callback too; like the
            # recorder's enablement this is config-driven, so it stays
            # uniform across processes
            or cfg.trace_events
        )
        else None
    )

    # The epoch drive, wrapped in the elastic-recovery loop: a recoverable
    # failure (RuntimeError from a blocked step — preemption, device loss,
    # or the --fault-at drill) rebuilds the backend and resumes from the
    # newest snapshot, up to cfg.max_restarts times.  The reference's model
    # is the 0-restart degenerate case: any failure kills the job
    # (SURVEY.md §5 "failure detection" row).
    restarts = 0
    # Elastic recovery is process-local: in a multi-process job the peers
    # would keep collectives posted (or rewind to a different step) while
    # this process restarts, deadlocking or diverging — there the recovery
    # unit is the whole job, relaunched with --resume, which every process
    # resolves identically.
    max_restarts = cfg.max_restarts
    if max_restarts > 0:
        if not _single_process():
            log.warning(
                "multi-process job: in-process elastic recovery disabled; "
                "on failure, relaunch the whole job with --resume %s",
                cfg.snapshot_dir,
            )
            max_restarts = 0
    # (source, step) to build/rebuild from; ALL board staging — including
    # the very first — happens INSIDE the try, so a device still detaching
    # when we construct the runner consumes a restart and retries, instead
    # of escaping with budget remaining
    pending: tuple | None = (input_path, start_step)
    first_build = True
    runner = board = None
    with maybe_profile(cfg.profile):
        while True:
            try:
                if pending is not None:
                    source, resume_step = pending
                    rewind_span = (
                        nullcontext()
                        if first_build
                        else obs.span(
                            "recovery-rewind", step=resume_step, restart=restarts
                        )
                    )
                    with rewind_span:
                        if not first_build:
                            # a failure poisoned the old backend: start fresh
                            backend = get_backend(
                                backend_name, rule=rule, **backend_kwargs
                            )
                            builds.labels(backend=resolved_backend).inc()
                        first_build = False
                        state["start"] = resume_step
                        state["last_snap"] = resume_step
                        # drop metric records the rewind is about to re-earn
                        recorder.records[:] = [
                            r for r in recorder.records if r["step"] <= resume_step
                        ]
                        runner, board = build_runner(source, resume_step)
                    pending = None
                state["chunk_t0"] = obs.now()
                with obs.span("drive", steps=max(0, steps - state["start"])):
                    drive_runner(
                        runner,
                        max(0, steps - state["start"]),
                        chunk_steps=chunk,
                        callback=callback,
                    )
                # the terminal device interactions — the final host gather
                # (non-stream) / the per-shard streamed output write — are
                # as killable as any step, so they sit inside the recovery
                # scope too; the retry rewinds to the newest snapshot,
                # re-drives the tail and re-attempts them
                if stream:
                    if cfg.output_file:
                        # output format == input format, so output.txt is a
                        # documented resume source — publish it atomically
                        # too (single-process; the multi-process collective
                        # write goes direct, like snapshots)
                        with obs.span("output-write", streamed=True):
                            out_p = Path(cfg.output_file)
                            out_p.parent.mkdir(parents=True, exist_ok=True)
                            if _single_process():
                                with ckpt.atomic_publish(out_p) as tmp:
                                    backend.write_runner_to_file(
                                        recovery.unwrap(runner),
                                        tmp,
                                        height,
                                        width,
                                        rule,
                                    )
                            else:
                                backend.write_runner_to_file(
                                    recovery.unwrap(runner),
                                    out_p,
                                    height,
                                    width,
                                    rule,
                                )
                else:
                    with obs.span("gather"):
                        board = runner.fetch()
                break
            except recovery.RECOVERABLE as e:
                if restarts >= max_restarts:
                    raise
                restarts += 1
                if state["written"]:
                    # only snapshots THIS run wrote are trusted restart
                    # sources — a stale snapshots/ dir left by an earlier,
                    # unrelated run cannot hijack the resume
                    snap = max(state["written"])
                    pending = (ckpt.snapshot_path(cfg.snapshot_dir, snap), snap)
                else:
                    pending = origin
                log.warning(
                    "recoverable failure (%s: %s); restart %d/%d from %s "
                    "at step %d",
                    type(e).__name__,
                    e,
                    restarts,
                    max_restarts,
                    pending[0],
                    pending[1],
                )
                if cfg.restart_wait_s > 0:
                    time.sleep(cfg.restart_wait_s)
    # the streamed per-shard collective write already happened inside the
    # recovery scope above (every process writes the byte ranges of the
    # shards it addresses — MPI_File_write_at_all, Parallel_Life_MPI.cpp:175
    # — never gated on the lead); only the whole-board single-writer path
    # remains, a pure host-side write
    lead = _is_lead_process()
    if cfg.output_file and not stream and lead:
        with obs.span("output-write", streamed=False):
            out_p = Path(cfg.output_file)
            out_p.parent.mkdir(parents=True, exist_ok=True)
            # whole-board write: single writer, like rank 0 owning the
            # host-materialized result; atomic because output.txt is itself a
            # documented resume source (output format == input format)
            with ckpt.atomic_publish(out_p) as tmp:
                write_board(tmp, board)

    elapsed = timer.elapsed
    # close() flushes the registry snapshot (compile counts, chunk-duration
    # histogram) into the sink and releases the persistent handle so
    # repeated in-process runs don't accumulate open fds until GC
    recorder.close()
    if lead:
        # Contract parity: the reference's lead-rank report
        # (Parallel_Life_MPI.cpp:234-236).
        print(f"Total time = {elapsed}")
    return RunResult(
        board=board,
        steps_run=remaining,
        elapsed_s=elapsed,
        backend=resolved_backend,
        rule=rule.name,
        metrics=recorder.records,
        restarts=restarts,
        run_id=run_id,
        # replay record: stamped whenever the run consumed the seed —
        # stochastic dynamics, or counter-seeded board staging
        seed=cfg.seed if (rule.stochastic or origin[0] is None) else None,
        temperature=cfg.temperature,
    )
