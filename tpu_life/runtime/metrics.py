"""Structured run metrics — what the reference's stdout prints grow up into.

The reference's observability is per-rank write confirmations and one
``Total time`` line (Parallel_Life_MPI.cpp:179, :234-236).  Here: a logger
emitting step index, live-cell count, steps/sec and cell-updates/sec at each
host-sync chunk, plus the same final ``Total time = <s>`` line for contract
parity (SURVEY.md §6a item 5).

Since the obs refactor the recorder sits on :class:`tpu_life.obs.
MetricsRegistry`: every record is stamped with the invocation's ``run_id``
and a wall-clock ``ts`` (so JSONL lines align with trace-event and
profiler timelines), per-chunk durations feed a histogram, and
:meth:`MetricsRecorder.close` appends the registry snapshot (``kind:
"metric"`` records) to the same sink — one file ``tpu-life stats`` reads
back whole.
"""

from __future__ import annotations

import json
import logging
import sys
import time

import numpy as np

from tpu_life import obs

log = logging.getLogger("tpu_life")


def configure_logging(verbose: bool) -> None:
    if not log.handlers:
        h = logging.StreamHandler(sys.stderr)
        h.setFormatter(logging.Formatter("%(asctime)s %(name)s %(message)s"))
        log.addHandler(h)
    # we attach our own handler, so records must not ALSO propagate to the
    # root logger — under pytest (or any app with a root handler) every
    # line used to print twice
    log.propagate = False
    log.setLevel(logging.DEBUG if verbose else logging.INFO)


class MetricsRecorder:
    def __init__(
        self,
        cell_count: int,
        enabled: bool,
        start_step: int = 0,
        sink: str | None = None,
        run_id: str | None = None,
        registry: obs.MetricsRegistry | None = None,
        labels: dict | None = None,
    ):
        self.cell_count = cell_count
        self.enabled = enabled or sink is not None
        self.start_step = start_step  # rates count only this run's steps
        self.records: list[dict] = []
        self.run_id = run_id or obs.new_run_id()
        self.registry = registry if registry is not None else obs.MetricsRegistry()
        self.sink = sink  # append each record as a JSON line here
        self._sink_handle = None  # persistent handle, flushed per record
        if sink:
            # open eagerly: a missing parent directory must fail HERE, at
            # construction, not minutes later when the first chunk syncs
            # (the old lazy open discarded a whole run's compute on a typo)
            obs.ensure_parent(sink)
            self._sink_handle = open(sink, "a")
        # bounded labels (backend, rule) on the run instruments; the chunk
        # histogram answers "how even are my host-sync chunks" and the step
        # counter makes multi-run sinks aggregable
        self._labels = dict(labels or {})
        labelnames = tuple(self._labels)
        self._chunk_seconds = self.registry.histogram(
            "run_chunk_seconds",
            "wall seconds per host-sync chunk",
            labels=labelnames,
        )
        self._steps_total = self.registry.counter(
            "run_steps_total", "simulation steps completed", labels=labelnames
        )
        self._last_elapsed = 0.0
        self._last_done = 0

    def _inst(self, family):
        return family.labels(**self._labels) if self._labels else family

    def record(self, rec: dict) -> None:
        """Append an arbitrary record (and mirror it to the JSONL sink).

        The generic entry point: ``record_chunk`` builds the per-chunk
        simulation record, the serving layer emits per-round queue/batch
        records — both land in the same ``records`` list and sink file,
        stamped with the run's correlation id and a wall-clock ``ts``.
        """
        if not self.enabled:
            return
        rec.setdefault("run_id", self.run_id)
        rec.setdefault("ts", time.time())
        self.records.append(rec)
        self._write_sink(rec)

    def _write_sink(self, rec: dict) -> None:
        # one persistent append handle, flushed per record: a JSONL
        # consumer tailing the sink sees each complete line as soon as the
        # chunk that produced it syncs, and a killed run loses nothing
        if not self.sink:
            return
        if self._sink_handle is None:
            # a recorder that keeps recording after close() reopens the
            # sink (append) — close-then-continue keeps its records
            self._sink_handle = open(self.sink, "a")
        self._sink_handle.write(json.dumps(rec) + "\n")
        self._sink_handle.flush()

    def flush_registry(self) -> None:
        """Append the registry snapshot (``kind: "metric"`` records) to the
        sink.  Snapshot lines go to the sink only — ``records`` (and so
        ``RunResult.metrics``) stays the per-chunk stream it always was."""
        if not self.sink:
            return
        for rec in self.registry.snapshot(run_id=self.run_id):
            rec["ts"] = time.time()
            self._write_sink(rec)

    def close(self) -> None:
        if self._sink_handle is not None:
            self.flush_registry()
            self._sink_handle.close()
            self._sink_handle = None

    def record_chunk(self, step: int, elapsed: float, live: int) -> None:
        """Record one host-sync chunk.  ``live`` comes from the runner's
        on-device sharded reduction (``Runner.live_count``) — the recorder
        never sees the board, so metrics cannot force a gather (SURVEY.md §5
        "live-cell count via sharded reduction")."""
        if not self.enabled:
            return
        done = step - self.start_step
        # rates report 0.0 (not NaN) when no time has elapsed: NaN is not
        # valid JSON, so a single zero-elapsed chunk used to poison the
        # JSONL sink for strict parsers downstream
        rec = {
            "step": step,
            "elapsed_s": elapsed,
            "live_cells": live,
            "steps_per_sec": done / elapsed if elapsed > 0 else 0.0,
            "cell_updates_per_sec": done * self.cell_count / elapsed
            if elapsed > 0
            else 0.0,
        }
        self._inst(self._chunk_seconds).observe(
            max(0.0, elapsed - self._last_elapsed)
        )
        self._last_elapsed = max(self._last_elapsed, elapsed)
        # counters take per-chunk deltas (done is cumulative; a recovery
        # rewind may send it backwards — clamp, never double-count)
        self._inst(self._steps_total).inc(max(0, done - self._last_done))
        self._last_done = max(self._last_done, done)
        self.record(rec)
        log.info(
            "step=%d live=%d steps/s=%.2f cells/s=%.3e",
            step,
            live,
            rec["steps_per_sec"],
            rec["cell_updates_per_sec"],
        )


def dump_board(board: np.ndarray, max_size: int = 64) -> str:
    """Small-board ASCII dump — the reference's commented-out debug print
    (Parallel_Life_MPI.cpp:223-229), resurrected behind --verbose."""
    h, w = board.shape
    if h > max_size or w > max_size:
        return f"<board {h}x{w} too large to dump>"
    return "\n".join("".join(str(int(c)) for c in row) for row in board)
