"""Structured run metrics — what the reference's stdout prints grow up into.

The reference's observability is per-rank write confirmations and one
``Total time`` line (Parallel_Life_MPI.cpp:179, :234-236).  Here: a logger
emitting step index, live-cell count, steps/sec and cell-updates/sec at each
host-sync chunk, plus the same final ``Total time = <s>`` line for contract
parity (SURVEY.md §6a item 5).
"""

from __future__ import annotations

import logging
import sys

import numpy as np

log = logging.getLogger("tpu_life")


def configure_logging(verbose: bool) -> None:
    if not log.handlers:
        h = logging.StreamHandler(sys.stderr)
        h.setFormatter(logging.Formatter("%(asctime)s %(name)s %(message)s"))
        log.addHandler(h)
    log.setLevel(logging.DEBUG if verbose else logging.INFO)


class MetricsRecorder:
    def __init__(
        self,
        cell_count: int,
        enabled: bool,
        start_step: int = 0,
        sink: str | None = None,
    ):
        self.cell_count = cell_count
        self.enabled = enabled or sink is not None
        self.start_step = start_step  # rates count only this run's steps
        self.records: list[dict] = []
        self.sink = sink  # append each record as a JSON line here
        self._sink_handle = None  # lazily opened, flushed per record

    def record(self, rec: dict) -> None:
        """Append an arbitrary record (and mirror it to the JSONL sink).

        The generic entry point: ``record_chunk`` builds the per-chunk
        simulation record, the serving layer emits per-round queue/batch
        records — both land in the same ``records`` list and sink file.
        """
        if not self.enabled:
            return
        self.records.append(rec)
        self._write_sink(rec)

    def _write_sink(self, rec: dict) -> None:
        # one persistent append handle, flushed per record: a JSONL
        # consumer tailing the sink sees each complete line as soon as the
        # chunk that produced it syncs, and a killed run loses nothing
        if not self.sink:
            return
        import json

        if self._sink_handle is None:
            self._sink_handle = open(self.sink, "a")
        self._sink_handle.write(json.dumps(rec) + "\n")
        self._sink_handle.flush()

    def close(self) -> None:
        if self._sink_handle is not None:
            self._sink_handle.close()
            self._sink_handle = None

    def record_chunk(self, step: int, elapsed: float, live: int) -> None:
        """Record one host-sync chunk.  ``live`` comes from the runner's
        on-device sharded reduction (``Runner.live_count``) — the recorder
        never sees the board, so metrics cannot force a gather (SURVEY.md §5
        "live-cell count via sharded reduction")."""
        if not self.enabled:
            return
        done = step - self.start_step
        # rates report 0.0 (not NaN) when no time has elapsed: NaN is not
        # valid JSON, so a single zero-elapsed chunk used to poison the
        # JSONL sink for strict parsers downstream
        rec = {
            "step": step,
            "elapsed_s": elapsed,
            "live_cells": live,
            "steps_per_sec": done / elapsed if elapsed > 0 else 0.0,
            "cell_updates_per_sec": done * self.cell_count / elapsed
            if elapsed > 0
            else 0.0,
        }
        self.records.append(rec)
        self._write_sink(rec)
        log.info(
            "step=%d live=%d steps/s=%.2f cells/s=%.3e",
            step,
            live,
            rec["steps_per_sec"],
            rec["cell_updates_per_sec"],
        )


def dump_board(board: np.ndarray, max_size: int = 64) -> str:
    """Small-board ASCII dump — the reference's commented-out debug print
    (Parallel_Life_MPI.cpp:223-229), resurrected behind --verbose."""
    h, w = board.shape
    if h > max_size or w > max_size:
        return f"<board {h}x{w} too large to dump>"
    return "\n".join("".join(str(int(c)) for c in row) for row in board)
