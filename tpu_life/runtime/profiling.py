"""Profiling hooks (SURVEY.md §5 "Tracing / profiling").

``--profile DIR`` wraps the run in a ``jax.profiler`` trace viewable in
XProf/Perfetto — the per-phase breakdown the reference's single
``MPI_Wtime`` bracket (Parallel_Life_MPI.cpp:199,233) can't give.
"""

from __future__ import annotations

from contextlib import contextmanager, nullcontext


@contextmanager
def _trace(trace_dir: str):
    import jax

    with jax.profiler.trace(trace_dir):
        yield


def maybe_profile(trace_dir: str | None):
    return _trace(trace_dir) if trace_dir else nullcontext()
