"""Profiling hooks (SURVEY.md §5 "Tracing / profiling").

``--profile DIR`` wraps the run in a ``jax.profiler`` trace viewable in
XProf/Perfetto — the per-phase breakdown the reference's single
``MPI_Wtime`` bracket (Parallel_Life_MPI.cpp:199,233) can't give.

Composes with ``--trace-events`` span tracing (tpu_life.obs): when both
are on, the device trace's extent appears as a ``jax-profile`` span in
the host trace, so the two timelines can be aligned by run_id + offset.
"""

from __future__ import annotations

from contextlib import contextmanager, nullcontext

from tpu_life import obs


@contextmanager
def _trace(trace_dir: str):
    import jax

    with obs.span("jax-profile", trace_dir=trace_dir):
        with jax.profiler.trace(trace_dir):
            yield


def maybe_profile(trace_dir: str | None):
    return _trace(trace_dir) if trace_dir else nullcontext()
