"""Checkpoint / resume.

The reference has none mid-run; its terminal ``output.txt`` doubles as a
restartable board because output format == input format
(Parallel_Life_MPI.cpp:10-11, :161-163; SURVEY.md §5).  We make that design
first-class: snapshots *are* board files in the contract codec, plus a tiny
JSON sidecar recording step/rule/geometry, so ``--resume`` works on any
snapshot — or on a bare ``output.txt`` from any backend or the reference
binary itself.
"""

from __future__ import annotations

import json
import os
import re
from pathlib import Path

import numpy as np

from tpu_life.io.codec import read_board, write_board

_SNAP_RE = re.compile(r"^board_(\d+)\.txt$")


def snapshot_path(directory: str | os.PathLike, step: int) -> Path:
    return Path(directory) / f"board_{step:09d}.txt"


def write_sidecar(p: Path, step: int, rule: str, height: int, width: int) -> None:
    meta = {"step": step, "rule": rule, "height": height, "width": width}
    p.with_suffix(".json").write_text(json.dumps(meta))


def save_snapshot(
    directory: str | os.PathLike,
    step: int,
    board: np.ndarray,
    *,
    rule: str,
) -> Path:
    d = Path(directory)
    d.mkdir(parents=True, exist_ok=True)
    p = snapshot_path(d, step)
    write_board(p, board)
    write_sidecar(p, step, rule, int(board.shape[0]), int(board.shape[1]))
    return p


def latest_snapshot(directory: str | os.PathLike) -> tuple[int, Path] | None:
    d = Path(directory)
    if not d.is_dir():
        return None
    best: tuple[int, Path] | None = None
    for f in d.iterdir():
        m = _SNAP_RE.match(f.name)
        if m:
            step = int(m.group(1))
            if best is None or step > best[0]:
                best = (step, f)
    return best


def resolve_resume(
    path: str | os.PathLike, height: int, width: int
) -> tuple[Path, int, int, int]:
    """Resolve a resume target to (board_file, completed_steps, height, width)
    without reading the board — so streaming loaders can pread stripes.

    ``path`` may be a snapshot (step recovered from its sidecar/filename), a
    snapshot *directory* (latest snapshot wins), or any contract-format board
    file (completed_steps = 0 unless a sidecar says otherwise).
    """
    p = Path(path)
    if p.is_dir():
        found = latest_snapshot(p)
        if found is None:
            raise FileNotFoundError(f"no snapshots in {p}")
        step, p = found
        return p, step, height, width
    step = 0
    sidecar = p.with_suffix(".json")
    if sidecar.exists():
        meta = json.loads(sidecar.read_text())
        step = int(meta.get("step", 0))
        height = int(meta.get("height", height))
        width = int(meta.get("width", width))
    else:
        m = _SNAP_RE.match(p.name)
        if m:
            step = int(m.group(1))
    return p, step, height, width


def load_resume(
    path: str | os.PathLike, height: int, width: int
) -> tuple[np.ndarray, int]:
    """Load a board to resume from; returns (board, completed_steps)."""
    p, step, height, width = resolve_resume(path, height, width)
    return read_board(p, height, width), step
