"""Checkpoint / resume.

The reference has none mid-run; its terminal ``output.txt`` doubles as a
restartable board because output format == input format
(Parallel_Life_MPI.cpp:10-11, :161-163; SURVEY.md §5).  We make that design
first-class: snapshots *are* board files in the contract codec, plus a tiny
JSON sidecar recording step/rule/geometry, so ``--resume`` works on any
snapshot — or on a bare ``output.txt`` from any backend or the reference
binary itself.
"""

from __future__ import annotations

import json
import logging
import os
import re
import zlib
from contextlib import contextmanager
from pathlib import Path

import numpy as np

from tpu_life.io.codec import encode_board, read_board, write_board

_SNAP_RE = re.compile(r"^board_(\d+)\.txt$")

log = logging.getLogger("tpu_life")


@contextmanager
def atomic_publish(p: Path):
    """Yield a tmp path to write; publish it onto ``p`` only on success.

    A crash mid-write must never leave a truncated ``p`` — resume paths
    trust these files — and must not litter orphan tmps either: on any
    failure the tmp is unlinked, on success ``os.replace`` lands the bytes
    atomically (POSIX rename).  The tmp name is per-writer (pid): two runs
    sharing a snapshot dir, or racing writers of the same step, must not
    interleave bytes into one tmp and publish a hybrid (ADVICE r4).
    """
    tmp = p.with_suffix(f".{os.getpid()}.tmp")
    try:
        yield tmp
        os.replace(tmp, p)
    finally:
        tmp.unlink(missing_ok=True)  # no-op after a successful replace


def snapshot_path(directory: str | os.PathLike, step: int) -> Path:
    return Path(directory) / f"board_{step:09d}.txt"


def crc_path(p: Path) -> Path:
    return p.with_suffix(".crc")


def write_crc_sidecar(p: Path, crc: int) -> None:
    """Publish the board file's CRC32 next to it (``board_N.crc``).

    The size check in :func:`snapshot_intact` only catches truncation; a
    bit-flipped but right-sized snapshot would resume garbage without
    this.  Written through the same atomic publish as the board, so a
    torn CRC file is impossible — a mismatching pair (crash between the
    two publishes) simply demotes the snapshot, which is the safe answer.
    """
    with atomic_publish(crc_path(p)) as tmp:
        tmp.write_text(f"{crc:08x}")


def write_sidecar(p: Path, step: int, rule: str, height: int, width: int) -> None:
    # published atomically: snapshot_intact() demotes a snapshot whose
    # sidecar is unparseable, so a torn sidecar must be impossible even
    # under racing writers (ADVICE r4)
    meta = {"step": step, "rule": rule, "height": height, "width": width}
    with atomic_publish(p.with_suffix(".json")) as tmp:
        tmp.write_text(json.dumps(meta))


def save_snapshot(
    directory: str | os.PathLike,
    step: int,
    board: np.ndarray,
    *,
    rule: str,
) -> Path:
    d = Path(directory)
    d.mkdir(parents=True, exist_ok=True)
    p = snapshot_path(d, step)
    # the sidecar follows the board so it never describes bytes that
    # aren't fully there; the CRC is computed from this writer's OWN
    # in-memory encoding (write_board is exactly f.write(encode_board)),
    # not a read-back — no extra filesystem pass, and it can never
    # describe a hybrid of two racing writers' bytes
    with atomic_publish(p) as tmp:
        write_board(tmp, board)
        crc = zlib.crc32(encode_board(board))
    write_crc_sidecar(p, crc)
    write_sidecar(p, step, rule, int(board.shape[0]), int(board.shape[1]))
    return p


def list_snapshots(directory: str | os.PathLike) -> list[tuple[int, Path]]:
    """All snapshots in ``directory``, newest first."""
    d = Path(directory)
    if not d.is_dir():
        return []
    found = []
    for f in d.iterdir():
        m = _SNAP_RE.match(f.name)
        if m:
            found.append((int(m.group(1)), f))
    return sorted(found, reverse=True)


def latest_snapshot(directory: str | os.PathLike) -> tuple[int, Path] | None:
    snaps = list_snapshots(directory)
    return snaps[0] if snaps else None


def snapshot_intact(p: Path, height: int, width: int) -> bool:
    """True when the snapshot's byte size matches its geometry (from the
    sidecar when present, the caller's otherwise) — a file truncated by a
    crash mid-write fails this — AND, when a ``.crc`` sidecar exists, its
    CRC32 matches the file bytes, so a corrupt-but-right-sized snapshot
    (bit rot, a torn multi-writer publish) demotes to the previous
    snapshot instead of resuming garbage.  Single-process writes publish
    atomically (``atomic_publish``) so can't be truncated; multi-process
    collective snapshot writes can, which is why directory resume checks
    this.  Snapshots from writers that predate the CRC sidecar (or the
    streamed collective writer) fall back to the size check alone."""
    h, w = height, width
    sidecar = p.with_suffix(".json")
    if sidecar.exists():
        try:
            meta = json.loads(sidecar.read_text())
            h = int(meta.get("height", h))
            w = int(meta.get("width", w))
        except (ValueError, OSError):
            return False
    try:
        # the two contract encodings (io/codec.py): ASCII digit grid
        # (discrete boards) or raw little-endian float32 (the continuous
        # tier) — their lengths can never coincide, so either size is an
        # unambiguous intact witness for its geometry
        if p.stat().st_size not in (h * (w + 1), 4 * h * w):
            return False
    except OSError:
        return False
    crc_file = crc_path(p)
    if crc_file.exists():
        try:
            expect = int(crc_file.read_text().strip(), 16)
            return zlib.crc32(p.read_bytes()) == expect
        except (ValueError, OSError):
            return False
    return True


def prune_snapshots(
    directory: str | os.PathLike, keep: int, steps: list[int]
) -> list[int]:
    """Delete all but the newest ``keep`` of the given snapshot ``steps``;
    returns the steps that remain.

    Retention only ever touches the snapshots the caller names (the current
    run's own writes) — a stale higher-numbered snapshot left by some
    earlier run is neither trusted as "newest" nor deleted; it simply isn't
    this run's to manage.  ``keep <= 0`` prunes nothing.
    """
    if keep <= 0:
        return sorted(set(steps))
    ordered = sorted(set(steps))
    drop, kept = ordered[:-keep], ordered[-keep:]
    for step in drop:
        p = snapshot_path(directory, step)
        p.unlink(missing_ok=True)
        p.with_suffix(".json").unlink(missing_ok=True)
        crc_path(p).unlink(missing_ok=True)
    return kept


def resolve_resume(
    path: str | os.PathLike, height: int, width: int
) -> tuple[Path, int, int, int]:
    """Resolve a resume target to (board_file, completed_steps, height, width)
    without reading the board — so streaming loaders can pread stripes.

    ``path`` may be a snapshot (step recovered from its sidecar/filename), a
    snapshot *directory* (latest snapshot wins), or any contract-format board
    file (completed_steps = 0 unless a sidecar says otherwise).
    """
    p = Path(path)
    if p.is_dir():
        snaps = list_snapshots(p)
        if not snaps:
            raise FileNotFoundError(f"no snapshots in {p}")
        # prefer the newest INTACT snapshot: a job killed mid-collective-
        # write can leave the newest truncated, and resuming must fall
        # back to the one before it rather than wedge forever
        for step, f in snaps:
            if snapshot_intact(f, height, width):
                if (step, f) != snaps[0]:
                    log.warning(
                        "skipping truncated snapshot %s; resuming from %s",
                        snaps[0][1],
                        f,
                    )
                return f, step, height, width
        raise FileNotFoundError(f"no intact snapshots in {p}")
    step = 0
    sidecar = p.with_suffix(".json")
    if sidecar.exists():
        meta = json.loads(sidecar.read_text())
        step = int(meta.get("step", 0))
        height = int(meta.get("height", height))
        width = int(meta.get("width", width))
    else:
        m = _SNAP_RE.match(p.name)
        if m:
            step = int(m.group(1))
    return p, step, height, width


def load_resume(
    path: str | os.PathLike, height: int, width: int
) -> tuple[np.ndarray, int]:
    """Load a board to resume from; returns (board, completed_steps)."""
    p, step, height, width = resolve_resume(path, height, width)
    return read_board(p, height, width), step
