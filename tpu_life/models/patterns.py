"""Well-known CA patterns, for tests and demos.

The reference ships only a ~50%-density random board (data.txt, SURVEY.md
§2.1).  Known patterns with hand-checkable evolution are the unit-test
vocabulary the reference lacks (SURVEY.md §4).
"""

from __future__ import annotations

import numpy as np


def _p(rows: list[str]) -> np.ndarray:
    return np.array([[int(c) for c in r] for r in rows], dtype=np.int8)


def _rle(text: str) -> np.ndarray:
    # larger patterns are defined via their published RLE strings through
    # the framework's own parser (tpu_life/io/rle.py)
    from tpu_life.io.rle import parse_rle

    return parse_rle(text)[0]


BLOCK = _p(["11", "11"])  # still life
BLINKER = _p(["111"])  # period-2 oscillator
TOAD = _p(["0111", "1110"])  # period-2 oscillator
BEACON = _p(["1100", "1100", "0011", "0011"])  # period-2 oscillator
GLIDER = _p(["010", "001", "111"])  # moves (+1, +1) every 4 steps
LWSS = _p(["01111", "10001", "00001", "10010"])  # lightweight spaceship
R_PENTOMINO = _p(["011", "110", "010"])  # methuselah
PULSAR = _rle(  # period-3 oscillator, 13x13
    "x = 13, y = 13\n"
    "2b3o3b3o2b$13b$o4bobo4bo$o4bobo4bo$o4bobo4bo$2b3o3b3o2b$13b$"
    "2b3o3b3o2b$o4bobo4bo$o4bobo4bo$o4bobo4bo$13b$2b3o3b3o2b!"
)
GOSPER_GLIDER_GUN = _rle(  # emits one glider every 30 steps
    "x = 36, y = 9\n"
    "24bo$22bobo$12b2o6b2o12b2o$11bo3bo4b2o12b2o$2o8bo5bo3b2o$"
    "2o8bo3bob2o4bobo$10bo5bo7bo$11bo3bo$12b2o!"
)


def place(board: np.ndarray, pattern: np.ndarray, top: int, left: int) -> np.ndarray:
    """Return a copy of ``board`` with ``pattern`` stamped at (top, left)."""
    out = board.copy()
    h, w = pattern.shape
    out[top : top + h, left : left + w] = pattern
    return out


def empty(height: int, width: int) -> np.ndarray:
    return np.zeros((height, width), dtype=np.int8)


def random_board(
    height: int,
    width: int,
    density: float = 0.5,
    *,
    states: int = 2,
    seed: int = 0,
) -> np.ndarray:
    """Random board matching the reference's ~50%-density uniform init."""
    rng = np.random.default_rng(seed)
    alive = rng.random((height, width)) < density
    if states == 2:
        return alive.astype(np.int8)
    state = rng.integers(1, states, size=(height, width), dtype=np.int8)
    return np.where(alive, state, 0).astype(np.int8)
